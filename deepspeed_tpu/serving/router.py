"""Prefix-affine, depth-balanced routing across scheduler replicas.

One scheduler replica holds one prefix cache, so WHERE a request lands
decides whether its shared prefix is warm: hashing by the prompt's
leading block sends all requests of one tenant/system-prompt to the
same replica (cache affinity), while pure hashing lets a hot prefix
overload its home replica. ``PrefixRouter`` does the standard
compromise — hash-affine with bounded spill: the hashed home replica
wins unless its reported queue depth exceeds the cluster minimum by
more than ``spill_slack``, in which case the request goes to the
shallowest queue (losing the warm prefix but bounding tail latency).

The router is process-topology-agnostic: it sees only prompts, a depth
vector, and (optionally) a liveness mask from ``fleet.FleetHealth``.
``examples/serve_router.py`` drives real scheduler replicas in separate
processes over pipes; unit tests drive it with synthetic depths.
"""

import zlib
from typing import Callable, List, Optional, Sequence, Tuple, Union

# Replica roles for disaggregated serving (serving/disagg.py): a
# ``prefill`` replica runs chunked prompt prefills and hands the KV off;
# a ``decode`` replica runs the continuous-batching token loop. The
# router only ever places DECODE traffic, so role-aware call sites fold
# prefill replicas out of the candidate set (route_trace below;
# FleetCoordinator keeps pool-local sub-routers).
ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"


class NoLiveReplicasError(RuntimeError):
    """Every replica in the fleet is down — nothing can take traffic."""


class PrefixRouter:
    def __init__(self, n_replicas: int, align: int = 64,
                 spill_slack: int = 2):
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        if spill_slack < 0:
            raise ValueError(f"spill_slack must be >= 0, got {spill_slack}")
        self.n_replicas = int(n_replicas)
        self.align = int(align)
        self.spill_slack = int(spill_slack)
        self.spills = 0
        self.affine = 0
        self.failovers = 0

    def home(self, prompt: Sequence[int]) -> int:
        """The hash-affine replica for this prompt's leading block."""
        head = tuple(int(t) for t in prompt[:self.align])
        digest = zlib.crc32(repr(head).encode())
        return digest % self.n_replicas

    def route(self, prompt: Sequence[int], depths: Sequence[int],
              live: Optional[Sequence[bool]] = None) -> Tuple[int, str]:
        """(replica index, 'affine'|'spill'|'failover') given reported
        queue depths and an optional liveness mask.

        Only live replicas are candidates — for the home AND for spills
        (routing to a dead replica loses the request outright). The home
        mapping itself stays a pure hash: when a down replica recovers,
        its mask bit flips back and every affine prompt returns to it
        with no rebalancing step (re-affinity is free).
        """
        if len(depths) != self.n_replicas:
            raise ValueError(
                f"got {len(depths)} depths for {self.n_replicas} replicas")
        if live is not None:
            live = [bool(x) for x in live]
            if len(live) != self.n_replicas:
                raise ValueError(
                    f"got {len(live)} live flags for "
                    f"{self.n_replicas} replicas")
            if not any(live):
                raise NoLiveReplicasError(
                    f"all {self.n_replicas} replicas are down")
        pref = self.home(prompt)
        candidates = [i for i in range(self.n_replicas)
                      if live is None or live[i]]
        if live is not None and not live[pref]:
            # home is dead: deterministic hand-off to the shallowest
            # survivor (ties to the lowest index)
            self.failovers += 1
            return min(candidates,
                       key=lambda i: (depths[i], i)), "failover"
        floor = min(depths[i] for i in candidates)
        if depths[pref] <= floor + self.spill_slack:
            self.affine += 1
            return pref, "affine"
        self.spills += 1
        # ties break to the lowest index — deterministic for tests
        return min(candidates, key=lambda i: (depths[i], i)), "spill"

    def stats(self) -> dict:
        total = self.affine + self.spills + self.failovers
        return {"affine": self.affine, "spills": self.spills,
                "failovers": self.failovers,
                "spill_rate": (self.spills / total) if total else 0.0}


def route_trace(router: PrefixRouter, prompts: List[Sequence[int]],
                costs: Sequence[int] = None,
                live: Union[Sequence[bool],
                            Callable[[int], Sequence[bool]], None] = None,
                roles: Optional[Sequence[str]] = None) -> List[int]:
    """Assign a whole trace against simulated depths (each routed request
    deepens its replica by its cost; default 1). Used by the benches to
    report affinity/spill/failover rates without spawning processes.

    The simulation used to silently skip ``route()``'s liveness and role
    machinery, so the failover branch was untestable offline:

    * ``live`` — a fixed mask, or a callable ``live(step) -> mask`` to
      script an outage mid-trace (replica dies at step 40, recovers at
      80). Dead replicas take nothing; ``router.failovers`` counts the
      requests whose hash home was down.
    * ``roles`` — per-replica :data:`ROLE_PREFILL`/:data:`ROLE_DECODE`.
      This trace is DECODE traffic, so prefill replicas are folded out
      of the candidate set exactly as a role-aware front door would
      (they still occupy their global indices — placement indices stay
      comparable with the fleet's).
    """
    depths = [0] * router.n_replicas
    role_ok = None
    if roles is not None:
        if len(roles) != router.n_replicas:
            raise ValueError(
                f"got {len(roles)} roles for {router.n_replicas} replicas")
        bad = set(roles) - {ROLE_PREFILL, ROLE_DECODE}
        if bad:
            raise ValueError(f"unknown replica roles {sorted(bad)}; "
                             f"choose from ('{ROLE_PREFILL}', "
                             f"'{ROLE_DECODE}')")
        role_ok = [r == ROLE_DECODE for r in roles]
        if not any(role_ok):
            raise NoLiveReplicasError(
                "every replica is a prefill replica — decode traffic "
                "has nowhere to land")
    out = []
    for i, p in enumerate(prompts):
        mask = live(i) if callable(live) else live
        if mask is not None:
            mask = [bool(x) for x in mask]
        if role_ok is not None:
            base = mask if mask is not None \
                else [True] * router.n_replicas
            mask = [a and b for a, b in zip(base, role_ok)]
        r, _ = router.route(p, depths, live=mask)
        depths[r] += 1 if costs is None else int(costs[i])
        out.append(r)
    return out

"""Prefix-affine, depth-balanced routing across scheduler replicas.

One scheduler replica holds one prefix cache, so WHERE a request lands
decides whether its shared prefix is warm: hashing by the prompt's
leading block sends all requests of one tenant/system-prompt to the
same replica (cache affinity), while pure hashing lets a hot prefix
overload its home replica. ``PrefixRouter`` does the standard
compromise — hash-affine with bounded spill: the hashed home replica
wins unless its reported queue depth exceeds the cluster minimum by
more than ``spill_slack``, in which case the request goes to the
shallowest queue (losing the warm prefix but bounding tail latency).

The router is process-topology-agnostic: it sees only prompts, a depth
vector, and (optionally) a liveness mask from ``fleet.FleetHealth``.
``examples/serve_router.py`` drives real scheduler replicas in separate
processes over pipes; unit tests drive it with synthetic depths.
"""

import zlib
from typing import List, Optional, Sequence, Tuple


class NoLiveReplicasError(RuntimeError):
    """Every replica in the fleet is down — nothing can take traffic."""


class PrefixRouter:
    def __init__(self, n_replicas: int, align: int = 64,
                 spill_slack: int = 2):
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        if spill_slack < 0:
            raise ValueError(f"spill_slack must be >= 0, got {spill_slack}")
        self.n_replicas = int(n_replicas)
        self.align = int(align)
        self.spill_slack = int(spill_slack)
        self.spills = 0
        self.affine = 0
        self.failovers = 0

    def home(self, prompt: Sequence[int]) -> int:
        """The hash-affine replica for this prompt's leading block."""
        head = tuple(int(t) for t in prompt[:self.align])
        digest = zlib.crc32(repr(head).encode())
        return digest % self.n_replicas

    def route(self, prompt: Sequence[int], depths: Sequence[int],
              live: Optional[Sequence[bool]] = None) -> Tuple[int, str]:
        """(replica index, 'affine'|'spill'|'failover') given reported
        queue depths and an optional liveness mask.

        Only live replicas are candidates — for the home AND for spills
        (routing to a dead replica loses the request outright). The home
        mapping itself stays a pure hash: when a down replica recovers,
        its mask bit flips back and every affine prompt returns to it
        with no rebalancing step (re-affinity is free).
        """
        if len(depths) != self.n_replicas:
            raise ValueError(
                f"got {len(depths)} depths for {self.n_replicas} replicas")
        if live is not None:
            live = [bool(x) for x in live]
            if len(live) != self.n_replicas:
                raise ValueError(
                    f"got {len(live)} live flags for "
                    f"{self.n_replicas} replicas")
            if not any(live):
                raise NoLiveReplicasError(
                    f"all {self.n_replicas} replicas are down")
        pref = self.home(prompt)
        candidates = [i for i in range(self.n_replicas)
                      if live is None or live[i]]
        if live is not None and not live[pref]:
            # home is dead: deterministic hand-off to the shallowest
            # survivor (ties to the lowest index)
            self.failovers += 1
            return min(candidates,
                       key=lambda i: (depths[i], i)), "failover"
        floor = min(depths[i] for i in candidates)
        if depths[pref] <= floor + self.spill_slack:
            self.affine += 1
            return pref, "affine"
        self.spills += 1
        # ties break to the lowest index — deterministic for tests
        return min(candidates, key=lambda i: (depths[i], i)), "spill"

    def stats(self) -> dict:
        total = self.affine + self.spills + self.failovers
        return {"affine": self.affine, "spills": self.spills,
                "failovers": self.failovers,
                "spill_rate": (self.spills / total) if total else 0.0}


def route_trace(router: PrefixRouter, prompts: List[Sequence[int]],
                costs: Sequence[int] = None) -> List[int]:
    """Assign a whole trace against simulated depths (each routed request
    deepens its replica by its cost; default 1). Used by the bench to
    report affinity/spill rates without spawning processes."""
    depths = [0] * router.n_replicas
    out = []
    for i, p in enumerate(prompts):
        r, _ = router.route(p, depths)
        depths[r] += 1 if costs is None else int(costs[i])
        out.append(r)
    return out

"""Prefix-affine, depth-balanced routing across scheduler replicas.

One scheduler replica holds one prefix cache, so WHERE a request lands
decides whether its shared prefix is warm: hashing by the prompt's
leading block sends all requests of one tenant/system-prompt to the
same replica (cache affinity), while pure hashing lets a hot prefix
overload its home replica. ``PrefixRouter`` does the standard
compromise — hash-affine with bounded spill: the hashed home replica
wins unless its reported queue depth exceeds the cluster minimum by
more than ``spill_slack``, in which case the request goes to the
shallowest queue (losing the warm prefix but bounding tail latency).

The router is process-topology-agnostic: it sees only prompts and a
depth vector. ``examples/serve_router.py`` drives real scheduler
replicas in separate processes over pipes; unit tests drive it with
synthetic depths.
"""

import zlib
from typing import List, Sequence, Tuple


class PrefixRouter:
    def __init__(self, n_replicas: int, align: int = 64,
                 spill_slack: int = 2):
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        if spill_slack < 0:
            raise ValueError(f"spill_slack must be >= 0, got {spill_slack}")
        self.n_replicas = int(n_replicas)
        self.align = int(align)
        self.spill_slack = int(spill_slack)
        self.spills = 0
        self.affine = 0

    def home(self, prompt: Sequence[int]) -> int:
        """The hash-affine replica for this prompt's leading block."""
        head = tuple(int(t) for t in prompt[:self.align])
        digest = zlib.crc32(repr(head).encode())
        return digest % self.n_replicas

    def route(self, prompt: Sequence[int],
              depths: Sequence[int]) -> Tuple[int, str]:
        """(replica index, 'affine'|'spill') given reported queue depths."""
        if len(depths) != self.n_replicas:
            raise ValueError(
                f"got {len(depths)} depths for {self.n_replicas} replicas")
        pref = self.home(prompt)
        floor = min(depths)
        if depths[pref] <= floor + self.spill_slack:
            self.affine += 1
            return pref, "affine"
        self.spills += 1
        # ties break to the lowest index — deterministic for tests
        return min(range(self.n_replicas),
                   key=lambda i: (depths[i], i)), "spill"

    def stats(self) -> dict:
        total = self.affine + self.spills
        return {"affine": self.affine, "spills": self.spills,
                "spill_rate": (self.spills / total) if total else 0.0}


def route_trace(router: PrefixRouter, prompts: List[Sequence[int]],
                costs: Sequence[int] = None) -> List[int]:
    """Assign a whole trace against simulated depths (each routed request
    deepens its replica by its cost; default 1). Used by the bench to
    report affinity/spill rates without spawning processes."""
    depths = [0] * router.n_replicas
    out = []
    for i, p in enumerate(prompts):
        r, _ = router.route(p, depths)
        depths[r] += 1 if costs is None else int(costs[i])
        out.append(r)
    return out

"""SLO-aware admission control for the serving front door.

The scheduler's queue used to grow without bound: under a burst, every
queued request pays the whole backlog's prefill time, p95 TTFT explodes,
and by the time the queue drains every client has timed out anyway —
the classic overload collapse. The fix every production front door
applies is the same: measure the latency you are actually delivering,
and when it breaches the SLO, shed new arrivals (429) until the backlog
drains, trading a few fast rejections for everyone else's latency.

``SLOAdmissionController`` is a policy object the scheduler consults on
every ``submit()``. It feeds on the telemetry bus rather than private
scheduler state:

* ``serve.first_token`` events supply the rolling TTFT window (the p95
  estimate is computed over the last ``window`` completions);
* ``data.prefetch_starved`` marks host-input backpressure — a starving
  input pipeline means admission prefill is about to slow down, so the
  controller treats it as an early overload signal;
* queue depth arrives with each ``decide()`` call.

Shedding is hysteretic: entered when p95 breaches the SLO with a loaded
queue, left only once p95 recovers below ``recover_frac * slo`` AND the
queue has drained to ``drain_to`` — without the drain condition the
controller would flap, admitting a burst the moment one fast completion
lands.

The bus holds bound-method subscribers weakly, so whoever builds the
controller must keep a strong reference (the scheduler does, via
``admission_controller=``).
"""

import time
from dataclasses import dataclass
from collections import deque
from typing import Any, Dict, Optional, Tuple

from deepspeed_tpu.telemetry.bus import (
    KIND_PREFETCH_STARVED,
    KIND_SERVE_FIRST_TOKEN,
    telemetry_bus,
)


@dataclass
class AdmissionConfig:
    slo_ttft_p95_s: float = 2.0     # the latency promise being held
    window: int = 64                # TTFT samples in the rolling window
    min_samples: int = 8            # below this, p95 is too noisy to act
    recover_frac: float = 0.8       # leave shedding at p95 < frac * slo
    drain_to: Optional[int] = None  # ... AND queue <= this (default: slots)
    starvation_grace_s: float = 2.0  # how long a prefetch-starved signal
                                     # counts as live backpressure
    sample_max_age_s: Optional[float] = 30.0  # TTFT samples older than
    # this are evidence of a past era, not the present: without aging,
    # the first arrivals after an idle period would be judged (and shed)
    # on breach-era p95 evidence that no longer describes the replica.
    # None disables aging (count-bounded window only).

    def __post_init__(self):
        if self.slo_ttft_p95_s <= 0:
            raise ValueError("slo_ttft_p95_s must be positive")
        if not 0 < self.recover_frac <= 1:
            raise ValueError("recover_frac must be in (0, 1]")
        if self.min_samples < 1 or self.window < self.min_samples:
            raise ValueError("need window >= min_samples >= 1")
        if self.sample_max_age_s is not None and self.sample_max_age_s <= 0:
            raise ValueError("sample_max_age_s must be positive (or None)")


class SLOAdmissionController:
    """Sheds load to hold a p95 TTFT SLO; see module docstring."""

    def __init__(self, config: Optional[AdmissionConfig] = None, bus=None,
                 clock=time.monotonic):
        self.config = config or AdmissionConfig()
        self._clock = clock
        self._ttfts: deque = deque(maxlen=self.config.window)
        self._shedding = False
        self._last_starved: Optional[float] = None
        self.shed_decisions = 0
        self.admit_decisions = 0
        self._bus = bus if bus is not None else telemetry_bus
        self._bus.subscribe(self.on_event)

    # -- telemetry intake ---------------------------------------------
    def on_event(self, ev: Dict[str, Any]) -> None:
        kind = ev.get("kind")
        if kind == KIND_SERVE_FIRST_TOKEN and "ttft_s" in ev:
            # samples carry their arrival time so an idle gap ages the
            # whole window out instead of freezing breach-era evidence
            self._ttfts.append((self._clock(), float(ev["ttft_s"])))
        elif kind == KIND_PREFETCH_STARVED:
            self._last_starved = self._clock()

    def _prune_stale(self) -> None:
        max_age = self.config.sample_max_age_s
        if max_age is None:
            return
        horizon = self._clock() - max_age
        while self._ttfts and self._ttfts[0][0] < horizon:
            self._ttfts.popleft()

    def p95_ttft(self) -> Optional[float]:
        self._prune_stale()
        if len(self._ttfts) < self.config.min_samples:
            return None
        xs = sorted(v for _, v in self._ttfts)
        return xs[min(len(xs) - 1, int(0.95 * len(xs)))]

    def _input_starved(self) -> bool:
        return (self._last_starved is not None and
                self._clock() - self._last_starved
                < self.config.starvation_grace_s)

    # -- the decision -------------------------------------------------
    def decide(self, queue_depth: int, slots: int) -> Tuple[bool, str]:
        """(admit, reason) for one arriving request."""
        cfg = self.config
        drain_to = cfg.drain_to if cfg.drain_to is not None else slots
        p95 = self.p95_ttft()
        if self._shedding:
            recovered = p95 is None or p95 < cfg.recover_frac * \
                cfg.slo_ttft_p95_s
            if recovered and queue_depth <= drain_to and \
                    not self._input_starved():
                self._shedding = False
            else:
                self.shed_decisions += 1
                return False, (
                    f"draining: p95 ttft {p95 if p95 is not None else 0:.3f}s"
                    f" vs slo {cfg.slo_ttft_p95_s:.3f}s, "
                    f"queue {queue_depth}")
        # a breach only matters when the queue is the cause: with fewer
        # requests than decode lanes, shedding would just waste capacity
        loaded = queue_depth >= max(1, slots)
        if loaded and p95 is not None and p95 > cfg.slo_ttft_p95_s:
            self._shedding = True
            self.shed_decisions += 1
            return False, (f"p95 ttft {p95:.3f}s over slo "
                           f"{cfg.slo_ttft_p95_s:.3f}s at depth "
                           f"{queue_depth}")
        if loaded and self._input_starved():
            self._shedding = True
            self.shed_decisions += 1
            return False, f"input pipeline starved at depth {queue_depth}"
        self.admit_decisions += 1
        return True, "ok"

    # -- introspection ------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        p95 = self.p95_ttft()
        return {
            "shedding": self._shedding,
            "p95_ttft_s": p95,
            "ttft_samples": len(self._ttfts),
            "shed_decisions": self.shed_decisions,
            "admit_decisions": self.admit_decisions,
            "slo_ttft_p95_s": self.config.slo_ttft_p95_s,
        }

    def close(self) -> None:
        self._bus.unsubscribe(self.on_event)

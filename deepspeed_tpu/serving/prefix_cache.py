"""Shared-prefix KV cache for the continuous-batching front door.

Production traffic is prefix-skewed: millions of requests share a system
prompt, and re-prefilling it per admission is the single biggest TTFT
lever (ROADMAP item 2; the Gemma-on-TPU serving writeup makes the same
point). This module caches the KV leaves of popular prompt prefixes so
the scheduler's admission prefill can resume mid-prompt instead of
starting cold.

Exactness is the whole game, and it pins the design:

* **Keys are PADDED column prefixes** (pads encoded as -1). The decode
  cache advances its position clock for pad columns too, and rotary
  phases are baked into cached keys at write time — so a prefix
  prefilled at pad offset 2 is NOT numerics-compatible with the same
  tokens at offset 5. Two prompts share an entry iff they agree on the
  leading padded columns, i.e. on the tokens AND on
  ``(-len) % prompt_bucket``. Bucketing quantizes offsets, so real
  traffic collides often; the bursty bench trace shows the effect.
* **Entries hold ``[1, ...]``-lane cache trees** exactly as the
  scheduler's admission prefill produces them; the scheduler copies on
  hit (continuation prefill donates its cache buffers) and splices the
  extended tree into a lane via the existing jitted ``_splice``.
* **Promotion is popularity-driven**: every admission bumps a counter
  per aligned candidate prefix of its padded prompt; the longest
  candidate reaching ``promote_after`` is snapshotted during that very
  admission (the prefill was running anyway, so materialization costs
  one jitted copy, not an extra forward).

Eviction is LRU over a byte budget derived from
``telemetry/memory.py``'s HBM accounting (explicit bytes win; a
fraction of detected HBM otherwise; a small fallback on backends with
no HBM figure, e.g. the CPU test mesh). Entries whose leaves are
currently being copied into a lane hold a refcount and are never
evicted mid-use.

Like the scheduler it feeds, this class is single-threaded by design —
one serving loop owns it. The router scales out with one cache per
replica process, not a shared one.
"""

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

from collections import OrderedDict

from deepspeed_tpu.telemetry.bus import (
    KIND_SERVE_PREFIX_EVICT,
    KIND_SERVE_PREFIX_HIT,
    KIND_SERVE_PREFIX_MISS,
    publish,
)

Key = Tuple[int, ...]


@dataclass
class PrefixCacheConfig:
    """Knobs for detection and retention.

    ``align`` sets the candidate prefix boundaries (every multiple of it
    is a potential cut). Any value is EXACT — continuation spans never
    cross a ring block regardless of where the snapshot cut — so this is
    purely a detection-granularity/memory knob; the natural choice is
    the ring layout block (or the prompt bucket for dense models), which
    ``serving.build_serving`` wires automatically.
    """
    align: int = 64
    promote_after: int = 2          # admissions sharing a prefix before
                                    # its KV is materialized
    min_prefix_tokens: int = 1      # REAL (non-pad) tokens a candidate
                                    # must contain
    budget_bytes: Optional[int] = None   # explicit cap wins over frac
    budget_frac_hbm: float = 0.05        # share of detected device HBM
    fallback_budget_bytes: int = 256 << 20  # no-HBM backends (CPU mesh)
    counter_capacity: int = 4096    # popularity counters kept (LRU)

    def __post_init__(self):
        if self.align < 1:
            raise ValueError(f"align must be >= 1, got {self.align}")
        if self.promote_after < 1:
            raise ValueError(
                f"promote_after must be >= 1, got {self.promote_after}")


class _Entry:
    __slots__ = ("key", "length", "cache", "nbytes", "refs")

    def __init__(self, key: Key, cache, nbytes: int):
        self.key = key
        self.length = len(key)
        self.cache = cache
        self.nbytes = int(nbytes)
        self.refs = 0


def _tree_nbytes(tree) -> int:
    import jax

    return sum(int(getattr(leaf, "nbytes", 0))
               for leaf in jax.tree.leaves(tree))


class PrefixCache:
    """Ref-counted LRU cache of prefilled prompt-prefix KV trees."""

    def __init__(self, config: Optional[PrefixCacheConfig] = None,
                 device=None):
        self.config = config or PrefixCacheConfig()
        self.budget_bytes = self._resolve_budget(device)
        self._entries: "OrderedDict[Key, _Entry]" = OrderedDict()
        self._counts: "OrderedDict[Key, int]" = OrderedDict()
        self.bytes_used = 0
        self.hits = 0
        self.misses = 0
        self.insertions = 0
        self.evictions = 0
        self.insert_skips = 0

    def _resolve_budget(self, device) -> int:
        cfg = self.config
        if cfg.budget_bytes is not None:
            return int(cfg.budget_bytes)
        from deepspeed_tpu.telemetry.memory import hbm_bytes

        total, _source = hbm_bytes(device)
        if total is None:
            return int(cfg.fallback_budget_bytes)
        return int(total * cfg.budget_frac_hbm)

    # -- candidate geometry -------------------------------------------
    def _pad_offset(self, cols: Key) -> int:
        o = 0
        for c in cols:
            if c >= 0:
                break
            o += 1
        return o

    def _candidate_lengths(self, cols: Key, limit: int):
        """Aligned cut lengths (ascending) eligible as cache keys: every
        multiple of ``align`` up to ``limit`` that leaves at least one
        trailing column AND contains >= min_prefix_tokens real tokens."""
        cfg = self.config
        off = self._pad_offset(cols)
        lo = off + cfg.min_prefix_tokens
        a = cfg.align
        first = ((max(lo, a) + a - 1) // a) * a
        return list(range(first, limit + 1, a))

    # -- the scheduler-facing protocol --------------------------------
    def lookup(self, cols: Key, limit: int,
               request_id=None) -> Optional[_Entry]:
        """Longest cached prefix of ``cols[:limit]``, or None. A returned
        entry is pinned (refs+1) — ``release()`` it once its leaves have
        been copied out."""
        for length in reversed(self._candidate_lengths(cols, limit)):
            entry = self._entries.get(cols[:length])
            if entry is not None:
                entry.refs += 1
                self._entries.move_to_end(entry.key)
                self.hits += 1
                publish(KIND_SERVE_PREFIX_HIT, request_id=request_id,
                        prefix_len=entry.length, nbytes=entry.nbytes)
                return entry
        self.misses += 1
        publish(KIND_SERVE_PREFIX_MISS, request_id=request_id,
                prompt_cols=len(cols))
        return None

    def release(self, entry: _Entry) -> None:
        entry.refs = max(0, entry.refs - 1)

    def promotion_target(self, cols: Key, limit: int,
                         have: int = 0) -> Optional[int]:
        """Bump popularity for every candidate prefix of this prompt;
        return the longest length past ``have`` whose count has reached
        ``promote_after`` and which is not already cached — the caller
        snapshots its cache there during the admission prefill."""
        cfg = self.config
        best = None
        for length in self._candidate_lengths(cols, limit):
            key = cols[:length]
            n = self._counts.pop(key, 0) + 1
            self._counts[key] = n  # pop+set keeps LRU order fresh
            if (n >= cfg.promote_after and length > have
                    and key not in self._entries):
                best = length
        while len(self._counts) > cfg.counter_capacity:
            self._counts.popitem(last=False)
        return best

    def insert(self, key: Key, cache, request_id=None) -> bool:
        """Adopt a prefilled cache tree for ``key``; evicts LRU unpinned
        entries to fit the byte budget. Returns False (and drops the
        tree) when the entry cannot fit — every survivor is pinned or
        the tree alone exceeds the budget."""
        if key in self._entries:
            self.insert_skips += 1
            return False
        nbytes = _tree_nbytes(cache)
        if not self._make_room(nbytes):
            self.insert_skips += 1
            return False
        self._entries[key] = _Entry(key, cache, nbytes)
        self.bytes_used += nbytes
        self.insertions += 1
        return True

    def _make_room(self, need: int) -> bool:
        if need > self.budget_bytes:
            return False
        while self.bytes_used + need > self.budget_bytes:
            victim = next((e for e in self._entries.values()
                           if e.refs == 0), None)
            if victim is None:
                return False  # everything left is mid-splice
            del self._entries[victim.key]
            self.bytes_used -= victim.nbytes
            self.evictions += 1
            publish(KIND_SERVE_PREFIX_EVICT, prefix_len=victim.length,
                    nbytes=victim.nbytes, bytes_used=self.bytes_used)
        return True

    # -- introspection ------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        total = self.hits + self.misses
        return {
            "entries": len(self._entries),
            "bytes_used": self.bytes_used,
            "budget_bytes": self.budget_bytes,
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": (self.hits / total) if total else 0.0,
            "insertions": self.insertions,
            "insert_skips": self.insert_skips,
            "evictions": self.evictions,
        }

    def __len__(self) -> int:
        return len(self._entries)

"""Disaggregated serving: prefill/decode split with exact KV hand-off.

Prefill and decode want different machines. Chunked prompt prefill is
compute-bound (big matmuls over whole blocks — MXU work), while the
continuous-batching token loop is memory-bound (one [slots, 1] step per
token, HBM-bandwidth-limited on the KV cache). A replica serving both
interleaves them on one set of cores, so every admission prefill stalls
every decoding lane's next token — the TTFT/ITL coupling that
disaggregated serving architectures (DistServe, Splitwise, the
reference's DeepSpeed-FastGen ancestry) exist to break.

This module splits the two phases over the machinery the scheduler
already has, without weakening any exactness guarantee:

* :class:`PrefillWorker` — a prefill-role replica: runs the SAME exact
  chunked prefill the scheduler's admission path runs (block-aligned
  spans via ``engine._chunked_prefill``, identical left-pad bucketing),
  and emits a :class:`KVHandoff` — the first sampled token plus the
  ``[1, ...]`` decode cache, sized in bytes as it would cross a wire.
* :class:`KVHandoff` — the transfer artifact. Exactness argument: the
  scheduler's ``kv_handoff`` admission splices this cache into a lane
  with the SAME jitted ``_splice`` used for local prefills, and greedy
  decode is a pure function of (weights, cache, last token) — so a
  decode replica continuing from a handed cache is token-identical to
  one that prefilled locally (tested in test_serving_disagg.py).
* :class:`DisaggServer` — in-process composition of N prefill workers
  and one decode scheduler: routes each prompt to a prefill worker
  (hash-affine via ``FleetCoordinator.place_prefill`` when a coordinator
  is wired, round-robin otherwise), accounts every hand-off as a
  ``serve.kv_transfer`` event, and submits the request to the decode
  scheduler with the hand-off attached. The decode scheduler may run
  int8 KV lanes and speculative decoding — both compose with hand-off
  because the handed cache is spliced through the same leaf protocol.

The int8 KV cache (``kv_cache_dtype="int8"`` on the model config /
``{"kv_cache": "int8"}`` in the inference config) earns its keep twice
here: resident lane bytes shrink ~2x vs bf16 (~3.9x vs fp32) so one
decode replica holds proportionally more lanes under the same HBM
budget (:func:`lane_kv_bytes` computes the capacity table), and the
hand-off payload — the bytes ``serve.kv_transfer`` meters — shrinks by
the same factor. NOTE: hand-off requires producer and consumer to agree
on ``prompt_bucket`` AND cache dtype; :class:`DisaggServer` validates
the bucket and leaves dtype agreement to the leaf-shape check in
``_splice`` (mismatched trees fail loudly at splice time).
"""

import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.sparse_attention.sparse_attention_utils import (
    ring_engaged,
)
from deepspeed_tpu.telemetry.bus import KIND_SERVE_KV_TRANSFER, publish

__all__ = ["KVHandoff", "PrefillWorker", "DisaggServer", "lane_kv_bytes",
           "tree_nbytes"]


def tree_nbytes(tree) -> int:
    """Total payload bytes of a pytree of arrays — what a cross-host
    KV hand-off actually ships (int8 leaves count 1 byte/elt, their f32
    scale sidebands count too: the wire cost is honest, not idealized)."""
    return int(sum(
        leaf.size * jnp.dtype(leaf.dtype).itemsize
        for leaf in jax.tree.leaves(tree) if hasattr(leaf, "dtype")))


def _probe_len(mcfg, bucket: int, bucketed) -> int:
    """Trace length for engine materialization: the training forward
    needs block-divisible T with the full window of blocks present
    (same probe the scheduler's _ensure_compiled uses)."""
    t_probe = bucket
    sc = getattr(mcfg, "sparse_attention", None)
    nswb = getattr(sc, "num_sliding_window_blocks", None)
    blk = getattr(sc, "block", None)
    if nswb and blk:
        t_probe = max(t_probe, int(nswb) * int(blk))
    return bucketed(t_probe)


def lane_kv_bytes(model, slots: int = 1) -> Dict[str, int]:
    """Per-lane decode KV-cache footprint for ``model`` — pure
    ``eval_shape``, no parameters materialized, so sizing a 70B-scale
    capacity table costs microseconds.

    Returns ``resident_bytes`` (what this cache stores: int8 payloads +
    f32 scale sidebands under ``kv_cache_dtype="int8"``) and
    ``unquantized_bytes`` (the compute-dtype twin) for ONE lane — the
    lanes-per-HBM capacity tables in docs/performance.md divide the HBM
    budget by these.
    """
    mcfg = model.config
    ring = ring_engaged(mcfg)
    blk = ring[2] if ring is not None else 64
    t_probe = _probe_len(mcfg, blk,
                         lambda t: ((t + blk - 1) // blk) * blk)
    init_probe = jnp.zeros((1, t_probe), jnp.int32)
    pshapes = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), init_probe,
                           deterministic=True))["params"]
    probe = jnp.zeros((slots, 1), jnp.int32)

    def shape_fn(params):
        _, vars_out = model.apply({"params": params}, probe,
                                  deterministic=True, decode=True,
                                  mutable=["cache"])
        return vars_out["cache"]

    shapes = jax.eval_shape(shape_fn, pshapes)
    compute_dt = jnp.dtype(getattr(mcfg, "dtype", jnp.float32))
    resident = 0
    unquant = 0

    def acc(path, sd):
        nonlocal resident, unquant
        name = path[-1].key if hasattr(path[-1], "key") else path[-1]
        nbytes = sd.size * jnp.dtype(sd.dtype).itemsize
        resident += nbytes
        if name in ("cached_key", "cached_value"):
            unquant += sd.size * compute_dt.itemsize
        elif name in ("cached_key_scale", "cached_value_scale"):
            pass  # sideband of the int8 store; the unquantized twin has none
        else:
            unquant += nbytes

    jax.tree_util.tree_map_with_path(acc, shapes)
    return {"resident_bytes": int(resident // slots),
            "unquantized_bytes": int(unquant // slots)}


@dataclass
class KVHandoff:
    """One prefill replica's output for one prompt: everything a decode
    replica needs to continue EXACTLY (greedy decode is a pure function
    of weights + cache + last token)."""
    request_id: Any
    first_token: int
    cache: Any            # [1, ...] decode cache pytree
    nbytes: int           # payload size as shipped (tree_nbytes)
    prompt_bucket: int    # the producer's bucket — consumer must match
    prefill_s: float = 0.0

    def as_submit_arg(self):
        """The ``kv_handoff=`` value for ``scheduler.submit``."""
        return (self.first_token, self.cache)


class PrefillWorker:
    """A prefill-role replica over one engine: exact chunked prompt
    prefill -> :class:`KVHandoff`. Temperature is pinned greedy — the
    hand-off's exactness story is the greedy purity argument, and the
    first token must match what the decode replica would have sampled."""

    def __init__(self, engine, prompt_bucket: Optional[int] = None,
                 replica: int = 0):
        self.engine = engine
        self.replica = int(replica)
        self._mcfg = getattr(engine.module, "config", None)
        ring = ring_engaged(self._mcfg) if self._mcfg is not None else None
        if prompt_bucket is None:
            prompt_bucket = ring[2] if ring is not None else 64
        if ring is not None and prompt_bucket % ring[2] != 0:
            raise ValueError(
                f"prompt_bucket {prompt_bucket} must be a multiple of "
                f"the ring layout block {ring[2]} (same rule as the "
                "decode scheduler — the cache bakes in the pad offset)")
        self.prompt_bucket = int(prompt_bucket)
        self.prefills = 0
        self.kv_bytes = 0

    def _bucketed(self, n: int) -> int:
        b = self.prompt_bucket
        return ((n + b - 1) // b) * b

    def _ensure_compiled(self):
        eng = self.engine
        if eng._params is None or not hasattr(eng, "_param_shardings"):
            eng._materialize(jnp.zeros(
                (1, _probe_len(self._mcfg, self.prompt_bucket,
                               self._bucketed)), jnp.int32))
        if eng._prefill_fn is None:
            eng._build_decode_fns()

    def prefill(self, prompt: Sequence[int], request_id=None) -> KVHandoff:
        """Run one prompt's exact chunked prefill; returns the hand-off."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("an empty prompt cannot seed generation")
        self._ensure_compiled()
        eng = self.engine
        t0 = time.monotonic()
        Lp = self._bucketed(len(prompt))
        ids = np.zeros((1, Lp), np.int32)
        mask = np.zeros((1, Lp), bool)
        ids[0, Lp - len(prompt):] = prompt
        mask[0, Lp - len(prompt):] = True
        logits_last, cache = eng._chunked_prefill(
            jnp.asarray(ids), jnp.asarray(mask))
        first = int(np.asarray(jnp.argmax(logits_last, axis=-1))[0])
        nbytes = tree_nbytes(cache)
        self.prefills += 1
        self.kv_bytes += nbytes
        return KVHandoff(request_id=request_id, first_token=first,
                         cache=cache, nbytes=nbytes,
                         prompt_bucket=self.prompt_bucket,
                         prefill_s=time.monotonic() - t0)

    def stats(self) -> Dict[str, Any]:
        return {"replica": self.replica, "prefills": self.prefills,
                "kv_bytes": self.kv_bytes}


class DisaggServer:
    """In-process prefill/decode disaggregation: N prefill workers feed
    one decode scheduler through :class:`KVHandoff`s.

    ``submit`` runs the prefill SYNCHRONOUSLY on the chosen worker (the
    in-process analogue of a prefill tier answering an RPC), accounts
    the hand-off (``serve.kv_transfer``), and queues the request on the
    decode scheduler with the cache attached — the decode loop never
    runs a prompt prefill, so its inter-token latency stops absorbing
    admission stalls. ``run`` drives the decode scheduler.

    ``coordinator`` (optional, a role-aware ``FleetCoordinator``) takes
    over prefill placement (hash-affine) and transfer accounting;
    without one, placement is round-robin and events publish directly.
    """

    def __init__(self, scheduler, prefill_workers: Sequence[PrefillWorker],
                 coordinator=None):
        if not prefill_workers:
            raise ValueError("DisaggServer needs >= 1 PrefillWorker")
        self.scheduler = scheduler
        self.workers = list(prefill_workers)
        self.coordinator = coordinator
        for w in self.workers:
            if w.prompt_bucket != scheduler.prompt_bucket:
                raise ValueError(
                    f"prefill worker bucket {w.prompt_bucket} != decode "
                    f"scheduler bucket {scheduler.prompt_bucket}: the "
                    "handed cache bakes in the pad offset, so producer "
                    "and consumer must bucket identically")
        self._rr = 0
        self.handoffs = 0
        self.handoff_bytes = 0

    def _pick_worker(self, prompt) -> int:
        if self.coordinator is not None:
            # in-process workers have no transport to heartbeat through,
            # and the coordinator's silence schedule would mark them
            # DOWN during a long prefill compile — a worker we can call
            # directly is alive by definition, so vouch for it here
            # (out-of-process replicas still live or die by their pipes)
            for w in self.workers:
                self.coordinator.health.heartbeat(w.replica)
            replica, _how = self.coordinator.place_prefill(prompt)
            for i, w in enumerate(self.workers):
                if w.replica == replica:
                    return i
            raise ValueError(
                f"coordinator placed prefill on replica {replica}, but "
                f"no PrefillWorker here carries that replica index")
        i = self._rr
        self._rr = (self._rr + 1) % len(self.workers)
        return i

    def submit(self, prompt: Sequence[int], max_new_tokens: int = 32,
               **submit_kw) -> int:
        """Prefill on a worker, hand off, queue on the decode scheduler.
        Returns the decode scheduler's request id."""
        idx = self._pick_worker(prompt)
        worker = self.workers[idx]
        h = worker.prefill(prompt)
        self.handoffs += 1
        self.handoff_bytes += h.nbytes
        rid = self.scheduler.submit(prompt, max_new_tokens=max_new_tokens,
                                    kv_handoff=h.as_submit_arg(),
                                    **submit_kw)
        if self.coordinator is not None:
            self.coordinator.record_kv_transfer(
                rid, from_replica=worker.replica, to_replica=-1,
                nbytes=h.nbytes, transfer_s=h.prefill_s)
        else:
            publish(KIND_SERVE_KV_TRANSFER, request_id=rid,
                    from_replica=worker.replica, to_replica=-1,
                    bytes=h.nbytes, transfers_total=self.handoffs,
                    bytes_total=self.handoff_bytes)
        return rid

    def run(self, poll_fn=None):
        return self.scheduler.run(poll_fn)

    def stats(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "handoffs": self.handoffs,
            "handoff_bytes": self.handoff_bytes,
            "workers": [w.stats() for w in self.workers],
            "frontdoor": self.scheduler.frontdoor_stats(),
        }
        if self.coordinator is not None:
            out["fleet"] = self.coordinator.stats()
        return out

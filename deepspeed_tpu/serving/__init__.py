"""Serving front door: shared-prefix KV cache, SLO admission, routing.

Three layers over ``inference/scheduler.py``'s continuous batching:

* :class:`PrefixCache` — prefill a popular prompt prefix once, splice
  its KV leaves into every admitted lane that shares it (exact: keys
  are padded column prefixes, continuations never cross a ring block);
* :class:`SLOAdmissionController` — telemetry-bus-driven load shedding
  that holds a p95 TTFT SLO with a bounded queue;
* :class:`PrefixRouter` — hash-affine, depth-balanced placement across
  replicas (``examples/serve_router.py`` runs it for real);
* :mod:`fleet` — replica health, request journaling, exact failover
  replay, and graceful drain (the fault-tolerance layer over all of
  the above).

``build_serving`` is the config-plumbing entry point — the serving
analogue of ``deepspeed_tpu.initialize(config=...)``.
"""

from typing import Any, Dict, Optional

from deepspeed_tpu.inference.scheduler import (
    AdmissionRejected,
    ContinuousBatchingScheduler,
    DeadlineExceededError,
    DrainingError,
    QueueFullError,
    RequestShedError,
)
from deepspeed_tpu.serving.admission import (
    AdmissionConfig,
    SLOAdmissionController,
)
from deepspeed_tpu.serving.fleet import (
    DOWN,
    HEALTHY,
    RECOVERING,
    SUSPECT,
    FleetCoordinator,
    FleetHealth,
    GracefulDrain,
    HealthConfig,
    JournalEntry,
    ReplicaDead,
    RequestJournal,
)
from deepspeed_tpu.serving.disagg import (
    DisaggServer,
    KVHandoff,
    PrefillWorker,
    lane_kv_bytes,
)
from deepspeed_tpu.serving.prefix_cache import (
    PrefixCache,
    PrefixCacheConfig,
)
from deepspeed_tpu.serving.router import (
    ROLE_DECODE,
    ROLE_PREFILL,
    NoLiveReplicasError,
    PrefixRouter,
    route_trace,
)

__all__ = [
    "AdmissionConfig",
    "AdmissionRejected",
    "ContinuousBatchingScheduler",
    "DOWN",
    "DeadlineExceededError",
    "DisaggServer",
    "DrainingError",
    "FleetCoordinator",
    "FleetHealth",
    "GracefulDrain",
    "HEALTHY",
    "HealthConfig",
    "JournalEntry",
    "KVHandoff",
    "NoLiveReplicasError",
    "PrefixCache",
    "PrefixCacheConfig",
    "PrefixRouter",
    "PrefillWorker",
    "QueueFullError",
    "RECOVERING",
    "ROLE_DECODE",
    "ROLE_PREFILL",
    "ReplicaDead",
    "RequestJournal",
    "RequestShedError",
    "SLOAdmissionController",
    "SUSPECT",
    "build_serving",
    "lane_kv_bytes",
    "route_trace",
]


def _default_align(engine, prompt_bucket: Optional[int]) -> int:
    """Ring layout block when the model rings, else the prompt bucket —
    the boundaries admission prefill naturally produces."""
    from deepspeed_tpu.ops.sparse_attention.sparse_attention_utils import \
        ring_engaged

    mcfg = getattr(engine.module, "config", None)
    ring = ring_engaged(mcfg) if mcfg is not None else None
    if ring is not None:
        return ring[2]
    return prompt_bucket if prompt_bucket else 64


def build_serving(engine, config: Optional[Dict[str, Any]] = None,
                  reject_callback=None,
                  draft_engine=None) -> ContinuousBatchingScheduler:
    """Assemble the front door from one config dict::

        build_serving(engine, {
            "slots": 8,
            "prompt_bucket": 64,
            "temperature": 0.0,
            "max_pending": 256,
            "prefix_cache": {"promote_after": 2,
                             "budget_bytes": 512 << 20},
            "admission": {"slo_ttft_p95_s": 2.0, "window": 64},
            "journal": True,
            "spec_k": 4,   # with draft_engine=: speculative decoding
        })

    ``prefix_cache``/``admission``/``journal`` accept a knob dict,
    ``True`` (all defaults), or ``False``/absent (off). Unknown keys
    raise — a typo'd knob silently running with defaults is how SLOs
    get missed. ``draft_engine`` (parameter, not a config key — it is a
    live engine, not a knob) plus ``spec_k`` turn on exact-greedy
    speculative decoding in the scheduler.
    """
    cfg = dict(config or {})
    slots = int(cfg.pop("slots", 8))
    prompt_bucket = cfg.pop("prompt_bucket", None)
    temperature = float(cfg.pop("temperature", 0.0))
    eos_token_id = cfg.pop("eos_token_id", None)
    max_pending = cfg.pop("max_pending", None)
    spec_k = int(cfg.pop("spec_k", 0))
    pc_cfg = cfg.pop("prefix_cache", False)
    adm_cfg = cfg.pop("admission", False)
    journal_cfg = cfg.pop("journal", False)
    if cfg:
        raise ValueError(f"unknown serving config keys: {sorted(cfg)}")

    prefix_cache = None
    if pc_cfg:
        knobs = dict(pc_cfg) if isinstance(pc_cfg, dict) else {}
        knobs.setdefault("align", _default_align(engine, prompt_bucket))
        prefix_cache = PrefixCache(PrefixCacheConfig(**knobs))

    admission = None
    if adm_cfg:
        knobs = dict(adm_cfg) if isinstance(adm_cfg, dict) else {}
        admission = SLOAdmissionController(AdmissionConfig(**knobs))

    journal = None
    if journal_cfg:
        knobs = dict(journal_cfg) if isinstance(journal_cfg, dict) else {}
        journal = RequestJournal(**knobs)

    return ContinuousBatchingScheduler(
        engine, slots=slots, prompt_bucket=prompt_bucket,
        temperature=temperature, eos_token_id=eos_token_id,
        max_pending=max_pending, prefix_cache=prefix_cache,
        admission_controller=admission, reject_callback=reject_callback,
        journal=journal, draft_engine=draft_engine, spec_k=spec_k)

"""Whole-process-group lifecycle: spawn children in their own group, reap
the entire tree with TERM -> KILL escalation.

Every parent in this codebase that holds child processes — the launcher's
ssh fan-out (``launcher/runner.py``), the autotuner's local experiment
relaunch (``autotuning/cli.py``), the dryrun harness's re-exec parent
(``__graft_entry__.py``) — must go through these two helpers. The failure
they close over: ``proc.terminate()`` signals only the direct child, so a
child that forks (every JAX training script under a launcher does) or
masks SIGTERM leaves grandchildren running after the parent gives up —
the 21-hour leaked JAX child of ROADMAP item 1.

Deliberately dependency-free (no jax, no package imports): importable
from ``__graft_entry__`` before the toolchain is set up.
"""

import os
import signal
import subprocess
import time
from typing import Union

__all__ = ["spawn_process_group", "reap_process_group"]


def spawn_process_group(cmd, **popen_kwargs) -> subprocess.Popen:
    """``subprocess.Popen`` with the child in its OWN session (hence its
    own process group), so :func:`reap_process_group` can signal the whole
    tree without touching the parent's group."""
    popen_kwargs.setdefault("start_new_session", True)
    return subprocess.Popen(cmd, **popen_kwargs)


def _group_alive(pgid: int) -> bool:
    try:
        os.killpg(pgid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:  # members exist but aren't ours
        return True


def _signal_group(pgid: int, sig: int) -> None:
    try:
        os.killpg(pgid, sig)
    except ProcessLookupError:
        pass


def _wait_group(proc: subprocess.Popen, pgid: int, timeout: float) -> bool:
    """Wait for the whole group to vanish; returns True if it did. Always
    reaps the direct child (``proc.wait``) so it can't linger as a zombie
    that keeps the group 'alive'."""
    deadline = time.monotonic() + timeout
    while True:
        remaining = deadline - time.monotonic()
        if proc.poll() is None:
            try:
                proc.wait(timeout=max(remaining, 0.05))
            except subprocess.TimeoutExpired:
                return False
        if not _group_alive(pgid):
            return True
        if remaining <= 0:
            return False
        time.sleep(min(0.05, max(remaining, 0.01)))


def reap_process_group(proc: Union[subprocess.Popen, int],
                       term_timeout: float = 10.0,
                       kill_timeout: float = 10.0) -> str:
    """TERM the child's process group; escalate to SIGKILL if anything in
    it (the child included) survives ``term_timeout`` seconds.

    ``proc`` is the ``Popen`` from :func:`spawn_process_group` (or a bare
    pid for callers that lost the handle). Returns how the group died:
    ``"exited"`` (already gone), ``"term"`` (SIGTERM sufficed), ``"kill"``
    (SIGKILL needed), or ``"survived"`` (unkillable even by SIGKILL after
    ``kill_timeout`` — caller should report, nothing more can be done).
    Never raises for already-dead processes.
    """
    pid = proc if isinstance(proc, int) else proc.pid
    try:
        pgid = os.getpgid(pid)
    except ProcessLookupError:
        pgid = pid  # direct child gone; sweep whatever group it had
    if pgid == os.getpgid(0):
        # child shares OUR group (caller bypassed spawn_process_group):
        # killpg would shoot this process too — fall back to the single pid
        if isinstance(proc, subprocess.Popen):
            if proc.poll() is not None:
                return "exited"
            proc.terminate()
            try:
                proc.wait(timeout=term_timeout)
                return "term"
            except subprocess.TimeoutExpired:
                proc.kill()
                try:
                    proc.wait(timeout=kill_timeout)
                    return "kill"
                except subprocess.TimeoutExpired:
                    return "survived"
        return "exited"

    if isinstance(proc, subprocess.Popen):
        already = proc.poll() is not None
    else:
        proc = None
        already = False
    if already and not _group_alive(pgid):
        return "exited"

    _signal_group(pgid, signal.SIGTERM)
    if proc is not None:
        if _wait_group(proc, pgid, term_timeout):
            return "exited" if already else "term"
    else:
        deadline = time.monotonic() + term_timeout
        while _group_alive(pgid) and time.monotonic() < deadline:
            time.sleep(0.05)
        if not _group_alive(pgid):
            return "term"

    _signal_group(pgid, signal.SIGKILL)
    if proc is not None:
        if _wait_group(proc, pgid, kill_timeout):
            return "kill"
    else:
        deadline = time.monotonic() + kill_timeout
        while _group_alive(pgid) and time.monotonic() < deadline:
            time.sleep(0.05)
        if not _group_alive(pgid):
            return "kill"
    return "survived"

"""Pytree path utilities shared across subsystems (params are addressed by
path string for sharding rules, MoE grouping, checkpoint reshaping)."""

from typing import Any, Dict

import jax


def key_str(entry) -> str:
    """One path entry -> string (handles DictKey/GetAttrKey/SequenceKey)."""
    for attr in ("key", "name", "idx"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def path_str(path) -> str:
    """Full pytree key path -> 'a/b/c'."""
    return "/".join(key_str(p) for p in path)


def flatten_with_paths(tree) -> Dict[str, Any]:
    """Pytree -> {path_string: leaf}."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    return {path_str(path): leaf for path, leaf in flat}


def flatten_dots(tree, keep_empty_nodes: bool = False) -> Dict[str, Any]:
    """State-dict-style nested dict -> {'a.b.c': leaf} (flax traverse_util
    flatten with dot-joined keys; the checkpoint/compression path scheme)."""
    from flax import traverse_util

    return {
        ".".join(k): v
        for k, v in traverse_util.flatten_dict(
            tree, keep_empty_nodes=keep_empty_nodes).items()
    }


def unflatten_dots(flat: Dict[str, Any]):
    """Inverse of :func:`flatten_dots`."""
    from flax import traverse_util

    return traverse_util.unflatten_dict(
        {tuple(k.split(".")): v for k, v in flat.items()})

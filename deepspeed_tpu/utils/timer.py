"""Wall-clock and throughput timers.

Parity with reference ``deepspeed/utils/timer.py`` (SynchronizedWallClockTimer
:20-133, ThroughputTimer :135). CUDA-event synchronisation is replaced by
``jax.block_until_ready`` on live arrays (the honest TPU analogue: XLA is
async-dispatched exactly like CUDA streams).
"""

import time
from collections import OrderedDict

from deepspeed_tpu.utils.logging import log_dist


_fence_fn = None


def _sync():
    """Timer-internal fence; never raises (timers must work device-less)."""
    try:
        fence()
    except Exception:  # pragma: no cover
        pass


def fence(tree=None):
    """Drain the device compute queue before reading the wall clock.

    ``block_until_ready`` can return BEFORE the accelerator queue drains on
    tunneled transports, so fence with a scalar HOST READ of a device-side
    reduction — of one element of the first leaf of ``tree`` (e.g.
    ``engine.params``) if given, else of a fresh tiny program enqueued
    behind everything pending (the device runs programs in order). Never
    read a full array as a fence: the transfer poisons the timing — and a
    full-leaf f32 upcast would allocate at the worst possible moment.

    Call ``prewarm_fence()`` once outside any timed window first: compiling
    the tiny fence program costs ~0.7 s on a tunneled transport, and a lazy
    first compile inside a measured region reads as a throughput regression
    (this is exactly what sank the round-3 BERT number by 31%).
    """
    import jax
    import jax.numpy as jnp

    leaves = jax.tree.leaves(tree) if tree is not None else []
    if leaves:
        float(jnp.sum(leaves[0].ravel()[:1].astype(jnp.float32)))
        return
    global _fence_fn
    if _fence_fn is None:
        _fence_fn = jax.jit(lambda: jnp.zeros(()))
    float(_fence_fn())


def prewarm_fence():
    """Compile + run the no-tree fence program once (outside timed regions)."""
    _sync()


class _Timer:
    def __init__(self, name: str):
        self.name_ = name
        self.started_ = False
        self.elapsed_ = 0.0
        self.start_time = 0.0
        self.count = 0

    def start(self, sync: bool = True):
        assert not self.started_, f"timer {self.name_} has already been started"
        if sync:
            _sync()
        self.start_time = time.time()
        self.started_ = True

    def stop(self, reset: bool = False, sync: bool = True):
        assert self.started_, f"timer {self.name_} is not started"
        if sync:
            _sync()
        elapsed = time.time() - self.start_time
        if reset:
            self.elapsed_ = elapsed
        else:
            self.elapsed_ += elapsed
        self.started_ = False
        self.count += 1

    def reset(self):
        self.started_ = False
        self.elapsed_ = 0.0
        self.count = 0

    def elapsed(self, reset: bool = True):
        started = self.started_
        if started:
            self.stop()
        elapsed = self.elapsed_
        if reset:
            self.reset()
        if started:
            self.start()
        return elapsed

    def mean(self):
        return (self.elapsed_ / self.count) if self.count else 0.0


class SynchronizedWallClockTimer:
    """Named-timer group; `log()` prints a one-line breakdown like the
    reference's wall_clock_breakdown output (engine.py:2063-2078)."""

    def __init__(self):
        self.timers = OrderedDict()

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def has(self, name: str) -> bool:
        return name in self.timers

    def log(self, names=None, normalizer: float = 1.0, reset: bool = True, ranks=None):
        assert normalizer > 0.0
        names = names if names is not None else list(self.timers)
        parts = []
        for name in names:
            if name in self.timers:
                elapsed = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                parts.append(f"{name}: {elapsed:.2f}")
        if parts:
            log_dist("time (ms) | " + " | ".join(parts), ranks=ranks)

    def get_mean(self, names, normalizer: float = 1.0):
        assert normalizer > 0.0
        return {
            name: self.timers[name].mean() * 1000.0 / normalizer
            for name in names
            if name in self.timers
        }


class ThroughputTimer:
    """samples/sec + optional TFLOPS reporting (reference utils/timer.py:135)."""

    def __init__(
        self,
        batch_size: int,
        start_step: int = 2,
        steps_per_output: int = 50,
        monitor_memory: bool = False,
        logging_fn=None,
    ):
        self.start_time = 0.0
        self.end_time = 0.0
        self.started = False
        self.batch_size = max(1, batch_size)
        self.start_step = start_step
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0.0
        self.step_elapsed_time = 0.0
        self._fence_epoch_time = None  # wall clock at last fenced report
        self._fence_epoch_step = 0
        self._fenced_total_time = 0.0
        self._fenced_total_steps = 0
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or (lambda msg: log_dist(msg, ranks=[0]))
        self.initialized = False

    def update_epoch_count(self):
        self.epoch_count += 1
        self.micro_step_count = 0

    def _init_timer(self):
        if self.initialized:
            return
        # compile the queue-drain fence now, while the caller is still in
        # its own compile/warmup phase — the lazy first compile costs ~0.7 s
        # on tunneled transports and must not land inside a measured region
        prewarm_fence()
        self.initialized = True

    def start(self):
        self._init_timer()
        self.started = True
        if self.global_step_count >= self.start_step:
            # NO device fence here: syncing every micro step would serialize
            # the dispatch pipeline (one fence costs a full in-flight step).
            # Throughput is fenced only at reporting boundaries (and the
            # baseline is seeded in stop() at the warmup crossing), so the
            # running average is exact and intermediate steps overlap.
            self.start_time = time.time()

    def _reseed_fence_epoch(self):
        """Drain the device queue and (re)anchor the fenced wall-clock
        baseline at the current step count."""
        _sync()
        self._fence_epoch_time = time.time()
        self._fence_epoch_step = self.global_step_count

    def stop(self, global_step: bool = False, report_speed: bool = True):
        if not self.started:
            return
        self.started = False
        self.micro_step_count += 1
        if global_step:
            self.global_step_count += 1
            if (self.global_step_count >= self.start_step
                    and self._fence_epoch_time is None):
                # crossing from warmup into the measured region: drain the
                # queue and seed the fenced baseline HERE, at the tail of
                # the last warmup step, so the drain (which waits out every
                # in-flight compile/step) is never charged to the first
                # measured interval
                self._reseed_fence_epoch()
        if self.start_time > 0:
            self.end_time = time.time()
            duration = self.end_time - self.start_time
            self.total_elapsed_time += duration
            self.step_elapsed_time += duration
            self.start_time = 0.0
            if global_step and report_speed and (
                self.global_step_count % self.steps_per_output == 0
            ):
                # steps in between are dispatch-only (no fence); honest
                # throughput = samples between fenced boundaries / the
                # fenced wall time between them
                prev_time, prev_step = (self._fence_epoch_time,
                                        self._fence_epoch_step)
                self._reseed_fence_epoch()
                curr = 0.0
                if prev_time is not None:
                    span = self._fence_epoch_time - prev_time
                    steps = self.global_step_count - prev_step
                    if span > 0:
                        curr = self.batch_size * steps / span
                    self._fenced_total_time += span
                    self._fenced_total_steps += steps
                self.logging(
                    "epoch={}/micro_step={}/global_step={}, "
                    "RunningAvgSamplesPerSec={:.3f}, CurrSamplesPerSec={:.3f}".format(
                        self.epoch_count,
                        self.micro_step_count,
                        self.global_step_count,
                        self.avg_samples_per_sec(),
                        curr,
                    )
                )
        if global_step:
            self.step_elapsed_time = 0.0

    def avg_samples_per_sec(self):
        # fenced boundary-to-boundary accounting only: before the first
        # fenced interval the host-side durations are dispatch-only and
        # would overreport by orders of magnitude — return 0 ("no honest
        # measurement yet") instead
        if self._fenced_total_time > 0:
            return (self.batch_size * self._fenced_total_steps
                    / self._fenced_total_time)
        return 0.0

"""Consolidate a deepspeed_tpu checkpoint into a single fp32 weights file.

Parity with reference ``deepspeed/utils/zero_to_fp32.py`` (482 LoC): that tool
stitches per-rank ZeRO shard files back into one fp32 state_dict. Here
checkpoints already store logically-global arrays (sharding is a runtime
property, not a file layout), so consolidation is a cast + rewrite — the tool
exists for workflow parity and for downcasting 16-bit model-only saves.

Usage:
    python -m deepspeed_tpu.utils.zero_to_fp32 <checkpoint_dir> <output_file> [tag]
"""

import argparse
import os
import sys

import numpy as np
from flax import serialization


def get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag=None):
    """Load the model state dict from a checkpoint dir, cast to fp32."""
    if tag is None:
        latest = os.path.join(checkpoint_dir, "latest")
        if not os.path.exists(latest):
            raise FileNotFoundError(f"no 'latest' file in {checkpoint_dir}")
        with open(latest) as f:
            tag = f.read().strip()
    path = os.path.join(checkpoint_dir, str(tag), "mp_rank_00_model_states.msgpack")
    with open(path, "rb") as f:
        state = serialization.msgpack_restore(f.read())
    module = state["module"]

    def cast(x):
        arr = np.asarray(x)
        if arr.dtype.kind == "f" and arr.dtype != np.float32:
            return arr.astype(np.float32)
        return arr

    import jax

    return jax.tree.map(cast, module)


def convert_zero_checkpoint_to_fp32_state_dict(checkpoint_dir, output_file, tag=None):
    sd = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag)
    payload = serialization.msgpack_serialize(sd)
    with open(output_file, "wb") as f:
        f.write(payload)
    print(f"saved consolidated fp32 state dict to {output_file}")
    return sd


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("checkpoint_dir")
    parser.add_argument("output_file")
    parser.add_argument("tag", nargs="?", default=None)
    args = parser.parse_args(argv)
    convert_zero_checkpoint_to_fp32_state_dict(
        args.checkpoint_dir, args.output_file, args.tag
    )


if __name__ == "__main__":
    main(sys.argv[1:])

"""Shared silence-schedule health state machine.

Extracted from ``serving/fleet.py``'s ``FleetHealth`` so the serving
fleet and the training cluster health plane (``runtime/health.py``)
track liveness with ONE state machine instead of two divergent copies:
``healthy → suspect → down → recovering → healthy``, where any sign of
life is a heartbeat that moves the state left and silence degrades it
right on a configured schedule. A transport-level EOF (the unambiguous
death signal) skips the timers via ``mark_down``.

The schedule itself is policy-free about *what* a member is (a serving
replica, a training process) and *what happens* on a transition: callers
pass ``on_transition(member, frm, to, reason, probes)`` and publish
their own telemetry there — ``FleetHealth`` keeps its edge-only
``serve.replica_down``/``serve.replica_up`` events, the cluster plane
publishes ``health.peer_down``/``health.peer_up``. The callback runs
under the schedule's lock (exactly where ``FleetHealth._set`` published
before the extraction), so observers see transitions in order.

stdlib-only and jax-free, like everything the supervisors import.
"""

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

# Member health states (the full cycle: healthy -> suspect -> down ->
# recovering -> healthy; heartbeats move left, silence moves right)
HEALTHY = "healthy"
SUSPECT = "suspect"
DOWN = "down"
RECOVERING = "recovering"

# on_transition(member, from_state, to_state, reason, probes)
TransitionHook = Callable[[int, str, str, str, int], None]


@dataclass
class HealthConfig:
    suspect_after_s: float = 2.0   # silence before healthy -> suspect
    down_after_s: float = 6.0      # silence before (any live) -> down
    recover_probes: int = 2        # heartbeats to go recovering -> healthy

    def __post_init__(self):
        if not 0 < self.suspect_after_s < self.down_after_s:
            raise ValueError(
                "need 0 < suspect_after_s < down_after_s, got "
                f"{self.suspect_after_s} / {self.down_after_s}")
        if self.recover_probes < 1:
            raise ValueError(
                f"recover_probes must be >= 1, got {self.recover_probes}")


class SilenceSchedule:
    """Heartbeat-driven liveness for ``n`` members; see module docstring.

    ``heartbeat(i)`` on every sign of life from member ``i``; ``sweep()``
    whenever time should drive the degradations; ``mark_down(i)`` when
    the transport says so (EOF beats any timer). Thread-safe: callers
    pump heartbeats from receiver threads while supervisors and tests
    poke the schedule from others.
    """

    def __init__(self, n: int, config: Optional[HealthConfig] = None,
                 clock: Callable[[], float] = time.monotonic,
                 on_transition: Optional[TransitionHook] = None):
        if n < 1:
            raise ValueError(f"member count must be >= 1, got {n}")
        self.n = int(n)
        self.config = config or HealthConfig()
        self._clock = clock
        self._on_transition = on_transition
        self._lock = threading.Lock()
        now = self._clock()
        self._state = [HEALTHY] * self.n
        self._last_beat = [now] * self.n
        self._probes = [0] * self.n
        # (ts, member, from, to) — bounded by the number of real
        # transitions, which is tiny; tests and demos read it
        self.transitions: List[Tuple[float, int, str, str]] = []

    def _set(self, i: int, to: str, reason: str) -> None:
        """Caller holds the lock; fires the hook on every real edge."""
        frm = self._state[i]
        if frm == to:
            return
        self._state[i] = to
        self.transitions.append((self._clock(), i, frm, to))
        if self._on_transition is not None:
            self._on_transition(i, frm, to, reason, self._probes[i])

    def heartbeat(self, i: int) -> str:
        """Member ``i`` showed a sign of life; returns its new state."""
        with self._lock:
            self._last_beat[i] = self._clock()
            st = self._state[i]
            if st == DOWN:
                self._probes[i] = 1
                if self.config.recover_probes <= 1:
                    self._set(i, HEALTHY, "recovered")
                else:
                    self._set(i, RECOVERING, "heartbeat")
            elif st == RECOVERING:
                self._probes[i] += 1
                if self._probes[i] >= self.config.recover_probes:
                    self._set(i, HEALTHY, "recovered")
            elif st == SUSPECT:
                self._set(i, HEALTHY, "heartbeat")
            return self._state[i]

    def sweep(self) -> None:
        """Apply the silence schedule to every member."""
        with self._lock:
            now = self._clock()
            for i in range(self.n):
                st = self._state[i]
                if st == DOWN:
                    continue
                silence = now - self._last_beat[i]
                if silence >= self.config.down_after_s:
                    self._probes[i] = 0
                    self._set(i, DOWN, f"silent {silence:.1f}s")
                elif st == HEALTHY and \
                        silence >= self.config.suspect_after_s:
                    self._set(i, SUSPECT, "silence")

    def mark_down(self, i: int, reason: str = "reported") -> None:
        """Unambiguous death (pipe EOF, waitpid): skip the timers."""
        with self._lock:
            self._probes[i] = 0
            self._set(i, DOWN, reason)

    def state(self, i: int) -> str:
        with self._lock:
            return self._state[i]

    def states(self) -> Dict[int, str]:
        with self._lock:
            return {i: s for i, s in enumerate(self._state)}

    def live(self) -> List[bool]:
        """The routing/membership mask: everything except ``down`` is
        live — ``suspect`` may just be slow and ``recovering`` is on its
        way back."""
        with self._lock:
            return [s != DOWN for s in self._state]

    def n_live(self) -> int:
        return sum(self.live())

    def silence(self, i: int) -> float:
        """Seconds since member ``i`` last showed life (for telemetry)."""
        with self._lock:
            return self._clock() - self._last_beat[i]

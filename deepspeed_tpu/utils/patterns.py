"""Module/parameter name pattern matching shared by compression and MoQ
(one matcher so the same ``modules`` config selects the same params)."""

import fnmatch
from typing import List


def match_name(name: str, patterns: List[str]) -> bool:
    """fnmatch with substring fallback: 'attention' matches
    'layer0.attention.query.kernel'."""
    return any(
        fnmatch.fnmatch(name, pat) or fnmatch.fnmatch(name, f"*{pat}*")
        for pat in patterns)

"""Reusable fault-injection harness for chaos-testing the storage and
supervision paths (docs/recovery.md).

Checkpoint durability code is exactly the code that never runs in a happy
CI: torn writes, transient IO errors, and mid-step kills only happen on
real pods at the worst possible moment. These context managers make those
failures reproducible in unit tests:

* :func:`failing_writes` — write-mode ``open()`` on matching paths raises
  (transiently for the first N calls, or permanently);
* :func:`torn_writes` — ``os.replace`` truncates the source file first,
  simulating a torn write that still got renamed (filesystem corruption,
  power loss without fsync);
* :func:`truncate_file` — post-hoc corruption of a file on disk;
* :func:`kill_at_step` — deliver a signal to a supervised child when a
  step file it writes reaches a chosen step (preemption at step K);
* :func:`nan_at_step` / :func:`spike_at_step` / :func:`hang_at_step` —
  corrupt or stall an engine's input batches from a chosen step, the
  training-health faults (NaN loss, loss spike, wedged step) that drive
  the sentinel's detect→skip→rollback→diverge path end-to-end
  (docs/recovery.md "Divergence and hang recovery");
* :func:`stall_at_step` / :func:`bitflip_at_step` — whole-process wedge
  (SIGSTOP) and silent parameter corruption, the cluster-scale faults
  only the cross-host health plane can catch (docs/recovery.md
  "Cluster health & SDC defense").

Everything here is process-global monkeypatching of ``builtins.open`` /
``os.replace`` — test-only machinery, deliberately free of jax imports so
agent/supervisor tests stay light.
"""

import builtins
import os
import signal as signal_module
import threading
import time
from contextlib import contextmanager
from typing import Callable, Optional, Union

Matcher = Optional[Union[str, Callable[[str], bool]]]

_WRITE_MODE_CHARS = set("wxa+")


class Injector:
    """Handle yielded by the context managers: ``injected`` counts the
    faults actually delivered (assert on it to prove the fault fired)."""

    def __init__(self):
        self.injected = 0
        self._lock = threading.Lock()

    def _bump(self):
        with self._lock:
            self.injected += 1


def _to_matcher(match: Matcher) -> Callable[[str], bool]:
    if match is None:
        return lambda path: True
    if callable(match):
        return match
    return lambda path, needle=str(match): needle in path


def _path_str(file) -> str:
    try:
        return os.fspath(file) if not isinstance(file, int) else ""
    except TypeError:
        return ""


@contextmanager
def failing_writes(match: Matcher = None, fail_times: Optional[int] = None,
                   exc: Callable[[str], BaseException] = None):
    """Make write-mode ``open()`` calls on matching paths raise.

    ``fail_times=None`` fails permanently; ``fail_times=N`` fails the
    first N matching opens then lets writes through (a transient blip the
    retry loop should absorb). Read-mode opens are never touched.
    """
    injector = Injector()
    matcher = _to_matcher(match)
    make_exc = exc or (lambda p: OSError(f"injected write failure: {p}"))
    real_open = builtins.open

    def fake_open(file, mode="r", *args, **kwargs):
        path = _path_str(file)
        if (path and (_WRITE_MODE_CHARS & set(mode)) and matcher(path)
                and (fail_times is None or injector.injected < fail_times)):
            injector._bump()
            raise make_exc(path)
        return real_open(file, mode, *args, **kwargs)

    builtins.open = fake_open
    try:
        yield injector
    finally:
        builtins.open = real_open


@contextmanager
def torn_writes(match: Matcher = None, keep_fraction: float = 0.5,
                fail_times: Optional[int] = None):
    """Truncate the source file of matching ``os.replace`` calls before
    renaming — the rename lands but the content is torn, which is what a
    crash between write and fsync leaves behind on real filesystems."""
    injector = Injector()
    matcher = _to_matcher(match)
    real_replace = os.replace

    def fake_replace(src, dst, **kwargs):
        src_s, dst_s = _path_str(src), _path_str(dst)
        if ((matcher(dst_s) or matcher(src_s))
                and (fail_times is None or injector.injected < fail_times)):
            truncate_file(src_s, keep_fraction=keep_fraction)
            injector._bump()
        return real_replace(src, dst, **kwargs)

    os.replace = fake_replace
    try:
        yield injector
    finally:
        os.replace = real_replace


def truncate_file(path: str, keep_fraction: float = 0.5,
                  keep_bytes: Optional[int] = None) -> int:
    """Corrupt ``path`` in place by truncation (torn-write aftermath).
    Returns the new size."""
    size = os.path.getsize(path)
    keep = keep_bytes if keep_bytes is not None else int(size * keep_fraction)
    keep = max(0, min(keep, size))
    with open(path, "r+b") as f:
        f.truncate(keep)
    return keep


@contextmanager
def kill_at_step(proc, step_file: str, step: int,
                 sig: int = signal_module.SIGTERM, poll_s: float = 0.02,
                 timeout_s: float = 120.0):
    """Deliver ``sig`` to a supervised child once the step counter it
    writes to ``step_file`` reaches ``step`` (preemption at a chosen
    point). The child contract: overwrite ``step_file`` with its current
    integer step. Yields an Injector whose ``injected`` is 1 after the
    signal fired."""
    injector = Injector()
    stop = threading.Event()

    def watch():
        deadline = time.monotonic() + timeout_s
        while not stop.is_set() and time.monotonic() < deadline:
            if proc.poll() is not None:
                return  # child already exited
            try:
                with open(step_file) as f:
                    current = int(f.read().strip() or -1)
            except (OSError, ValueError):
                current = -1
            if current >= step:
                try:
                    proc.send_signal(sig)
                finally:
                    injector._bump()
                return
            time.sleep(poll_s)

    watcher = threading.Thread(target=watch, daemon=True)
    watcher.start()
    try:
        yield injector
    finally:
        stop.set()
        watcher.join(timeout=timeout_s)


# ---------------------------------------------------------------------------
# training-health faults (sentinel chaos; numpy-only, still jax-free)
# ---------------------------------------------------------------------------
def _map_float_leaves(batch, fn):
    """Apply ``fn`` to every floating-point array leaf of a batch pytree
    (dict / tuple / list / array), leaving integer leaves (token ids,
    masks) untouched."""
    import numpy as np

    if isinstance(batch, dict):
        return {k: _map_float_leaves(v, fn) for k, v in batch.items()}
    if isinstance(batch, (tuple, list)):
        return type(batch)(_map_float_leaves(v, fn) for v in batch)
    arr = np.asarray(batch)
    if np.issubdtype(arr.dtype, np.floating):
        return fn(arr)
    return batch


@contextmanager
def _batch_fault(engine, step: int, times: Optional[int],
                 apply: Callable):
    """Wrap ``engine._put_batch`` so ``apply(batch)`` fires on batches
    dispatched at ``engine.global_steps >= step``, at most ``times``
    times (None = every matching batch). Count-limiting is what lets a
    run RECOVER after the sentinel rolls back — the fault stops firing
    and training continues clean."""
    injector = Injector()
    real_put = engine._put_batch  # bound method (class attr lookup)

    def faulty_put(batch):
        if engine.global_steps >= step and (
                times is None or injector.injected < times):
            injector._bump()
            batch = apply(batch)
        return real_put(batch)

    engine._put_batch = faulty_put  # instance attr shadows the method
    try:
        yield injector
    finally:
        engine.__dict__.pop("_put_batch", None)


@contextmanager
def nan_at_step(engine, step: int, times: Optional[int] = 1):
    """Poison the float leaves of input batches with NaN from global step
    ``step`` on (at most ``times`` batches) — the bf16 divergence that
    the fp16 loss-scale path never sees."""
    import numpy as np

    def poison(batch):
        return _map_float_leaves(batch, lambda a: np.full_like(a, np.nan))

    with _batch_fault(engine, step, times, poison) as injector:
        yield injector


@contextmanager
def spike_at_step(engine, step: int, scale: float = 1e3,
                  times: Optional[int] = 1):
    """Scale the float leaves of input batches by ``scale`` from global
    step ``step`` on — a finite loss spike (bad shard, corrupt record)
    that the non-finite check cannot catch but the window should."""
    def amplify(batch):
        return _map_float_leaves(batch, lambda a: a * scale)

    with _batch_fault(engine, step, times, amplify) as injector:
        yield injector


@contextmanager
def hang_at_step(engine, step: int, seconds: float,
                 times: Optional[int] = 1):
    """Stall batch dispatch for ``seconds`` at global step ``step`` — a
    fake wedged step (hung collective / dead host transfer) for the hang
    watchdog to catch."""
    def stall(batch):
        time.sleep(seconds)
        return batch

    with _batch_fault(engine, step, times, stall) as injector:
        yield injector


@contextmanager
def stall_at_step(engine, step: int, sleep_s: Optional[float] = None,
                  times: Optional[int] = 1):
    """Wedge THIS WHOLE PROCESS at global step ``step`` — the cluster
    health plane's target fault (docs/recovery.md "Cluster health & SDC
    defense"), as opposed to :func:`hang_at_step` which stalls only the
    batch path and leaves daemon threads (and the process) responsive.

    ``sleep_s=None`` delivers ``SIGSTOP`` to the process itself: every
    thread — including the health plane's heartbeat sender — freezes,
    which is what a kernel-level wedge or a stopped VM looks like to
    peers, and only SIGCONT/SIGKILL from outside can end it. A float
    ``sleep_s`` sleeps inside batch dispatch instead (a bounded stall
    the process recovers from by itself; useful where SIGSTOP would
    wedge the TEST harness too)."""
    def wedge(batch):
        if sleep_s is None:
            os.kill(os.getpid(), signal_module.SIGSTOP)
        else:
            time.sleep(sleep_s)
        return batch

    with _batch_fault(engine, step, times, wedge) as injector:
        yield injector


@contextmanager
def bitflip_at_step(engine, step: int, leaf: Optional[str] = None,
                    bit: int = 1, times: Optional[int] = 1):
    """Flip one mantissa bit of one element in a parameter leaf of
    ``engine._params`` at global step ``step`` — a silent data
    corruption (SDC): the run keeps training on a wrong weight with no
    NaN, no crash, nothing for the sentinel to see. Only the health
    plane's cross-host parameter-digest probe can catch it, which is
    exactly what this injector exists to prove.

    ``leaf`` selects the target by path substring (e.g. ``"dense/w"``);
    None takes the first floating-point leaf. ``bit`` is the bit index
    to XOR in element 0 — low mantissa bits make the corruption
    numerically tiny, maximally silent. The flip is applied to every
    addressable shard of the leaf so a replicated array stays
    self-consistent WITHIN the process (the digest divergence is
    between processes: only this one flips).

    Unlike the batch faults above this imports jax; keep it out of
    agent-side tests."""
    import jax
    import numpy as np

    def flip(batch):
        flat, treedef = jax.tree_util.tree_flatten_with_path(engine._params)
        target = None
        for path, arr in flat:
            if not hasattr(arr, "dtype") or not (
                    np.issubdtype(arr.dtype, np.floating)
                    or arr.dtype.name == "bfloat16"):
                continue
            name = jax.tree_util.keystr(path)
            if leaf is None or leaf in name:
                target = (path, name, arr)
                break
        if target is None:
            raise ValueError(f"bitflip_at_step: no float leaf matching "
                             f"{leaf!r} in engine._params")
        path, name, arr = target
        uint_dtype = np.dtype(f"uint{arr.dtype.itemsize * 8}")
        bufs = []
        for sh in arr.addressable_shards:
            data = np.array(sh.data)  # owned, writable copy
            view = data.reshape(-1).view(uint_dtype)
            view[0] ^= np.asarray(1 << bit, dtype=uint_dtype)
            bufs.append(jax.device_put(data, sh.device))
        flipped = jax.make_array_from_single_device_arrays(
            arr.shape, arr.sharding, bufs)
        leaves = [flipped if p is path else a for p, a in flat]
        engine._params = jax.tree_util.tree_unflatten(treedef, leaves)
        return batch

    with _batch_fault(engine, step, times, flip) as injector:
        yield injector

"""Version shims for the jax API surface this repo targets.

The codebase is written against the modern ``jax.shard_map`` entry point
(keyword ``check_vma``). Older jax releases only ship
``jax.experimental.shard_map.shard_map`` and spell the keyword
``check_rep``. Importing this module (``deepspeed_tpu/__init__`` does it
before anything else) installs a translating alias on the ``jax`` module
so every call site — library, tests, benchmarks, and user code doing
``from jax import shard_map`` — keeps the one modern spelling.
"""

import functools
import inspect

import jax

if not hasattr(jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _shard_map

    if "check_vma" in inspect.signature(_shard_map).parameters:
        jax.shard_map = _shard_map
    else:

        @functools.wraps(_shard_map)
        def _compat_shard_map(f, *args, check_vma=None, **kwargs):
            if check_vma is not None:
                kwargs.setdefault("check_rep", check_vma)
            return _shard_map(f, *args, **kwargs)

        jax.shard_map = _compat_shard_map

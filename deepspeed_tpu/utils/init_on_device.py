"""Meta-device model construction (reference ``utils/init_on_device.py:10``
OnDevice: build huge models without allocating real weights).

JAX already separates trace from allocation, so "meta init" is
``jax.eval_shape`` over ``model.init`` — exact shapes/dtypes, zero bytes.
``OnDevice(dtype=..., device="meta")`` keeps the reference's context-manager
spelling; ``materialize`` turns the abstract tree into real (optionally
sharded) arrays, which is where a ZeRO-3 build hands each leaf its
partition spec instead of ever holding the full model.
"""

from typing import Any, Optional

import jax
import jax.numpy as jnp


class OnDevice:
    """Context manager + helpers for abstract-then-materialize init.

    The ``with`` form mirrors the reference's spelling; all behavior is
    explicit through ``ctx.init`` / ``materialize`` (nothing is globally
    intercepted — JAX needs no monkey-patching to defer allocation)."""

    def __init__(self, dtype=jnp.float32, device: str = "meta",
                 enabled: bool = True):
        self.dtype = dtype
        self.device = device
        self.enabled = enabled

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    # ------------------------------------------------------------------
    def init(self, model, rngs, *args, **kwargs):
        """model.init that never allocates: returns a ShapeDtypeStruct
        pytree when device == 'meta', real arrays otherwise."""
        if self.enabled and self.device == "meta":
            out = jax.eval_shape(lambda r: model.init(r, *args, **kwargs),
                                 rngs)
            return jax.tree.map(
                lambda s: jax.ShapeDtypeStruct(
                    s.shape,
                    self.dtype if jnp.issubdtype(s.dtype, jnp.floating)
                    else s.dtype),
                out)
        return model.init(rngs, *args, **kwargs)

    @staticmethod
    def materialize(abstract_tree, init_fn=None, rng=None,
                    shardings=None):
        """Turn a meta tree into real arrays. ``init_fn(key, shape, dtype)``
        defaults to zeros; with ``shardings`` each leaf is created directly
        with its target sharding (the zero.Init pattern: nothing is ever
        allocated unsharded)."""
        leaves, treedef = jax.tree.flatten(abstract_tree)
        if init_fn is None:
            def init_fn(key, shape, dtype):
                return jnp.zeros(shape, dtype)
        keys = (jax.random.split(rng, len(leaves)) if rng is not None
                else [None] * len(leaves))

        shard_leaves = (jax.tree.leaves(shardings)
                        if shardings is not None else [None] * len(leaves))
        out = []
        for leaf, key, shard in zip(leaves, keys, shard_leaves):
            make = lambda: init_fn(key, leaf.shape, leaf.dtype)  # noqa: E731
            if shard is not None:
                arr = jax.jit(make, out_shardings=shard)()
            else:
                arr = make()
            out.append(arr)
        return jax.tree.unflatten(treedef, out)


def param_count(abstract_tree) -> int:
    import numpy as np

    return int(sum(np.prod(l.shape) for l in jax.tree.leaves(abstract_tree)))

"""Rank-aware logging utilities.

Capability parity with the reference's ``deepspeed/utils/logging.py`` (log_dist,
rank-filtered logger) re-expressed for JAX: "rank" is ``jax.process_index()``.
"""

import functools
import logging
import os
import sys

LOG_LEVEL = os.environ.get("DEEPSPEED_TPU_LOG_LEVEL", "INFO").upper()

_FORMAT = "[%(asctime)s] [%(levelname)s] [%(name)s:%(lineno)d] %(message)s"


@functools.lru_cache(None)
def _create_logger(name: str, level: str) -> logging.Logger:
    logger = logging.getLogger(name)
    logger.setLevel(level)
    logger.propagate = False
    handler = logging.StreamHandler(stream=sys.stdout)
    handler.setFormatter(logging.Formatter(_FORMAT))
    logger.addHandler(handler)
    return logger


logger = _create_logger("deepspeed_tpu", LOG_LEVEL)


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:  # pragma: no cover - jax not initialised yet
        return 0


def should_log_on_rank(ranks=None) -> bool:
    """True when the current process should emit a log line.

    Mirrors reference ``deepspeed/utils/logging.py`` log_dist rank filtering:
    ``ranks=None`` or ``[-1]`` means all ranks; otherwise only listed ranks log.
    """
    if ranks is None:
        ranks = [0]
    my_rank = _process_index()
    return -1 in ranks or my_rank in ranks


def log_dist(message: str, ranks=None, level=logging.INFO) -> None:
    if should_log_on_rank(ranks):
        logger.log(level, "[Rank %s] %s", _process_index(), message)


def warning_once(message: str) -> None:
    _warn_once_cached(message)


@functools.lru_cache(None)
def _warn_once_cached(message: str) -> None:
    logger.warning(message)

"""Step-level performance tracer.

Where the flops profiler answers "what does the model cost?" (per-module
HLO accounting), this module answers "where does a training step's time
and wire traffic actually go?" — the attribution layer ROADMAP items 3
(MFU plateau) and 4 (quantized collectives) both stall without.

Three jobs, all config-gated behind the ``step_profiler`` block:

1. **Analytic MFU** — FLOPs / bytes-accessed come from the compiled
   step's XLA cost analysis (``flops_profiler.cost_analysis``, i.e. the
   post-partition per-device module), not hand-derived ``6N`` counts.
   Achieved TFLOPS over the fenced mean step time is divided by a
   hardware-peak table keyed on ``jax.devices()[0].device_kind``.
2. **Phase attribution** — per-step wall time is split into named phases
   (``dataloader``, ``h2d``, ``compiled_step``, ``sentinel``,
   ``checkpoint``, ...) via the existing ``SynchronizedWallClockTimer``.
   Each phase stop drains the device queue (``utils.timer.fence``) so
   device work is charged to the phase that dispatched it; the residual
   between the phase sum and the fenced step envelope is reported as
   ``other``, so phases always sum to the step wall time. Every fence
   is gated on the profiling window: with the profiler disabled (or
   outside ``[start_step, start_step + num_steps)``) ``phase()`` returns
   a shared no-op context manager and the healthy path gains **zero**
   device syncs — the invariant the sentinel work established and the
   r3 regression taught us to guard.
3. **Trace export** — the same phase spans are emitted as Chrome
   trace-event JSON (``ph: "X"`` complete events, microsecond ts/dur)
   loadable in perfetto / ``chrome://tracing``, with optional
   ``jax.profiler`` trace capture over the same window for op-level
   drill-down.

Cumulative ``Perf/*`` (and the comm logger's ``Comm/*``) counters are
pushed through ``MonitorMaster`` when the window closes.
"""

import contextlib
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional

from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer, fence

# ---------------------------------------------------------------------------
# Hardware peak table
# ---------------------------------------------------------------------------

# Dense (non-sparse) bf16 peak TFLOPS per jax device, keyed by substrings of
# ``device_kind`` (first match wins — order newest/most-specific first).
# Sources: Google TPU system architecture pages; v2/v3 are per-core because a
# jax device is one core there, v4+ are per-chip. The CPU entry is a nominal
# documented figure for the 8-virtual-device test mesh: MFU numbers on CPU
# are for plumbing tests, not performance claims.
HW_PEAK_BF16_TFLOPS = (
    ("v6e", 918.0),
    ("v6 lite", 918.0),
    ("v5p", 459.0),
    ("v5e", 197.0),
    ("v5 lite", 197.0),
    ("v5", 459.0),
    ("v4", 275.0),
    ("v3", 61.5),
    ("v2", 22.5),
    ("cpu", 0.5),
)


def peak_tflops(device=None, override: Optional[float] = None):
    """``(peak_bf16_tflops, source)`` for ``device`` (default: devices()[0]).

    ``override`` (the config's ``peak_tflops``) wins over the table; an
    unrecognised device kind falls back to the v5e figure so MFU is still
    emitted (flagged via the source string) rather than crashing the run.
    """
    if override:
        return float(override), "config override"
    kind = ""
    try:
        if device is None:
            import jax

            device = jax.devices()[0]
        kind = str(getattr(device, "device_kind", device)).lower()
    except Exception:  # pragma: no cover - backend-less host
        return 197.0, "unknown device (v5e default)"
    for sub, peak in HW_PEAK_BF16_TFLOPS:
        if sub in kind:
            return peak, f"device_kind={kind!r}"
    return 197.0, f"unrecognised device_kind={kind!r} (v5e default)"


# Reusable no-op context manager returned on every non-profiled step:
# nullcontext carries no per-enter state, so one shared instance keeps the
# disabled path at a single attribute check + dict-free ``with``.
_NULL_CTX = contextlib.nullcontext()

_TIMER_PREFIX = "step_profiler/"


class StepProfiler:
    """Config-gated step tracer (see module docstring).

    Engine protocol::

        prof.begin_step(global_step)     # fenced anchor, idempotent
        with prof.phase("h2d"): ...      # fenced stop charges device work
        with prof.phase("compiled_step"): ...
        prof.record_cost("train_step", jitted_fn, args)   # once per key
        prof.end_step(global_step)       # fenced envelope; residual→other

    ``end_step`` on the window's last step (or an explicit ``finalize()``)
    writes the trace artifact and pushes ``Perf/*`` / ``Comm/*`` counters
    through the monitor.
    """

    def __init__(self, config, timers: Optional[SynchronizedWallClockTimer] = None,
                 monitor=None):
        self.cfg = config
        self.enabled = bool(config.enabled)
        self.timers = timers if timers is not None else SynchronizedWallClockTimer()
        self.monitor = monitor
        self.window = range(config.start_step,
                            config.start_step + config.num_steps)
        self.records: List[Dict[str, Any]] = []
        self._costs: Dict[str, Dict[str, float]] = {}
        self._events: List[Dict[str, Any]] = []
        self._window_active = False
        self._in_step = False
        self._finalized = False
        self._t_base = 0.0
        self._step_t0 = 0.0
        self._step_idx = -1
        self._phase_acc: Dict[str, float] = {}
        self._jax_trace_on = False
        self._pid = 0
        # subsystem gauges merged into perf_counters (the engine feeds
        # the data-pipeline prefetch queue-depth/starvation stats here)
        self.aux_counters: Dict[str, float] = {}
        # HBM accounting (docs/observability.md "Memory accounting"):
        # compiled-step memory_analysis captured once per window, plus
        # live allocator watermarks maxed over the windowed steps
        self._memory: Optional[Dict[str, float]] = None
        self._live_mem_peak: Dict[str, float] = {}

    def set_memory(self, mem: Optional[Dict[str, float]]) -> None:
        """Record the compiled-step memory breakdown (once; later calls
        with None or after a first set are ignored)."""
        if mem and self._memory is None:
            self._memory = {str(k): float(v) for k, v in mem.items()}

    def has_memory(self) -> bool:
        return self._memory is not None

    def set_aux_counters(self, counters: Dict[str, float]) -> None:
        """Attach external gauges to the ``Perf/*`` export. Last write
        wins per key; cheap enough to call every step."""
        self.aux_counters.update(
            {str(k): float(v) for k, v in counters.items()})

    # -- gating ------------------------------------------------------------
    def active_for(self, step: int) -> bool:
        return (self.enabled and not self._finalized and step in self.window)

    def _fence(self):
        try:
            fence()
        except Exception:  # pragma: no cover - device-less host
            pass

    # -- step envelope -----------------------------------------------------
    def begin_step(self, step: int) -> None:
        if self._in_step or not self.active_for(step):
            return
        if not self._window_active:
            self._window_active = True
            try:
                import jax

                self._pid = jax.process_index()
            except Exception:  # pragma: no cover
                self._pid = 0
            # first fence compiles the drain program — pay that before the
            # first timed anchor, never inside a measured span
            self._fence()
            self._t_base = time.perf_counter()
            self._maybe_start_jax_trace()
        self._fence()
        self._step_t0 = time.perf_counter()
        self._step_idx = step
        self._phase_acc = {}
        self._in_step = True

    def phase(self, name: str):
        """Context manager attributing its span (host + device work it
        dispatched) to ``name``. A strict no-op outside the window."""
        if not self._window_active or self._finalized:
            return _NULL_CTX
        return self._phase_ctx(name)

    @contextlib.contextmanager
    def _phase_ctx(self, name: str):
        timer = self.timers(_TIMER_PREFIX + name)
        t0 = time.perf_counter()
        if not timer.started_:
            timer.start(sync=False)  # previous fenced stop already drained
            own = True
        else:  # pragma: no cover - re-entrant phase; count outer span only
            own = False
        try:
            yield
        finally:
            self._fence()  # charge dispatched device work to this phase
            t1 = time.perf_counter()
            if own:
                timer.stop(sync=False)
            if self._in_step:
                self._phase_acc[name] = self._phase_acc.get(name, 0.0) + (t1 - t0)
            self._emit_event(name, t0, t1, cat="phase")

    def end_step(self, step: Optional[int] = None, comm_counters=None,
                 cost_cb: Optional[Callable[[], Optional[Dict]]] = None,
                 mem_cb: Optional[Callable[[], Optional[Dict]]] = None,
                 live_mem_cb: Optional[Callable[[], Optional[Dict]]] = None
                 ) -> None:
        if not self._in_step:
            return
        self._fence()
        t1 = time.perf_counter()
        total = t1 - self._step_t0
        measured = sum(self._phase_acc.values())
        other = max(0.0, total - measured)
        rec = {
            "step": self._step_idx,
            "total_s": total,
            "phases_s": dict(self._phase_acc),
            "other_s": other,
        }
        self.records.append(rec)
        self._emit_event(f"step {self._step_idx}", self._step_t0, t1,
                         cat="step", args={"phases_ms": {
                             k: round(v * 1e3, 3)
                             for k, v in self._phase_acc.items()}})
        self._in_step = False
        # compiled-step cost, once per window — AFTER the envelope closed:
        # cost extraction re-lowers the step (a compile) and must never be
        # charged to a measured span
        if cost_cb is not None and "optimizer_step" not in self._costs:
            try:
                cost = cost_cb()
            except Exception as e:  # pragma: no cover
                logger.warning(f"step_profiler: cost callback failed: {e}")
                cost = None
            if cost:
                self.set_cost("optimizer_step", cost)
        # compiled-step memory, once per window — same placement as the
        # cost callback: the lowering is a compile-cache hit but still
        # host work that must not land inside a measured span
        if mem_cb is not None and self._memory is None:
            try:
                self.set_memory(mem_cb())
            except Exception as e:  # pragma: no cover
                logger.warning(
                    f"step_profiler: memory callback failed: {e}")
        # live allocator watermarks: a host-local PJRT query (no sync),
        # sampled inside the already-fenced window and maxed over steps
        if live_mem_cb is not None:
            try:
                stats = live_mem_cb()
            except Exception:  # pragma: no cover
                stats = None
            if stats:
                for k, v in stats.items():
                    self._live_mem_peak[k] = max(
                        self._live_mem_peak.get(k, 0.0), float(v))
        if self._step_idx >= self.window.stop - 1:
            self.finalize(comm_counters=comm_counters)

    # -- compiled-step cost -------------------------------------------------
    def record_cost(self, key: str, fn: Callable, args, mult: int = 1) -> None:
        """Record XLA cost analysis of ``fn(*args)`` once per ``key``.

        ``mult`` scales the contribution into the per-step total (e.g. the
        fwd/bwd program runs ``gradient_accumulation_steps`` times per
        optimizer step). Cheap after the first call: a dict lookup.
        """
        if key in self._costs or not self._window_active or self._finalized:
            return
        try:
            from deepspeed_tpu.profiling.flops_profiler.profiler import (
                cost_analysis)

            cost = cost_analysis(fn, *args)
        except Exception as e:  # pragma: no cover - backend w/o cost model
            logger.warning(f"step_profiler: cost analysis for {key!r} "
                           f"unavailable: {e}")
            cost = {"flops": 0.0, "bytes_accessed": 0.0, "optimal_seconds": 0.0}
        cost["mult"] = mult
        self._costs[key] = cost

    def set_cost(self, key: str, cost: Dict[str, float], mult: int = 1) -> None:
        """Record a pre-computed cost dict (``{"flops", "bytes_accessed"}``)."""
        c = dict(cost)
        c.setdefault("flops", 0.0)
        c.setdefault("bytes_accessed", 0.0)
        c["mult"] = mult
        self._costs[key] = c

    def has_cost(self, key: str) -> bool:
        return key in self._costs

    @property
    def flops_per_step(self) -> float:
        """Per-device FLOPs per optimizer step (post-partition module)."""
        return sum(c["flops"] * c["mult"] for c in self._costs.values())

    @property
    def bytes_per_step(self) -> float:
        return sum(c["bytes_accessed"] * c["mult"] for c in self._costs.values())

    # -- results -----------------------------------------------------------
    def summary(self) -> Dict[str, Any]:
        n = len(self.records)
        if not n:
            return {"steps_profiled": 0}
        totals = [r["total_s"] for r in self.records]
        mean_s = sum(totals) / n
        phases: Dict[str, float] = {}
        for r in self.records:
            for k, v in r["phases_s"].items():
                phases[k] = phases.get(k, 0.0) + v
            phases["other"] = phases.get("other", 0.0) + r["other_s"]
        phases_ms = {k: v / n * 1e3 for k, v in phases.items()}
        covered = sum(v for k, v in phases.items() if k != "other")
        peak, peak_src = peak_tflops(override=self.cfg.peak_tflops)
        tflops = (self.flops_per_step / mean_s / 1e12) if mean_s > 0 else 0.0
        out = {
            "steps_profiled": n,
            "window": [self.window.start, self.window.stop],
            "step_time_ms": {"mean": mean_s * 1e3,
                             "min": min(totals) * 1e3,
                             "max": max(totals) * 1e3},
            "phases_ms": phases_ms,
            # fraction of the fenced step envelope explained by named
            # phases (the acceptance bar: >= 0.95 i.e. within 5%)
            "phase_coverage": covered / sum(totals) if sum(totals) else 0.0,
            "flops_per_step": self.flops_per_step,
            "bytes_accessed_per_step": self.bytes_per_step,
            "analytic_tflops": tflops,
            "peak_tflops": peak,
            "peak_source": peak_src,
            "analytic_mfu": tflops / peak if peak else 0.0,
            "hbm_gb_per_s": (self.bytes_per_step / mean_s / 1e9)
            if mean_s > 0 else 0.0,
            "costs": {k: dict(v) for k, v in self._costs.items()},
        }
        if self._memory is not None:
            out["memory"] = dict(self._memory)
        if self._live_mem_peak:
            out["live_memory_peak"] = dict(self._live_mem_peak)
        return out

    def mem_counters(self) -> Dict[str, float]:
        """Flat ``Mem/*`` counters: the compiled-step breakdown plus
        ``live_``-prefixed allocator watermarks (empty on backends
        without either source — CPU with no mem_cb set)."""
        out = {k: float(v) for k, v in (self._memory or {}).items()}
        for k, v in self._live_mem_peak.items():
            out[f"live_{k}"] = float(v)
        return out

    def perf_counters(self) -> Dict[str, float]:
        """Flat numeric counters for ``Monitor`` export (``Perf/<name>``)."""
        s = self.summary()
        if not s.get("steps_profiled"):
            return {}
        out = {
            "steps_profiled": float(s["steps_profiled"]),
            "step_ms_mean": s["step_time_ms"]["mean"],
            "phase_coverage": s["phase_coverage"],
            "flops_per_step": s["flops_per_step"],
            "bytes_accessed_per_step": s["bytes_accessed_per_step"],
            "analytic_tflops": s["analytic_tflops"],
            "analytic_mfu": s["analytic_mfu"],
            "hbm_gb_per_s": s["hbm_gb_per_s"],
        }
        for k, v in s["phases_ms"].items():
            out[f"phase_{k}_ms"] = v
        out.update(self.aux_counters)
        return out

    # -- trace export ------------------------------------------------------
    def _emit_event(self, name: str, t0: float, t1: float, cat: str = "phase",
                    args: Optional[Dict] = None) -> None:
        ev = {
            "name": name,
            "cat": cat,
            "ph": "X",
            "ts": (t0 - self._t_base) * 1e6,
            "dur": (t1 - t0) * 1e6,
            "pid": self._pid,
            "tid": 1 if cat == "step" else 0,
        }
        if args:
            ev["args"] = args
        self._events.append(ev)

    def trace_events(self) -> Dict[str, Any]:
        meta = [
            {"name": "process_name", "ph": "M", "pid": self._pid, "tid": 0,
             "args": {"name": "deepspeed_tpu step profiler"}},
            {"name": "thread_name", "ph": "M", "pid": self._pid, "tid": 0,
             "args": {"name": "phases"}},
            {"name": "thread_name", "ph": "M", "pid": self._pid, "tid": 1,
             "args": {"name": "steps"}},
        ]
        return {"traceEvents": meta + list(self._events),
                "displayTimeUnit": "ms"}

    def export_trace(self, path: Optional[str] = None) -> Optional[str]:
        path = path or self.cfg.trace_path
        if not path:
            return None
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.trace_events(), f)
        os.replace(tmp, path)
        return path

    # -- jax.profiler passthrough -----------------------------------------
    def _maybe_start_jax_trace(self) -> None:
        if not (self.cfg.jax_trace and self.cfg.jax_trace_dir):
            return
        try:
            import jax

            jax.profiler.start_trace(self.cfg.jax_trace_dir)
            self._jax_trace_on = True
        except Exception as e:  # pragma: no cover
            logger.warning(f"step_profiler: jax trace unavailable: {e}")

    def _stop_jax_trace(self) -> None:
        if not self._jax_trace_on:
            return
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:  # pragma: no cover
            pass
        self._jax_trace_on = False

    # -- window close ------------------------------------------------------
    def finalize(self, comm_counters=None) -> Optional[Dict[str, Any]]:
        """Close the window: stop traces, write artifacts, export counters.

        Idempotent; safe to call even if the run ended mid-window."""
        if self._finalized:
            return None
        if self._in_step:  # run ended inside a step — close the envelope
            self.end_step()
            if self._finalized:  # end_step on last window step recursed here
                return None
        if callable(comm_counters):
            try:
                comm_counters = comm_counters()
            except Exception:  # pragma: no cover
                comm_counters = None
        self._finalized = True
        self._stop_jax_trace()
        summary = self.summary()
        path = None
        try:
            import jax

            rank0 = jax.process_index() == 0
        except Exception:  # pragma: no cover
            rank0 = True
        if rank0:
            path = self.export_trace()
        if self.monitor is not None and getattr(self.monitor, "enabled", False) \
                and self.cfg.emit_counters:
            from deepspeed_tpu.monitor.monitor import counter_events

            step = self.records[-1]["step"] if self.records else 0
            events = counter_events("Perf", self.perf_counters(), step)
            if comm_counters:
                events += counter_events("Comm", comm_counters, step)
            mem = self.mem_counters()
            if mem:
                events += counter_events("Mem", mem, step)
            if events:
                self.monitor.write_events(events)
        if summary.get("steps_profiled"):
            log_dist(
                "step_profiler: {n} steps, mean {ms:.1f} ms, coverage "
                "{cov:.1%}, analytic {tf:.2f} TFLOPS ({mfu:.1%} MFU vs "
                "{peak:g} peak, {src})".format(
                    n=summary["steps_profiled"],
                    ms=summary["step_time_ms"]["mean"],
                    cov=summary["phase_coverage"],
                    tf=summary["analytic_tflops"],
                    mfu=summary["analytic_mfu"],
                    peak=summary["peak_tflops"],
                    src=summary["peak_source"]) +
                (f", trace → {path}" if path else ""),
                ranks=[0])
        return summary

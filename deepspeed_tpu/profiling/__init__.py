from deepspeed_tpu.profiling import flops_profiler  # noqa: F401
from deepspeed_tpu.profiling.step_profiler import (  # noqa: F401
    StepProfiler,
    peak_tflops,
)

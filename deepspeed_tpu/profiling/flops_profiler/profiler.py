"""FLOPS profiler via XLA HLO cost analysis.

Reference ``profiling/flops_profiler/profiler.py:17`` monkey-patches
``torch.nn.functional`` and tensor methods to COUNT MACs per module
(:788-830) and uses module hooks for latency. On TPU the compiler already
knows: ``jit(f).lower(...).compile().cost_analysis()`` returns exact HLO
flops and bytes for the whole fused program — more accurate than
patch-counting (it sees XLA fusions, remat recompute, and collective
traffic). Latency comes from timed, ``block_until_ready``-fenced replays.

``get_model_profile`` is the reference's public entry (same name); the
``FlopsProfiler`` class profiles any jitted callable and pretty-prints a
summary with achieved TFLOPS vs the step wall clock.
"""

import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

from deepspeed_tpu.utils.logging import logger


def _num(x) -> float:
    try:
        return float(x)
    except (TypeError, ValueError):
        return 0.0


def params_count(params) -> int:
    return int(sum(np.prod(np.shape(p))
                   for p in jax.tree.leaves(params)))


def cost_analysis(fn: Callable, *args, **kwargs) -> Dict[str, float]:
    """Compile ``fn`` for the given args and return HLO cost metrics:
    flops, bytes accessed, and the compiler's optimal-seconds estimate."""
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    compiled = jitted.lower(*args, **kwargs).compile()
    ca = compiled.cost_analysis() or {}
    if isinstance(ca, (list, tuple)):  # older jax: one dict per computation
        ca = ca[0] if ca else {}
    return {
        "flops": _num(ca.get("flops", 0)),
        "bytes_accessed": _num(ca.get("bytes accessed", 0)),
        "optimal_seconds": _num(ca.get("optimal_seconds", 0)),
    }


def measure_latency(fn: Callable, *args, warmup: int = 1, iters: int = 5,
                    **kwargs) -> float:
    """Median wall-clock seconds of a device-fenced call."""
    for _ in range(warmup):
        jax.block_until_ready(fn(*args, **kwargs))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args, **kwargs))
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def number_to_string(num: float, units: Optional[str] = None,
                     precision: int = 2) -> str:
    """Human-readable magnitudes (reference profiler's flops_to_string
    family, one generic implementation)."""
    scale = {"T": 1e12, "G": 1e9, "M": 1e6, "K": 1e3, "": 1.0}
    if units is None:
        for units, s in scale.items():
            if abs(num) >= s and s > 1:
                break
        else:
            units = ""
    return f"{num / scale[units]:.{precision}f} {units}"


flops_to_string = number_to_string
params_to_string = number_to_string
macs_to_string = number_to_string


class FlopsProfiler:
    """Profile a jitted step function (reference FlopsProfiler, but the
    "model" is a function + example args, the JAX unit of execution)."""

    def __init__(self, fn: Callable = None, ds_config=None):
        self.fn = fn
        self.config = getattr(ds_config, "flops_profiler", None)
        self.profile: Dict[str, Any] = {}

    def profile_fn(self, *args, measure_time: bool = True,
                   params=None, **kwargs) -> Dict[str, Any]:
        costs = cost_analysis(self.fn, *args, **kwargs)
        prof = dict(costs)
        prof["params"] = params_count(params) if params is not None else None
        if measure_time:
            latency = measure_latency(self.fn, *args, **kwargs)
            prof["latency_s"] = latency
            prof["achieved_tflops"] = (
                costs["flops"] / latency / 1e12 if latency > 0 else 0.0)
            prof["achieved_gbps"] = (
                costs["bytes_accessed"] / latency / 1e9 if latency > 0
                else 0.0)
        self.profile = prof
        return prof

    def print_profile(self) -> str:
        p = self.profile
        lines = ["-" * 60, "deepspeed_tpu flops profiler (HLO cost analysis)"]
        if p.get("params") is not None:
            lines.append(f"params:            "
                         f"{number_to_string(p['params'])}")
        lines.append(f"flops per call:    "
                     f"{number_to_string(p.get('flops', 0))}FLOPs")
        lines.append(f"bytes accessed:    "
                     f"{number_to_string(p.get('bytes_accessed', 0))}B")
        if "latency_s" in p:
            lines.append(f"latency:           {p['latency_s'] * 1e3:.2f} ms")
            lines.append(f"achieved:          "
                         f"{p['achieved_tflops']:.2f} TFLOPS, "
                         f"{p['achieved_gbps']:.1f} GB/s")
        lines.append("-" * 60)
        out = "\n".join(lines)
        logger.info("\n" + out)
        return out


def get_model_profile(model, args=None, kwargs=None, print_profile=True,
                      as_string: bool = False,
                      **_ignored) -> Tuple[Any, Any, Any]:
    """Reference public API (``get_model_profile``): returns
    (flops, macs, params) of one forward call.

    ``model`` is a callable (e.g. ``lambda x: module.apply(vars, x)``) or
    a flax ``nn.Module`` — modules additionally get the per-module tree
    breakdown (``profile_model_tree``), like the reference's printed
    profile. MACs are reported as flops/2 (HLO counts multiply-adds as 2).
    """
    args = args or ()
    kwargs = kwargs or {}
    import flax.linen as nn

    if isinstance(model, nn.Module):
        rows, total = profile_model_tree(
            model, *args, print_profile=print_profile, model_kwargs=kwargs)
        flops, macs, params = total["flops"], total["macs"], total["params"]
        if as_string:
            return (number_to_string(flops) + "FLOPs",
                    number_to_string(macs) + "MACs",
                    number_to_string(params))
        return flops, macs, params
    prof = FlopsProfiler(model)
    result = prof.profile_fn(*args, measure_time=False, **kwargs)
    if print_profile:
        prof.print_profile()
    flops = result["flops"]
    macs = flops / 2
    params = result["params"]
    if as_string:
        return (number_to_string(flops) + "FLOPs",
                number_to_string(macs) + "MACs",
                number_to_string(params or 0))
    return flops, macs, params


# ---------------------------------------------------------------------------
# per-module tree (reference profiler.py:235 print_model_profile / :788-830
# per-module MAC counting — here each submodule's cost comes from compiling
# it in isolation at the exact avals it saw inside the full forward)
# ---------------------------------------------------------------------------

def _is_array_leaf(x) -> bool:
    return hasattr(x, "dtype") and hasattr(x, "shape")


def _avalize(tree):
    """Array leaves -> ShapeDtypeStruct; everything else passes through."""
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
        if _is_array_leaf(x) else x, tree)


def _split_static(tree):
    """Split a pytree into (avals_list, rebuild_fn). Non-array leaves
    (python bools like ``deterministic``, Nones) stay STATIC inside the
    rebuild closure — re-tracing them as device scalars would break the
    module's python control flow."""
    leaves, treedef = jax.tree.flatten(tree)
    is_arr = [_is_array_leaf(l) for l in leaves]
    avals = [jax.ShapeDtypeStruct(l.shape, l.dtype)
             for l, a in zip(leaves, is_arr) if a]
    statics = [l for l, a in zip(leaves, is_arr) if a is False]

    def rebuild(arrs):
        arrs_it, static_it = iter(arrs), iter(statics)
        rebuilt = [next(arrs_it) if a else next(static_it) for a in is_arr]
        return jax.tree.unflatten(treedef, rebuilt)

    return avals, rebuild


def _scan_multiplier(full_params, path, local_params) -> int:
    """Detect an nn.scan body: the stored param subtree carries a leading
    layer axis the per-iteration view lacks; the ratio is the multiplier."""
    sub = full_params.get("params", full_params)
    for key in path:
        if not isinstance(sub, dict) or key not in sub:
            return 1
        sub = sub[key]
    full_leaves = jax.tree.leaves(sub)
    local_leaves = jax.tree.leaves(local_params.get("params", local_params))
    if not full_leaves or len(full_leaves) != len(local_leaves):
        return 1
    f, l = full_leaves[0], local_leaves[0]
    fs, ls = tuple(np.shape(f)), tuple(np.shape(l))
    if len(fs) == len(ls) + 1 and fs[1:] == ls:
        return int(fs[0])
    return 1


def profile_model_tree(model, *args, variables=None, depth: int = 3,
                       top_n: int = 3, print_profile: bool = True,
                       measure: bool = False, model_kwargs: dict = None,
                       **kwargs):
    """Per-module cost breakdown of a flax model's forward pass.

    Walks the module tree by intercepting every submodule ``__call__``
    during ONE ``eval_shape`` trace (zero device work), then compiles each
    submodule standalone at the avals it actually received and reads the
    HLO cost analysis. Scan bodies are costed once and multiplied by the
    layer count (detected from the stored params' leading layer axis) —
    the reference's per-module tree (profiler.py:17, :788-830) without
    any monkey-patching, and with compiler-exact counts.

    Returns ``(rows, total)``: rows are dicts with path/name/flops/macs/
    params/multiplier/share; ``total`` is the WHOLE-program cost (which
    depth-1 rows plus the "unattributed" remainder sum to exactly).
    """
    import flax.linen as nn

    # model-call kwargs: pass via model_kwargs to avoid collisions with
    # this function's own options (a model whose __call__ takes `depth`
    # would otherwise silently lose it to the tree-depth cutoff)
    kwargs = {**(model_kwargs or {}), **kwargs}
    # split static leaves (python bools like a positional `deterministic`)
    # out of the top-level args — tracing them as device scalars would
    # break the model's python control flow, same as for submodules
    arg_avals, top_rebuild = _split_static(args)

    if variables is None:
        # eval_shape takes ShapeDtypeStructs directly — no concrete zeros
        variables = jax.eval_shape(
            lambda arrs: model.init(jax.random.PRNGKey(0),
                                    *top_rebuild(arrs), **kwargs),
            arg_avals)
    var_avals = _avalize(variables)

    def _apply(v, arrs):
        return model.apply(v, *top_rebuild(arrs), **kwargs)

    whole = cost_analysis(_apply, var_avals, arg_avals)
    whole["params"] = params_count(
        variables.get("params", variables))

    records = {}
    order = []
    active = []  # path stack: skip self-nested re-entry (super().__call__)

    def interceptor(next_fun, call_args, call_kwargs, context):
        mod = context.module
        path = tuple(mod.path)
        if (context.method_name != "__call__" or not path
                or len(path) > depth or path in active):
            return next_fun(*call_args, **call_kwargs)
        active.append(path)
        try:
            if path not in records:
                # record each path ONCE: flax transforms (nn.scan carry
                # discovery, remat) re-trace bodies, so trace-time call
                # counts do not reflect runtime execution counts — the
                # scan multiplier below carries the repetition instead
                try:
                    m, v = mod.unbind()
                    records[path] = {
                        "module": m, "vars": v, "args": call_args,
                        "kwargs": dict(call_kwargs),
                        "name": type(m).__name__,
                    }
                    order.append(path)
                except Exception:  # pragma: no cover - exotic modules
                    pass
            return next_fun(*call_args, **call_kwargs)
        finally:
            active.pop()

    with nn.intercept_methods(interceptor):
        # FRESH lambda on purpose: jax caches traces by function identity,
        # and a cache hit from the cost_analysis above would skip tracing
        # entirely — the interceptor would never fire
        jax.eval_shape(lambda v, a: _apply(v, a), var_avals, arg_avals)

    rows = []
    for path in order:
        r = records[path]
        m = r["module"]
        arg_list, rebuild = _split_static((r["args"], r["kwargs"]))
        v_avals = _avalize(r["vars"])

        def run(v, arrs, _m=m, _rebuild=rebuild):
            a, kw = _rebuild(arrs)
            return _m.apply(v, *a, **kw)

        try:
            cost = cost_analysis(run, v_avals, arg_list)
        except Exception:       # a fragment that cannot compile standalone
            cost = {"flops": 0.0, "bytes_accessed": 0.0,
                    "optimal_seconds": 0.0}
        mult = _scan_multiplier(variables, path, r["vars"])
        p_local = params_count(r["vars"].get("params", {}))
        rows.append({
            "path": path, "name": r["name"], "depth": len(path),
            "multiplier": mult,
            "std_flops": cost["flops"],
            "std_bytes": cost["bytes_accessed"],
            "flops": cost["flops"] * mult,
            "bytes_accessed": cost["bytes_accessed"] * mult,
            "params": p_local * mult,
        })

    # XLA's cost analysis counts a scan/while BODY once, not x trip count:
    # both the whole-program number and every ancestor of a scan body
    # undercount by (mult - 1) x body cost. Detect scan-body roots (the
    # shallowest path where the multiplier appears) and fold the missing
    # repetitions into their ancestors and the program total, so depth-1
    # rows + unattributed still sum to the total EXACTLY.
    mult_of = {}

    def parent_mult(path):
        for i in range(len(path) - 1, 0, -1):
            if path[:i] in mult_of:
                return mult_of[path[:i]]
        return 1

    for r in rows:    # pre-order: parents precede children
        if r["multiplier"] == 1:
            # paramless modules (Dropout, activations) carry no layer axis
            # to detect the scan from — they repeat with their parent
            pm = parent_mult(r["path"])
            if pm > 1:
                r["multiplier"] = pm
                r["flops"] *= pm
                r["bytes_accessed"] *= pm
        mult_of[r["path"]] = r["multiplier"]

    total_flops = whole["flops"]
    total_bytes = whole["bytes_accessed"]
    for r in rows:
        pm = parent_mult(r["path"])
        if r["multiplier"] > pm:    # scan-body root
            extra = r["std_flops"] * (r["multiplier"] - pm)
            extra_bytes = r["std_bytes"] * (r["multiplier"] - pm)
            total_flops += extra
            total_bytes += extra_bytes
            for a in rows:
                if (len(a["path"]) < len(r["path"])
                        and r["path"][:len(a["path"])] == a["path"]):
                    a["flops"] += extra
                    a["bytes_accessed"] += extra_bytes
    for r in rows:
        r["macs"] = r["flops"] / 2
        r["share"] = r["flops"] / total_flops if total_flops else 0.0
        del r["std_flops"], r["std_bytes"]

    top_level = [r for r in rows if r["depth"] == 1]
    attributed = sum(r["flops"] for r in top_level)
    unattributed = total_flops - attributed
    total = dict(whole, flops=total_flops, macs=total_flops / 2,
                 bytes_accessed=total_bytes,
                 scan_body_once_flops=whole["flops"],
                 unattributed_flops=unattributed)

    if measure:
        # whole-program wall clock, attributed to modules by flops share
        # (XLA fuses across module boundaries, so per-module timers do not
        # exist post-compilation; the reference's hook latencies have the
        # mirror-image caveat — they measure eager, unfused execution)
        concrete_arrs = [l for l in jax.tree.leaves(args)
                         if _is_array_leaf(l)]
        all_concrete = not any(
            isinstance(l, jax.ShapeDtypeStruct)
            for l in jax.tree.leaves((variables, concrete_arrs)))
        if all_concrete:
            latency = measure_latency(
                jax.jit(_apply), variables, concrete_arrs)
            total["latency_s"] = latency
            for r in rows:
                r["est_latency_s"] = latency * r["share"]

    if print_profile:
        lines = ["-" * 72,
                 "deepspeed_tpu flops profiler: per-module tree "
                 "(HLO cost analysis)",
                 f"{type(model).__name__}: "
                 f"params {number_to_string(total['params'])}| "
                 f"MACs {number_to_string(total['macs'])}| "
                 f"flops {number_to_string(total['flops'])}"]
        if "latency_s" in total:
            lines.append(f"measured latency: {total['latency_s']*1e3:.2f} ms"
                         f" (per-module estimates = flops share x this)")
        for r in rows:
            pad = "  " * r["depth"]
            x = (f" x{r['multiplier']}" if r["multiplier"] > 1 else "")
            lat = (f"| ~{r['est_latency_s']*1e3:.2f} ms"
                   if "est_latency_s" in r else "")
            lines.append(
                f"{pad}{'/'.join(r['path'])}{x}: "
                f"params {number_to_string(r['params'])}| "
                f"MACs {number_to_string(r['macs'])}| "
                f"{r['share'] * 100:.1f}% of total flops{lat}")
        lines.append(
            f"  (unattributed: ops outside submodules, fusion deltas = "
            f"{number_to_string(unattributed)}FLOPs)")
        for d in sorted({r["depth"] for r in rows}):
            at_d = sorted((r for r in rows if r["depth"] == d),
                          key=lambda r: -r["flops"])[:top_n]
            lines.append(
                f"top {len(at_d)} at depth {d} by flops: "
                + ", ".join(f"{'/'.join(r['path'])} "
                            f"({number_to_string(r['flops'])})"
                            for r in at_d))
        lines.append("-" * 72)
        out = "\n".join(lines)
        logger.info("\n" + out)

    return rows, total

"""FLOPS profiler via XLA HLO cost analysis.

Reference ``profiling/flops_profiler/profiler.py:17`` monkey-patches
``torch.nn.functional`` and tensor methods to COUNT MACs per module
(:788-830) and uses module hooks for latency. On TPU the compiler already
knows: ``jit(f).lower(...).compile().cost_analysis()`` returns exact HLO
flops and bytes for the whole fused program — more accurate than
patch-counting (it sees XLA fusions, remat recompute, and collective
traffic). Latency comes from timed, ``block_until_ready``-fenced replays.

``get_model_profile`` is the reference's public entry (same name); the
``FlopsProfiler`` class profiles any jitted callable and pretty-prints a
summary with achieved TFLOPS vs the step wall clock.
"""

import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

from deepspeed_tpu.utils.logging import logger


def _num(x) -> float:
    try:
        return float(x)
    except (TypeError, ValueError):
        return 0.0


def params_count(params) -> int:
    return int(sum(np.prod(np.shape(p))
                   for p in jax.tree.leaves(params)))


def cost_analysis(fn: Callable, *args, **kwargs) -> Dict[str, float]:
    """Compile ``fn`` for the given args and return HLO cost metrics:
    flops, bytes accessed, and the compiler's optimal-seconds estimate."""
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    compiled = jitted.lower(*args, **kwargs).compile()
    ca = compiled.cost_analysis() or {}
    return {
        "flops": _num(ca.get("flops", 0)),
        "bytes_accessed": _num(ca.get("bytes accessed", 0)),
        "optimal_seconds": _num(ca.get("optimal_seconds", 0)),
    }


def measure_latency(fn: Callable, *args, warmup: int = 1, iters: int = 5,
                    **kwargs) -> float:
    """Median wall-clock seconds of a device-fenced call."""
    for _ in range(warmup):
        jax.block_until_ready(fn(*args, **kwargs))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args, **kwargs))
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def number_to_string(num: float, units: Optional[str] = None,
                     precision: int = 2) -> str:
    """Human-readable magnitudes (reference profiler's flops_to_string
    family, one generic implementation)."""
    scale = {"T": 1e12, "G": 1e9, "M": 1e6, "K": 1e3, "": 1.0}
    if units is None:
        for units, s in scale.items():
            if abs(num) >= s and s > 1:
                break
        else:
            units = ""
    return f"{num / scale[units]:.{precision}f} {units}"


flops_to_string = number_to_string
params_to_string = number_to_string
macs_to_string = number_to_string


class FlopsProfiler:
    """Profile a jitted step function (reference FlopsProfiler, but the
    "model" is a function + example args, the JAX unit of execution)."""

    def __init__(self, fn: Callable = None, ds_config=None):
        self.fn = fn
        self.config = getattr(ds_config, "flops_profiler", None)
        self.profile: Dict[str, Any] = {}

    def profile_fn(self, *args, measure_time: bool = True,
                   params=None, **kwargs) -> Dict[str, Any]:
        costs = cost_analysis(self.fn, *args, **kwargs)
        prof = dict(costs)
        prof["params"] = params_count(params) if params is not None else None
        if measure_time:
            latency = measure_latency(self.fn, *args, **kwargs)
            prof["latency_s"] = latency
            prof["achieved_tflops"] = (
                costs["flops"] / latency / 1e12 if latency > 0 else 0.0)
            prof["achieved_gbps"] = (
                costs["bytes_accessed"] / latency / 1e9 if latency > 0
                else 0.0)
        self.profile = prof
        return prof

    def print_profile(self) -> str:
        p = self.profile
        lines = ["-" * 60, "deepspeed_tpu flops profiler (HLO cost analysis)"]
        if p.get("params") is not None:
            lines.append(f"params:            "
                         f"{number_to_string(p['params'])}")
        lines.append(f"flops per call:    "
                     f"{number_to_string(p.get('flops', 0))}FLOPs")
        lines.append(f"bytes accessed:    "
                     f"{number_to_string(p.get('bytes_accessed', 0))}B")
        if "latency_s" in p:
            lines.append(f"latency:           {p['latency_s'] * 1e3:.2f} ms")
            lines.append(f"achieved:          "
                         f"{p['achieved_tflops']:.2f} TFLOPS, "
                         f"{p['achieved_gbps']:.1f} GB/s")
        lines.append("-" * 60)
        out = "\n".join(lines)
        logger.info("\n" + out)
        return out


def get_model_profile(model, args=None, kwargs=None, print_profile=True,
                      as_string: bool = False,
                      **_ignored) -> Tuple[Any, Any, Any]:
    """Reference public API (``get_model_profile``): returns
    (flops, macs, params) of one forward call.

    ``model`` is a callable (e.g. ``lambda x: module.apply(vars, x)``);
    MACs are reported as flops/2 (HLO counts multiply-adds as 2 flops).
    """
    args = args or ()
    kwargs = kwargs or {}
    prof = FlopsProfiler(model)
    result = prof.profile_fn(*args, measure_time=False, **kwargs)
    if print_profile:
        prof.print_profile()
    flops = result["flops"]
    macs = flops / 2
    params = result["params"]
    if as_string:
        return (number_to_string(flops) + "FLOPs",
                number_to_string(macs) + "MACs",
                number_to_string(params or 0))
    return flops, macs, params

from deepspeed_tpu.profiling.flops_profiler.profiler import (  # noqa: F401
    FlopsProfiler,
    cost_analysis,
    flops_to_string,
    get_model_profile,
    macs_to_string,
    measure_latency,
    number_to_string,
    params_count,
    params_to_string,
    profile_model_tree,
)

"""Device mesh / topology management.

TPU-native replacement for the reference's process-group plumbing
(``deepspeed/utils/groups.py``, ``deepspeed/runtime/pipe/topology.py:9-453``):
instead of materialising torch.distributed groups per parallel dimension, we
build ONE ``jax.sharding.Mesh`` with named axes and express every parallel
strategy as a PartitionSpec over those axes.

Axis semantics (order = mesh layout; ``tp`` innermost so tensor-parallel
collectives ride the shortest ICI hops):

* ``pp``   — pipeline stages (reference runtime/pipe/)
* ``dp``   — pure data parallel (replicated params; reference engine.py DDP path)
* ``fsdp`` — sharded data parallel; ZeRO-1/2/3 shard optimizer/grads/params here
             (reference runtime/zero/)
* ``ep``   — expert parallel for MoE all-to-all (reference deepspeed/moe/)
* ``sp``   — sequence/context parallel (absent in the reference snapshot;
             first-class here, see SURVEY.md §2.2)
* ``tp``   — Megatron-style tensor parallel (reference mpu protocol /
             module_inject tensor slicing)

The global batch is sharded over (dp, fsdp, ep): fsdp is *sharded* data
parallelism and each expert-parallel group sees distinct data, matching the
reference's expert-data-parallel group construction (utils/groups.py:109-265).
"""

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

AXIS_ORDER: Tuple[str, ...] = ("pp", "dp", "fsdp", "ep", "sp", "tp")
BATCH_AXES: Tuple[str, ...] = ("dp", "fsdp", "ep")


class MeshTopology:
    """Named-axis device mesh with ProcessTopology-parity queries
    (reference pipe/topology.py: get_coord, axis sizes, rank mapping)."""

    def __init__(
        self,
        dp: int = -1,
        fsdp: int = 1,
        tp: int = 1,
        pp: int = 1,
        ep: int = 1,
        sp: int = 1,
        devices: Optional[Sequence] = None,
    ):
        if devices is None:
            devices = jax.devices()
        devices = list(devices)
        n = len(devices)

        sizes: Dict[str, int] = {
            "pp": pp, "dp": dp, "fsdp": fsdp, "ep": ep, "sp": sp, "tp": tp
        }
        bad = {a: s for a, s in sizes.items() if s != -1 and s < 1}
        if bad:
            raise ValueError(f"Mesh axis sizes must be >= 1 (or -1 to infer): {bad}")
        unknown = [a for a, s in sizes.items() if s == -1]
        if len(unknown) > 1:
            raise ValueError(f"At most one mesh axis may be -1, got {unknown}")
        fixed = int(np.prod([s for s in sizes.values() if s != -1]))
        if unknown:
            if n % fixed != 0:
                raise ValueError(
                    f"{n} devices not divisible by fixed axes product {fixed}"
                )
            sizes[unknown[0]] = n // fixed
        total = int(np.prod(list(sizes.values())))
        if total != n:
            raise ValueError(
                f"Mesh axes {sizes} require {total} devices but {n} are available"
            )

        self.axis_sizes = sizes
        shape = tuple(sizes[a] for a in AXIS_ORDER)
        # slice structure (multi-slice TPU pods): how many DCN-connected
        # slices the devices span and how the slice count factors into the
        # outer mesh axes. On a single slice / CPU backend every factor is
        # 1 — consumers (the hierarchical gradient exchange) read
        # dcn_size("dp") and fall back to the flat exchange at 1.
        is_tpu = bool(devices) and getattr(
            devices[0], "platform", "cpu") == "tpu"
        self.num_slices = (len({getattr(d, "slice_index", None) or 0
                                for d in devices}) if is_tpu else 1)
        self.dcn_shape = (self._derive_dcn_shape(shape, self.num_slices)
                          if self.num_slices > 1
                          else tuple(1 for _ in shape))
        device_array = self._arrange(devices, shape)
        self.mesh = Mesh(device_array, AXIS_ORDER)

    @staticmethod
    def _derive_dcn_shape(shape: Tuple[int, ...], n_slices: int
                          ) -> Tuple[int, ...]:
        """Factor the slice count into the OUTERMOST axes (AXIS_ORDER:
        pp, dp, fsdp, ...), so collectives of the inner axes (tp/sp/ep)
        never cross the data-center network: each element of the result
        divides the global axis size; their product is n_slices."""
        import math

        # only pp/dp/fsdp may absorb the slice dimension; a DCN hop inside
        # an ep all-to-all, sp ring, or tp matmul psum defeats the layout
        n_dcn_eligible = 3  # AXIS_ORDER prefix (pp, dp, fsdp)
        remaining = n_slices
        dcn = []
        for i, size in enumerate(shape):
            g = math.gcd(size, remaining) if i < n_dcn_eligible else 1
            dcn.append(g)
            remaining //= g
        if remaining != 1:
            raise ValueError(
                f"cannot distribute {n_slices} slices over mesh axes "
                f"{dict(zip(AXIS_ORDER, shape))}: the outer axes "
                f"(pp/dp/fsdp) must jointly absorb a factor of {n_slices} "
                f"so no tp/sp/ep collective crosses DCN"
            )
        return tuple(dcn)

    @staticmethod
    def _arrange(devices: List, shape: Tuple[int, ...]) -> np.ndarray:
        """Physical device layout. On one real TPU slice use mesh_utils so
        the innermost axes land on adjacent ICI neighbours; on a MULTI-SLICE
        job (device.slice_index varies) build a hybrid ICI x DCN mesh where
        the slice dimension is absorbed by the outermost parallel axes —
        the 'collectives ride ICI, not DCN' layout. Plain reshape off-TPU."""
        is_tpu = bool(devices) and getattr(
            devices[0], "platform", "cpu") == "tpu"
        slice_ids = ({getattr(d, "slice_index", None) or 0 for d in devices}
                     if is_tpu else set())
        try:
            from jax.experimental import mesh_utils
        except Exception:
            if len(slice_ids) > 1:
                # the plain-reshape fallback is exactly the silent
                # DCN-crossing layout the multi-slice branch exists to
                # reject — fail loudly instead
                raise RuntimeError(
                    "multi-slice TPU job but jax.experimental.mesh_utils "
                    "is unavailable: cannot build the hybrid ICI x DCN "
                    "mesh; a plain reshape would route tp/sp collectives "
                    "over DCN")
            return np.array(devices).reshape(shape)
        if is_tpu:
            if len(slice_ids) > 1:
                # multi-slice must not silently fall back: a plain reshape
                # would route tp/sp collectives over DCN
                dcn_shape = MeshTopology._derive_dcn_shape(
                    shape, len(slice_ids))
                per_slice = tuple(s // d for s, d in zip(shape, dcn_shape))
                return mesh_utils.create_hybrid_device_mesh(
                    per_slice, dcn_shape, devices=devices)
            try:
                return mesh_utils.create_device_mesh(shape, devices=devices)
            except Exception:
                pass
        return np.array(devices).reshape(shape)

    # -- size queries (parity: groups.get_data_parallel_world_size etc.) ---
    def size(self, axis: str) -> int:
        return self.axis_sizes[axis]

    def dcn_size(self, axis: str) -> int:
        """How many DCN-connected slice groups the axis spans (1 on a
        single slice): the factor of ``num_slices`` that
        :meth:`_derive_dcn_shape` assigned to this axis. An axis with
        ``dcn_size > 1`` has its slice dimension as the SLOW (outer)
        dimension — rank = slice_idx * per_slice + ici_idx (the
        ``create_hybrid_device_mesh`` layout ``comm.bucketed.
        hierarchy_groups`` assumes)."""
        return self.dcn_shape[AXIS_ORDER.index(axis)]

    @property
    def num_devices(self) -> int:
        return int(np.prod(list(self.axis_sizes.values())))

    @property
    def data_parallel_size(self) -> int:
        """Number of distinct data shards = dp * fsdp * ep."""
        return int(np.prod([self.axis_sizes[a] for a in BATCH_AXES]))

    @property
    def model_parallel_size(self) -> int:
        return self.axis_sizes["tp"]

    @property
    def pipe_parallel_size(self) -> int:
        return self.axis_sizes["pp"]

    @property
    def expert_parallel_size(self) -> int:
        return self.axis_sizes["ep"]

    @property
    def sequence_parallel_size(self) -> int:
        return self.axis_sizes["sp"]

    def active_axes(self) -> List[str]:
        return [a for a in AXIS_ORDER if self.axis_sizes[a] > 1]

    # -- coordinate queries (parity: ProcessTopology.get_coord) ------------
    def coord_of(self, flat_rank: int) -> Dict[str, int]:
        """Coordinates of a LOGICAL mesh position (row-major index into the
        mesh array). On real TPU slices ``_arrange`` permutes devices for ICI
        locality, so a logical position is generally NOT the device's index in
        ``jax.devices()`` — use :meth:`coord_of_device` to query by device."""
        shape = tuple(self.axis_sizes[a] for a in AXIS_ORDER)
        coords = np.unravel_index(flat_rank, shape)
        return dict(zip(AXIS_ORDER, (int(c) for c in coords)))

    def coord_of_device(self, device) -> Dict[str, int]:
        """Mesh coordinates of a physical jax device."""
        for idx, dev in np.ndenumerate(self.mesh.devices):
            if dev == device:
                return dict(zip(AXIS_ORDER, (int(c) for c in idx)))
        raise ValueError(f"device {device} is not in this mesh")

    def filter_ranks(self, **axis_values) -> List[int]:
        """All LOGICAL mesh positions (row-major, see coord_of) whose
        coordinates match the given axis values
        (parity: ProcessTopology.filter_match, pipe/topology.py)."""
        out = []
        for r in range(self.num_devices):
            c = self.coord_of(r)
            if all(c[a] == v for a, v in axis_values.items()):
                out.append(r)
        return out

    # -- sharding helpers --------------------------------------------------
    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec(*spec))

    def batch_spec(self) -> PartitionSpec:
        axes = [a for a in BATCH_AXES if self.axis_sizes[a] > 1]
        return PartitionSpec(tuple(axes) if axes else None)

    def batch_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, self.batch_spec())

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec())

    def __repr__(self):
        active = {a: s for a, s in self.axis_sizes.items() if s > 1}
        return f"MeshTopology({active or 'single-device'}, devices={self.num_devices})"


# ---------------------------------------------------------------------------
# Default-mesh registry (parity with groups.initialize global state,
# reference utils/groups.py:45)
# ---------------------------------------------------------------------------
_DEFAULT_TOPOLOGY: Optional[MeshTopology] = None


def set_default_topology(topo: MeshTopology) -> None:
    global _DEFAULT_TOPOLOGY
    _DEFAULT_TOPOLOGY = topo


def get_default_topology() -> MeshTopology:
    global _DEFAULT_TOPOLOGY
    if _DEFAULT_TOPOLOGY is None:
        _DEFAULT_TOPOLOGY = MeshTopology()
    return _DEFAULT_TOPOLOGY


def reset_default_topology() -> None:
    global _DEFAULT_TOPOLOGY
    _DEFAULT_TOPOLOGY = None


def topology_from_config(mesh_config, devices=None) -> MeshTopology:
    """Build a MeshTopology from a config MeshConfig/dict."""
    if hasattr(mesh_config, "to_dict"):
        mesh_config = mesh_config.to_dict()
    mesh_config = dict(mesh_config or {})
    return MeshTopology(
        dp=mesh_config.get("dp", -1),
        fsdp=mesh_config.get("fsdp", 1),
        tp=mesh_config.get("tp", 1),
        pp=mesh_config.get("pp", 1),
        ep=mesh_config.get("ep", 1),
        sp=mesh_config.get("sp", 1),
        devices=devices,
    )


# ---------------------------------------------------------------------------
# Parameter sharding rules (FSDP-style "shard the largest divisible dim")
# ---------------------------------------------------------------------------
def shard_largest_dim_spec(
    shape: Tuple[int, ...], axis_name: str, axis_size: int, min_size: int = 0
) -> PartitionSpec:
    """PartitionSpec that shards the largest dim divisible by ``axis_size``.

    This is the TPU-native analogue of ZeRO-3 flat-buffer partitioning
    (reference zero/partition_parameters.py:882): instead of flattening and
    slicing bytes, we annotate a whole dimension and let XLA insert the
    all-gather at use (and skip params below the persistence threshold,
    mirroring stage3 param_persistence_threshold).
    """
    if axis_size <= 1 or not shape:
        return PartitionSpec()
    numel = int(np.prod(shape))
    if numel < max(min_size, axis_size):
        return PartitionSpec()
    candidates = [i for i, d in enumerate(shape) if d % axis_size == 0]
    if not candidates:
        return PartitionSpec()
    best = max(candidates, key=lambda i: shape[i])
    spec = [None] * len(shape)
    spec[best] = axis_name
    return PartitionSpec(*spec)

"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

ABSENT in the reference snapshot (SURVEY.md §2.2 — DeepSpeed-Ulysses landed
~v0.10); first-class here because long-context is a headline TPU capability.

* **Ring attention**: Q stays put; K/V chunks rotate around the ``sp`` ring
  via ``lax.ppermute`` while each step folds one chunk into an online-softmax
  accumulator — attention memory O(T/sp) per device, comm rides ICI
  neighbour links (blockwise-parallel transformer / ring attention papers,
  see PAPERS.md).
* **Ulysses**: ``lax.all_to_all`` re-shards [seq/sp, heads] -> [seq,
  heads/sp]; each device runs FULL attention for its head slice, then the
  inverse all-to-all restores sequence sharding (DeepSpeed-Ulysses
  semantics).

Both are expressed with ``jax.shard_map`` over the named mesh so they
compose with dp/fsdp/tp axes and differentiate through (ppermute/all_to_all
have exact transposes).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.mesh import BATCH_AXES, get_default_topology

NEG_INF = -1e30


# ---------------------------------------------------------------------------
# ring attention (local function; runs inside shard_map)
# ---------------------------------------------------------------------------
def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool,
                          scale: float):
    """q/k/v: LOCAL [B, C, H, D] chunks of the sp-sharded sequence."""
    sp = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    B, C, H, D = q.shape

    qf = q.astype(jnp.float32) * scale
    m0 = jnp.full((B, C, H, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, C, H, 1), jnp.float32)
    acc0 = jnp.zeros((B, C, H, D), jnp.float32)

    q_pos = my * C + jnp.arange(C)

    def step(carry, step_idx):
        k_cur, v_cur, m, l, acc = carry
        src = (my - step_idx) % sp  # whose chunk we hold this step
        s = jnp.einsum("bqhd,bkhd->bqhk", qf, k_cur.astype(jnp.float32))
        if causal:
            k_pos = src * C + jnp.arange(C)
            vis = q_pos[:, None] >= k_pos[None, :]      # [C, C]
            s = jnp.where(vis[None, :, None, :], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bqhk,bkhd->bqhd", p, v_cur.astype(jnp.float32))
        # rotate K/V to the next neighbour (ICI ring)
        perm = [(i, (i + 1) % sp) for i in range(sp)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, m_new, l_new, acc_new), None

    (k_f, v_f, m, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(sp))
    out = acc / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Ulysses attention (local function; runs inside shard_map)
# ---------------------------------------------------------------------------
def _ulysses_attention_local(q, k, v, *, axis_name: str, causal: bool,
                             scale: float):
    """q/k/v: LOCAL [B, C, H, D]; all_to_all to [B, T, H/sp, D], full
    attention per head slice, all_to_all back."""
    sp = jax.lax.psum(1, axis_name)
    B, C, H, D = q.shape

    def scatter_heads(x):
        # [B, C, H, D] -> [B, sp*C, H/sp, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def gather_heads(x):
        # inverse
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qh, kh, vh = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    T = qh.shape[1]
    s = jnp.einsum("bqhd,bkhd->bhqk", qh.astype(jnp.float32),
                   kh.astype(jnp.float32)) * scale
    if causal:
        vis = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(vis[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, vh.astype(jnp.float32))
    return gather_heads(out.astype(q.dtype))


# ---------------------------------------------------------------------------
# public wrappers: global arrays -> shard_map over the default mesh
# ---------------------------------------------------------------------------
def _wrap(local_fn, q, k, v, causal, scale):
    topo = get_default_topology()
    sp = topo.size("sp")
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    if sp <= 1:
        raise ValueError("sequence-parallel attention needs an sp mesh axis "
                         "> 1 (got sp=1)")
    assert q.shape[1] % sp == 0, (
        f"seq len {q.shape[1]} not divisible by sp={sp}")

    batch = tuple(a for a in BATCH_AXES if topo.size(a) > 1) or None
    head = "tp" if topo.size("tp") > 1 else None
    spec = P(batch, "sp", head, None)

    fn = functools.partial(local_fn, axis_name="sp", causal=causal,
                           scale=float(scale))
    return jax.shard_map(
        fn, mesh=topo.mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)


def ring_attention(q, k, v, *, causal: bool = True,
                   scale: Optional[float] = None):
    """Ring attention over the sp axis; q/k/v are GLOBAL
    [batch, seq, heads, head_dim] arrays (sharded by the caller's jit)."""
    return _wrap(_ring_attention_local, q, k, v, causal, scale)


def ulysses_attention(q, k, v, *, causal: bool = True,
                      scale: Optional[float] = None):
    """DeepSpeed-Ulysses-style all-to-all head-parallel attention over sp."""
    topo = get_default_topology()
    sp = topo.size("sp")
    # heads are sharded over tp first; the all_to_all splits the LOCAL count
    local_heads = q.shape[2] // max(topo.size("tp"), 1)
    assert local_heads % sp == 0, (
        f"ulysses needs per-device heads ({q.shape[2]} / tp="
        f"{topo.size('tp')} = {local_heads}) divisible by sp ({sp})")
    return _wrap(_ulysses_attention_local, q, k, v, causal, scale)

"""HuggingFace checkpoint import — the injection-policy weight maps.

Parity with reference ``deepspeed/module_inject/replace_policy.py`` (per-
architecture weight-name maps: HFGPT2 :404, HFBert :124, ...) and the
weight-copying half of ``replace_transformer_layer``
(``module_inject/replace_module.py:277``): the reference walks an HF torch
model, pulls weights out by per-architecture policy, and packs them into its
fused inference modules (optionally tensor-sliced per MP rank).

TPU re-design: the "fused module" is our flax model (whose forward IS the
fused path — XLA/Pallas), so injection reduces to a pure weight-layout
transform: HF torch ``state_dict`` -> flax param pytree. Tensor-parallel
slicing (``ReplaceWithTensorSlicing``, replace_module.py:18) does not touch
the weights at all here — the models' ``tp_rules`` PartitionSpecs shard the
converted tree when it materializes on the mesh.

Conventions (both converters):

* torch ``nn.Linear`` stores ``[out, in]`` -> transposed to flax's
  ``[in, out]``. HF GPT-2's ``Conv1D`` already stores ``[in, out]``.
* with ``scan_layers=True`` per-layer trees are stacked on a leading
  ``n_layer`` axis (the scan layout).
* every converted model runs with ``dropout=0`` (serving) unless overridden.
"""

from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np


def _np(t) -> np.ndarray:
    """torch tensor (any device/dtype) -> float32 numpy."""
    if hasattr(t, "detach"):
        t = t.detach().cpu()
        if hasattr(t, "float"):
            t = t.float()
        return t.numpy()
    return np.asarray(t, np.float32)


def _deinterleave_qkv(w, b, n_head: int, head_dim: int):
    """Fused per-head-interleaved qkv (rows laid out [H, 3, D] — NeoX,
    BLOOM, Megatron-LM) -> flax c_attn {kernel [C, 3C] as [q|k|v], bias}."""
    H, D = n_head, head_dim
    w = w.reshape(H, 3, D, -1)
    b = b.reshape(H, 3, D)
    kernel = np.concatenate(
        [w[:, j].reshape(H * D, -1) for j in range(3)], axis=0).T
    bias = np.concatenate([b[:, j].reshape(H * D) for j in range(3)])
    return {"kernel": kernel, "bias": bias}


def _stack(layers):
    """[{path: leaf}, ...] per layer -> one tree stacked on axis 0."""
    import jax

    return jax.tree.map(lambda *xs: np.stack(xs, axis=0), *layers)


def _pack_gpt_layers(params, layers, scan_layers: bool):
    """Install per-layer trees into a GPT param tree: stacked on a leading
    axis under ``h/block`` for the scan layout, else ``h_{i}``."""
    if scan_layers:
        params["h"] = {"block": _stack(layers)}
    else:
        for i, lp in enumerate(layers):
            params[f"h_{i}"] = lp
    return params


# ---------------------------------------------------------------------------
# GPT-2 (reference HFGPT2LayerPolicy, replace_policy.py:404)
# ---------------------------------------------------------------------------
def gpt2_config_from_hf(hf_config, **overrides):
    """Map a ``transformers.GPT2Config`` onto our :class:`GPTConfig`."""
    from deepspeed_tpu.models.transformer_lm import GPTConfig

    kw = dict(
        vocab_size=hf_config.vocab_size,
        n_positions=hf_config.n_positions,
        n_embd=hf_config.n_embd,
        n_layer=hf_config.n_layer,
        n_head=hf_config.n_head,
        layer_norm_epsilon=hf_config.layer_norm_epsilon,
        dropout=0.0,
    )
    kw.update(overrides)
    return GPTConfig(**kw)


def gpt2_params_from_hf(state_dict: Dict[str, Any], n_layer: int,
                        scan_layers: bool = True) -> Dict[str, Any]:
    """HF ``GPT2LMHeadModel``/``GPT2Model`` state dict -> GPT param tree."""
    sd = {k.removeprefix("transformer."): v for k, v in state_dict.items()}

    def ln(prefix):
        return {"scale": _np(sd[f"{prefix}.weight"]),
                "bias": _np(sd[f"{prefix}.bias"])}

    def conv1d(prefix):
        # HF Conv1D keeps [in, out] — flax Dense layout already
        return {"kernel": _np(sd[f"{prefix}.weight"]),
                "bias": _np(sd[f"{prefix}.bias"])}

    def layer(i):
        p = f"h.{i}"
        return {
            "ln_1": ln(f"{p}.ln_1"),
            "attn": {"c_attn": conv1d(f"{p}.attn.c_attn"),
                     "c_proj": conv1d(f"{p}.attn.c_proj")},
            "ln_2": ln(f"{p}.ln_2"),
            "mlp": {"c_fc": conv1d(f"{p}.mlp.c_fc"),
                    "c_proj": conv1d(f"{p}.mlp.c_proj")},
        }

    params = {
        "wte": {"embedding": _np(sd["wte.weight"])},
        "wpe": {"embedding": _np(sd["wpe.weight"])},
        "ln_f": ln("ln_f"),
    }
    return _pack_gpt_layers(params, [layer(i) for i in range(n_layer)],
                            scan_layers)


def gpt2_from_hf(hf_model, dtype=jnp.bfloat16, **config_overrides):
    """``transformers.GPT2LMHeadModel`` -> ``(GPT module, params)``.

    The LM head needs no weights of its own — ours is tied to ``wte`` exactly
    like HF's (``lm_head.weight`` aliases ``transformer.wte.weight``).
    """
    from deepspeed_tpu.models.transformer_lm import GPT

    cfg = gpt2_config_from_hf(hf_model.config, dtype=dtype,
                              **config_overrides)
    params = gpt2_params_from_hf(hf_model.state_dict(), cfg.n_layer,
                                 scan_layers=cfg.scan_layers)
    return GPT(cfg), params


# ---------------------------------------------------------------------------
# BERT (reference HFBertLayerPolicy, replace_policy.py:124)
# ---------------------------------------------------------------------------
_BERT_GELU = {"gelu": False, "gelu_new": True, "gelu_pytorch_tanh": True,
              "gelu_fast": True}


def _bert_gelu(act: str) -> bool:
    if act not in _BERT_GELU:
        raise ValueError(
            f"unsupported BERT hidden_act {act!r}; the policy supports "
            f"{sorted(_BERT_GELU)}")
    return _BERT_GELU[act]


def bert_config_from_hf(hf_config, **overrides):
    from deepspeed_tpu.models.bert import BertConfig

    kw = dict(
        vocab_size=hf_config.vocab_size,
        max_position_embeddings=hf_config.max_position_embeddings,
        type_vocab_size=hf_config.type_vocab_size,
        hidden_size=hf_config.hidden_size,
        num_hidden_layers=hf_config.num_hidden_layers,
        num_attention_heads=hf_config.num_attention_heads,
        intermediate_size=hf_config.intermediate_size,
        layer_norm_eps=hf_config.layer_norm_eps,
        # HF "gelu" is the exact erf form; "gelu_new"/"gelu_pytorch_tanh"
        # are the tanh approximation; anything else is unsupported
        approximate_gelu=_bert_gelu(hf_config.hidden_act),
        dropout=0.0,
    )
    kw.update(overrides)
    return BertConfig(**kw)


def bert_params_from_hf(state_dict: Dict[str, Any], n_layer: int,
                        scan_layers: bool = True,
                        use_mlm_bias: bool = True) -> Dict[str, Any]:
    """HF ``BertForMaskedLM``/``BertModel`` state dict -> param tree for
    :class:`deepspeed_tpu.models.bert.BertForPreTraining`."""
    sd = {k.removeprefix("bert."): v for k, v in state_dict.items()}

    def ln(prefix):
        return {"scale": _np(sd[f"{prefix}.weight"]),
                "bias": _np(sd[f"{prefix}.bias"])}

    def linear(prefix):
        # torch Linear [out, in] -> [in, out]
        return {"kernel": _np(sd[f"{prefix}.weight"]).T,
                "bias": _np(sd[f"{prefix}.bias"])}

    def layer(i):
        p = f"encoder.layer.{i}"
        q = linear(f"{p}.attention.self.query")
        k = linear(f"{p}.attention.self.key")
        v = linear(f"{p}.attention.self.value")
        return {
            "attention": {
                "qkv": {
                    "kernel": np.concatenate(
                        [q["kernel"], k["kernel"], v["kernel"]], axis=1),
                    "bias": np.concatenate(
                        [q["bias"], k["bias"], v["bias"]]),
                },
                "output": linear(f"{p}.attention.output.dense"),
            },
            "ln_attn": ln(f"{p}.attention.output.LayerNorm"),
            "intermediate": linear(f"{p}.intermediate.dense"),
            "output": linear(f"{p}.output.dense"),
            "ln_out": ln(f"{p}.output.LayerNorm"),
        }

    emb = "embeddings"
    params = {
        "word_embeddings": {"embedding": _np(
            sd[f"{emb}.word_embeddings.weight"])},
        "position_embeddings": {"embedding": _np(
            sd[f"{emb}.position_embeddings.weight"])},
        "token_type_embeddings": {"embedding": _np(
            sd[f"{emb}.token_type_embeddings.weight"])},
        "embeddings_ln": ln(f"{emb}.LayerNorm"),
    }
    layers = [layer(i) for i in range(n_layer)]
    if scan_layers:
        params["encoder"] = {"layer": _stack(layers)}
    else:
        params["encoder"] = {f"layer_{i}": lp for i, lp in enumerate(layers)}

    # MLM head (cls.predictions.*); the decoder weight is tied to
    # word_embeddings in HF (tie_word_embeddings) just like our model
    if "cls.predictions.transform.dense.weight" in state_dict:
        params["mlm_dense"] = {
            "kernel": _np(
                state_dict["cls.predictions.transform.dense.weight"]).T,
            "bias": _np(state_dict["cls.predictions.transform.dense.bias"]),
        }
        params["mlm_ln"] = {
            "scale": _np(
                state_dict["cls.predictions.transform.LayerNorm.weight"]),
            "bias": _np(
                state_dict["cls.predictions.transform.LayerNorm.bias"]),
        }
        if use_mlm_bias and "cls.predictions.bias" in state_dict:
            params["mlm_bias"] = _np(state_dict["cls.predictions.bias"])
    return params


def bert_from_hf(hf_model, dtype=jnp.bfloat16, **config_overrides):
    """``transformers.BertForMaskedLM`` -> ``(BertForPreTraining, params)``."""
    from deepspeed_tpu.models.bert import BertForPreTraining

    sd = hf_model.state_dict()
    has_bias = "cls.predictions.bias" in sd
    cfg = bert_config_from_hf(hf_model.config, dtype=dtype,
                              use_mlm_bias=has_bias, **config_overrides)
    params = bert_params_from_hf(sd, cfg.num_hidden_layers,
                                 scan_layers=cfg.scan_layers,
                                 use_mlm_bias=cfg.use_mlm_bias)
    return BertForPreTraining(cfg), params


# ---------------------------------------------------------------------------
# GPT-NeoX (reference GPTNEOXLayerPolicy, replace_policy.py:486)
# ---------------------------------------------------------------------------
def gptneox_from_hf(hf_model, dtype=jnp.bfloat16, **config_overrides):
    """``transformers.GPTNeoXForCausalLM`` -> ``(GPT, params)``.

    NeoX fuses qkv per head (``query_key_value`` rows interleave
    q_h/k_h/v_h); our layout is [q_all | k_all | v_all], so the fused weight
    is de-interleaved here — the same transform the reference's policy does
    with ``attention.query_key_value`` before slicing across MP ranks.
    """
    from deepspeed_tpu.models.transformer_lm import GPT, GPTConfig

    hc = hf_model.config
    kw = dict(
        vocab_size=hc.vocab_size,
        n_positions=hc.max_position_embeddings,
        n_embd=hc.hidden_size,
        n_layer=hc.num_hidden_layers,
        n_head=hc.num_attention_heads,
        intermediate_size=hc.intermediate_size,
        layer_norm_epsilon=hc.layer_norm_eps,
        activation={"gelu": "gelu", "gelu_new": "gelu_tanh",
                    "relu": "relu"}.get(hc.hidden_act, hc.hidden_act),
        rotary=True,
        rotary_pct=hc.rotary_pct,
        rope_theta=float(getattr(hc, "rotary_emb_base", None)
                         or getattr(hc, "rope_theta", 10000.0)),
        learned_positions=False,
        tie_word_embeddings=bool(getattr(hc, "tie_word_embeddings", False)),
        parallel_residual=hc.use_parallel_residual,
        dropout=0.0, dtype=dtype,
    )
    kw.update(config_overrides)
    cfg = GPTConfig(**kw)

    sd = {k.removeprefix("gpt_neox."): v
          for k, v in hf_model.state_dict().items()}
    H, D = cfg.n_head, cfg.head_dim

    def ln(prefix):
        return {"scale": _np(sd[f"{prefix}.weight"]),
                "bias": _np(sd[f"{prefix}.bias"])}

    def qkv(i):
        return _deinterleave_qkv(
            _np(sd[f"layers.{i}.attention.query_key_value.weight"]),
            _np(sd[f"layers.{i}.attention.query_key_value.bias"]), H, D)

    def linear(prefix):
        return {"kernel": _np(sd[f"{prefix}.weight"]).T,
                "bias": _np(sd[f"{prefix}.bias"])}

    def layer(i):
        p = f"layers.{i}"
        return {
            "ln_1": ln(f"{p}.input_layernorm"),
            "ln_2": ln(f"{p}.post_attention_layernorm"),
            "attn": {"c_attn": qkv(i),
                     "c_proj": linear(f"{p}.attention.dense")},
            "mlp": {"c_fc": linear(f"{p}.mlp.dense_h_to_4h"),
                    "c_proj": linear(f"{p}.mlp.dense_4h_to_h")},
        }

    params = {
        "wte": {"embedding": _np(sd["embed_in.weight"])},
        "ln_f": ln("final_layer_norm"),
    }
    _pack_gpt_layers(params, [layer(i) for i in range(cfg.n_layer)],
                     cfg.scan_layers)
    if not cfg.tie_word_embeddings:
        params["lm_head"] = _np(hf_model.state_dict()["embed_out.weight"]).T
    return GPT(cfg), params


# ---------------------------------------------------------------------------
# GPT-J (reference HFGPTJLayerPolicy, replace_policy.py:279)
# ---------------------------------------------------------------------------
def gptj_from_hf(hf_model, dtype=jnp.bfloat16, **config_overrides):
    """``transformers.GPTJForCausalLM`` -> ``(GPT, params)``.

    GPT-J: parallel residual with a single shared LayerNorm (duplicated here
    into ln_1/ln_2), interleaved rotary over ``rotary_dim`` dims, biasless
    attention, biased MLP, untied LM head with bias.
    """
    from deepspeed_tpu.models.transformer_lm import GPT, GPTConfig

    hc = hf_model.config
    head_dim = hc.n_embd // hc.n_head
    kw = dict(
        vocab_size=hc.vocab_size,
        n_positions=hc.n_positions,
        n_embd=hc.n_embd,
        n_layer=hc.n_layer,
        n_head=hc.n_head,
        intermediate_size=getattr(hc, "n_inner", None) or 4 * hc.n_embd,
        layer_norm_epsilon=hc.layer_norm_epsilon,
        activation="gelu_tanh",  # HF "gelu_new"
        use_bias=True,
        attn_bias=False,
        rotary=True,
        rotary_pct=(hc.rotary_dim or head_dim) / head_dim,
        rotary_interleaved=True,
        learned_positions=False,
        tie_word_embeddings=False,
        lm_head_bias=True,
        parallel_residual=True,
        dropout=0.0, dtype=dtype,
    )
    kw.update(config_overrides)
    cfg = GPTConfig(**kw)

    full_sd = hf_model.state_dict()
    sd = {k.removeprefix("transformer."): v for k, v in full_sd.items()}

    def ln(prefix):
        return {"scale": _np(sd[f"{prefix}.weight"]),
                "bias": _np(sd[f"{prefix}.bias"])}

    def linear(prefix, bias=True):
        out = {"kernel": _np(sd[f"{prefix}.weight"]).T}
        if bias:
            out["bias"] = _np(sd[f"{prefix}.bias"])
        return out

    def layer(i):
        p = f"h.{i}"
        shared_ln = ln(f"{p}.ln_1")
        qw = _np(sd[f"{p}.attn.q_proj.weight"]).T
        kw_ = _np(sd[f"{p}.attn.k_proj.weight"]).T
        vw = _np(sd[f"{p}.attn.v_proj.weight"]).T
        return {
            "ln_1": shared_ln,
            "ln_2": {k: v.copy() for k, v in shared_ln.items()},
            "attn": {
                "c_attn": {"kernel": np.concatenate([qw, kw_, vw], axis=1)},
                "c_proj": linear(f"{p}.attn.out_proj", bias=False),
            },
            "mlp": {"c_fc": linear(f"{p}.mlp.fc_in"),
                    "c_proj": linear(f"{p}.mlp.fc_out")},
        }

    params = {
        "wte": {"embedding": _np(sd["wte.weight"])},
        "ln_f": ln("ln_f"),
        "lm_head": _np(full_sd["lm_head.weight"]).T,
        "lm_head_bias": _np(full_sd["lm_head.bias"]),
    }
    _pack_gpt_layers(params, [layer(i) for i in range(cfg.n_layer)],
                     cfg.scan_layers)
    return GPT(cfg), params


# ---------------------------------------------------------------------------
# OPT (reference HFOPTLayerPolicy, replace_policy.py:540)
# ---------------------------------------------------------------------------
def opt_from_hf(hf_model, dtype=jnp.bfloat16, **config_overrides):
    """``transformers.OPTForCausalLM`` -> ``(GPT, params)``.

    Pre-LN OPT variants only (``do_layer_norm_before=True``; the 350m
    post-LN layout is rejected). OPT's learned positions carry a +2 offset —
    the first two embedding rows are dropped.
    """
    from deepspeed_tpu.models.transformer_lm import GPT, GPTConfig

    hc = hf_model.config
    if not hc.do_layer_norm_before or getattr(
            hc, "_remove_final_layer_norm", False):
        raise ValueError("only pre-LN OPT variants are supported")
    if hc.word_embed_proj_dim != hc.hidden_size:
        raise ValueError("OPT word_embed_proj_dim != hidden_size "
                         "(projected-embedding variants unsupported)")
    kw = dict(
        vocab_size=hc.vocab_size,
        n_positions=hc.max_position_embeddings,
        n_embd=hc.hidden_size,
        n_layer=hc.num_hidden_layers,
        n_head=hc.num_attention_heads,
        intermediate_size=hc.ffn_dim,
        layer_norm_epsilon=1e-5,  # torch nn.LayerNorm default (OPT uses it)
        activation={"relu": "relu", "gelu": "gelu"}[hc.activation_function],
        tie_word_embeddings=bool(hc.tie_word_embeddings),
        dropout=0.0, dtype=dtype,
    )
    kw.update(config_overrides)
    cfg = GPTConfig(**kw)

    full_sd = hf_model.state_dict()
    sd = {k.removeprefix("model.decoder."): v for k, v in full_sd.items()}

    def ln(prefix):
        return {"scale": _np(sd[f"{prefix}.weight"]),
                "bias": _np(sd[f"{prefix}.bias"])}

    def linear(prefix):
        return {"kernel": _np(sd[f"{prefix}.weight"]).T,
                "bias": _np(sd[f"{prefix}.bias"])}

    def layer(i):
        p = f"layers.{i}"
        q = linear(f"{p}.self_attn.q_proj")
        k = linear(f"{p}.self_attn.k_proj")
        v = linear(f"{p}.self_attn.v_proj")
        return {
            "ln_1": ln(f"{p}.self_attn_layer_norm"),
            "ln_2": ln(f"{p}.final_layer_norm"),
            "attn": {
                "c_attn": {
                    "kernel": np.concatenate(
                        [q["kernel"], k["kernel"], v["kernel"]], axis=1),
                    "bias": np.concatenate(
                        [q["bias"], k["bias"], v["bias"]]),
                },
                "c_proj": linear(f"{p}.self_attn.out_proj"),
            },
            "mlp": {"c_fc": linear(f"{p}.fc1"),
                    "c_proj": linear(f"{p}.fc2")},
        }

    params = {
        "wte": {"embedding": _np(sd["embed_tokens.weight"])},
        # OPTLearnedPositionalEmbedding indexes at position+2
        "wpe": {"embedding": _np(sd["embed_positions.weight"])[2:]},
        "ln_f": ln("final_layer_norm"),
    }
    _pack_gpt_layers(params, [layer(i) for i in range(cfg.n_layer)],
                     cfg.scan_layers)
    if not cfg.tie_word_embeddings:
        params["lm_head"] = _np(full_sd["lm_head.weight"]).T
    return GPT(cfg), params


# ---------------------------------------------------------------------------
# LLaMA family (beyond the reference snapshot's policy list — the same
# injection surface extended to the RMSNorm/SwiGLU/GQA generation)
# ---------------------------------------------------------------------------
def llama_from_hf(hf_model, dtype=jnp.bfloat16, **config_overrides):
    """``transformers.LlamaForCausalLM`` (and Mistral-style configs) ->
    ``(GPT, params)``: RMSNorm, SwiGLU, full rotary, grouped-query KV."""
    from deepspeed_tpu.models.transformer_lm import GPT, GPTConfig

    hc = hf_model.config
    if getattr(hc, "rope_scaling", None):
        raise ValueError(
            "rope_scaling (NTK/linear/llama3 scaled RoPE) is not supported "
            "by this policy; plain rope_theta only")
    if getattr(hc, "sliding_window", None):
        from deepspeed_tpu.utils.logging import logger

        logger.warning(
            "sliding_window=%s ignored: converted model attends over the "
            "full context (exact only for sequences within the window)",
            hc.sliding_window)
    kw = dict(
        vocab_size=hc.vocab_size,
        n_positions=hc.max_position_embeddings,
        n_embd=hc.hidden_size,
        n_layer=hc.num_hidden_layers,
        n_head=hc.num_attention_heads,
        n_kv_head=getattr(hc, "num_key_value_heads", None),
        intermediate_size=hc.intermediate_size,
        layer_norm_epsilon=hc.rms_norm_eps,
        norm="rmsnorm",
        activation={"silu": "silu", "gelu": "gelu"}[hc.hidden_act],
        gated_mlp=True,
        use_bias=False,
        attn_bias=bool(getattr(hc, "attention_bias", False)),
        rotary=True,
        rope_theta=float(getattr(hc, "rope_theta", 10000.0)),
        learned_positions=False,
        tie_word_embeddings=bool(hc.tie_word_embeddings),
        dropout=0.0, dtype=dtype,
    )
    kw.update(config_overrides)
    cfg = GPTConfig(**kw)

    full_sd = hf_model.state_dict()
    sd = {k.removeprefix("model."): v for k, v in full_sd.items()}

    def rms(prefix):
        return {"scale": _np(sd[f"{prefix}.weight"])}

    def linear(prefix, bias=False):
        out = {"kernel": _np(sd[f"{prefix}.weight"]).T}
        if bias:
            out["bias"] = _np(sd[f"{prefix}.bias"])
        return out

    ab = cfg.attn_bias

    def layer(i):
        p = f"layers.{i}"
        q = linear(f"{p}.self_attn.q_proj", bias=ab)
        k = linear(f"{p}.self_attn.k_proj", bias=ab)
        v = linear(f"{p}.self_attn.v_proj", bias=ab)
        c_attn = {"kernel": np.concatenate(
            [q["kernel"], k["kernel"], v["kernel"]], axis=1)}
        if ab:
            c_attn["bias"] = np.concatenate(
                [q["bias"], k["bias"], v["bias"]])
        return {
            "ln_1": rms(f"{p}.input_layernorm"),
            "ln_2": rms(f"{p}.post_attention_layernorm"),
            "attn": {
                "c_attn": c_attn,
                "c_proj": linear(f"{p}.self_attn.o_proj", bias=ab),
            },
            "mlp": {"c_gate": linear(f"{p}.mlp.gate_proj"),
                    "c_fc": linear(f"{p}.mlp.up_proj"),
                    "c_proj": linear(f"{p}.mlp.down_proj")},
        }

    params = {
        "wte": {"embedding": _np(sd["embed_tokens.weight"])},
        "ln_f": rms("norm"),
    }
    _pack_gpt_layers(params, [layer(i) for i in range(cfg.n_layer)],
                     cfg.scan_layers)
    if not cfg.tie_word_embeddings:
        params["lm_head"] = _np(full_sd["lm_head.weight"]).T
    return GPT(cfg), params


# ---------------------------------------------------------------------------
# Mixtral (beyond the reference snapshot: its MoE layer surface —
# deepspeed/moe/layer.py — extended to the HF sparse-MoE generation)
# ---------------------------------------------------------------------------
def mixtral_from_hf(hf_model, dtype=jnp.bfloat16, **config_overrides):
    """``transformers.MixtralForCausalLM`` -> ``(GPT, params)``: the LLaMA
    trunk (RMSNorm/GQA/rotary) with top-2 gated-SwiGLU experts mapped onto
    the expert-parallel MoE layer (moe/layer.py).

    Routing parity: Mixtral renormalizes the softmax over the top-2 logits,
    which equals our full-softmax-then-top-2-renormalize gating; eval
    capacity is set so no token drops (Mixtral has no capacity limit).
    """
    from deepspeed_tpu.models.transformer_lm import GPT, GPTConfig

    hc = hf_model.config
    if getattr(hc, "rope_scaling", None):
        raise ValueError("rope_scaling is not supported by this policy")
    if getattr(hc, "sliding_window", None):
        from deepspeed_tpu.utils.logging import logger

        logger.warning(
            "sliding_window=%s ignored: converted model attends over the "
            "full context (exact only for sequences within the window)",
            hc.sliding_window)
    E = hc.num_local_experts
    kw = dict(
        vocab_size=hc.vocab_size,
        n_positions=hc.max_position_embeddings,
        n_embd=hc.hidden_size,
        n_layer=hc.num_hidden_layers,
        n_head=hc.num_attention_heads,
        n_kv_head=hc.num_key_value_heads,
        intermediate_size=hc.intermediate_size,
        layer_norm_epsilon=hc.rms_norm_eps,
        norm="rmsnorm",
        activation={"silu": "silu"}[hc.hidden_act],
        use_bias=False,
        rotary=True,
        rope_theta=float(hc.rope_theta),
        learned_positions=False,
        tie_word_embeddings=bool(hc.tie_word_embeddings),
        moe_num_experts=E,
        moe_top_k=hc.num_experts_per_tok,
        moe_gated_experts=True,
        moe_aux_loss_coef=float(getattr(hc, "router_aux_loss_coef", 0.001)),
        # Mixtral itself has no capacity limit; capacity = tokens would be
        # exact but makes dispatch tensors O(E*T^2), so both train and
        # eval keep bounded factors (4x headroom over perfectly balanced
        # top-2 routing at eval — drops only under >4x imbalance; raise
        # moe_eval_capacity_factor toward num_local_experts for exactness
        # on short prompts)
        moe_capacity_factor=2.0,
        moe_eval_capacity_factor=4.0,
        dropout=0.0, dtype=dtype,
    )
    kw.update(config_overrides)
    cfg = GPTConfig(**kw)

    full_sd = hf_model.state_dict()
    sd = {k.removeprefix("model."): v for k, v in full_sd.items()}

    def rms(prefix):
        return {"scale": _np(sd[f"{prefix}.weight"])}

    def linear(prefix):
        return {"kernel": _np(sd[f"{prefix}.weight"]).T}

    def layer(i):
        p = f"layers.{i}"
        qw = linear(f"{p}.self_attn.q_proj")["kernel"]
        kw_ = linear(f"{p}.self_attn.k_proj")["kernel"]
        vw = linear(f"{p}.self_attn.v_proj")["kernel"]
        moe = f"{p}.block_sparse_moe"
        # experts.{e}.w1 = gate, w3 = up, w2 = down (all [out, in])
        wg = np.stack([_np(sd[f"{moe}.experts.{e}.w1.weight"]).T
                       for e in range(E)])
        wi = np.stack([_np(sd[f"{moe}.experts.{e}.w3.weight"]).T
                       for e in range(E)])
        wo = np.stack([_np(sd[f"{moe}.experts.{e}.w2.weight"]).T
                       for e in range(E)])
        return {
            "ln_1": rms(f"{p}.input_layernorm"),
            "ln_2": rms(f"{p}.post_attention_layernorm"),
            "attn": {
                "c_attn": {"kernel": np.concatenate([qw, kw_, vw], axis=1)},
                "c_proj": linear(f"{p}.self_attn.o_proj"),
            },
            "mlp": {
                "gate": {"kernel": _np(sd[f"{moe}.gate.weight"]).T},
                "experts": {"wi": wi, "wg": wg, "wo": wo},
            },
        }

    params = {
        "wte": {"embedding": _np(sd["embed_tokens.weight"])},
        "ln_f": rms("norm"),
    }
    _pack_gpt_layers(params, [layer(i) for i in range(cfg.n_layer)],
                     cfg.scan_layers)
    if not cfg.tie_word_embeddings:
        params["lm_head"] = _np(full_sd["lm_head.weight"]).T
    return GPT(cfg), params


# ---------------------------------------------------------------------------
# CLIP (reference HFCLIPLayerPolicy, replace_policy.py:186 + DSClipEncoder)
# ---------------------------------------------------------------------------
def clip_from_hf(hf_model, dtype=jnp.bfloat16, **config_overrides):
    """``transformers.CLIPModel`` -> ``(CLIPModel, params)``: both towers
    convert onto the shared GPT-trunk blocks (quick_gelu; causal text,
    bidirectional vision)."""
    from deepspeed_tpu.models.clip import (
        CLIPModel,
        CLIPTextConfig,
        CLIPVisionConfig,
    )

    import dataclasses as _dc

    tc, vc = hf_model.config.text_config, hf_model.config.vision_config
    text_cfg = CLIPTextConfig(
        vocab_size=tc.vocab_size, hidden_size=tc.hidden_size,
        num_hidden_layers=tc.num_hidden_layers,
        num_attention_heads=tc.num_attention_heads,
        intermediate_size=tc.intermediate_size,
        max_position_embeddings=tc.max_position_embeddings,
        layer_norm_eps=tc.layer_norm_eps, hidden_act=tc.hidden_act,
        projection_dim=hf_model.config.projection_dim,
        eos_token_id=tc.eos_token_id, dtype=dtype)
    vision_cfg = CLIPVisionConfig(
        image_size=vc.image_size, patch_size=vc.patch_size,
        num_channels=vc.num_channels, hidden_size=vc.hidden_size,
        num_hidden_layers=vc.num_hidden_layers,
        num_attention_heads=vc.num_attention_heads,
        intermediate_size=vc.intermediate_size,
        layer_norm_eps=vc.layer_norm_eps, hidden_act=vc.hidden_act,
        projection_dim=hf_model.config.projection_dim, dtype=dtype)
    # overrides apply to whichever tower config defines the field (dtype,
    # param_dtype, scan_layers, ... — like the sibling converters)
    for key, val in config_overrides.items():
        applied = False
        for cfg in ("text", "vision"):
            c = text_cfg if cfg == "text" else vision_cfg
            if any(f.name == key for f in _dc.fields(c)):
                if cfg == "text":
                    text_cfg = _dc.replace(text_cfg, **{key: val})
                else:
                    vision_cfg = _dc.replace(vision_cfg, **{key: val})
                applied = True
        if not applied:
            raise ValueError(f"unknown CLIP config override {key!r}")
    if text_cfg.scan_layers is not True or vision_cfg.scan_layers is not True:
        raise NotImplementedError(
            "clip_from_hf packs layers in the scan layout only")

    full_sd = {k: v for k, v in hf_model.state_dict().items()}

    def ln(prefix):
        return {"scale": _np(full_sd[f"{prefix}.weight"]),
                "bias": _np(full_sd[f"{prefix}.bias"])}

    def linear(prefix):
        return {"kernel": _np(full_sd[f"{prefix}.weight"]).T,
                "bias": _np(full_sd[f"{prefix}.bias"])}

    def tower_layers(tower, n_layer):
        def layer(i):
            p = f"{tower}.encoder.layers.{i}"
            q = linear(f"{p}.self_attn.q_proj")
            k = linear(f"{p}.self_attn.k_proj")
            v = linear(f"{p}.self_attn.v_proj")
            return {
                "ln_1": ln(f"{p}.layer_norm1"),
                "ln_2": ln(f"{p}.layer_norm2"),
                "attn": {
                    "c_attn": {
                        "kernel": np.concatenate(
                            [q["kernel"], k["kernel"], v["kernel"]], axis=1),
                        "bias": np.concatenate(
                            [q["bias"], k["bias"], v["bias"]]),
                    },
                    "c_proj": linear(f"{p}.self_attn.out_proj"),
                },
                "mlp": {"c_fc": linear(f"{p}.mlp.fc1"),
                        "c_proj": linear(f"{p}.mlp.fc2")},
            }

        return {"block": _stack([layer(i) for i in range(n_layer)])}

    text_params = {
        "token_embedding": {"embedding": _np(
            full_sd["text_model.embeddings.token_embedding.weight"])},
        "position_embedding": {"embedding": _np(
            full_sd["text_model.embeddings.position_embedding.weight"])},
        "h": tower_layers("text_model", text_cfg.num_hidden_layers),
        "ln_f": ln("text_model.final_layer_norm"),
        "text_projection": {
            "kernel": _np(full_sd["text_projection.weight"]).T},
    }
    # note the reference-era HF key typo: "pre_layrnorm"
    pre_ln_key = ("vision_model.pre_layrnorm"
                  if "vision_model.pre_layrnorm.weight" in full_sd
                  else "vision_model.pre_layernorm")
    vision_params = {
        "patch_embedding": {"kernel": _np(
            full_sd["vision_model.embeddings.patch_embedding.weight"]
        ).transpose(2, 3, 1, 0)},
        "class_embedding": _np(
            full_sd["vision_model.embeddings.class_embedding"]),
        "position_embedding": {"embedding": _np(
            full_sd["vision_model.embeddings.position_embedding.weight"])},
        "pre_layernorm": ln(pre_ln_key),
        "h": tower_layers("vision_model", vision_cfg.num_hidden_layers),
        "post_layernorm": ln("vision_model.post_layernorm"),
        "visual_projection": {
            "kernel": _np(full_sd["visual_projection.weight"]).T},
    }
    params = {"text_model": text_params, "vision_model": vision_params,
              "logit_scale": _np(full_sd["logit_scale"])}
    return CLIPModel(text_cfg, vision_cfg), params


# ---------------------------------------------------------------------------
# BLOOM (reference BLOOMLayerPolicy, replace_policy.py:444) — ALiBi position
# bias, LN on the word embeddings, per-head-interleaved fused qkv
# ---------------------------------------------------------------------------
def bloom_from_hf(hf_model, dtype=jnp.bfloat16, **config_overrides):
    """``transformers.BloomForCausalLM`` -> ``(GPT, params)``."""
    from deepspeed_tpu.models.transformer_lm import GPT, GPTConfig

    hc = hf_model.config
    if getattr(hc, "apply_residual_connection_post_layernorm", False):
        raise ValueError(
            "apply_residual_connection_post_layernorm BLOOM variants are "
            "not supported (pre-LN residual only)")
    kw = dict(
        vocab_size=hc.vocab_size,
        n_positions=int(config_overrides.pop("n_positions", 2048)),
        n_embd=hc.hidden_size,
        n_layer=hc.n_layer,
        n_head=hc.n_head,
        layer_norm_epsilon=hc.layer_norm_epsilon,
        activation="gelu_tanh",  # BloomGelu IS the tanh approximation
        alibi=True,
        embed_layernorm=True,
        learned_positions=False,
        tie_word_embeddings=True,
        dropout=0.0, dtype=dtype,
    )
    kw.update(config_overrides)
    cfg = GPTConfig(**kw)

    sd = {k.removeprefix("transformer."): v
          for k, v in hf_model.state_dict().items()}
    H, D = cfg.n_head, cfg.head_dim

    def ln(prefix):
        return {"scale": _np(sd[f"{prefix}.weight"]),
                "bias": _np(sd[f"{prefix}.bias"])}

    def qkv(i):
        # fused [3C, C] with per-head interleave [H, 3, D] on the rows —
        # the same de-interleave the reference's policy applies before MP
        # slicing (replace_policy.py:462 attention.query_key_value)
        return _deinterleave_qkv(
            _np(sd[f"h.{i}.self_attention.query_key_value.weight"]),
            _np(sd[f"h.{i}.self_attention.query_key_value.bias"]), H, D)

    def linear(prefix):
        return {"kernel": _np(sd[f"{prefix}.weight"]).T,
                "bias": _np(sd[f"{prefix}.bias"])}

    def layer(i):
        p = f"h.{i}"
        return {
            "ln_1": ln(f"{p}.input_layernorm"),
            "ln_2": ln(f"{p}.post_attention_layernorm"),
            "attn": {"c_attn": qkv(i),
                     "c_proj": linear(f"{p}.self_attention.dense")},
            "mlp": {"c_fc": linear(f"{p}.mlp.dense_h_to_4h"),
                    "c_proj": linear(f"{p}.mlp.dense_4h_to_h")},
        }

    params = {
        "wte": {"embedding": _np(sd["word_embeddings.weight"])},
        "ln_embed": ln("word_embeddings_layernorm"),
        "ln_f": ln("ln_f"),
    }
    _pack_gpt_layers(params, [layer(i) for i in range(cfg.n_layer)],
                     cfg.scan_layers)
    return GPT(cfg), params


# ---------------------------------------------------------------------------
# Megatron-LM GPT (reference MegatronLayerPolicy, replace_policy.py:324;
# checkpoint layout also consumed by state_dict_factory.MegatronSDLoader).
# Megatron is not an importable dependency here, so the policy converts the
# CHECKPOINT layout (a state dict) rather than walking live modules.
# ---------------------------------------------------------------------------
def megatron_gpt_from_sd(state_dict: Dict[str, Any], n_layer: int,
                         n_head: int, dtype=jnp.bfloat16,
                         **config_overrides):
    """Megatron-LM GPT2Model state dict -> ``(GPT, params)``.

    Accepts both the raw module layout (``language_model.embedding...``)
    and checkpoint wrappers holding it under ``model``/``module``. The
    fused qkv rows interleave per head like NeoX (``[H, 3, D]``).
    """
    from deepspeed_tpu.models.transformer_lm import GPT, GPTConfig

    sd = state_dict
    for wrap in ("model", "module"):
        if wrap in sd and isinstance(sd[wrap], dict):
            sd = sd[wrap]
    flat = {}
    for k, v in sd.items():
        flat[k.removeprefix("language_model.")] = v
    sd = flat

    wte = _np(sd["embedding.word_embeddings.weight"])
    wpe = _np(sd["embedding.position_embeddings.weight"])
    n_embd = wte.shape[1]
    kw = dict(
        vocab_size=wte.shape[0],
        n_positions=wpe.shape[0],
        n_embd=n_embd,
        n_layer=n_layer,
        n_head=n_head,
        activation="gelu_tanh",
        tie_word_embeddings=True,
        dropout=0.0, dtype=dtype,
    )
    kw.update(config_overrides)
    cfg = GPTConfig(**kw)
    H, D = cfg.n_head, cfg.head_dim

    def ln(prefix):
        return {"scale": _np(sd[f"{prefix}.weight"]),
                "bias": _np(sd[f"{prefix}.bias"])}

    def qkv(i):
        return _deinterleave_qkv(
            _np(sd[f"transformer.layers.{i}.attention.query_key_value"
                   ".weight"]),
            _np(sd[f"transformer.layers.{i}.attention.query_key_value"
                   ".bias"]), H, D)

    def linear(prefix):
        return {"kernel": _np(sd[f"{prefix}.weight"]).T,
                "bias": _np(sd[f"{prefix}.bias"])}

    def layer(i):
        p = f"transformer.layers.{i}"
        return {
            "ln_1": ln(f"{p}.input_layernorm"),
            "ln_2": ln(f"{p}.post_attention_layernorm"),
            "attn": {"c_attn": qkv(i),
                     "c_proj": linear(f"{p}.attention.dense")},
            "mlp": {"c_fc": linear(f"{p}.mlp.dense_h_to_4h"),
                    "c_proj": linear(f"{p}.mlp.dense_4h_to_h")},
        }

    params = {
        "wte": {"embedding": wte},
        "wpe": {"embedding": wpe},
        "ln_f": ln("transformer.final_layernorm"),
    }
    _pack_gpt_layers(params, [layer(i) for i in range(cfg.n_layer)],
                     cfg.scan_layers)
    return GPT(cfg), params


# ---------------------------------------------------------------------------
# dispatch (reference replace_policy.py generic_policies / policy match in
# replace_module.py:277)
# ---------------------------------------------------------------------------
_HF_CONVERTERS = {
    "GPT2LMHeadModel": gpt2_from_hf,
    "GPT2Model": gpt2_from_hf,  # tied head: no extra params needed
    "BertForMaskedLM": bert_from_hf,
    "BertForPreTraining": bert_from_hf,
    # (bare BertModel is NOT convertible: our BertForPreTraining target
    # unconditionally owns MLM-head params the headless state dict lacks)
    "GPTNeoXForCausalLM": gptneox_from_hf,
    "GPTJForCausalLM": gptj_from_hf,
    "OPTForCausalLM": opt_from_hf,
    "LlamaForCausalLM": llama_from_hf,
    "MistralForCausalLM": llama_from_hf,
    "MixtralForCausalLM": mixtral_from_hf,
    "BloomForCausalLM": bloom_from_hf,
    "BloomModel": bloom_from_hf,  # tied head
    "CLIPModel": clip_from_hf,
}


# ---------------------------------------------------------------------------
# export: flax params -> HF GPT-2 state dict (the reverse policy; reference
# save_mp_checkpoint_path writes HF-loadable shards from injected modules,
# replace_module.py — here the engine's trained params convert back so
# checkpoints round-trip into the HF ecosystem)
# ---------------------------------------------------------------------------
def gpt2_to_hf_state_dict(params: Dict[str, Any], n_layer: int,
                          scan_layers: bool = True) -> Dict[str, np.ndarray]:
    """GPT param tree (GPT-2 architecture knobs) -> HF ``GPT2LMHeadModel``
    state dict (numpy; caller wraps in torch tensors if needed)."""
    import jax

    def _n(x):
        return np.asarray(x, np.float32)

    sd: Dict[str, np.ndarray] = {
        "transformer.wte.weight": _n(params["wte"]["embedding"]),
        "transformer.wpe.weight": _n(params["wpe"]["embedding"]),
        "transformer.ln_f.weight": _n(params["ln_f"]["scale"]),
        "transformer.ln_f.bias": _n(params["ln_f"]["bias"]),
    }
    sd["lm_head.weight"] = sd["transformer.wte.weight"]  # tied

    def layer_tree(i):
        if scan_layers:
            blk = params["h"]["block"]
            return jax.tree.map(lambda x: x[i], blk)
        return params[f"h_{i}"]

    for i in range(n_layer):
        lp = layer_tree(i)
        p = f"transformer.h.{i}"
        for ln in ("ln_1", "ln_2"):
            sd[f"{p}.{ln}.weight"] = _n(lp[ln]["scale"])
            sd[f"{p}.{ln}.bias"] = _n(lp[ln]["bias"])
        for mod, names in (("attn", ("c_attn", "c_proj")),
                           ("mlp", ("c_fc", "c_proj"))):
            for nm in names:
                sd[f"{p}.{mod}.{nm}.weight"] = _n(lp[mod][nm]["kernel"])
                sd[f"{p}.{mod}.{nm}.bias"] = _n(lp[mod][nm]["bias"])
    return sd


def _converter_for(model):
    """Match the model's class or any base class (fine-tuned subclasses and
    wrappers convert via their HF parent)."""
    for klass in type(model).__mro__:
        conv = _HF_CONVERTERS.get(klass.__name__)
        if conv is not None:
            return conv
    return None


def is_hf_model(model) -> bool:
    """True for a torch-backed transformers model we can convert."""
    # flax modules have no state_dict; torch modules always do
    return (hasattr(model, "state_dict") and hasattr(model, "config")
            and _converter_for(model) is not None)


def import_hf_model(model, dtype=jnp.bfloat16, **config_overrides
                    ) -> Tuple[Any, Dict[str, Any]]:
    """Convert a supported HF torch model to ``(flax module, params)``."""
    conv = _converter_for(model)
    if conv is None:
        raise ValueError(
            f"no HF injection policy for {type(model).__name__}; "
            f"supported: {sorted(_HF_CONVERTERS)}")
    return conv(model, dtype=dtype, **config_overrides)

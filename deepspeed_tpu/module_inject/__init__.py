"""Injection policies — tensor-parallel sharding rules per architecture.

Parity with reference ``deepspeed/module_inject/replace_policy.py`` (per-
architecture weight maps: HFGPT2 :404, HFBert :124, ...) and
``ReplaceWithTensorSlicing`` (replace_module.py:18): on TPU, "kernel
injection with tensor slicing" is a ``path, shape -> PartitionSpec``
function applied as jit shardings — no module surgery, XLA emits the
column/row-parallel collectives (LinearLayer/LinearAllreduce,
module_inject/layers.py:9/25) from the specs.
"""

from typing import Callable, Optional

_POLICIES = {}


def register_policy(name: str, rules: Callable) -> None:
    _POLICIES[name.lower()] = rules


def policy_for(model) -> Optional[Callable]:
    """Resolve TP rules for a model: an explicit ``tp_rules`` attribute wins
    (the generic path, like reference replace_wo_policy :773); otherwise the
    registry is consulted by class name (the policy path :277)."""
    rules = getattr(model, "tp_rules", None)
    if rules is not None:
        return rules
    return _POLICIES.get(type(model).__name__.lower())


def _builtin_policies():
    from deepspeed_tpu.models.bert import bert_tp_rules
    from deepspeed_tpu.models.transformer_lm import gpt_tp_rules

    register_policy("gpt", gpt_tp_rules)
    register_policy("bertforpretraining", bert_tp_rules)


_builtin_policies()


def __getattr__(name):
    # HF checkpoint-import policies (heavy deps: torch/transformers) load
    # lazily; ``from deepspeed_tpu.module_inject import import_hf_model``
    _hf_api = ("import_hf_model", "is_hf_model", "gpt2_from_hf",
               "bert_from_hf", "gptneox_from_hf", "gptj_from_hf",
               "opt_from_hf", "llama_from_hf", "mixtral_from_hf",
               "bloom_from_hf", "megatron_gpt_from_sd",
               "clip_from_hf", "gpt2_to_hf_state_dict",
               "gpt2_config_from_hf", "gpt2_params_from_hf",
               "bert_config_from_hf", "bert_params_from_hf")
    if name in _hf_api:
        from deepspeed_tpu.module_inject import hf

        return getattr(hf, name)
    # diffusers UNet policy (state-dict level; reference replace_policy.py:30)
    if name in ("unet_from_sd", "unet_attention_from_sd", "DSUNetAttention"):
        from deepspeed_tpu.module_inject import unet

        return getattr(unet, name)
    raise AttributeError(name)

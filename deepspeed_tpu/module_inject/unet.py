"""Diffusers UNet injection policy as a state-dict converter.

Reference parity: ``module_inject/replace_policy.py:30`` (UNetPolicy) fuses
the q/k/v projections of every attention block inside a diffusers
``UNet2DConditionModel`` for the fused inference kernels, and
``model_implementations/diffusers/unet.py`` (DSUNet) wraps the whole model
for CUDA-graph replay.

TPU re-design: ``diffusers`` is not importable in this environment, so —
exactly like ``megatron_gpt_from_sd`` (hf.py) does for Megatron — the policy
consumes the CHECKPOINT layout rather than walking live torch modules: it
scans a diffusers-format state dict for attention blocks
(``*.to_q/.to_k/.to_v/.to_out.0``), fuses each into the layout
:class:`DSUNetAttention` consumes (one qkv matmul for self-attention — the
reference policy's first branch — or q + fused kv for cross-attention, its
second branch), and returns flax modules + params. The CUDA-graph wrapper
needs no counterpart: ``jax.jit`` IS the graph capture on TPU
(docs/DIVERGENCES.md).
"""

from typing import Any, Dict, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


def _np(x) -> np.ndarray:
    """torch tensor / array-like -> float32 numpy (no torch import needed)."""
    if hasattr(x, "detach"):
        x = x.detach().cpu().numpy()
    return np.asarray(x, dtype=np.float32)


class DSUNetAttention(nn.Module):
    """Fused (cross-)attention block matching diffusers ``CrossAttention``
    semantics: no q/k/v bias, ``softmax(q k^T / sqrt(d)) v``, biased output
    projection. Self-attention runs one fused qkv matmul (reference
    UNetPolicy.attention branch 1, replace_policy.py:47); cross-attention
    fuses k and v over the context (branch 2 keeps them separate — one
    matmul fewer here)."""

    heads: int
    inner_dim: int           # heads * dim_head
    out_dim: int             # query_dim (to_out output features)
    self_attention: bool
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, hidden, context=None):
        if self.self_attention:
            assert context is None, "self-attention block got a context"
            qkv = nn.Dense(3 * self.inner_dim, use_bias=False,
                           dtype=self.dtype, name="to_qkv")(hidden)
            q, k, v = jnp.split(qkv, 3, axis=-1)
        else:
            ctx = hidden if context is None else context
            q = nn.Dense(self.inner_dim, use_bias=False, dtype=self.dtype,
                         name="to_q")(hidden)
            kv = nn.Dense(2 * self.inner_dim, use_bias=False,
                          dtype=self.dtype, name="to_kv")(ctx)
            k, v = jnp.split(kv, 2, axis=-1)

        B, N, _ = q.shape
        M = k.shape[1]
        d = self.inner_dim // self.heads
        q = q.reshape(B, N, self.heads, d)
        k = k.reshape(B, M, self.heads, d)
        v = v.reshape(B, M, self.heads, d)
        scores = jnp.einsum("bnhd,bmhd->bhnm", q, k) * (d ** -0.5)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        out = jnp.einsum("bhnm,bmhd->bnhd", probs.astype(v.dtype), v)
        out = out.reshape(B, N, self.inner_dim)
        return nn.Dense(self.out_dim, use_bias=True, dtype=self.dtype,
                        name="to_out")(out)


def unet_attention_from_sd(sd: Dict[str, Any], prefix: str, heads: int,
                           dtype=jnp.float32
                           ) -> Tuple[DSUNetAttention, Dict[str, Any]]:
    """One attention block's weights -> ``(DSUNetAttention, params)``.

    ``prefix`` addresses the block (e.g.
    ``down_blocks.0.attentions.0.transformer_blocks.0.attn1``); ``heads``
    comes from the model config, exactly as the reference policy reads
    ``client_module.heads`` (replace_policy.py:56) — a state dict alone
    does not record it.
    """
    qw = _np(sd[f"{prefix}.to_q.weight"])          # torch [inner, q_dim]
    kw = _np(sd[f"{prefix}.to_k.weight"])          # torch [inner, ctx_dim]
    vw = _np(sd[f"{prefix}.to_v.weight"])
    ow = _np(sd[f"{prefix}.to_out.0.weight"])      # torch [q_dim, inner]
    ob = _np(sd[f"{prefix}.to_out.0.bias"])
    inner = qw.shape[0]
    if inner % heads:
        raise ValueError(
            f"{prefix}: inner dim {inner} not divisible by heads={heads}")
    # diffusers naming is authoritative (attn1 = self, attn2 = cross): a
    # UNet whose cross_attention_dim equals the block width would fool the
    # shape heuristic the reference policy uses, and a fused-qkv module
    # cannot accept a context at inference. Shapes are the fallback for
    # nonstandard prefixes.
    if prefix.endswith(".attn1"):
        self_attn = True
    elif prefix.endswith(".attn2"):
        self_attn = False
    else:
        self_attn = qw.shape[1] == kw.shape[1]
    if self_attn and qw.shape[1] != kw.shape[1]:
        raise ValueError(
            f"{prefix}: named self-attention but q/k input dims differ "
            f"({qw.shape[1]} vs {kw.shape[1]})")

    out_p = {"kernel": ow.T, "bias": ob}
    if self_attn:
        params = {
            "to_qkv": {"kernel": np.concatenate([qw, kw, vw], axis=0).T},
            "to_out": out_p,
        }
    else:
        params = {
            "to_q": {"kernel": qw.T},
            "to_kv": {"kernel": np.concatenate([kw, vw], axis=0).T},
            "to_out": out_p,
        }
    module = DSUNetAttention(
        heads=heads, inner_dim=inner, out_dim=ow.shape[0],
        self_attention=self_attn, dtype=dtype)
    return module, params


def unet_from_sd(sd: Dict[str, Any], heads: int, dtype=jnp.float32
                 ) -> Dict[str, Tuple[DSUNetAttention, Dict[str, Any]]]:
    """Scan a diffusers UNet state dict and convert EVERY attention block
    (the modules the reference UNetPolicy targets; the conv backbone stays
    with its source runtime). Returns ``{block_prefix: (module, params)}``.

    ``heads`` may be an int (uniform, SD-1.x style) or a callable
    ``prefix -> int`` for UNets with per-resolution head counts.
    """
    prefixes = sorted(
        k[: -len(".to_q.weight")] for k in sd if k.endswith(".to_q.weight"))
    if not prefixes:
        raise ValueError(
            "no attention blocks (*.to_q.weight) found: not a diffusers "
            "UNet-style state dict")
    get_heads = heads if callable(heads) else (lambda _p: heads)
    return {
        p: unet_attention_from_sd(sd, p, get_heads(p), dtype=dtype)
        for p in prefixes
    }

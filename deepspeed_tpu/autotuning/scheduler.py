"""Parallel experiment scheduling over a host pool.

Counterpart of reference ``autotuning/scheduler.py:27`` (ResourceManager):
the reference keeps a queue of tuning experiments and a pool of nodes,
assigns each experiment the nodes it needs, launches it through the
multi-node runner, and reaps completions to free the nodes. The TPU-native
shape is the same resource loop with the torch/NCCL specifics removed: a
bounded worker pool drains the experiment list, each worker leases one
host from the pool for the lifetime of its experiment (one experiment per
host — a relaunched TPU script owns the host's chips via the per-HOST
process model, launcher/runner.py), and results come back in experiment
order. On a single host the pool has one lease and the schedule
degenerates to the sequential loop.
"""

import queue
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence


class ResourceManager:
    """Lease-based experiment scheduler.

    ``hosts``: ordered ``{hostname: slots}`` (the ``fetch_hostfile``
    shape); ``None``/empty means the local host only. Slots do not
    subdivide an experiment — one experiment leases one whole host, the
    reference's default when an experiment needs all of a node's devices.
    """

    def __init__(self, hosts: Optional[Dict[str, int]] = None,
                 max_parallel: Optional[int] = None):
        names = list(hosts) if hosts else ["localhost"]
        self.hosts = names
        self.max_parallel = min(max_parallel or len(names), len(names))

    def run(self, experiments: Sequence[Any],
            launch_fn: Callable[[int, Any, str], Any]) -> List[Any]:
        """Run ``launch_fn(index, experiment, host)`` for every experiment,
        at most ``max_parallel`` concurrently, never two concurrent
        experiments on one host. Returns results in experiment order; a
        launch_fn exception becomes that experiment's result (the loop
        never dies half-scheduled — the reference's fault model, where a
        failed experiment is recorded and the node is reclaimed)."""
        results: List[Any] = [None] * len(experiments)
        if not experiments:
            return results
        pool: "queue.Queue[str]" = queue.Queue()
        for h in self.hosts[: self.max_parallel]:
            pool.put(h)
        work: "queue.Queue[int]" = queue.Queue()
        for i in range(len(experiments)):
            work.put(i)

        def worker():
            while True:
                try:
                    i = work.get_nowait()
                except queue.Empty:
                    return
                host = pool.get()  # lease: blocks until a host frees up
                try:
                    results[i] = launch_fn(i, experiments[i], host)
                except Exception as e:  # noqa: BLE001 — recorded, not fatal
                    results[i] = e
                finally:
                    pool.put(host)
                    work.task_done()

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(self.max_parallel)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return results

"""Autotuner (reference ``autotuning/autotuner.py:31`` + scheduler.py).

Reference flow: profile the model once, generate ZeRO-stage x micro-batch
experiment configs from templates, launch each as a separate job via the
resource manager, read back metrics, pick the best config. TPU re-design:
experiments run IN-PROCESS — each candidate builds a fresh engine, runs a
few measured steps on the real compiled program, and reports throughput.
That keeps the semantics (real measured steps, not a model) while dropping
the multi-job machinery a single TPU host doesn't need; multi-host sweeps
can still fan the same experiment list out via the launcher.
"""

import itertools
import time
from typing import Any, Callable, Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

DEFAULT_TUNING_MICRO_BATCHES = (1, 2, 4, 8)
DEFAULT_ZERO_STAGES = (0, 1, 2, 3)


class AutotuningConfig:
    """Parse the reference's ``autotuning`` block (constants.py keys)."""

    def __init__(self, d: Optional[Dict[str, Any]] = None):
        d = d or {}
        self.enabled = d.get("enabled", False)
        self.fast = d.get("fast", True)
        self.metric = d.get("metric", "throughput")
        self.start_profile_step = d.get("start_profile_step", 3)
        self.end_profile_step = d.get("end_profile_step", 5)
        self.tuner_type = d.get("tuner_type", "gridsearch")
        self.tuner_num_trials = d.get("tuner_num_trials", 50)
        self.tuner_early_stopping = d.get("tuner_early_stopping", 5)
        self.max_train_micro_batch_size_per_gpu = d.get(
            "max_train_micro_batch_size_per_gpu", 64)
        self.min_train_micro_batch_size_per_gpu = d.get(
            "min_train_micro_batch_size_per_gpu", 1)
        self.num_tuning_micro_batch_sizes = d.get(
            "num_tuning_micro_batch_sizes", 3)
        self.zero_stages = d.get("zero_stages", list(DEFAULT_ZERO_STAGES))
        self.mp_size = d.get("mp_size", 1)
        # TPU extension dimensions (absent -> dimension collapsed, base
        # config untouched): the knobs that actually move throughput on
        # TPU are remat policy, the tensor-parallel degree, and optimizer
        # offload — not just stage x micro-batch
        self.tp_sizes = d.get("tp_sizes", None)
        self.remat_policies = d.get("remat_policies", None)
        self.offload_devices = d.get("offload_devices", None)
        if self.remat_policies is not None:
            bad = set(self.remat_policies) - {"none", "selective", "full"}
            if bad:
                raise ValueError(f"unknown remat policies {sorted(bad)}")
        if self.offload_devices is not None:
            bad = set(self.offload_devices) - {"none", "cpu", "nvme"}
            if bad:
                raise ValueError(f"unknown offload devices {sorted(bad)}")
        if self.tp_sizes is not None:
            if not all(isinstance(t, int) and t >= 1
                       for t in self.tp_sizes):
                raise ValueError(
                    f"tp_sizes must be positive ints, got {self.tp_sizes}")
        if self.metric not in ("throughput", "latency", "flops"):
            raise ValueError(f"unknown autotuning metric {self.metric!r}")
        if self.tuner_type not in ("gridsearch", "random", "model_based"):
            raise ValueError(
                f"unknown tuner_type {self.tuner_type!r}; expected "
                f"gridsearch|random|model_based")


class Autotuner:
    """Generate and evaluate (zero_stage, micro_batch) experiments."""

    def __init__(self, base_config: Dict[str, Any],
                 tuning_config: Optional[Dict[str, Any]] = None):
        self.base_config = dict(base_config)
        self.base_config.pop("autotuning", None)
        self.cfg = AutotuningConfig(
            tuning_config
            if tuning_config is not None
            else base_config.get("autotuning", {}))

    # ------------------------------------------------------------------
    def generate_experiments(self) -> List[Dict[str, Any]]:
        """ZeRO-stage x micro-batch grid (reference _generate_experiments
        from config_templates/template_zero*.json). Micro batches are
        powers of two SPANNING [min, max], subsampled evenly to
        num_tuning_micro_batch_sizes (largest always kept — it is usually
        the throughput winner)."""
        lo = self.cfg.min_train_micro_batch_size_per_gpu
        hi = self.cfg.max_train_micro_batch_size_per_gpu
        candidates = []
        m = 1
        while m <= hi:
            if m >= lo:
                candidates.append(m)
            m *= 2
        if not candidates:
            candidates = [lo]
        n = min(self.cfg.num_tuning_micro_batch_sizes, len(candidates))
        # even spread ANCHORED AT THE LARGEST candidate (usually the
        # throughput winner): n=1 must pick max, not min
        last = len(candidates) - 1
        idx = [last - round(i * last / max(n - 1, 1))
               for i in range(n)] if n > 1 else [last]
        mbs = sorted({candidates[i] for i in idx})
        # optional TPU dimensions multiply in only when configured
        extra_dims = []
        for key, values in (("tp_size", self.cfg.tp_sizes),
                            ("remat_policy", self.cfg.remat_policies),
                            ("offload_device", self.cfg.offload_devices)):
            if values:
                extra_dims.append([(key, v) for v in values])
        exps = []
        for combo in itertools.product(self.cfg.zero_stages, mbs,
                                       *extra_dims):
            stage, mb = combo[0], combo[1]
            exp = {"zero_stage": stage,
                   "train_micro_batch_size_per_gpu": mb}
            exp.update(dict(combo[2:]))
            exps.append(exp)
        return exps

    def exp_to_config(self, exp: Dict[str, Any]) -> Dict[str, Any]:
        cfg = dict(self.base_config)
        cfg["train_micro_batch_size_per_gpu"] = \
            exp["train_micro_batch_size_per_gpu"]
        cfg.pop("train_batch_size", None)  # re-derived from micro batch
        zero = dict(cfg.get("zero_optimization", {}))
        zero["stage"] = exp["zero_stage"]
        if "offload_device" in exp:
            if exp["offload_device"] == "none":
                zero.pop("offload_optimizer", None)
                # the deprecated alias would re-create the offload block
                zero.pop("cpu_offload", None)
            else:
                # preserve user-set fields (nvme_path, pin_memory, ...)
                zero["offload_optimizer"] = {
                    **(zero.get("offload_optimizer") or {}),
                    "device": exp["offload_device"]}
        cfg["zero_optimization"] = zero
        if "tp_size" in exp or "remat_policy" in exp:
            tpu = dict(cfg.get("tpu", {}))
            if "remat_policy" in exp:
                tpu["remat"] = exp["remat_policy"]
            if "tp_size" in exp:
                mesh = dict(tpu.get("mesh", {}))
                mesh["tp"] = exp["tp_size"]
                mesh.setdefault("dp", -1)
                tpu["mesh"] = mesh
            cfg["tpu"] = tpu
        return cfg

    # ------------------------------------------------------------------
    def measure(self, model_factory: Callable[[], Any],
                data: List[Any], exp: Dict[str, Any]) -> Optional[float]:
        """Run one experiment in-process; returns the metric (higher is
        better) or None if the config fails (e.g. OOM)."""
        import deepspeed_tpu
        from deepspeed_tpu.runtime.dataloader import RepeatingLoader

        config = self.exp_to_config(exp)
        try:
            engine, _, loader, _ = deepspeed_tpu.initialize(
                model=model_factory(), config=config, training_data=data)
            it = iter(RepeatingLoader(loader))
            for _ in range(self.cfg.start_profile_step):
                engine.train_batch(it)  # warmup + compile
            steps = max(self.cfg.end_profile_step
                        - self.cfg.start_profile_step, 1)
            from deepspeed_tpu.utils.timer import fence

            fence(engine.params)
            t0 = time.perf_counter()
            for _ in range(steps):
                engine.train_batch(it)
            fence(engine.params)
            dt = (time.perf_counter() - t0) / steps
        except Exception as e:
            logger.warning(f"experiment {exp} failed: {e}")
            return None
        samples = engine.train_batch_size
        if self.cfg.metric == "latency":
            return -dt
        # throughput (and flops ~ proportional at fixed model)
        return samples / dt

    # ------------------------------------------------------------------
    def tune(self, model_factory: Callable[[], Any],
             data: List[Any]) -> Dict[str, Any]:
        """Full loop: returns the best full engine config."""
        from deepspeed_tpu.autotuning.tuner import (
            GridSearchTuner,
            ModelBasedTuner,
            RandomTuner,
        )

        exps = self.generate_experiments()
        tuner_cls = {"gridsearch": GridSearchTuner,
                     "random": RandomTuner,
                     "model_based": ModelBasedTuner}[self.cfg.tuner_type]
        tuner = tuner_cls(
            exps, lambda e: self.measure(model_factory, data, e),
            early_stopping=self.cfg.tuner_early_stopping)
        best = tuner.tune(self.cfg.tuner_num_trials)
        if best is None:
            raise RuntimeError("autotuning found no working experiment")
        logger.info(
            f"autotuning best: {best} "
            f"({self.cfg.metric}={tuner.best_metric:.2f}); "
            f"{len(tuner.records)} experiments evaluated")
        self.records = tuner.records
        return self.exp_to_config(best)

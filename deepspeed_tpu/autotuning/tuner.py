"""Experiment-selection strategies (reference ``autotuning/tuner/``:
index_based_tuner.py grid/random, model_based_tuner.py + cost_model.py).

The model-based tuner replaces the reference's xgboost cost model with an
incrementally-fit ridge regression over one-hot experiment features —
no extra dependency, same role: predict the metric for unexplored
experiments and evaluate the most promising first.
"""

import random
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

Experiment = Dict[str, Any]


class BaseTuner:
    def __init__(self, exps: List[Experiment],
                 metric_fn: Callable[[Experiment], Optional[float]],
                 early_stopping: int = 0):
        self.all_exps = list(exps)
        self.metric_fn = metric_fn
        self.early_stopping = early_stopping
        self.records: List[Tuple[Experiment, Optional[float]]] = []
        self.best_exp: Optional[Experiment] = None
        self.best_metric = -float("inf")

    def next_batch(self, k: int) -> List[Experiment]:
        raise NotImplementedError

    def tune(self, num_trials: Optional[int] = None) -> Experiment:
        budget = num_trials or len(self.all_exps)
        stale = 0
        while self.all_exps and len(self.records) < budget:
            for exp in self.next_batch(1):
                metric = self.metric_fn(exp)
                self.records.append((exp, metric))
                if metric is not None and metric > self.best_metric:
                    self.best_metric = metric
                    self.best_exp = exp
                    stale = 0
                elif self.best_exp is not None:
                    # failures before ANY success (e.g. leading OOM configs)
                    # must not exhaust the early-stopping budget
                    stale += 1
            if self.early_stopping and stale >= self.early_stopping:
                break
        return self.best_exp


class GridSearchTuner(BaseTuner):
    """In-order exhaustive sweep (reference index_based_tuner.py)."""

    def next_batch(self, k: int) -> List[Experiment]:
        batch, self.all_exps = self.all_exps[:k], self.all_exps[k:]
        return batch


class RandomTuner(BaseTuner):
    def __init__(self, exps, metric_fn, early_stopping: int = 0,
                 seed: int = 0):
        super().__init__(exps, metric_fn, early_stopping)
        self._rng = random.Random(seed)

    def next_batch(self, k: int) -> List[Experiment]:
        k = min(k, len(self.all_exps))
        picks = [self.all_exps.pop(self._rng.randrange(len(self.all_exps)))
                 for _ in range(k)]
        return picks


class ModelBasedTuner(BaseTuner):
    """Predict-then-evaluate (reference model_based_tuner.py:14)."""

    def __init__(self, exps, metric_fn, early_stopping: int = 0,
                 explore: int = 2):
        super().__init__(exps, metric_fn, early_stopping)
        self.explore = explore  # random warm-start evaluations
        self._keys = sorted({(k, str(v)) for e in exps
                             for k, v in e.items()})
        self._index = {kv: i for i, kv in enumerate(self._keys)}

    def _featurize(self, exp: Experiment) -> np.ndarray:
        x = np.zeros(len(self._keys) + 1, dtype=np.float64)
        x[-1] = 1.0  # bias
        for k, v in exp.items():
            i = self._index.get((k, str(v)))
            if i is not None:
                x[i] = 1.0
        return x

    def _predict(self) -> Optional[np.ndarray]:
        obs = [(self._featurize(e), m) for e, m in self.records
               if m is not None]
        if len(obs) < self.explore:
            return None
        X = np.stack([x for x, _ in obs])
        y = np.array([m for _, m in obs])
        d = X.shape[1]
        w = np.linalg.solve(X.T @ X + 1e-3 * np.eye(d), X.T @ y)
        return np.stack(
            [self._featurize(e) for e in self.all_exps]) @ w

    def next_batch(self, k: int) -> List[Experiment]:
        preds = self._predict()
        out = []
        for _ in range(min(k, len(self.all_exps))):
            if preds is None:
                out.append(self.all_exps.pop(0))
            else:
                i = int(np.argmax(preds))
                preds = np.delete(preds, i)
                out.append(self.all_exps.pop(i))
        return out

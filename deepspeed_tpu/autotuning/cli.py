"""Launcher-driven autotuning: ``deepspeed_tpu.launcher.runner --autotuning
run|tune script.py --deepspeed_config ds.json``.

Counterpart of the reference's script-relaunch flow (autotuner.py +
autotuning/scheduler.py ResourceManager): the launcher re-runs the USER
SCRIPT once per experiment with a mutated DS config, each run reports its
measured throughput through a metric file (the engine writes it when
``DS_TPU_AUTOTUNING_RESULT`` is set — reference engine's
autotuning_metric_path), results are ranked, and mode ``run`` finally
launches the script for real with the winning config. Single-host; the
multi-host fan-out composes by launching through the runner itself.
"""

import json
import os
import subprocess
import sys
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.autotuning.autotuner import Autotuner
from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.utils.procgroup import (reap_process_group,
                                           spawn_process_group)

RESULT_ENV = "DS_TPU_AUTOTUNING_RESULT"
END_STEP_ENV = "DS_TPU_AUTOTUNING_END_STEP"
START_STEP_ENV = "DS_TPU_AUTOTUNING_START_STEP"


def _find_config(user_args: List[str]) -> Tuple[Optional[int], Optional[str]]:
    """Locate the DS config path in the script's argv (the reference reads
    --deepspeed_config; a bare positional *.json also counts)."""
    for i, a in enumerate(user_args):
        if a in ("--deepspeed_config", "--deepspeed-config"):
            if i + 1 < len(user_args):
                return i + 1, user_args[i + 1]
        if a.startswith("--deepspeed_config="):
            return i, a.split("=", 1)[1]
    for i, a in enumerate(user_args):
        if a.endswith(".json") and os.path.exists(a):
            return i, a
    return None, None


def _swapped_args(user_args: List[str], idx: int, new_path: str) -> List[str]:
    out = list(user_args)
    if out[idx].startswith("--deepspeed_config="):
        out[idx] = f"--deepspeed_config={new_path}"
    else:
        out[idx] = new_path
    return out


def run_autotuning(mode: str, user_script: str, user_args: List[str],
                   exps_dir: Optional[str] = None,
                   timeout_s: int = 1800,
                   hosts: Optional[Dict[str, Any]] = None,
                   final_launch=None) -> int:
    """Execute the tune loop; returns a process exit code.

    ``hosts`` (a hostname-keyed mapping — only the keys are used) turns on
    parallel experiment scheduling: a :class:`~deepspeed_tpu.autotuning.
    scheduler.ResourceManager` leases one host per experiment and runs up
    to ``len(hosts)`` experiments concurrently (reference ResourceManager,
    autotuning/scheduler.py:27). Without hosts the pool has one lease and
    the loop is sequential on this machine.

    ``final_launch``: mode ``run``'s finalizer — called with the winning
    config path and expected to launch the real job on the REAL topology
    (the runner passes its own multi-host relaunch). Required when hosts
    were given: a plain local relaunch would run the production job on one
    host with a config tuned for the pool's topology."""
    from deepspeed_tpu.autotuning.scheduler import ResourceManager

    cfg_idx, cfg_path = _find_config(user_args)
    if cfg_path is None:
        logger.error("--autotuning needs a DS config in the script args "
                     "(--deepspeed_config ds.json or a positional *.json)")
        return 2
    with open(cfg_path) as f:
        base = json.load(f)
    tuner = Autotuner(base)
    exps = tuner.generate_experiments()
    exps_dir = exps_dir or os.path.join(
        os.path.dirname(os.path.abspath(cfg_path)), "autotuning_exps")
    os.makedirs(exps_dir, exist_ok=True)
    results_dir = os.path.join(os.path.dirname(exps_dir),
                               "autotuning_results")
    os.makedirs(results_dir, exist_ok=True)

    def launch(i: int, exp: Dict[str, Any], host: str) -> Dict[str, Any]:
        exp_cfg = tuner.exp_to_config(exp)
        exp_dir = os.path.join(exps_dir, f"exp_{i}")
        os.makedirs(exp_dir, exist_ok=True)
        exp_cfg_path = os.path.join(exp_dir, "ds_config.json")
        with open(exp_cfg_path, "w") as f:
            json.dump(exp_cfg, f, indent=2)
        metric_path = os.path.join(exp_dir, "metric.json")
        env = dict(os.environ)
        # the relaunched script must resolve this very package, even when
        # the parent got it via sys.path manipulation rather than install
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = os.pathsep.join(
            [pkg_root] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH")
                          else []))
        env[RESULT_ENV] = metric_path
        env.setdefault(END_STEP_ENV,
                       str(tuner.cfg.end_profile_step))
        env.setdefault(START_STEP_ENV,
                       str(tuner.cfg.start_profile_step))
        cmd = [sys.executable, user_script] + _swapped_args(
            user_args, cfg_idx, exp_cfg_path)
        remote = host not in ("localhost", "127.0.0.1")
        if remote:
            # remote lease: ship the command over the ssh transport the
            # multi-node launcher uses (one experiment owns that host's
            # chips for its lifetime; metric files land on the SHARED
            # filesystem the hostfile flow already assumes for configs).
            # Everything interpolated into the remote shell line is
            # shlex-quoted — the launcher's own ssh builder does the same.
            import shlex

            from deepspeed_tpu.launcher.multinode_runner import (
                _shjoin)

            envs = " ".join(
                f"{k}={shlex.quote(str(v))}" for k, v in
                [(RESULT_ENV, env[RESULT_ENV]),
                 (END_STEP_ENV, env[END_STEP_ENV]),
                 (START_STEP_ENV, env[START_STEP_ENV]),
                 ("PYTHONPATH", env["PYTHONPATH"])])
            cmd = ["ssh", "-o", "StrictHostKeyChecking=no", host,
                   f"cd {shlex.quote(os.getcwd())} && {envs} "
                   f"{_shjoin(cmd)}"]
        logger.info(f"autotuning exp {i}/{len(exps)} on {host}: {exp}")
        log_path = os.path.join(exp_dir, "stdout.log")
        try:
            with open(log_path, "wb") as log_f:
                # own process group: on timeout the WHOLE experiment tree
                # is reaped (TERM -> KILL), not just the direct child —
                # a leaked JAX worker would hold the local chips busy for
                # every subsequent experiment
                proc = spawn_process_group(
                    cmd, env=env, stdout=log_f, stderr=subprocess.STDOUT)
                try:
                    proc.wait(timeout=timeout_s)
                except subprocess.TimeoutExpired:
                    reap_process_group(proc)
                    raise
            ok = proc.returncode == 0 and os.path.exists(metric_path)
        except subprocess.TimeoutExpired:
            ok = False
            if remote:
                # the timeout killed only the LOCAL ssh client; reap the
                # remote job before the host lease returns to the pool, or
                # the next experiment scheduled there inherits busy chips
                import shlex

                try:
                    subprocess.run(
                        ["ssh", "-o", "StrictHostKeyChecking=no", host,
                         f"pkill -f {shlex.quote(exp_cfg_path)}"],
                        timeout=30)
                except (subprocess.TimeoutExpired, OSError):
                    logger.warning(
                        f"could not reap timed-out experiment on {host}; "
                        "subsequent experiments there may fail")
        if not ok:
            logger.warning(f"autotuning exp {i} failed; see {log_path}")
        rec = {"exp": exp, "config": exp_cfg_path, "ok": ok, "host": host}
        if ok:
            with open(metric_path) as f:
                rec.update(json.load(f))
        return rec

    rm = ResourceManager(hosts)
    records = rm.run(list(exps), launch)
    records = [r if isinstance(r, dict) else
               {"exp": exps[i], "ok": False, "error": str(r)}
               for i, r in enumerate(records)]

    scored = [r for r in records if r.get("ok") and "samples_per_sec" in r]
    summary = {"experiments": records, "best": None}
    code = 1
    if scored:
        best = max(scored, key=lambda r: r["samples_per_sec"])
        summary["best"] = best
        logger.info(f"autotuning best: {best['exp']} "
                    f"({best['samples_per_sec']:.2f} samples/sec)")
        code = 0
    with open(os.path.join(results_dir, "summary.json"), "w") as f:
        json.dump(summary, f, indent=2)

    if mode == "run" and scored:
        best_cfg = summary["best"]["config"]
        if final_launch is not None:
            return final_launch(best_cfg)
        if hosts:
            # a plain local relaunch would run the production job on ONE
            # host with a config tuned for the pool topology — exactly the
            # silent-wrong-topology hazard the runner guard used to catch
            logger.error(
                "tuning finished but no multi-host finalizer was "
                f"provided; launch the winning config yourself: "
                f"--deepspeed_config {best_cfg} with your hostfile")
            return 1
        env = dict(os.environ)
        env.pop(RESULT_ENV, None)
        cmd = [sys.executable, user_script] + _swapped_args(
            user_args, cfg_idx, best_cfg)
        # production relaunch in its own group: a ctrl-C here must not
        # leave the (freshly tuned, long-running) training tree behind
        proc = spawn_process_group(cmd, env=env)
        try:
            return proc.wait()
        except KeyboardInterrupt:
            reap_process_group(proc)
            raise
    return code


def write_metric_file(path: str, samples_per_sec: float,
                      ms_per_step: float) -> None:
    """Engine-side: drop the measured metric where the tuner looks."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"samples_per_sec": round(float(samples_per_sec), 4),
                   "ms_per_step": round(float(ms_per_step), 3)}, f)
    os.replace(tmp, path)

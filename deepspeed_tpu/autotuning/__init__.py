"""Autotuning (reference ``deepspeed/autotuning/``): measured in-process
sweeps over ZeRO-stage x micro-batch experiments with grid/random/
model-based tuners."""

from deepspeed_tpu.autotuning.autotuner import (  # noqa: F401
    Autotuner,
    AutotuningConfig,
)
from deepspeed_tpu.autotuning.tuner import (  # noqa: F401
    GridSearchTuner,
    ModelBasedTuner,
    RandomTuner,
)

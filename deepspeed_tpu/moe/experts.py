"""Stacked expert FFNs.

Parity with reference ``deepspeed/moe/experts.py:9`` (Experts = ModuleList of
cloned FFNs, each rank holding ``num_local_experts``). TPU re-design: ONE
parameter tensor with a leading ``experts`` axis, sharded over the ``ep`` mesh
axis — "local experts" are the shard XLA assigns this device; the per-expert
loop becomes a batched einsum on the MXU.
"""

from typing import Any, Callable

import flax.linen as nn
import jax.numpy as jnp


class StackedExperts(nn.Module):
    """[E, C, M] -> [E, C, M] two-layer gelu FFN, vectorized over experts.

    Param shapes carry the expert axis first (``wi: [E, M, H]``,
    ``wo: [E, H, M]``) so expert-parallel sharding rules can address it
    (see moe/layer.py moe_sharding_rules).
    """

    num_experts: int
    d_model: int
    d_hidden: int
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    activation: Callable = nn.gelu

    @nn.compact
    def __call__(self, x):
        wi = self.param(
            "wi", nn.initializers.lecun_normal(),
            (self.num_experts, self.d_model, self.d_hidden), self.param_dtype,
        )
        bi = self.param(
            "bi", nn.initializers.zeros,
            (self.num_experts, self.d_hidden), self.param_dtype,
        )
        wo = self.param(
            "wo", nn.initializers.lecun_normal(),
            (self.num_experts, self.d_hidden, self.d_model), self.param_dtype,
        )
        bo = self.param(
            "bo", nn.initializers.zeros,
            (self.num_experts, self.d_model), self.param_dtype,
        )
        x = x.astype(self.dtype)
        h = jnp.einsum("ecm,emh->ech", x, wi.astype(self.dtype))
        h = h + bi[:, None, :].astype(self.dtype)
        h = self.activation(h)
        y = jnp.einsum("ech,ehm->ecm", h, wo.astype(self.dtype))
        y = y + bo[:, None, :].astype(self.dtype)
        return y

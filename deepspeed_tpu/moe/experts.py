"""Stacked expert FFNs.

Parity with reference ``deepspeed/moe/experts.py:9`` (Experts = ModuleList of
cloned FFNs, each rank holding ``num_local_experts``). TPU re-design: ONE
parameter tensor with a leading ``experts`` axis, sharded over the ``ep`` mesh
axis — "local experts" are the shard XLA assigns this device; the per-expert
loop becomes a batched einsum on the MXU.
"""

from typing import Any, Callable

import flax.linen as nn
import jax.numpy as jnp


class StackedExperts(nn.Module):
    """[E, C, M] -> [E, C, M] two-layer FFN, vectorized over experts.

    Param shapes carry the expert axis first (``wi: [E, M, H]``,
    ``wo: [E, H, M]``) so expert-parallel sharding rules can address it
    (see moe/layer.py moe_sharding_rules).

    ``gated=True`` makes each expert a SwiGLU FFN (Mixtral-style:
    ``wo @ (act(wg x) * (wi x))``, biasless), with a ``wg`` gate tensor
    alongside ``wi`` — same expert-parallel layout.
    """

    num_experts: int
    d_model: int
    d_hidden: int
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    activation: Callable = nn.gelu
    gated: bool = False
    use_bias: bool = True

    @nn.compact
    def __call__(self, x):
        E, M, H = self.num_experts, self.d_model, self.d_hidden
        wi = self.param("wi", nn.initializers.lecun_normal(),
                        (E, M, H), self.param_dtype)
        wo = self.param("wo", nn.initializers.lecun_normal(),
                        (E, H, M), self.param_dtype)
        x = x.astype(self.dtype)
        h = jnp.einsum("ecm,emh->ech", x, wi.astype(self.dtype))
        if self.use_bias:
            bi = self.param("bi", nn.initializers.zeros, (E, H),
                            self.param_dtype)
            h = h + bi[:, None, :].astype(self.dtype)
        if self.gated:
            wg = self.param("wg", nn.initializers.lecun_normal(),
                            (E, M, H), self.param_dtype)
            g = jnp.einsum("ecm,emh->ech", x, wg.astype(self.dtype))
            h = self.activation(g) * h
        else:
            h = self.activation(h)
        y = jnp.einsum("ech,ehm->ecm", h, wo.astype(self.dtype))
        if self.use_bias:
            bo = self.param("bo", nn.initializers.zeros, (E, M),
                            self.param_dtype)
            y = y + bo[:, None, :].astype(self.dtype)
        return y

"""The MoE layer.

Parity with reference ``deepspeed/moe/layer.py:15`` (MoE = TopKGate +
MOELayer + Experts with expert-parallel all-to-all) re-designed for SPMD:

* gate: small fp32 Dense (reference TopKGate wg, sharded_moe.py:351)
* dispatch: einsum to ``[experts, capacity, model]`` + a PartitionSpec("ep")
  sharding constraint — GSPMD emits the all-to-all the reference implements
  as the ``_AllToAll`` autograd function (sharded_moe.py:89)
* experts: one stacked tensor sharded over ``ep`` (moe/experts.py)
* expert vs non-expert gradient groups (reference engine.py:2225-2287) need
  no special handling: the global-view jit program reduces each param over
  exactly the axes it is replicated on.

The layer returns ``(y, l_aux, exp_counts)``; the model adds
``aux_coef * l_aux`` to its loss (reference stores l_aux on the module and
the engine collects it).
"""

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.moe.experts import StackedExperts
from deepspeed_tpu.moe.sharded_moe import (
    combine_tokens,
    dispatch_tokens,
    topk_gating,
)


def _ep_constraint(x, ndim_spec):
    """Sharding constraint over the ep axis; a no-op when no ep axis is
    active in the default topology."""
    from deepspeed_tpu.parallel.mesh import get_default_topology

    topo = get_default_topology()
    if topo.size("ep") <= 1:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(topo.mesh, PartitionSpec(*ndim_spec))
    )


class MoE(nn.Module):
    """Drop-in FFN replacement (reference moe/layer.py:15 wraps an `expert`
    module; here the expert FFN is built from d_model/d_hidden)."""

    d_model: int
    d_hidden: int
    num_experts: int = 1
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None
    drop_tokens: bool = True
    use_rts: bool = True
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    gated_experts: bool = False      # SwiGLU experts (Mixtral-style)
    expert_activation: Any = None    # defaults: gelu, or silu when gated

    @nn.compact
    def __call__(self, x, *, deterministic: bool = True):
        orig_shape = x.shape
        d_model = orig_shape[-1]
        tokens = x.reshape(-1, d_model)

        # gate in fp32 (reference TopKGate casts input to float, wg fp32)
        gate_logits = nn.Dense(
            self.num_experts, use_bias=False, dtype=jnp.float32,
            param_dtype=jnp.float32, name="gate",
        )(tokens.astype(jnp.float32))

        rng = None
        if not deterministic and self.has_rng("gating"):
            rng = self.make_rng("gating")

        gout = topk_gating(
            gate_logits,
            k=self.k,
            capacity_factor=(self.capacity_factor if not deterministic
                             else self.eval_capacity_factor),
            min_capacity=self.min_capacity,
            rng=rng,
            noisy_gate_policy=self.noisy_gate_policy,
            drop_tokens=self.drop_tokens,
            use_rts=self.use_rts,
        )

        dispatched = dispatch_tokens(gout.dispatch_mask, tokens)  # [E,C,M]
        dispatched = _ep_constraint(dispatched, ("ep", None, None))
        act = self.expert_activation or (
            nn.silu if self.gated_experts else nn.gelu)
        expert_out = StackedExperts(
            num_experts=self.num_experts,
            d_model=self.d_model,
            d_hidden=self.d_hidden,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            activation=act,
            gated=self.gated_experts,
            use_bias=not self.gated_experts,
            name="experts",
        )(dispatched)
        expert_out = _ep_constraint(expert_out, ("ep", None, None))
        y = combine_tokens(gout.combine_weights, expert_out, dtype=x.dtype)
        return y.reshape(orig_shape), gout.l_aux, gout.exp_counts


def expert_axis(path: str, ndim: int) -> Optional[int]:
    """Index of the expert axis in a :class:`StackedExperts` param (the same
    layout convention :func:`moe_param_spec` encodes: 3rd-from-last for
    wi/wg/wo, 2nd-from-last for bi/bo — robust to a leading scan-layer
    axis), or None for non-expert leaves / shapes too small to carry one
    (e.g. flattened error-feedback buffers)."""
    if "experts/" not in path:
        return None
    if path.endswith(("experts/wi", "experts/wg", "experts/wo")):
        ax = ndim - 3
    elif path.endswith(("experts/bi", "experts/bo")):
        ax = ndim - 2
    else:
        return None
    return ax if ax >= 0 else None


def moe_param_spec(path: str, shape) -> Optional[PartitionSpec]:
    """Expert-parallel PartitionSpec for MoE params, composable with TP rules.

    Expert tensors carry the expert axis 3rd-from-last (wi/wo) or 2nd-from-
    last (bi/bo) — robust to a leading scan-layer axis. Column-parallel tp on
    wi's hidden dim, row-parallel on wo's hidden dim (Megatron FFN pattern).
    """
    ndim = len(shape)

    def spec(**axis_by_dim):
        s = [None] * ndim
        for d, a in axis_by_dim.items():
            s[int(d)] = a
        return PartitionSpec(*s)

    ep_ax = expert_axis(path, ndim)  # single source of the layout rule
    if ep_ax is None:
        return None
    if path.endswith(("experts/wi", "experts/wg")):
        return spec(**{str(ep_ax): "ep", str(ndim - 1): "tp"})
    if path.endswith("experts/wo"):
        return spec(**{str(ep_ax): "ep", str(ndim - 2): "tp"})
    return spec(**{str(ep_ax): "ep"})  # bi/bo

"""Mixture-of-Experts with expert parallelism (reference ``deepspeed/moe/``)."""

from deepspeed_tpu.moe.layer import MoE, moe_param_spec  # noqa: F401
from deepspeed_tpu.moe.experts import StackedExperts  # noqa: F401
from deepspeed_tpu.moe.sharded_moe import (  # noqa: F401
    GatingOutput,
    combine_tokens,
    dispatch_tokens,
    static_capacity,
    top1_gating,
    top2_gating,
    topk_gating,
)
from deepspeed_tpu.moe.utils import (  # noqa: F401
    is_moe_param_path,
    split_moe_params,
)

"""MoE param utilities (reference ``deepspeed/moe/utils.py``:
is_moe_param, split_params_into_different_moe_groups_for_optimizer).

In the pytree world a param is identified by its path, so the expert/
non-expert split is a path predicate instead of a tensor attribute."""

from typing import Any, Tuple

import jax

from deepspeed_tpu.utils.tree import path_str


def is_moe_param_path(path: str) -> bool:
    """True for expert-parallel params (sharded over ep, NOT reduced over it)."""
    return "experts/" in path or path.endswith("/experts")


def split_moe_params(params) -> Tuple[Any, Any]:
    """Partition a param pytree into (expert, non-expert) trees with None at
    the complementary leaves (reference splits torch param_groups)."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    paths = [path_str(path) for path, _ in flat]

    def select(moe: bool):
        leaves = [
            leaf if is_moe_param_path(path) == moe else None
            for path, (_, leaf) in zip(paths, flat)
        ]
        return jax.tree_util.tree_unflatten(treedef, leaves)

    return select(True), select(False)

"""Top-k gating + dispatch/combine math for Mixture-of-Experts.

Capability parity with reference ``deepspeed/moe/sharded_moe.py`` (top1gating
:177, top2gating :278, MOELayer :439): softmax gating with static capacity,
load-balancing auxiliary loss, random token selection (RTS), gumbel-noise
second-expert choice, einsum dispatch/combine.

TPU re-design notes:

* Capacity is computed at TRACE time from the static token count — XLA needs
  static shapes, and the reference's ``drop_tokens=False`` dynamic capacity
  (all-reduced max) becomes "capacity = all tokens" here (worst case, static).
* The reference's ``_AllToAll`` autograd function + expert process groups
  collapse into a sharding constraint: dispatched tensors are laid out
  ``[experts, capacity, model]`` and annotated with PartitionSpec("ep", ...);
  GSPMD inserts the all-to-all (and its transpose in the backward) itself.
* Everything is differentiable exactly where the reference is: gradients flow
  through the gate probabilities in combine_weights and through l_aux; the
  argmax/top-k index paths are non-differentiable in both.
"""

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class GatingOutput(NamedTuple):
    l_aux: jnp.ndarray           # scalar load-balance loss
    combine_weights: jnp.ndarray  # [tokens, experts, capacity] float
    dispatch_mask: jnp.ndarray    # [tokens, experts, capacity] bool
    exp_counts: jnp.ndarray       # [experts] int32 — tokens routed per expert


def static_capacity(num_tokens: int, num_experts: int, capacity_factor: float,
                    min_capacity: int) -> int:
    """Static per-expert capacity (reference sharded_moe.py:155 _capacity).

    Python math on static shapes so the jitted program has fixed buffers.
    """
    capacity = int(np.ceil((num_tokens / num_experts) * capacity_factor))
    capacity = max(capacity, min_capacity)
    return min(capacity, num_tokens)


def _gumbel(rng, shape):
    return jax.random.gumbel(rng, shape, dtype=jnp.float32)


def _one_hot(idx, n):
    return jax.nn.one_hot(idx, n, dtype=jnp.int32)


def top1_gating(
    logits: jnp.ndarray,
    capacity_factor: float = 1.0,
    min_capacity: int = 4,
    rng: Optional[jax.Array] = None,
    noisy_gate_policy: Optional[str] = None,
    drop_tokens: bool = True,
    use_rts: bool = True,
    used_token: Optional[jnp.ndarray] = None,
) -> GatingOutput:
    """Top-1 (Switch) gating (reference sharded_moe.py:177).

    ``rng`` drives RSample noise and random-token-selection; pass None for
    deterministic eval (noise and RTS are skipped, matching the reference's
    behaviour when no stochastic path is active).
    """
    logits = logits.astype(jnp.float32)
    num_tokens, num_experts = logits.shape
    gates = jax.nn.softmax(logits, axis=-1)

    if drop_tokens:
        capacity = static_capacity(num_tokens, num_experts, capacity_factor,
                                   min_capacity)
    else:
        capacity = num_tokens  # static worst case (reference all-reduces a max)

    if noisy_gate_policy == "RSample" and rng is not None:
        rng, sub = jax.random.split(rng)
        indices1 = jnp.argmax(logits + _gumbel(sub, logits.shape), axis=-1)
    else:
        indices1 = jnp.argmax(gates, axis=-1)
    mask1 = _one_hot(indices1, num_experts)
    if used_token is not None:
        mask1 = mask1 * used_token[:, None].astype(mask1.dtype)

    exp_counts = jnp.sum(mask1, axis=0).astype(jnp.int32)

    # load-balance loss (reference :218): mean(gate_prob) . mean(assignment)
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1.astype(jnp.float32), axis=0)
    l_aux = jnp.sum(me * ce) * num_experts

    if use_rts and rng is not None:
        # Random Token Selection (reference :227): random priority per routed
        # token, keep the top-`capacity` per expert
        rng, sub = jax.random.split(rng)
        priority = mask1.astype(jnp.float32) * jax.random.uniform(
            sub, mask1.shape, dtype=jnp.float32
        )
        _, top_idx = jax.lax.top_k(priority.T, capacity)  # [E, C] token ids
        keep = jnp.zeros((num_experts, num_tokens), jnp.int32)
        keep = jax.vmap(lambda row, idx: row.at[idx].set(1))(keep, top_idx)
        mask1 = mask1 * keep.T
        locations1 = jnp.cumsum(mask1, axis=0) - 1
    else:
        # deterministic: first-come-first-served by position (stable top-k)
        locations1 = jnp.cumsum(mask1, axis=0) - 1
        mask1 = mask1 * (locations1 < capacity).astype(mask1.dtype)

    locations1_s = jnp.sum(locations1 * mask1, axis=-1)

    gates = gates * mask1.astype(jnp.float32)
    locations1_sc = jax.nn.one_hot(locations1_s, capacity, dtype=jnp.float32)
    combine = jnp.einsum("te,tc->tec", gates, locations1_sc)
    # zero out dropped tokens' capacity rows (one_hot(0) would alias slot 0)
    combine = combine * mask1[..., None].astype(jnp.float32)
    dispatch = combine > 0
    return GatingOutput(l_aux, combine, dispatch, exp_counts)


def top2_gating(
    logits: jnp.ndarray,
    capacity_factor: float = 1.0,
    min_capacity: int = 4,
    rng: Optional[jax.Array] = None,
) -> GatingOutput:
    """Top-2 (GShard) gating (reference sharded_moe.py:278): second expert via
    gumbel-max over the non-top logits, combined weights renormalized over the
    two selected experts."""
    logits = logits.astype(jnp.float32)
    num_tokens, num_experts = logits.shape
    gates = jax.nn.softmax(logits, axis=-1)
    capacity = static_capacity(num_tokens, num_experts, 2.0 * capacity_factor,
                               min_capacity)

    indices1 = jnp.argmax(gates, axis=-1)
    mask1 = _one_hot(indices1, num_experts)

    if rng is not None:
        logits_w_noise = logits + _gumbel(rng, logits.shape)
    else:
        logits_w_noise = logits
    logits_except1 = jnp.where(mask1.astype(bool), -jnp.inf, logits_w_noise)
    indices2 = jnp.argmax(logits_except1, axis=-1)
    mask2 = _one_hot(indices2, num_experts)

    locations1 = jnp.cumsum(mask1, axis=0) - 1
    locations2 = jnp.cumsum(mask2, axis=0) - 1
    locations2 = locations2 + jnp.sum(mask1, axis=0, keepdims=True)

    exp_counts = jnp.sum(mask1, axis=0).astype(jnp.int32)

    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1.astype(jnp.float32), axis=0)
    l_aux = jnp.mean(me * ce) * num_experts * num_experts

    mask1 = mask1 * (locations1 < capacity).astype(mask1.dtype)
    mask2 = mask2 * (locations2 < capacity).astype(mask2.dtype)

    locations1_s = jnp.sum(locations1 * mask1, axis=-1)
    locations2_s = jnp.sum(locations2 * mask2, axis=-1)

    mask1_f = mask1.astype(jnp.float32)
    mask2_f = mask2.astype(jnp.float32)
    gates1_s = jnp.einsum("te,te->t", gates, mask1_f)
    gates2_s = jnp.einsum("te,te->t", gates, mask2_f)
    denom = jnp.maximum(gates1_s + gates2_s, jnp.finfo(jnp.float32).eps)
    gates1_s = gates1_s / denom
    gates2_s = gates2_s / denom

    gates1 = jnp.einsum("t,te->te", gates1_s, mask1_f)
    gates2 = jnp.einsum("t,te->te", gates2_s, mask2_f)
    loc1_sc = jax.nn.one_hot(locations1_s, capacity, dtype=jnp.float32)
    loc2_sc = jax.nn.one_hot(locations2_s, capacity, dtype=jnp.float32)
    combine = (
        jnp.einsum("te,tc->tec", gates1, loc1_sc) * mask1_f[..., None]
        + jnp.einsum("te,tc->tec", gates2, loc2_sc) * mask2_f[..., None]
    )
    dispatch = combine > 0
    return GatingOutput(l_aux, combine, dispatch, exp_counts)


def topk_gating(logits, k: int, **kwargs) -> GatingOutput:
    if k == 1:
        return top1_gating(logits, **kwargs)
    if k == 2:
        # these knobs only exist on the top-1 path (as in the reference, where
        # top2gating takes no noise/RTS/drop arguments) — reject non-defaults
        # rather than silently changing routing behaviour
        unsupported = {
            "noisy_gate_policy": None, "drop_tokens": True,
            "use_rts": True, "used_token": None,
        }
        for name, default in unsupported.items():
            if name in kwargs and kwargs.pop(name) != default:
                raise ValueError(
                    f"top-2 gating does not support {name} "
                    "(top-1-only option, see reference sharded_moe.py:278)"
                )
        return top2_gating(logits, **kwargs)
    raise ValueError(f"only top-1 and top-2 gating are supported, got k={k}")


def dispatch_tokens(dispatch_mask: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """[T,E,C] bool x [T,M] -> [E,C,M] (reference MOELayer einsum "sec,sm->ecm",
    sharded_moe.py:439 forward). MXU-friendly: a single batched matmul."""
    return jnp.einsum("tec,tm->ecm", dispatch_mask.astype(x.dtype), x)


def combine_tokens(combine_weights: jnp.ndarray, expert_out: jnp.ndarray,
                   dtype=None) -> jnp.ndarray:
    """[T,E,C] x [E,C,M] -> [T,M] (reference einsum "sec,ecm->sm")."""
    y = jnp.einsum(
        "tec,ecm->tm", combine_weights,
        expert_out.astype(combine_weights.dtype),
    )
    return y.astype(dtype) if dtype is not None else y

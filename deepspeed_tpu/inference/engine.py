"""The inference engine.

Parity with reference ``deepspeed/inference/engine.py`` (InferenceEngine :32)
and ``deepspeed.init_inference`` (__init__.py:225): wrap a model for serving
with tensor-parallel sharding, dtype conversion (fp16/bf16/int8), sharded
checkpoint loading, and a generation loop over a KV-cache decode path.

TPU re-design:

* MP groups + tensor slicing (engine.py:212, replace_module.py) become a
  ``tp`` mesh axis + PartitionSpecs from the injection policy
  (module_inject); params materialize pre-sharded.
* CUDA-graph capture/replay (engine.py:523-551) is just jit: prefill and
  decode-step are compiled once and replayed.
* The fused decode kernels (softmax_context KV-cache attention,
  pt_binding.cpp) are the model's ``decode=True`` path; its cache lives in a
  flax ``cache`` collection threaded through the jitted step.
"""

import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization

from deepspeed_tpu.module_inject import policy_for
from deepspeed_tpu.parallel.mesh import MeshTopology, set_default_topology
from deepspeed_tpu.runtime.checkpoint_engine import MsgpackCheckpointEngine
from deepspeed_tpu.runtime.zero.sharding import ZeroShardingRules
from deepspeed_tpu.utils.logging import log_dist


def _conform_host_quantized(host, shapes):
    """Host-side conversion of a dense imported param tree to the model's
    {q, scale} int8 storage structure. The structure (which leaves are
    quantized) comes from ``shapes`` — the eval_shape of
    models.transformer_lm.quantize_block_params — and the scale/clip math
    from the quantizer module, so neither can drift from the device path."""
    from deepspeed_tpu.ops.quantizer import quantize_weight_per_column_np

    if isinstance(shapes, dict) and set(shapes) == {"q", "scale"}:
        q, scale = quantize_weight_per_column_np(host, num_bits=8)
        return {"q": q, "scale": scale}
    if isinstance(shapes, dict):
        if not isinstance(host, dict):
            raise ValueError(
                f"imported params have a leaf where the model expects a "
                f"submodule with keys {sorted(shapes)}")
        if set(host) != set(shapes):
            # keep the loud structure-mismatch the dense placement path
            # raises — silently dropping misnamed imported leaves would
            # serve a half-loaded model
            raise ValueError(
                f"imported params do not match the model: extra "
                f"{sorted(set(host) - set(shapes))}, missing "
                f"{sorted(set(shapes) - set(host))}")
        return {k: _conform_host_quantized(host[k], v)
                for k, v in shapes.items()}
    return host


def prefill_chunk_spans(model_cfg, T: int):
    """Spans for an EXACT ring-cache prefill of a ``T``-token prompt.

    Returns None when a single pass is already exact: dense-cache models
    (no ring), or ``T <= ring_len`` from a fresh cache (no key is evicted
    before every query of the pass has attended it). Otherwise returns
    ``[(start, end), ...]`` block-aligned spans of at most ONE layout block
    each: a mid-stream pass covering layout blocks ``[b0, b1]`` needs
    blocks ``[b0 - w_blk .. b1]`` simultaneously ring-resident, and the
    ring holds exactly ``w_blk + 1`` blocks, so ``b1 == b0`` — one block
    per pass. The partial tail span stays inside one block, so it is exact
    too. ``<= ring_len``-token passes per the model's prefill guard.
    """
    from deepspeed_tpu.ops.sparse_attention.sparse_attention_utils import (
        ring_engaged,
        ring_storage_len,
    )

    ring = ring_engaged(model_cfg) if model_cfg is not None else None
    if ring is None:
        return None
    w_blk, g_tok, blk = ring
    ring_len = ring_storage_len(model_cfg, ring)
    if T <= ring_len:
        return None
    return [(s, min(s + blk, T)) for s in range(0, T, blk)]


def continuation_chunk_spans(model_cfg, start: int, end: int):
    """Spans for an EXACT continuation prefill of columns ``[start, end)``
    on a cache that already holds ``start`` written positions.

    The prefix-cache admission path resumes a chunked prefill mid-prompt
    (``prefill_chunk_spans`` only covers start-from-0), and ``start`` need
    NOT be block-aligned: a promotion snapshot can cut anywhere. The same
    residency argument applies span-by-span: a pass writing positions
    ``[s, e)`` evicts up to position ``e - ring_len``, while its earliest
    query needs block ``s//blk - w_blk`` resident — guaranteed iff the
    span never crosses a layout-block boundary. When ``end <= ring_len``
    nothing is evicted at all, so one pass is exact regardless of
    alignment; dense caches are always one pass.
    """
    from deepspeed_tpu.ops.sparse_attention.sparse_attention_utils import (
        ring_engaged,
        ring_storage_len,
    )

    if not 0 <= start < end:
        raise ValueError(f"bad continuation span [{start}, {end})")
    ring = ring_engaged(model_cfg) if model_cfg is not None else None
    if ring is not None:
        w_blk, g_tok, blk = ring
        ring_len = ring_storage_len(model_cfg, ring)
        if end > ring_len:
            return [(s, min(end, (s // blk + 1) * blk))
                    for s in range(start, end)
                    if s == start or s % blk == 0]
    return [(start, end)]


def init_inference(model, config: Optional[Dict[str, Any]] = None,
                   mp_size: int = 1, dtype=None, checkpoint: Optional[str] = None,
                   replace_with_kernel_inject: bool = True, seed: int = 0,
                   ep_size: int = 1, **kwargs):
    """Build an InferenceEngine (reference deepspeed/__init__.py:225;
    ``ep_size`` is the reference's expert-parallel serving knob — engine.py
    :227 builds the EP process groups, moe_inference.py:206 serves through
    them)."""
    config = dict(config or {})
    config.setdefault("tensor_parallel", {"tp_size": mp_size})
    if ep_size != 1:
        # copy the nested dict (the shallow config copy above would let
        # setdefault mutate the CALLER's moe block), and overwrite like
        # dtype/checkpoint do — an explicit argument wins over the config
        config["moe"] = dict(config.get("moe") or {}, ep_size=ep_size)
    if dtype is not None:
        config["dtype"] = dtype
    if checkpoint is not None:
        config["checkpoint"] = checkpoint
    config["replace_with_kernel_inject"] = replace_with_kernel_inject
    return InferenceEngine(model, config, seed=seed)


class InferenceEngine:
    def __init__(self, model, config: Dict[str, Any], seed: int = 0):
        self.module = model
        self._config = config
        tp_size = int(config.get("tensor_parallel", {}).get("tp_size", 1))
        self.mp_world_size = tp_size
        # expert-parallel serving (reference inference/engine.py:227
        # _create_ep_parallel_group + moe_inference.py:206): converted MoE
        # expert stacks shard over the ep axis instead of replicating —
        # an 8-expert model at ep=4 holds 2 experts' weights per chip, and
        # GSPMD emits the dispatch/combine all-to-alls from the layer's
        # sharding constraints
        ep_size = int(config.get("moe", {}).get("ep_size", 1))
        self.ep_world_size = ep_size

        n = len(jax.devices())
        assert n % (tp_size * ep_size) == 0, (
            f"tp_size {tp_size} x ep_size {ep_size} does not divide "
            f"{n} devices")
        self.topology = MeshTopology(tp=tp_size, ep=ep_size,
                                     dp=n // (tp_size * ep_size))
        set_default_topology(self.topology)

        dtype = config.get("dtype")
        self.dtype = {None: None, "fp16": jnp.float16, "float16": jnp.float16,
                      "bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
                      "fp32": jnp.float32, "float32": jnp.float32,
                      "int8": jnp.int8}.get(dtype, dtype)

        # HF torch model? Run the injection policy: convert weights into the
        # equivalent flax model (reference replace_transformer_layer,
        # module_inject/replace_module.py:277 — there it swaps fused CUDA
        # modules in; here the flax model IS the fused path)
        from deepspeed_tpu.module_inject.hf import import_hf_model, is_hf_model

        hf_params = None
        if is_hf_model(model):
            compute = self.dtype if self.dtype in (
                jnp.float16, jnp.bfloat16, jnp.float32) else jnp.bfloat16
            self.module, hf_params = import_hf_model(model, dtype=compute)
            model = self.module

        # int8 serving, model-level: when the model's config supports
        # quantized_weights, let it store kernels int8-at-rest and
        # dequantize per layer INSIDE its scan (the convert fuses with
        # that layer's dots; measured 19% faster decode vs bf16 at 350M).
        # Models without the flag fall back to engine-level quantization
        # in _cast (functional, but the stacked dequant outside the layer
        # scan costs bandwidth).
        self._model_quantized = False
        cfg_obj = getattr(model, "config", None)
        if self.dtype == jnp.int8 and cfg_obj is not None:
            import dataclasses as _dc

            if any(f.name == "quantized_weights"
                   for f in _dc.fields(cfg_obj)):
                model = model.clone(config=_dc.replace(
                    cfg_obj, quantized_weights=True))
                self.module = model
                self._model_quantized = True
            # below ~200M params decode is dispatch-bound, not weight-
            # bandwidth-bound, and int8 measures a LOSS (gpt2_125m
            # 0.84-0.96x, benchmarks/inference/int8_results.json); the win
            # starts around 350M (2.88 -> 2.33 ms/token) and grows with
            # size (1.37x at 1.3B b1). Serve as asked, but say so once.
            try:
                from deepspeed_tpu.models.transformer_lm import num_params
                n_model_params = num_params(cfg_obj)
            except Exception:
                n_model_params = None
            if n_model_params is not None and n_model_params < 200e6:
                from deepspeed_tpu.utils.logging import warning_once

                warning_once(
                    f"dtype=int8 on a ~{n_model_params / 1e6:.0f}M-param "
                    "model: decode at this size is dispatch-bound and int8 "
                    "measures slower than bf16 (0.84-0.96x at 125M, "
                    "benchmarks/inference/int8_results.json); the win "
                    "starts around 350M params")

        # int8 KV cache (serving capacity lever, GPTConfig.kv_cache_dtype):
        # orthogonal to weight quantization — "kv_cache": "int8" stores the
        # decode cache int8 with per-slot f32 scales and dequantizes on
        # read (models/transformer_lm.py decode attention). Same clone
        # pattern as quantized_weights above.
        kv_cache = config.get("kv_cache")
        if kv_cache is not None:
            import dataclasses as _dc

            kcfg = getattr(model, "config", None)
            if kcfg is None or not any(f.name == "kv_cache_dtype"
                                       for f in _dc.fields(kcfg)):
                raise ValueError(
                    "inference config 'kv_cache' needs a model whose config "
                    "carries kv_cache_dtype (models/transformer_lm.GPTConfig)")
            model = model.clone(config=_dc.replace(
                kcfg, kv_cache_dtype=kv_cache))
            self.module = model

        # injection policy -> TP sharding rules (reference
        # _apply_injection_policy, inference/engine.py:364)
        rules = policy_for(model) if config.get(
            "replace_with_kernel_inject", True) else None
        # int8 quantized_weights composes with tp>1: ZeroShardingRules
        # derives the {q, scale} leaf specs from the dense kernel rule
        # (sharding.py _quantized_leaf_spec — the reference's post-slice
        # GroupQuantizer geometry, replace_module.py:139)
        self.sharding_rules = ZeroShardingRules(
            self.topology, stage=0, tp_rules=rules)

        self._rng = jax.random.PRNGKey(seed)
        # imported weights stay HOST-side until _materialize device_puts
        # each leaf with its TP sharding: an eager jnp.asarray would land
        # the full unsharded model on one chip first (7B fp32 = 28 GB),
        # OOMing even when tp>1 would fit (same rule as the training
        # engine's _place_initial_params)
        self._params = None
        self._host_params = hf_params
        self._prefill_fn = None
        self._decode_k_fn = None
        self._fwd_fn = None
        self._profile = bool(config.get("profile_model_time", False))
        self._model_times = []

        if config.get("checkpoint"):
            # params materialize directly from the checkpoint, sharded
            self._load_checkpoint(config["checkpoint"])

        log_dist(f"InferenceEngine: tp={tp_size}, ep={ep_size}, "
                 f"dtype={self.dtype}", ranks=[0])

    # ------------------------------------------------------------------
    def _compute_dtype(self):
        """The module's compute dtype (bf16 fallback) — the dtype in-graph
        dequant converts to and host placement casts non-quantized floating
        leaves to; one definition so the two cannot diverge."""
        return getattr(getattr(self.module, "config", None), "dtype",
                       None) or jnp.bfloat16

    def _cast(self, params):
        if self.dtype in (jnp.float16, jnp.bfloat16):
            return jax.tree.map(lambda x: x.astype(self.dtype)
                                if jnp.issubdtype(x.dtype, jnp.floating)
                                else x, params)
        if self.dtype == jnp.int8:
            if self._model_quantized:
                # the model stores its own {q, scale} layout (init/
                # conform already produced it) — nothing to do here
                return params
            # engine-level fallback for models WITHOUT the config flag:
            # same self-describing {q, scale} storage (reference
            # GroupQuantizer + int8 GEMM path, replace_module.py:139,
            # pt_binding.cpp:1535), dequantized in _dequant at the apply
            # call sites. Caveat vs the model-level path: for scanned
            # models the dequant sits OUTSIDE the layer scan, so the
            # stacked bf16 copy materializes per step — functional, not
            # the bandwidth win (int8_results.json measures both).
            from deepspeed_tpu.models.transformer_lm import \
                quantize_block_params

            self._engine_quantized = True
            return quantize_block_params(params)
        return params

    def _dequant(self, params):
        """Trace-level inverse of the engine-level int8 cast (identity for
        model-level quantized_weights, where the layer scan dequantizes)."""
        if not getattr(self, "_engine_quantized", False):
            return params
        from deepspeed_tpu.models.transformer_lm import \
            dequantize_block_params

        return dequantize_block_params(params, self._compute_dtype())

    def _materialize(self, input_ids):
        model = self.module
        rng = self._rng

        # quantized models cannot run init through their map_variables
        # transform (see _maybe_quantized_block) — initialize a DENSE twin
        # and convert its tree to the {q, scale} storage structure
        init_model = model
        if self._model_quantized:
            import dataclasses as _dc

            init_model = model.clone(config=_dc.replace(
                model.config, quantized_weights=False))

        def init_fn(r):
            return init_model.init({"params": r}, input_ids,
                                   deterministic=True)["params"]

        shapes = jax.eval_shape(init_fn, rng)
        if self._model_quantized:
            from deepspeed_tpu.models.transformer_lm import \
                quantize_block_params

            shapes = jax.eval_shape(quantize_block_params, shapes)
        if self._model_quantized and self._host_params is not None:
            # imported weights are dense; conform them HOST-side to the
            # model's {q, scale} storage structure before placement (an
            # on-device quantize would land each full-precision leaf on
            # one chip first — the exact OOM placement exists to avoid)
            self._host_params = _conform_host_quantized(
                self._host_params, shapes)
        self._param_shardings = self.sharding_rules.param_sharding_tree(shapes)
        if self._host_params is not None:
            # each device receives only its shard; half-precision cast
            # happens on HOST so full-precision leaves never transit
            cast = self.dtype if self.dtype in (jnp.float16, jnp.bfloat16) \
                else None
            if self.dtype == jnp.int8:
                # non-quantized floating leaves (embeddings, norms) serve
                # at the module's compute dtype — imported fp32 would
                # double their HBM footprint/traffic. Scales pre-cast too:
                # dequant casts them to the same dtype in-graph, so the
                # quantized math is unchanged.
                cast = self._compute_dtype()

            def place(leaf, shape_dtype, sharding):
                arr = np.asarray(leaf)
                # jnp.issubdtype: ml_dtypes bfloat16 is NOT np.floating
                if cast is not None and jnp.issubdtype(
                        arr.dtype, jnp.floating):
                    arr = arr.astype(cast)
                if arr.shape != shape_dtype.shape:
                    raise ValueError(
                        f"loaded leaf shape {arr.shape} != model shape "
                        f"{shape_dtype.shape}")
                return jax.device_put(arr, sharding)

            self._params = jax.tree.map(
                place, self._host_params, shapes, self._param_shardings)
            self._host_params = None  # free the host copy
            if self.dtype == jnp.int8:
                self._params = self._cast(self._params)
        else:
            # no imported/loaded weights: random init, sharded at creation
            if self._model_quantized:
                from deepspeed_tpu.models.transformer_lm import \
                    quantize_block_params

                # ONE jit: the dense init tree is an internal value XLA
                # frees layer-by-layer, never a materialized output
                # (dense-plus-int8 peak would be the OOM pattern the
                # host-placement path above exists to avoid)
                self._params = jax.jit(
                    lambda r: quantize_block_params(init_fn(r)),
                    out_shardings=self._param_shardings)(rng)
            else:
                self._params = jax.jit(
                    init_fn, out_shardings=self._param_shardings)(rng)
                self._params = self._cast(self._params)

    # ------------------------------------------------------------------
    def _place_batch(self, arr):
        """Shard a [B, ...] serving batch over the mesh's data axes
        (dp x ep) when B divides them — the inference analogue of the
        training engine's _put_batch. For MoE models this is what makes
        expert parallelism real: tokens live batch-sharded, so the MoE
        dispatch/combine constraints become all-to-alls instead of local
        slicing over a replicated copy. Indivisible batches (e.g. batch-1
        latency serving) stay replicated."""
        bs = int(np.prod([self.topology.size(a)
                          for a in ("dp", "fsdp", "ep")]))
        if bs > 1 and arr.shape[0] % bs == 0:
            return jax.device_put(arr, self.topology.batch_sharding())
        return arr

    def forward(self, input_ids, **kwargs):
        """Full forward returning logits (jit-compiled once — the CUDA-graph
        analogue)."""
        # model modules read the ambient topology at trace time (VocabEmbed
        # one-hot vs gather) — re-assert before any lazy compile
        set_default_topology(self.topology)
        input_ids = self._place_batch(jnp.asarray(input_ids))
        if self._params is None or not hasattr(self, "_param_shardings"):
            self._materialize(input_ids)
        if self._fwd_fn is None:
            model = self.module

            def f(params, ids):
                return model.apply({"params": self._dequant(params)}, ids,
                                   deterministic=True)

            self._fwd_fn = jax.jit(f)
        t0 = time.time()
        out = self._fwd_fn(self._params, input_ids)
        if self._profile:
            jax.block_until_ready(out)
            self._model_times.append(time.time() - t0)
        return out

    __call__ = forward

    def model_times(self):
        times = self._model_times
        self._model_times = []
        return times

    # ------------------------------------------------------------------
    # generation (prefill + greedy/sampled decode over the KV cache)
    # ------------------------------------------------------------------
    def _build_decode_fns(self):
        """Compiled once per input shape (jit's shape cache); the cache
        buffer is donated so decode steps update KV in place."""
        model = self.module

        def prefill(params, ids, mask):
            # cache variables are created on first mutable apply; the whole
            # prompt is written into the KV cache in one pass
            logits, vars_out = model.apply(
                {"params": self._dequant(params)}, ids, attention_mask=mask,
                deterministic=True, decode=True, mutable=["cache"])
            return logits[:, -1], vars_out["cache"]

        def prefill_more(params, ids, mask, cache):
            # continuation pass of a chunked prefill: the cache already
            # exists, this span's tokens append at the rows' cache_index
            logits, vars_out = model.apply(
                {"params": self._dequant(params), "cache": cache}, ids,
                attention_mask=mask, deterministic=True, decode=True,
                mutable=["cache"])
            return logits[:, -1], vars_out["cache"]

        def one_token(params, token, cache, rng, temperature):
            # dequant HERE, inside the decode scan body: the int8->compute
            # convert fuses into the dots, so the per-token weight traffic
            # stays int8 on the wire
            logits, vars_out = model.apply(
                {"params": self._dequant(params), "cache": cache},
                token[:, None],
                deterministic=True, decode=True, mutable=["cache"])
            logits = logits[:, -1]

            def sample(r):
                return jax.random.categorical(r, logits / temperature, axis=-1)

            def greedy(_):
                return jnp.argmax(logits, axis=-1)

            next_tok = jax.lax.cond(temperature > 0, sample, greedy, rng)
            return next_tok.astype(jnp.int32), vars_out["cache"]

        def decode_k(params, token, cache, rng, temperature, k):
            """k tokens in ONE compiled program (lax.scan over the step).

            A Python token loop pays a dispatch round-trip per token —
            pure overhead at small batch; the reference amortizes it with
            CUDA-graph replay (inference/engine.py:523), the jit analogue
            of which is this scan. The rng chain (split per step) matches
            the per-token loop exactly, so sampled output is identical for
            a given starting key.
            """

            def body(carry, _):
                tok, cache, rng = carry
                rng, sub = jax.random.split(rng)
                nxt, cache = one_token(params, tok, cache, sub, temperature)
                return (nxt, cache, rng), nxt

            (tok, cache, rng), toks = jax.lax.scan(
                body, (token, cache, rng), None, length=k)
            # toks: [k, B] -> [B, k]
            return toks.swapaxes(0, 1), tok, cache, rng

        def verify_greedy(params, toks, cache):
            """Speculative-decode verification: ONE batched forward over
            ``[B, k+1]`` columns ``[t0, d1..dk]``. Column ``j``'s logits
            condition on ``t0..d_j`` exactly as sequential decode would, so
            ``argmax`` per column IS the greedy token after accepting ``j``
            drafts — acceptance is a host-side prefix match, and the
            scheduler rewinds the cache clocks past the first mismatch
            (ContinuousBatchingScheduler._rewind)."""
            logits, vars_out = model.apply(
                {"params": self._dequant(params), "cache": cache}, toks,
                deterministic=True, decode=True, mutable=["cache"])
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), \
                vars_out["cache"]

        self._prefill_fn = jax.jit(prefill)
        self._prefill_more_fn = jax.jit(prefill_more, donate_argnums=(3,))
        self._decode_k_fn = jax.jit(decode_k, static_argnums=(5,),
                                    donate_argnums=(2,))
        self._verify_greedy_fn = jax.jit(verify_greedy, donate_argnums=(2,))

    def _chunked_prefill(self, input_ids, attention_mask):
        """Prefill ``input_ids`` exactly: one pass when that is exact,
        block-aligned ``<= ring_len``-token passes for prompts longer than
        the ring (prefill_chunk_spans has the derivation). Returns
        (last-token logits, cache); with LEFT-aligned prompts the final
        span's last column is the last real token of every row."""
        mcfg = getattr(self.module, "config", None)
        spans = prefill_chunk_spans(mcfg, int(input_ids.shape[1]))
        if spans is None:
            return self._prefill_fn(self._params, input_ids, attention_mask)
        s0, e0 = spans[0]
        logits_last, cache = self._prefill_fn(
            self._params, input_ids[:, s0:e0], attention_mask[:, s0:e0])
        for s, e in spans[1:]:
            logits_last, cache = self._prefill_more_fn(
                self._params, input_ids[:, s:e], attention_mask[:, s:e],
                cache)
        return logits_last, cache

    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 0.0, attention_mask=None):
        """Greedy (temperature=0) or sampled generation.

        Ragged batches: pass ``attention_mask`` (1 = real token). Prompts
        are LEFT-aligned internally (pads moved to the front) so valid
        tokens stay physically contiguous in the KV cache — the masked
        decode then matches per-sequence generation exactly (reference
        inference_context.h masked decode; the padding-mask-aware cache
        lives in models/transformer_lm.py's decode attention).
        """
        set_default_topology(self.topology)
        mcfg = getattr(self.module, "config", None)
        # ONE ring decision for this call: drives both the dense-decode
        # divergence warning and the streaming cap below (shared helper —
        # the model's decode branch consults the same one)
        from deepspeed_tpu.ops.sparse_attention.sparse_attention_utils \
            import ring_engaged

        ring = ring_engaged(mcfg) if mcfg is not None else None
        if getattr(mcfg, "sparse_attention", None) is not None:
            # window(+leading-global) layouts decode through the ring KV
            # cache — the training sparse math exactly (transformer_lm
            # sparse_kv_cache); only layouts a ring cannot express (e.g.
            # BigBird's random links) fall back to dense decode, which
            # sees strictly MORE keys than training did — close, not
            # identical math (docs/DIVERGENCES.md Inference section)
            if ring is None:
                from deepspeed_tpu.utils.logging import warning_once

                warning_once(
                    "generate() on a sparse_attention-configured model: "
                    "this layout decodes with DENSE attention (training "
                    "was block-sparse); window/longformer layouts decode "
                    "sparse-exactly via the ring KV cache — including "
                    "prompts longer than the ring, which prefill in "
                    "block-aligned chunks — see docs/DIVERGENCES.md")
        input_ids = jnp.asarray(input_ids)
        if attention_mask is not None:
            ids_np = np.asarray(input_ids)
            m_np = np.asarray(attention_mask).astype(bool)
            if m_np.shape != ids_np.shape:
                raise ValueError(
                    f"attention_mask shape {m_np.shape} != input_ids "
                    f"shape {ids_np.shape}")
            if not m_np.any(axis=1).all():
                empty = np.where(~m_np.any(axis=1))[0].tolist()
                raise ValueError(
                    f"attention_mask rows {empty} have no valid tokens; "
                    "an empty prompt cannot seed generation")
            T = ids_np.shape[1]
            out_ids = np.zeros_like(ids_np)
            out_m = np.zeros_like(m_np)
            for b in range(ids_np.shape[0]):
                vtok = ids_np[b][m_np[b]]
                out_ids[b, T - len(vtok):] = vtok
                out_m[b, T - len(vtok):] = True
            input_ids = jnp.asarray(out_ids)
            attention_mask = jnp.asarray(out_m)
        if max_new_tokens < 0:
            raise ValueError(f"max_new_tokens must be >= 0, got {max_new_tokens}")
        if max_new_tokens == 0:
            return jnp.zeros((input_ids.shape[0], 0), jnp.int32)
        max_pos = getattr(mcfg, "n_positions", None)
        if max_pos is not None and input_ids.shape[1] + max_new_tokens > max_pos:
            # streaming decode: a ring-cached model with no learned
            # position table (rotary/ALiBi-free-running positions) has
            # nothing that saturates at n_positions — the ring evicts old
            # window blocks and globals persist (the attention-sink
            # pattern), so generation length is unbounded at O(window)
            # memory. Models with a wpe table keep the hard cap.
            streaming = (ring is not None
                         and not getattr(mcfg, "learned_positions", True))
            if not streaming:
                raise ValueError(
                    f"prompt ({input_ids.shape[1]}) + max_new_tokens "
                    f"({max_new_tokens}) exceeds the KV cache capacity "
                    f"(n_positions={max_pos})")
        if self._params is None or not hasattr(self, "_param_shardings"):
            self._materialize(input_ids)
        if self._prefill_fn is None:
            self._build_decode_fns()
        self._rng, rng = jax.random.split(self._rng)

        if attention_mask is None:
            attention_mask = jnp.ones(input_ids.shape, jnp.bool_)
        input_ids = self._place_batch(input_ids)
        attention_mask = self._place_batch(attention_mask)
        logits_last, cache = self._chunked_prefill(input_ids,
                                                   attention_mask)
        rng, sub = jax.random.split(rng)
        if temperature > 0:
            tok = jax.random.categorical(
                sub, logits_last / temperature, axis=-1).astype(jnp.int32)
        else:
            tok = jnp.argmax(logits_last, axis=-1).astype(jnp.int32)
        out = [tok[:, None]]
        temp = jnp.float32(temperature)
        # chunked scan decode, binary-decomposed: each dispatch runs the
        # largest power-of-two scan <= min(chunk, remaining), so ANY
        # max_new_tokens is served by at most log2(chunk) distinct compiled
        # scan lengths (cached across calls — no per-length recompile) and
        # never by per-token dispatches (each costs a full host->device
        # round-trip: ~40 ms/token on the tunneled transport vs 2.4 inside
        # the scan). Measured (gpt2-125m, 64 new tokens, tunneled v5e,
        # ms/token p50): scan length 1: 5.7, 8: 3.7, 16: 2.6, 32: 2.4,
        # 63: 3.4 — 16-32 is the plateau, so chunk defaults to 32.
        chunk = max(1, int(self._config.get("decode_chunk", 32)))
        eff = 1 << (chunk.bit_length() - 1)
        if eff != chunk:
            from deepspeed_tpu.utils.logging import warning_once

            # each dispatch runs the largest power-of-two scan <= chunk
            # (binary tail decomposition bounds the compile cache); say so
            # once instead of silently flooring a configured 24 to 16
            warning_once(
                f"decode_chunk={chunk} is not a power of two; dispatches "
                f"use {eff}-token scans (plus a binary-decomposed tail)")
        remaining = max_new_tokens - 1
        while remaining > 0:
            k = min(chunk, remaining)
            k = 1 << (k.bit_length() - 1)  # largest power of two <= k
            toks, tok, cache, rng = self._decode_k_fn(
                self._params, tok, cache, rng, temp, k)
            out.append(toks)
            remaining -= k
        return jnp.concatenate(out, axis=1)

    # ------------------------------------------------------------------
    def _load_checkpoint(self, path: str):
        """Load a msgpack state dict saved by the training engine
        (save_checkpoint model states or save_16bit_model); resharding onto
        the inference mesh happens at materialization (reference
        state_dict_factory MP resharding, state_dict_factory.py:20)."""
        state = MsgpackCheckpointEngine().load(path)
        module = state.get("module", state)
        # HOST-side arrays; placed per-shard at _materialize (see __init__)
        self._host_params = serialization.msgpack_restore(
            serialization.msgpack_serialize(module)) if not isinstance(
                module, dict) else module
        self._params = None

    @property
    def params(self):
        return self._params

"""Continuous-batching serving loop over the KV-cache decode path.

``InferenceEngine.generate`` serves ONE fixed batch start-to-finish: every
sequence waits for the slowest, and a finished lane burns decode FLOPs as
padding until lockstep termination. Production serving (vLLM-style
continuous batching; the reference's DeepSpeed-FastGen/MII serving layer)
instead keeps a fixed-shape decode batch hot and swaps *sequences* through
its lanes:

* the decode step is ONE jitted ``[slots, 1]`` program, compiled once —
  admissions and evictions never change its shape, so the hot loop never
  recompiles (the CUDA-graph-replay discipline, applied to scheduling);
* a finished sequence's lane is freed immediately and refilled from the
  pending queue: admission runs an EXACT chunked prefill on a ``[1, Lp]``
  batch (engine.prefill_chunk_spans — block-aligned passes keep every
  chunk's window ring-resident) and splices the resulting cache into the
  lane's cache rows with ``dynamic_update_slice`` — possible because the
  model's decode caches carry PER-ROW clocks (``cache_index[B]``,
  ``slot_pos[B, S]``), so one lane's time axis resets without touching its
  neighbors;
* completion is per-sequence (EOS or per-request max tokens), not
  lockstep, and every emitted token fires a streaming callback.

Free lanes keep decoding garbage tokens — attention is row-independent and
the masked softmax is NaN-safe, so a garbage lane costs FLOPs but never
contaminates a neighbor; its next admission overwrites every cache row it
touched.

Prompts are LEFT-padded to a ``prompt_bucket`` multiple to bound prefill
compile count (bucket is a multiple of the layout block for ring models,
so whole-block shifts preserve window visibility exactly; rotary positions
are relative, ALiBi shifts are row-constant under softmax, and wpe reads
the per-row semantic ``position`` counter — the same left-padding argument
as ``generate``'s ragged path). Caveat, shared with that path: BSLongformer
leading-global slots are PHYSICAL positions, so left-padding shifts real
tokens out of the global region — serve those layouts through
``generate``, or with bucket == prompt length.
"""

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference.engine import (
    continuation_chunk_spans,
    prefill_chunk_spans,
)
from deepspeed_tpu.parallel.mesh import set_default_topology


class AdmissionRejected(RuntimeError):
    """Base for 429-style rejections; carries the request id (or None when
    rejected before one was issued) and a machine-readable reason."""

    def __init__(self, message: str, reason: str = "rejected"):
        super().__init__(message)
        self.reason = reason


class QueueFullError(AdmissionRejected):
    """submit() hit the scheduler's ``max_pending`` bound."""

    def __init__(self, message: str):
        super().__init__(message, reason="queue_full")


class RequestShedError(AdmissionRejected):
    """The admission controller shed this request to hold its SLO."""

    def __init__(self, message: str, reason: str = "slo_shed"):
        super().__init__(message, reason=reason)


class DeadlineExceededError(AdmissionRejected):
    """The request's deadline expired before it could be served."""

    def __init__(self, message: str):
        super().__init__(message, reason="deadline")


class DrainingError(AdmissionRejected):
    """Admission is closed: the scheduler is draining (SIGTERM)."""

    def __init__(self, message: str):
        super().__init__(message, reason="draining")


@dataclass
class Request:
    """One sequence to serve: prompt token ids plus completion rules."""
    prompt: Sequence[int]
    max_new_tokens: int = 32
    eos_token_id: Optional[int] = None
    # called as callback(request_id, token_id, done) per emitted token
    stream_callback: Optional[Callable[[int, int, bool], None]] = None
    request_id: Optional[int] = None
    # absolute time.monotonic() by which the FIRST token must be on its
    # way; an expired request is shed from the queue, never a lane
    t_deadline: Optional[float] = None
    # failover replay: tokens this request already emitted on a replica
    # that died. Admission re-prefills prompt + replay_tokens (prompt at
    # its original bucket, then continuation_chunk_spans over the
    # emitted region — identical pad offset and chunk geometry to the
    # uninterrupted run) and decoding continues under the ORIGINAL
    # max_new_tokens budget. Greedy decode is a pure function of
    # (weights, tokens-so-far), so the continuation is token-identical.
    replay_tokens: Optional[List[int]] = None
    # disaggregated serving hand-off (serving/disagg.py): a prefill
    # replica already ran this prompt's exact chunked prefill, and
    # admission splices the handed ``(first_token, [1, ...] cache)``
    # into a lane instead of prefilling locally. The producer must have
    # used the SAME prompt_bucket — the cache bakes in the pad offset.
    kv_handoff: Optional[Any] = None


@dataclass
class Completion:
    """Result + latency telemetry for one served request."""
    request_id: int
    tokens: List[int]
    prompt_len: int
    t_submit: float = 0.0
    t_admit: float = 0.0
    t_first_token: float = 0.0
    t_done: float = 0.0

    @property
    def ttft_s(self) -> float:
        """Time-to-first-token from submission (includes queue wait)."""
        return self.t_first_token - self.t_submit

    @property
    def per_token_s(self) -> float:
        """Mean inter-token latency after the first token."""
        n = len(self.tokens)
        if n <= 1:
            return 0.0
        return (self.t_done - self.t_first_token) / (n - 1)


@dataclass
class _Lane:
    req: Request
    comp: Completion
    emitted: int = 0


@dataclass
class ServingStats:
    completions: List[Completion] = field(default_factory=list)
    wall_s: float = 0.0
    decode_steps: int = 0

    def summary(self) -> Dict[str, Any]:
        ttfts = sorted(c.ttft_s for c in self.completions)
        pts = [c.per_token_s for c in self.completions if len(c.tokens) > 1]
        total_tokens = sum(len(c.tokens) for c in self.completions)

        def pct(xs, q):
            if not xs:
                return 0.0
            return float(xs[min(len(xs) - 1, int(q * len(xs)))])

        return {
            "num_sequences": len(self.completions),
            "total_generated_tokens": total_tokens,
            "wall_s": self.wall_s,
            "aggregate_tokens_per_s": (total_tokens / self.wall_s
                                       if self.wall_s > 0 else 0.0),
            "ttft_s": {"mean": float(np.mean(ttfts)) if ttfts else 0.0,
                       "p50": pct(ttfts, 0.50), "p95": pct(ttfts, 0.95)},
            "per_token_ms": {
                "mean": float(np.mean(pts)) * 1e3 if pts else 0.0,
                "p50": pct(sorted(pts), 0.50) * 1e3,
                "p95": pct(sorted(pts), 0.95) * 1e3},
            "decode_steps": self.decode_steps,
        }


class ContinuousBatchingScheduler:
    """Slot-based continuous batching over an ``InferenceEngine``.

    ``submit()`` requests (before or during ``run()`` — a stream callback
    may submit follow-ups), then ``run()`` drives admissions, the jitted
    fixed-shape decode loop, per-sequence completion, and streaming
    callbacks until the queue drains. Returns completions in finish order.
    """

    def __init__(self, engine, slots: int = 8,
                 prompt_bucket: Optional[int] = None,
                 temperature: float = 0.0,
                 eos_token_id: Optional[int] = None,
                 max_pending: Optional[int] = None,
                 prefix_cache=None,
                 admission_controller=None,
                 reject_callback: Optional[Callable] = None,
                 journal=None,
                 health_provider=None,
                 draft_engine=None,
                 spec_k: int = 0):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if max_pending is not None and max_pending < 1:
            raise ValueError(f"max_pending must be >= 1, got {max_pending}")
        self.engine = engine
        self.slots = int(slots)
        self.temperature = float(temperature)
        self.eos_token_id = eos_token_id
        # front-door hooks (serving/ wires these; all duck-typed so the
        # scheduler keeps zero imports from the serving package):
        #   max_pending — bound on the submit queue, SLO controller or not
        #   prefix_cache — serving.PrefixCache (lookup/promotion_target/
        #       insert/release protocol used in _admit_prefill)
        #   admission_controller — .decide(queue_depth, slots) ->
        #       (admit, reason), consulted per submit()
        #   reject_callback(request_id, reason) — the 429 hook, invoked
        #       before the typed error is raised
        #   journal — serving.RequestJournal (record_submit/record_token/
        #       record_shed), the exact-failover flight record
        #   health_provider — .states() dict folded into frontdoor_stats
        #       and the per-iteration serve.stats event
        #   draft_engine + spec_k — draft-model speculative decoding: the
        #       draft proposes spec_k greedy tokens per lane per step, ONE
        #       batched target forward verifies them, and the per-row
        #       cache clocks rewind past the first mismatch. Exact vs
        #       sequential greedy by construction (every emitted token is
        #       a target-argmax given its prefix), so it composes with
        #       failover replay and the prefix cache unchanged.
        self.max_pending = None if max_pending is None else int(max_pending)
        self.prefix_cache = prefix_cache
        self.admission_controller = admission_controller
        self.reject_callback = reject_callback
        self.journal = journal
        self.health_provider = health_provider
        self.draft_engine = draft_engine
        self.spec_k = int(spec_k)
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.shed_count = 0
        self.deadline_shed_count = 0
        self._draining = False
        self.drain_reason: Optional[str] = None
        self._lanes_active = 0
        self._mcfg = getattr(engine.module, "config", None)

        from deepspeed_tpu.ops.sparse_attention.sparse_attention_utils \
            import ring_engaged

        self._ring = ring_engaged(self._mcfg) if self._mcfg is not None \
            else None
        if prompt_bucket is None:
            prompt_bucket = self._ring[2] if self._ring is not None else 64
        if self._ring is not None and prompt_bucket % self._ring[2] != 0:
            raise ValueError(
                f"prompt_bucket {prompt_bucket} must be a multiple of the "
                f"ring layout block {self._ring[2]}: admission prefill "
                "left-pads to the bucket, and only whole-block shifts "
                "preserve the training window visibility exactly")
        self.prompt_bucket = int(prompt_bucket)

        # hard capacity for models whose decode cannot stream (dense cache
        # or learned positions): prompt + generation must fit n_positions
        self._max_pos = getattr(self._mcfg, "n_positions", None)
        self._streaming = (self._ring is not None and
                           not getattr(self._mcfg, "learned_positions", True))

        # speculative decoding preconditions — checked HERE, not in the
        # hot loop, because every one of them is a config property
        if self.spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {spec_k}")
        if (draft_engine is None) != (self.spec_k == 0):
            raise ValueError(
                "speculative decoding needs BOTH a draft_engine and "
                f"spec_k >= 1 (got draft_engine="
                f"{'set' if draft_engine is not None else 'None'}, "
                f"spec_k={spec_k})")
        self._draft_mcfg = None
        self._draft_ring = None
        if draft_engine is not None:
            if self.temperature != 0.0:
                raise ValueError(
                    "speculative decoding here is EXACT-greedy only "
                    "(accepted tokens are target argmaxes); temperature "
                    f"must be 0.0, got {temperature}")
            if self._ring is not None:
                blk = self._ring[2]
                slack = int(getattr(self._mcfg, "kv_cache_slack_blocks",
                                    0) or 0)
                if slack < 1:
                    raise ValueError(
                        "speculative decoding over a ring KV cache needs "
                        "kv_cache_slack_blocks >= 1 on the TARGET model: "
                        "the k+1-column verify pass writes every column "
                        "before attention reads, and without a slack "
                        "block an unaligned pass can evict entries its "
                        "own earlier columns still need "
                        "(ops/sparse_attention ring_storage_len)")
                if self.spec_k > blk:
                    raise ValueError(
                        f"spec_k ({spec_k}) must be <= the ring layout "
                        f"block ({blk}): one slack block makes passes of "
                        "at most `block` tokens exact")
            self._draft_mcfg = getattr(draft_engine.module, "config", None)
            self._draft_ring = (ring_engaged(self._draft_mcfg)
                                if self._draft_mcfg is not None else None)
            if self._draft_ring is not None and \
                    self.prompt_bucket % self._draft_ring[2] != 0:
                raise ValueError(
                    f"prompt_bucket {self.prompt_bucket} must be a "
                    f"multiple of the DRAFT model's ring block "
                    f"({self._draft_ring[2]}): admission prefills the "
                    "draft cache at the same bucket")

        self._pending: deque = deque()
        self._next_id = 0
        self._splice_fn = None
        self._copy_fn = None
        self._rewind_fn = None
        self._empty_cache_shapes = None
        self._kv_stats_static = None

    # ------------------------------------------------------------------
    def submit(self, prompt: Sequence[int], max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None,
               stream_callback: Optional[Callable] = None,
               deadline_s: Optional[float] = None,
               replay_tokens: Optional[Sequence[int]] = None,
               kv_handoff: Optional[Any] = None) -> int:
        """Queue one request; returns its request id.

        Raises ``QueueFullError`` when the queue is at ``max_pending``,
        ``RequestShedError`` when the admission controller sheds,
        ``DeadlineExceededError`` when ``deadline_s`` is already spent,
        and ``DrainingError`` once ``begin_drain`` closed admission —
        all AdmissionRejected, the 429 surface. The reject callback
        fires first, so a server can answer the client before the raise
        unwinds.

        ``deadline_s`` is a relative first-token budget: a request still
        queued when it expires is shed from the queue (never occupying a
        lane), with a ``serve.deadline_shed`` event. ``replay_tokens``
        marks a failover replay (see ``Request.replay_tokens``): the
        stream callback fires only for NEW tokens — the client already
        holds the replayed prefix.

        ``kv_handoff`` is the disaggregated-prefill hand-off (see
        ``Request.kv_handoff``): admission splices the handed cache
        instead of prefilling locally. Mutually exclusive with
        ``replay_tokens`` — a replayed request must re-run its emitted
        region, which the hand-off by definition has not seen.
        """
        prompt = list(int(t) for t in prompt)
        if not prompt:
            raise ValueError("an empty prompt cannot seed generation")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if kv_handoff is not None and replay_tokens:
            raise ValueError(
                "kv_handoff and replay_tokens are mutually exclusive: a "
                "failover replay must re-run its emitted tokens, which a "
                "prefill hand-off has not seen")
        replay = [int(t) for t in replay_tokens] if replay_tokens else []
        if replay and len(replay) >= max_new_tokens:
            raise ValueError(
                f"replay of {len(replay)} tokens exhausts the "
                f"max_new_tokens budget ({max_new_tokens}) — the request "
                "already finished; do not replay it")
        depth = len(self._pending)
        if self._draining:
            self._reject(DrainingError(
                "admission is closed: the scheduler is draining "
                f"({self.drain_reason})"), depth)
        if deadline_s is not None and deadline_s <= 0:
            from deepspeed_tpu.telemetry.bus import KIND_SERVE_DEADLINE_SHED

            self._reject(DeadlineExceededError(
                f"deadline_s={deadline_s} already expired at submit"),
                depth, kind=KIND_SERVE_DEADLINE_SHED)
        if self.max_pending is not None and depth >= self.max_pending:
            self._reject(QueueFullError(
                f"admission queue is full ({depth}/{self.max_pending} "
                "pending); retry after the scheduler drains"), depth)
        if self.admission_controller is not None:
            admit, reason = self.admission_controller.decide(
                queue_depth=depth, slots=self.slots)
            if not admit:
                self._reject(RequestShedError(
                    f"request shed by admission control: {reason}"), depth)
        bucketed = self._bucketed_len(len(prompt))
        if self._max_pos is not None and not self._streaming and \
                bucketed + max_new_tokens > self._max_pos:
            raise ValueError(
                f"bucketed prompt ({bucketed}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the KV cache capacity "
                f"(n_positions={self._max_pos})")
        rid = self._next_id
        self._next_id += 1
        now = time.monotonic()
        req = Request(prompt=prompt, max_new_tokens=int(max_new_tokens),
                      eos_token_id=(self.eos_token_id if eos_token_id is None
                                    else eos_token_id),
                      stream_callback=stream_callback, request_id=rid,
                      t_deadline=(None if deadline_s is None
                                  else now + float(deadline_s)),
                      replay_tokens=replay or None,
                      kv_handoff=kv_handoff)
        if self.journal is not None:
            self.journal.record_submit(
                rid, prompt, req.max_new_tokens,
                deadline=req.t_deadline, emitted=replay)
        self._pending.append((req, now))
        return rid

    def _reject(self, exc: AdmissionRejected, depth: int, kind=None):
        """Publish serve.shed (or ``kind``), fire the 429 callback,
        raise ``exc``."""
        from deepspeed_tpu.telemetry.bus import KIND_SERVE_SHED, publish

        self.shed_count += 1
        if isinstance(exc, DeadlineExceededError):
            self.deadline_shed_count += 1
        publish(kind or KIND_SERVE_SHED, severity="warning",
                reason=exc.reason, queue_depth=depth,
                shed_total=self.shed_count)
        if self.reject_callback is not None:
            try:
                self.reject_callback(None, exc.reason)
            except Exception:  # the callback must not mask the rejection
                pass
        raise exc

    def begin_drain(self, reason: str = "drain") -> None:
        """Close admission (SIGTERM posture). Safe from a signal
        handler: sets a flag, publishes one ``serve.drain``, touches no
        jax state. ``run()`` finishes the lanes already decoding and
        returns with the queue intact for journal hand-off."""
        if self._draining:
            return
        from deepspeed_tpu.telemetry.bus import KIND_SERVE_DRAIN, publish

        self._draining = True
        self.drain_reason = str(reason)
        publish(KIND_SERVE_DRAIN, severity="warning", phase="begin",
                reason=self.drain_reason, queue_depth=len(self._pending),
                lanes_active=self._lanes_active)

    @property
    def draining(self) -> bool:
        return self._draining

    def _shed_expired(self, req: Request, t_submit: float) -> None:
        """Drop one queue entry whose deadline passed before a lane
        freed up — it never occupies a lane or runs a prefill."""
        from deepspeed_tpu.telemetry.bus import (
            KIND_SERVE_DEADLINE_SHED,
            publish,
        )

        now = time.monotonic()
        self.deadline_shed_count += 1
        publish(KIND_SERVE_DEADLINE_SHED, severity="warning",
                request_id=req.request_id, waited_s=now - t_submit,
                late_s=now - req.t_deadline,
                queue_depth=len(self._pending),
                deadline_shed_total=self.deadline_shed_count)
        if self.journal is not None:
            self.journal.record_shed(req.request_id)
        if self.reject_callback is not None:
            try:
                self.reject_callback(req.request_id, "deadline")
            except Exception:
                pass

    def _bucketed_len(self, n: int) -> int:
        b = self.prompt_bucket
        return ((n + b - 1) // b) * b

    # ------------------------------------------------------------------
    def _ensure_compiled(self):
        eng = self.engine
        set_default_topology(eng.topology)
        # the engine's param-shape init traces the TRAINING forward, whose
        # sparse layout requires block-divisible T with at least the full
        # window of blocks present (sparsity_config make_layout); param
        # shapes don't depend on B or T, so one [1, T_probe] probe does
        if eng._params is None or not hasattr(eng, "_param_shardings"):
            t_probe = self.prompt_bucket
            sc = getattr(self._mcfg, "sparse_attention", None)
            nswb = getattr(sc, "num_sliding_window_blocks", None)
            blk = getattr(sc, "block", None)
            if nswb and blk:
                t_probe = max(t_probe, int(nswb) * int(blk))
            eng._materialize(
                jnp.zeros((1, self._bucketed_len(t_probe)), jnp.int32))
        if eng._prefill_fn is None:
            eng._build_decode_fns()
        de = self.draft_engine
        if de is None:
            return
        # the draft engine compiles the same way, probed at ITS layout's
        # minimum trace length (its sparse config may differ)
        if de._params is None or not hasattr(de, "_param_shardings"):
            t_probe = self.prompt_bucket
            sc = getattr(self._draft_mcfg, "sparse_attention", None)
            nswb = getattr(sc, "num_sliding_window_blocks", None)
            blk = getattr(sc, "block", None)
            if nswb and blk:
                t_probe = max(t_probe, int(nswb) * int(blk))
            de._materialize(
                jnp.zeros((1, self._bucketed_len(t_probe)), jnp.int32))
        if de._prefill_fn is None:
            de._build_decode_fns()

    def _cache_shapes_for(self, eng):
        """Leaf geometry (jax.eval_shape, nothing materialized) of one
        ``[slots]``-lane decode cache for ``eng``'s model."""
        model = eng.module
        probe = jnp.zeros((self.slots, 1), jnp.int32)

        def shape_fn(params):
            _, vars_out = model.apply(
                {"params": eng._dequant(params)}, probe,
                deterministic=True, decode=True, mutable=["cache"])
            return vars_out["cache"]

        return jax.eval_shape(shape_fn, eng._params)

    def _cache_shapes(self):
        """The TARGET engine's cache geometry, memoized — `_empty_cache`
        initializes lanes from it and `kv_cache_stats` accounts resident
        bytes from it without allocating anything."""
        if self._empty_cache_shapes is None:
            self._empty_cache_shapes = self._cache_shapes_for(self.engine)
        return self._empty_cache_shapes

    def _empty_cache(self, eng=None):
        """A ``[slots]``-lane cache with every per-row clock at its virgin
        value, WITHOUT running the model (a real apply would advance
        ``cache_index``/``position`` and bake garbage into ``slot_pos``):
        eval_shape the decode apply for the leaf geometry, then initialize
        by name — ``slot_pos`` is -1 (no position cached), everything else
        zeros (``valid`` bools are False, clocks are 0). ``eng`` defaults
        to the target engine; pass the draft engine for its lane cache."""
        if eng is None or eng is self.engine:
            shapes = self._cache_shapes()
        else:
            shapes = self._cache_shapes_for(eng)

        def init_leaf(path, sd):
            name = path[-1].key if hasattr(path[-1], "key") else path[-1]
            if name == "slot_pos":
                return jnp.full(sd.shape, -1, sd.dtype)
            return jnp.zeros(sd.shape, sd.dtype)

        return jax.tree_util.tree_map_with_path(init_leaf, shapes)

    def _splice(self, cache, sub_cache, lane):
        """Write a freshly prefilled ``[1, ...]`` cache into batch lane
        ``lane`` of the full cache. The batch axis differs per leaf (flax
        nn.scan caches carry a leading layer axis: ``[L, B, ...]`` vs the
        top-level ``position``/``cache_index`` at ``[B]``), so each leaf
        locates its own first differing axis. Jitted once, lane traced."""
        if self._splice_fn is None:

            def splice(full, sub, lane_idx):
                def one(f, s):
                    if f.shape == s.shape:  # slots == 1
                        return s
                    ax = next(i for i, (a, b)
                              in enumerate(zip(f.shape, s.shape)) if a != b)
                    starts = tuple(lane_idx if i == ax else 0
                                   for i in range(f.ndim))
                    return jax.lax.dynamic_update_slice(f, s, starts)

                return jax.tree.map(one, full, sub)

            self._splice_fn = jax.jit(splice, donate_argnums=(0,))
        return self._splice_fn(cache, sub_cache, jnp.int32(lane))

    def _copy_tree(self, tree):
        """Jitted deep copy of a cache pytree. Continuation prefill DONATES
        its cache argument, so both the cached entry handed to a lane and
        the snapshot taken at a promotion boundary must be fresh buffers —
        extending a cached tree in place would invalidate the cache."""
        if self._copy_fn is None:
            self._copy_fn = jax.jit(
                lambda t: jax.tree.map(jnp.copy, t))
        return self._copy_fn(tree)

    def _rewind(self, snapshot, cache, delta):
        """Step every per-row cache clock back by ``delta[B]`` REJECTED
        tokens, restoring from ``snapshot`` (the copy taken before the
        speculative pass) every entry those rejected writes clobbered.

        Selective per-slot restore, not a wholesale snapshot swap: the
        accepted prefix's writes must SURVIVE — they are exactly the
        writes sequential decode would have made — so a slot is stale
        (take snapshot) iff its entry was written at a position at or
        past the new clock: ring caches compare ``slot_pos`` against the
        new ``cache_index``, dense caches compare the storage position
        itself (storage index == semantic position). ``cache_index`` and
        the top-level ``position`` counters step back by delta. Ragged
        per-lane acceptance is just a ragged ``delta``. Jitted once;
        only the live cache is donated (each output leaf can reuse at
        most one input buffer, so donating the snapshot too would just
        warn)."""
        if self._rewind_fn is None:
            from collections.abc import Mapping

            def rewind(c0, c1, d):
                def rewind_attn(a0, a1):
                    ci = a1["cache_index"]
                    # ci is [B] ([L, B] under nn.scan); d broadcasts up
                    idx_new = ci - d.astype(ci.dtype)
                    if "slot_pos" in a1:
                        stale = a1["slot_pos"] >= idx_new[..., None]
                    else:
                        s_len = a1["cached_key"].shape[-3]
                        pos = jnp.arange(s_len, dtype=ci.dtype)
                        stale = pos >= idx_new[..., None]
                    out = {}
                    for k in a1:
                        if k == "cache_index":
                            out[k] = idx_new
                            continue
                        v0, v1 = a0[k], a1[k]
                        m = stale.reshape(
                            stale.shape + (1,) * (v1.ndim - stale.ndim))
                        out[k] = jnp.where(m, v0, v1)
                    return out

                def walk(t0, t1, top):
                    out = {}
                    for k in t1:
                        v1 = t1[k]
                        if isinstance(v1, Mapping):
                            if "cache_index" in v1:
                                out[k] = rewind_attn(t0[k], v1)
                            else:
                                out[k] = walk(t0[k], v1, False)
                        elif top and k == "position":
                            out[k] = v1 - d.astype(v1.dtype)
                        else:
                            out[k] = v1
                    return out

                return walk(c0, c1, True)

            self._rewind_fn = jax.jit(rewind, donate_argnums=(1,))
        return self._rewind_fn(snapshot, cache, delta)

    def _draft_prefill(self, ids: np.ndarray, mask: np.ndarray,
                       req: Request):
        """Chunked prefill of the DRAFT model's cache for one admission
        (logits discarded — the draft only proposes from decode steps).
        Replays run the same continuation spans so a failed-over
        request's draft clock lands where its target clock does."""
        de = self.draft_engine
        _, sub = de._chunked_prefill(jnp.asarray(ids), jnp.asarray(mask))
        if req.replay_tokens:
            Lp = ids.shape[1]
            E = len(req.replay_tokens)
            rep_ids = np.asarray([req.replay_tokens], np.int32)
            rep_mask = np.ones((1, E), bool)
            for s, e in continuation_chunk_spans(self._draft_mcfg,
                                                 Lp, Lp + E):
                _, sub = de._prefill_more_fn(
                    de._params, jnp.asarray(rep_ids[:, s - Lp:e - Lp]),
                    jnp.asarray(rep_mask[:, s - Lp:e - Lp]), sub)
        return sub

    def _admit_prefill(self, req: Request):
        """Exact (chunked when needed) prefill of one prompt on a
        ``[1, Lp]`` batch; returns (first sampled token, sub cache,
        draft sub cache — None without speculative decoding)."""
        eng = self.engine
        Lp = self._bucketed_len(len(req.prompt))
        ids = np.zeros((1, Lp), np.int32)
        mask = np.zeros((1, Lp), bool)
        ids[0, Lp - len(req.prompt):] = req.prompt
        mask[0, Lp - len(req.prompt):] = True
        # the draft cache is ALWAYS built locally — a hand-off carries
        # only the target cache (the draft is a decode-side accessory)
        draft_sub = (self._draft_prefill(ids, mask, req)
                     if self.draft_engine is not None else None)
        if req.kv_handoff is not None:
            # disaggregated hand-off: a prefill replica already ran this
            # prompt's exact chunked prefill at the same bucket. Copy
            # before splicing — the producer may fan the same entry out
            # to several decode lanes, and _splice donates.
            first_tok, sub_cache = req.kv_handoff
            return int(first_tok), self._copy_tree(sub_cache), draft_sub
        if self.prefix_cache is not None:
            logits_last, sub_cache = self._prefix_prefill(
                ids, mask, req.request_id)
        else:
            logits_last, sub_cache = eng._chunked_prefill(
                jnp.asarray(ids), jnp.asarray(mask))
        if req.replay_tokens:
            # failover replay: re-run the emitted tokens as a chunked
            # CONTINUATION prefill starting at the original bucket Lp —
            # identical pad offset and chunk geometry to the
            # uninterrupted run, so the cache state (and every logit
            # after it) is bit-identical to the run that died
            E = len(req.replay_tokens)
            rep_ids = np.asarray([req.replay_tokens], np.int32)
            rep_mask = np.ones((1, E), bool)
            for s, e in continuation_chunk_spans(self._mcfg, Lp, Lp + E):
                logits_last, sub_cache = eng._prefill_more_fn(
                    eng._params, jnp.asarray(rep_ids[:, s - Lp:e - Lp]),
                    jnp.asarray(rep_mask[:, s - Lp:e - Lp]), sub_cache)
        eng._rng, sub = jax.random.split(eng._rng)
        if self.temperature > 0:
            tok = jax.random.categorical(
                sub, logits_last / self.temperature, axis=-1)
        else:
            tok = jnp.argmax(logits_last, axis=-1)
        return int(np.asarray(tok)[0]), sub_cache, draft_sub

    def _prefix_prefill(self, ids: np.ndarray, mask: np.ndarray,
                        request_id):
        """Admission prefill through the shared-prefix cache.

        The cache key is the PADDED column prefix (pads encoded as -1):
        decode positions advance for pad columns too and rotary phases are
        baked into cached keys at write time, so a cached prefix is only
        numerics-compatible with the cold path at the same padded offset.
        Two prompts therefore share an entry iff they agree on both the
        leading tokens AND ``(-len) % prompt_bucket``.

        On a hit: copy the entry's leaves (continuation donates) and resume
        the chunked prefill from the cached length via
        ``continuation_chunk_spans`` — spans that never cross a layout
        block keep every chunk exact, same argument as the cold path. On a
        promotion (``promotion_target``): prefill ``[0, c)`` cold, snapshot
        a copy into the cache, continue to ``Lp``. With no hit and no
        promotion this is byte-for-byte the cold ``_chunked_prefill``.
        """
        eng = self.engine
        pc = self.prefix_cache
        Lp = ids.shape[1]
        cols = tuple(int(t) if m else -1
                     for t, m in zip(ids[0], mask[0]))
        # limit Lp-1 keeps >= 1 column for the continuation pass, so the
        # final span always regenerates the last-token logits
        entry = pc.lookup(cols, limit=Lp - 1, request_id=request_id)
        start = 0
        cache = None
        if entry is not None:
            start = entry.length
            cache = self._copy_tree(entry.cache)
            pc.release(entry)
        target = pc.promotion_target(cols, limit=Lp - 1, have=start)

        logits_last = None
        if cache is None:
            cold_end = target if target is not None else Lp
            logits_last, cache = eng._chunked_prefill(
                jnp.asarray(ids[:, :cold_end]),
                jnp.asarray(mask[:, :cold_end]))
            start = cold_end
        if target is not None and target > start:
            for s, e in continuation_chunk_spans(self._mcfg, start, target):
                logits_last, cache = eng._prefill_more_fn(
                    eng._params, jnp.asarray(ids[:, s:e]),
                    jnp.asarray(mask[:, s:e]), cache)
            start = target
        if target is not None:
            pc.insert(cols[:target], self._copy_tree(cache),
                      request_id=request_id)
        if start < Lp:
            for s, e in continuation_chunk_spans(self._mcfg, start, Lp):
                logits_last, cache = eng._prefill_more_fn(
                    eng._params, jnp.asarray(ids[:, s:e]),
                    jnp.asarray(mask[:, s:e]), cache)
        return logits_last, cache

    def kv_cache_stats(self, hbm_override_gib: Optional[float] = None
                       ) -> Dict[str, Any]:
        """KV-cache byte accounting from the memoized leaf geometry.

        ``resident_bytes`` is what THIS cache actually stores (int8
        payloads plus their f32 scale sidebands when kv_cache_dtype is
        "int8"); ``unquantized_bytes`` is the compute-dtype twin — the
        same geometry with ``cached_key``/``cached_value`` at the model
        dtype and no sidebands. Their ratio is the honest compression
        factor, and with a known HBM size (telemetry/memory.hbm_bytes)
        ``lanes_at_hbm_budget`` says how many decode lanes of THIS
        per-lane footprint fit the part — the capacity number the
        disaggregated-serving sizing tables are built from."""
        from deepspeed_tpu.telemetry.memory import hbm_bytes

        if self._kv_stats_static is None:
            shapes = self._cache_shapes()
            compute_dt = jnp.dtype(getattr(self._mcfg, "dtype",
                                           jnp.float32))
            resident = 0
            unquant = 0

            def acc(path, sd):
                nonlocal resident, unquant
                name = path[-1].key if hasattr(path[-1], "key") \
                    else path[-1]
                nbytes = sd.size * jnp.dtype(sd.dtype).itemsize
                resident += nbytes
                if name in ("cached_key", "cached_value"):
                    unquant += sd.size * compute_dt.itemsize
                elif name in ("cached_key_scale", "cached_value_scale"):
                    pass  # sideband of the int8 store; the twin has none
                else:
                    unquant += nbytes

            jax.tree_util.tree_map_with_path(acc, shapes)
            self._kv_stats_static = {
                "kv_cache_dtype": (getattr(self._mcfg, "kv_cache_dtype",
                                           None) or "compute"),
                "resident_bytes": int(resident),
                "unquantized_bytes": int(unquant),
                "bytes_per_lane": int(resident // self.slots),
                "lanes": self.slots,
                "compression_ratio": (float(unquant) / float(resident)
                                      if resident else 1.0),
            }
        out = dict(self._kv_stats_static)
        hbm, source = hbm_bytes(override_gib=hbm_override_gib)
        if hbm:
            out["hbm_bytes"] = int(hbm)
            out["hbm_source"] = source
            per_lane = out["bytes_per_lane"]
            out["lanes_at_hbm_budget"] = (int(hbm // per_lane)
                                          if per_lane else 0)
        return out

    def frontdoor_stats(self) -> Dict[str, Any]:
        """Shed + prefix-cache + health counters for benches/servers."""
        out: Dict[str, Any] = {"shed": self.shed_count,
                               "deadline_shed": self.deadline_shed_count,
                               "pending": len(self._pending),
                               "lanes_active": self._lanes_active,
                               "draining": self._draining}
        if self.prefix_cache is not None:
            out["prefix"] = self.prefix_cache.stats()
        if self.admission_controller is not None and \
                hasattr(self.admission_controller, "stats"):
            out["admission"] = self.admission_controller.stats()
        if self.journal is not None and hasattr(self.journal, "stats"):
            out["journal"] = self.journal.stats()
        if self.health_provider is not None and \
                hasattr(self.health_provider, "states"):
            out["health"] = dict(self.health_provider.states())
        if self.draft_engine is not None:
            out["spec"] = {
                "k": self.spec_k,
                "proposed": self.spec_proposed,
                "accepted": self.spec_accepted,
                "accept_rate": (self.spec_accepted / self.spec_proposed
                                if self.spec_proposed else 0.0)}
        # gated on the geometry already being traced (run() does it):
        # frontdoor_stats must stay safe on fake/unmaterialized engines
        if self._empty_cache_shapes is not None or \
                self._kv_stats_static is not None:
            out["kv_cache"] = self.kv_cache_stats()
        return out

    def _publish_stats(self, stats: "ServingStats", lanes) -> None:
        """One ``serve.stats`` snapshot per scheduler iteration — queue
        depth, lane occupancy, shed counters, prefix hit-rate and fleet
        health, so dashboards see front-door pressure without polling."""
        from deepspeed_tpu.telemetry.bus import KIND_SERVE_STATS, publish

        self._lanes_active = sum(1 for l in lanes if l is not None)
        payload: Dict[str, Any] = {
            "queue_depth": len(self._pending),
            "lanes_active": self._lanes_active,
            "shed": self.shed_count,
            "deadline_shed": self.deadline_shed_count,
            "decode_steps": stats.decode_steps,
            "draining": self._draining,
        }
        if self.prefix_cache is not None:
            payload["prefix_hit_rate"] = \
                self.prefix_cache.stats().get("hit_rate", 0.0)
        if self.health_provider is not None and \
                hasattr(self.health_provider, "states"):
            payload["health"] = dict(self.health_provider.states())
        if self._empty_cache_shapes is not None:
            kv = self.kv_cache_stats()
            payload["kv_resident_bytes"] = kv["resident_bytes"]
            payload["kv_unquantized_bytes"] = kv["unquantized_bytes"]
        publish(KIND_SERVE_STATS, **payload)

    # ------------------------------------------------------------------
    def run(self, poll_fn: Optional[Callable[[], None]] = None
            ) -> ServingStats:
        """Serve the queue to completion; returns stats + completions.

        ``poll_fn`` (optional) is called once per loop iteration, between
        decode steps — the hook a fleet replica uses to pump its control
        pipe, so failover replays submitted mid-run land in free lanes
        without waiting for this run to finish.

        While draining (``begin_drain``): no new admissions, lanes
        already decoding finish normally, and the loop exits with the
        pending queue INTACT — the caller hands those (and nothing else)
        off via the journal.
        """
        self._ensure_compiled()
        eng = self.engine
        stats = ServingStats()
        lanes: List[Optional[_Lane]] = [None] * self.slots
        tok = np.zeros((self.slots,), np.int32)
        cache = self._empty_cache()
        eng._rng, rng = jax.random.split(eng._rng)
        temp = jnp.float32(self.temperature)
        use_spec = self.draft_engine is not None
        draft_cache = draft_rng = None
        if use_spec:
            de = self.draft_engine
            draft_cache = self._empty_cache(de)
            de._rng, draft_rng = jax.random.split(de._rng)
        t_run0 = time.monotonic()

        from deepspeed_tpu.telemetry.bus import (
            KIND_SERVE_ADMIT,
            KIND_SERVE_EVICT,
            KIND_SERVE_FIRST_TOKEN,
            KIND_SERVE_SPEC_ACCEPT,
            publish,
        )

        def finish(lane_no: int, lane: _Lane):
            lane.comp.t_done = time.monotonic()
            stats.completions.append(lane.comp)
            lanes[lane_no] = None
            publish(KIND_SERVE_EVICT, request_id=lane.req.request_id,
                    lane=lane_no, tokens=lane.emitted,
                    queue_depth=len(self._pending))

        def emit(lane_no: int, lane: _Lane, token: int) -> bool:
            """Record one token; returns True when the sequence is done."""
            now = time.monotonic()
            lane.comp.tokens.append(token)
            lane.emitted += 1
            if lane.comp.t_first_token == 0.0:
                lane.comp.t_first_token = now
                # replays do not republish serve.first_token: the client
                # saw its first token on the replica that died, and a
                # replay-time sample would bias the admission p95 window
                if lane.req.replay_tokens is None:
                    publish(KIND_SERVE_FIRST_TOKEN,
                            request_id=lane.req.request_id, lane=lane_no,
                            ttft_s=now - lane.comp.t_submit)
            done = (lane.emitted >= lane.req.max_new_tokens
                    or (lane.req.eos_token_id is not None
                        and token == lane.req.eos_token_id))
            if self.journal is not None:
                self.journal.record_token(
                    lane.req.request_id, token, done=done)
            if lane.req.stream_callback is not None:
                lane.req.stream_callback(lane.req.request_id, token, done)
            return done

        while True:
            if poll_fn is not None:
                poll_fn()
            active = any(l is not None for l in lanes)
            if self._draining:
                if not active:
                    break  # queue left intact for journal hand-off
            elif not (self._pending or active):
                break
            # admissions: fill every free lane from the queue. A request
            # that completes AT admission (max_new 1, or first token is
            # EOS) frees its lane for the next pending request immediately.
            # An expired deadline sheds here — before the prefill, so a
            # doomed request never occupies a lane. Draining admits none.
            for lane_no in range(self.slots if not self._draining else 0):
                while lanes[lane_no] is None and self._pending:
                    req, t_submit = self._pending.popleft()
                    if req.t_deadline is not None and \
                            time.monotonic() > req.t_deadline:
                        self._shed_expired(req, t_submit)
                        continue
                    replayed = len(req.replay_tokens or ())
                    comp = Completion(request_id=req.request_id,
                                      tokens=list(req.replay_tokens or ()),
                                      prompt_len=len(req.prompt),
                                      t_submit=t_submit)
                    comp.t_admit = time.monotonic()
                    publish(KIND_SERVE_ADMIT, request_id=req.request_id,
                            lane=lane_no, prompt_len=len(req.prompt),
                            replayed=replayed,
                            queue_wait_s=comp.t_admit - t_submit,
                            queue_depth=len(self._pending))
                    first_tok, sub_cache, draft_sub = \
                        self._admit_prefill(req)
                    cache = self._splice(cache, sub_cache, lane_no)
                    if draft_sub is not None:
                        draft_cache = self._splice(
                            draft_cache, draft_sub, lane_no)
                    tok[lane_no] = first_tok
                    lane = _Lane(req=req, comp=comp, emitted=replayed)
                    lanes[lane_no] = lane
                    if emit(lane_no, lane, first_tok):
                        finish(lane_no, lane)

            self._publish_stats(stats, lanes)
            if not any(l is not None for l in lanes):
                continue  # everything admitted finished at token 1

            if use_spec:
                # speculative step: the draft proposes k greedy tokens
                # per lane (k sequential cheap steps), the target
                # verifies them in ONE [slots, k+1] forward, and both
                # caches rewind past each lane's first mismatch.
                # m_eff = min(m, k-1): no bonus token — accepting all k
                # would need the draft's k-th proposal in ITS cache,
                # which the proposal loop never wrote. Every emitted
                # token is a target argmax given the emitted prefix, so
                # the stream is exactly sequential greedy.
                k = self.spec_k
                de = self.draft_engine
                snap = self._copy_tree(cache)
                draft_snap = self._copy_tree(draft_cache)
                props, _, draft_cache, draft_rng = de._decode_k_fn(
                    de._params, jnp.asarray(tok), draft_cache, draft_rng,
                    jnp.float32(0.0), k)
                cols = jnp.concatenate(
                    [jnp.asarray(tok)[:, None], props], axis=1)
                g, cache = eng._verify_greedy_fn(eng._params, cols, cache)
                stats.decode_steps += 1
                g_np = np.asarray(g)
                props_np = np.asarray(props)
                matches = props_np == g_np[:, :k]
                m = np.where(matches.all(axis=1), k,
                             matches.argmin(axis=1))
                m_eff = np.minimum(m, k - 1).astype(np.int64)
                cache = self._rewind(
                    snap, cache, jnp.asarray((k - m_eff).astype(np.int32)))
                draft_cache = self._rewind(
                    draft_snap, draft_cache,
                    jnp.asarray((k - 1 - m_eff).astype(np.int32)))
                live = [ln for ln in range(self.slots)
                        if lanes[ln] is not None]
                self.spec_proposed += k * len(live)
                accepted_now = int(sum(int(m_eff[ln]) for ln in live))
                self.spec_accepted += accepted_now
                publish(KIND_SERVE_SPEC_ACCEPT, k=k, lanes=len(live),
                        proposed=k * len(live), accepted=accepted_now,
                        proposed_total=self.spec_proposed,
                        accepted_total=self.spec_accepted)
                for lane_no in live:
                    lane = lanes[lane_no]
                    for j in range(int(m_eff[lane_no]) + 1):
                        if emit(lane_no, lane, int(g_np[lane_no, j])):
                            finish(lane_no, lane)
                            break
                tok = g_np[np.arange(self.slots), m_eff] \
                    .astype(np.int32).copy()
            else:
                # ONE fixed-shape decode step for all lanes (garbage
                # lanes included — row-independent attention keeps them
                # harmless)
                toks, _, cache, rng = eng._decode_k_fn(
                    eng._params, jnp.asarray(tok), cache, rng, temp, 1)
                stats.decode_steps += 1
                tok = np.asarray(toks[:, 0]).astype(np.int32).copy()
                for lane_no in range(self.slots):
                    lane = lanes[lane_no]
                    if lane is None:
                        continue
                    if emit(lane_no, lane, int(tok[lane_no])):
                        finish(lane_no, lane)

        stats.wall_s = time.monotonic() - t_run0
        return stats

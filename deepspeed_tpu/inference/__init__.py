from deepspeed_tpu.inference.engine import (InferenceEngine, init_inference,
                                            prefill_chunk_spans)
from deepspeed_tpu.inference.scheduler import (Completion,
                                               ContinuousBatchingScheduler,
                                               Request, ServingStats)

__all__ = ["InferenceEngine", "init_inference", "prefill_chunk_spans",
           "ContinuousBatchingScheduler", "Request", "Completion",
           "ServingStats"]

from deepspeed_tpu.inference.engine import (InferenceEngine,
                                            continuation_chunk_spans,
                                            init_inference,
                                            prefill_chunk_spans)
from deepspeed_tpu.inference.scheduler import (AdmissionRejected, Completion,
                                               ContinuousBatchingScheduler,
                                               QueueFullError, Request,
                                               RequestShedError, ServingStats)

__all__ = ["InferenceEngine", "init_inference", "prefill_chunk_spans",
           "continuation_chunk_spans", "ContinuousBatchingScheduler",
           "Request", "Completion", "ServingStats", "AdmissionRejected",
           "QueueFullError", "RequestShedError"]

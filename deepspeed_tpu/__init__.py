"""deepspeed_tpu — a TPU-native large-model training & inference framework.

Capability surface of DeepSpeed v0.7.5 (reference ``deepspeed/__init__.py``)
re-designed for JAX/XLA: ``initialize()`` builds a training engine whose
forward/backward/step are jitted SPMD programs over a named device mesh;
``init_inference()`` builds a kernel-fused inference engine. ZeRO, tensor,
pipeline, expert, and sequence parallelism are PartitionSpecs over mesh axes
(see ``deepspeed_tpu/parallel/mesh.py``), not process groups.
"""

from deepspeed_tpu.version import __version__  # noqa: F401

# install jax version shims (jax.shard_map spelling) before any submodule
# traces a program
from deepspeed_tpu.utils import jax_compat  # noqa: F401

from deepspeed_tpu import comm  # noqa: F401
from deepspeed_tpu.runtime.config import DeepSpeedConfig  # noqa: F401
from deepspeed_tpu.runtime.sentinel import DivergenceError  # noqa: F401
from deepspeed_tpu.parallel.mesh import (  # noqa: F401
    MeshTopology,
    get_default_topology,
    set_default_topology,
)


def initialize(*args, **kwargs):
    """Build a DeepSpeedEngine (reference deepspeed/__init__.py:51).

    Imported lazily so light-weight users (config/comm only) avoid pulling the
    full runtime.
    """
    try:
        from deepspeed_tpu.runtime.engine import initialize as _initialize
    except ModuleNotFoundError as e:  # pragma: no cover
        raise NotImplementedError(
            "deepspeed_tpu.runtime.engine is not available in this build"
        ) from e

    return _initialize(*args, **kwargs)


def init_inference(*args, **kwargs):
    """Build an InferenceEngine (reference deepspeed/__init__.py:225)."""
    try:
        from deepspeed_tpu.inference.engine import init_inference as _init_inference
    except ModuleNotFoundError as e:  # pragma: no cover
        raise NotImplementedError(
            "deepspeed_tpu.inference.engine is not available in this build"
        ) from e

    return _init_inference(*args, **kwargs)


def add_config_arguments(parser):
    """Attach --deepspeed/--deepspeed_config argparse flags
    (reference deepspeed/__init__.py:209)."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed-TPU configurations")
    group.add_argument(
        "--deepspeed",
        default=False,
        action="store_true",
        help="Enable DeepSpeed-TPU (helper flag for argument parsing)",
    )
    group.add_argument(
        "--deepspeed_config", default=None, type=str,
        help="Path to the DeepSpeed-TPU JSON config file",
    )
    return parser

"""Experiment monitoring fan-out (reference ``deepspeed/monitor/monitor.py:24``).

``MonitorMaster`` dispatches ``(tag, value, step)`` events to every enabled
backend: TensorBoard (if the package is importable), Weights & Biases (if
importable and logged in), and a dependency-free CSV writer. Events are
written rank-0-only, matching the reference's ``rank == 0`` gating — here
"rank 0" is jax.process_index() == 0 (multi-host) since within a process all
devices see the same host Python.
"""

import csv
import os
from typing import List, Optional, Tuple

import jax

from deepspeed_tpu.utils.logging import logger

Event = Tuple[str, float, int]


def counter_events(prefix: str, counters, step: int) -> List[Event]:
    """Shape a dict of monotonic counters into monitor events
    (``prefix/name``), the wall_clock_breakdown-style export used for the
    engine's fault-tolerance stats (saves/loads/fallbacks/retries)."""
    return [(f"{prefix}/{name}", float(value), step)
            for name, value in sorted(counters.items())]


class Monitor:
    """Backend interface (reference monitor/monitor.py Monitor ABC)."""

    def __init__(self, config):
        self.config = config
        self.enabled = bool(getattr(config, "enabled", False))

    def write_events(self, event_list: List[Event]):
        raise NotImplementedError

    def flush(self):
        """Push buffered rows to durable storage. Called from the flight
        recorder's crash-dump path (signal handlers / excepthook), so it
        must be safe to invoke at any moment and must not raise."""

    def close(self):
        """Flush and release backend resources (idempotent)."""


class TensorBoardMonitor(Monitor):
    """reference monitor/tensorboard.py — needs tensorboardX or torch tb."""

    def __init__(self, config):
        super().__init__(config)
        self.summary_writer = None
        if not self.enabled or jax.process_index() != 0:
            return
        try:
            from torch.utils.tensorboard import SummaryWriter
        except ImportError:
            try:
                from tensorboardX import SummaryWriter  # type: ignore
            except ImportError:
                logger.warning(
                    "tensorboard requested but no SummaryWriter available")
                return
        log_dir = os.path.join(config.output_path or "./runs",
                               config.job_name)
        self.summary_writer = SummaryWriter(log_dir=log_dir)

    def write_events(self, event_list: List[Event]):
        if self.summary_writer is None:
            return
        for tag, value, step in event_list:
            self.summary_writer.add_scalar(tag, value, step)
        self.summary_writer.flush()

    def close(self):
        if self.summary_writer is not None:
            self.summary_writer.close()
            self.summary_writer = None


class WandbMonitor(Monitor):
    """reference monitor/wandb.py."""

    def __init__(self, config):
        super().__init__(config)
        self.wandb = None
        if not self.enabled or jax.process_index() != 0:
            return
        try:
            import wandb  # type: ignore
        except ImportError:
            logger.warning("wandb requested but not installed")
            return
        wandb.init(project=config.project, group=config.group,
                   entity=config.team)
        self.wandb = wandb

    def write_events(self, event_list: List[Event]):
        if self.wandb is None:
            return
        for tag, value, step in event_list:
            self.wandb.log({tag: value}, step=step)

    def close(self):
        if self.wandb is not None:
            self.wandb.finish()
            self.wandb = None


class CsvMonitor(Monitor):
    """reference monitor/csv_monitor.py — one csv file per event tag.

    Handles stay open across batches (one open per tag per run, not per
    batch) and every batch ends with ``flush()``-to-OS, so a crash-dump
    ``flush()``/``close()`` from a signal handler leaves complete rows on
    disk instead of a truncated csv."""

    def __init__(self, config):
        super().__init__(config)
        self.log_dir = None
        self._files = {}
        if not self.enabled or jax.process_index() != 0:
            return
        self.log_dir = os.path.join(config.output_path or "./csv_logs",
                                    config.job_name)
        os.makedirs(self.log_dir, exist_ok=True)

    def _path(self, tag: str) -> str:
        # tag components become path-safe file names (Train/loss -> Train_loss)
        return os.path.join(self.log_dir,
                            tag.replace("/", "_").replace(" ", "_") + ".csv")

    def _file(self, tag: str):
        f = self._files.get(tag)
        if f is None or f.closed:
            path = self._path(tag)
            new = not os.path.exists(path) or os.path.getsize(path) == 0
            f = open(path, "a", newline="")
            if new:
                csv.writer(f).writerow(["step", tag])
            self._files[tag] = f
        return f

    def write_events(self, event_list: List[Event]):
        if self.log_dir is None:
            return
        by_tag = {}
        for tag, value, step in event_list:
            by_tag.setdefault(tag, []).append((step, value))
        for tag, rows in by_tag.items():
            f = self._file(tag)
            csv.writer(f).writerows(rows)
            # flush per batch: readers (tests, tail -f) see whole rows,
            # and an abrupt kill loses at most the in-flight batch
            f.flush()

    def flush(self):
        for f in self._files.values():
            try:
                f.flush()
                os.fsync(f.fileno())
            except Exception:
                pass  # crash path: durability is best-effort

    def close(self):
        self.flush()
        for f in self._files.values():
            try:
                f.close()
            except Exception:
                pass
        self._files = {}
        self.log_dir = None


class MonitorMaster(Monitor):
    """Fan-out to all enabled backends (reference monitor/monitor.py:24).

    Backends are isolated from each other: one backend raising (a full
    disk under the csv dir, a wandb network error) must not cost the
    others their events — the failure is logged once per backend and the
    fan-out continues."""

    def __init__(self, ds_config):
        self.tb_monitor: Optional[TensorBoardMonitor] = None
        self.wandb_monitor: Optional[WandbMonitor] = None
        self.csv_monitor: Optional[CsvMonitor] = None
        self.backends: List[Monitor] = []
        self._warned = set()  # backend ids already logged as failing
        self.enabled = False

        tb_cfg = getattr(ds_config, "tensorboard", None)
        wandb_cfg = getattr(ds_config, "wandb", None)
        csv_cfg = getattr(ds_config, "csv_monitor", None)
        if jax.process_index() == 0:
            if tb_cfg is not None and tb_cfg.enabled:
                self.tb_monitor = TensorBoardMonitor(tb_cfg)
                self.add_backend(self.tb_monitor)
            if wandb_cfg is not None and wandb_cfg.enabled:
                self.wandb_monitor = WandbMonitor(wandb_cfg)
                self.add_backend(self.wandb_monitor)
            if csv_cfg is not None and csv_cfg.enabled:
                self.csv_monitor = CsvMonitor(csv_cfg)
                self.add_backend(self.csv_monitor)

    def add_backend(self, monitor: Monitor):
        """Register an extra fan-out target (tests use fakes; the flight
        recorder does not go through here — it subscribes to the bus)."""
        self.backends.append(monitor)
        self.enabled = True

    def _guard(self, m: Monitor, op, *args):
        try:
            op(*args)
        except Exception as e:
            if id(m) not in self._warned:
                self._warned.add(id(m))
                logger.warning("monitor backend %s failed (%s: %s); "
                               "continuing with the others",
                               type(m).__name__, type(e).__name__, e)

    def write_events(self, event_list: List[Event]):
        if jax.process_index() != 0:
            return
        for m in self.backends:
            self._guard(m, m.write_events, event_list)

    def write_counters(self, prefix: str, counters, step: int):
        """Export a dict of cumulative counters as ``prefix/name`` scalars
        — the ``Perf/*`` / ``Comm/*`` convention the step profiler and
        comms logger use (profiling/step_profiler.py ``finalize``)."""
        if counters:
            self.write_events(counter_events(prefix, counters, step))

    def flush(self):
        """Crash-dump hook (flight recorder flush_hooks): push every
        backend's buffers to disk without closing anything."""
        for m in self.backends:
            self._guard(m, m.flush)

    def close(self):
        """Flush/close every backend (graceful-shutdown path). Idempotent;
        later ``write_events`` calls become no-ops."""
        for m in self.backends:
            try:
                m.close()
            except Exception as e:  # closing must never mask shutdown
                logger.warning("monitor close failed: %s", e)
        self.enabled = False

from deepspeed_tpu.monitor.monitor import (  # noqa: F401
    CsvMonitor,
    Monitor,
    MonitorMaster,
    TensorBoardMonitor,
    WandbMonitor,
)

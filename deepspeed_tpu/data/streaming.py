"""Deterministic, seed+epoch-keyed, DP-sharded streaming sample source.

The MLPerf TPU-v3 pods work makes deterministic sharded input order a
correctness requirement at scale-out: every process must be able to
recompute exactly which samples it owns from ``(seed, epoch)`` alone, so a
restore (or an elastic restart on a different host) replays the identical
stream. This source keeps the ``DeepSpeedDataLoader`` idiom — a fresh
``np.random.RandomState(seed + epoch)`` permutation per epoch — and adds
the things the batch-level loader cannot express:

* **sharding**: shard ``r`` of ``n`` owns global positions ``r, r+n,
  r+2n, ...`` of the epoch permutation (truncated to the common length
  ``n * (len // n)``), so shards are disjoint and equally sized;
* **mid-epoch resume**: ``state_dict`` carries a sample cursor, not just
  ``(epoch, seed)``, so a restore continues from the exact next document;
* **elastic re-stride**: the state also records the shard GEOMETRY
  (``num_shards``, the global ``epoch_offset`` this incarnation started
  striding from, and the ``epoch_boundary`` the epoch was started with).
  Loading it on a DIFFERENT shard count is pure arithmetic: all ranks of
  the old topology advance in lockstep, so the consumed set is exactly
  the global-order prefix ``[epoch_offset, epoch_offset + cursor * N)``;
  the new topology re-strides the remainder ``[frontier, boundary)`` at
  stride N' — zero samples lost or duplicated, for any (N, N') pair
  including non-divisor shrinks (property-tested in
  tests/unit/test_elastic_reshard.py).

``reseed(offset)`` derives a fresh order (seed = base + offset) and
restarts the epoch traversal — the sentinel's rollback re-entry path:
replaying the exact stream that diverged once would diverge again.
"""

from typing import Any, Dict

import numpy as np


class ShardedSampleStream:
    """Infinite iterator over a map-style dataset in a deterministic,
    sharded, per-epoch-shuffled order.

    ``next(stream)`` returns one sample and advances the cursor; epoch
    boundaries are internal (the order is rebuilt, ``epoch`` increments).
    """

    def __init__(self, dataset, *, shuffle: bool = True, seed: int = 0,
                 shard_rank: int = 0, num_shards: int = 1):
        if num_shards < 1 or not (0 <= shard_rank < num_shards):
            raise ValueError(
                f"invalid shard {shard_rank}/{num_shards}")
        if len(dataset) < num_shards:
            raise ValueError(
                f"dataset of {len(dataset)} samples cannot be split into "
                f"{num_shards} non-empty shards")
        self.dataset = dataset
        self.shuffle = shuffle
        self.seed = int(seed)
        self._base_seed = int(seed)
        self.shard_rank = shard_rank
        self.num_shards = num_shards
        self.epoch = 0
        self.cursor = 0  # samples already drawn by this shard this stride
        # where this incarnation's stride begins in the epoch's global
        # order (0 for a fresh epoch; the consumed frontier after an
        # elastic re-stride) and where the epoch ends (fixed by the
        # topology that STARTED the epoch — a resumed epoch must keep the
        # original truncation or samples appear/vanish at the tail)
        self.epoch_offset = 0
        self.epoch_boundary = self._default_boundary(num_shards)
        # bumped whenever the order changes out-of-band (reseed or
        # load_state_dict) so downstream stages can restart/flush
        self.order_version = 0
        self._order = None
        self._order_key = None

    def _default_boundary(self, num_shards: int) -> int:
        return num_shards * (len(self.dataset) // num_shards)

    @property
    def samples_per_epoch(self) -> int:
        """Per-shard epoch length (the common truncated length)."""
        return len(self.dataset) // self.num_shards

    def _full_order(self) -> np.ndarray:
        """The epoch's GLOBAL permutation — a pure function of
        (seed, epoch), identical on every rank of every topology."""
        key = (self.seed, self.epoch)
        if self._order_key != key:
            order = np.arange(len(self.dataset))
            if self.shuffle:
                np.random.RandomState(self.seed + self.epoch).shuffle(order)
            self._order = order
            self._order_key = key
        return self._order

    def _next_global(self) -> int:
        """Global position of this shard's next sample: the stride base
        plus this rank's interleave offset."""
        return (self.epoch_offset + self.shard_rank
                + self.cursor * self.num_shards)

    def __iter__(self):
        return self

    def __next__(self) -> Any:
        g = self._next_global()
        if g >= self.epoch_boundary:
            self.epoch += 1
            self.cursor = 0
            self.epoch_offset = 0
            self.epoch_boundary = self._default_boundary(self.num_shards)
            g = self._next_global()
        sample = self.dataset[int(self._full_order()[g])]
        self.cursor += 1
        return sample

    # -- loader protocol (see runtime/dataloader.py) -----------------------
    def reseed(self, offset: int):
        """Fresh deterministic order: seed = base seed + offset, epoch
        traversal restarted."""
        self.seed = self._base_seed + int(offset)
        self.cursor = 0
        self.epoch_offset = 0
        self.epoch_boundary = self._default_boundary(self.num_shards)
        self.order_version += 1

    def state_dict(self) -> Dict[str, int]:
        return {"seed": self.seed, "epoch": self.epoch,
                "cursor": self.cursor,
                "num_shards": self.num_shards,
                "epoch_offset": self.epoch_offset,
                "epoch_boundary": self.epoch_boundary}

    def load_state_dict(self, state: Dict[str, int]):
        """Resume, re-striding when the state was saved under a different
        shard count. All ranks advance in lockstep (the engine steps them
        together), so a saved ``cursor`` under ``N`` shards means the
        global prefix ``[epoch_offset, epoch_offset + cursor * N)`` is
        consumed; the new topology strides the remainder. Legacy three-int
        states (no geometry) resume same-topology, bit-identical to the
        old behavior."""
        self.seed = int(state.get("seed", self.seed))
        self.epoch = int(state.get("epoch", self.epoch))
        cursor = int(state.get("cursor", self.cursor))
        saved_shards = state.get("num_shards")
        saved_offset = int(state.get("epoch_offset", 0))
        saved_boundary = state.get("epoch_boundary")
        if saved_shards is None or int(saved_shards) == self.num_shards:
            # same topology (or pre-geometry state): exact per-rank resume
            self.cursor = cursor
            self.epoch_offset = saved_offset
            self.epoch_boundary = int(
                saved_boundary if saved_boundary is not None
                else self._default_boundary(self.num_shards))
        else:
            # elastic re-stride: advance the global frontier past what the
            # old topology consumed, restart this rank's stride there
            saved_shards = int(saved_shards)
            self.cursor = 0
            self.epoch_offset = saved_offset + cursor * saved_shards
            self.epoch_boundary = int(
                saved_boundary if saved_boundary is not None
                else self._default_boundary(saved_shards))
        self.order_version += 1

"""Deterministic, seed+epoch-keyed, DP-sharded streaming sample source.

The MLPerf TPU-v3 pods work makes deterministic sharded input order a
correctness requirement at scale-out: every process must be able to
recompute exactly which samples it owns from ``(seed, epoch)`` alone, so a
restore (or an elastic restart on a different host) replays the identical
stream. This source keeps the ``DeepSpeedDataLoader`` idiom — a fresh
``np.random.RandomState(seed + epoch)`` permutation per epoch — and adds
the two things the batch-level loader cannot express:

* **sharding**: shard ``r`` of ``n`` owns ``order[r::n]`` truncated to the
  common length, so shards are disjoint and equally sized in every epoch;
* **mid-epoch resume**: ``state_dict`` carries a sample cursor, not just
  ``(epoch, seed)``, so a restore continues from the exact next document.

``reseed(offset)`` derives a fresh order (seed = base + offset) and
restarts the epoch traversal — the sentinel's rollback re-entry path:
replaying the exact stream that diverged once would diverge again.
"""

from typing import Any, Dict

import numpy as np


class ShardedSampleStream:
    """Infinite iterator over a map-style dataset in a deterministic,
    sharded, per-epoch-shuffled order.

    ``next(stream)`` returns one sample and advances the cursor; epoch
    boundaries are internal (the order is rebuilt, ``epoch`` increments).
    """

    def __init__(self, dataset, *, shuffle: bool = True, seed: int = 0,
                 shard_rank: int = 0, num_shards: int = 1):
        if num_shards < 1 or not (0 <= shard_rank < num_shards):
            raise ValueError(
                f"invalid shard {shard_rank}/{num_shards}")
        if len(dataset) < num_shards:
            raise ValueError(
                f"dataset of {len(dataset)} samples cannot be split into "
                f"{num_shards} non-empty shards")
        self.dataset = dataset
        self.shuffle = shuffle
        self.seed = int(seed)
        self._base_seed = int(seed)
        self.shard_rank = shard_rank
        self.num_shards = num_shards
        self.epoch = 0
        self.cursor = 0  # samples already drawn from this shard this epoch
        # bumped whenever the order changes out-of-band (reseed or
        # load_state_dict) so downstream stages can restart/flush
        self.order_version = 0
        self._order = None
        self._order_key = None

    @property
    def samples_per_epoch(self) -> int:
        """Per-shard epoch length (the common truncated length)."""
        return len(self.dataset) // self.num_shards

    def _epoch_order(self) -> np.ndarray:
        key = (self.seed, self.epoch)
        if self._order_key != key:
            order = np.arange(len(self.dataset))
            if self.shuffle:
                np.random.RandomState(self.seed + self.epoch).shuffle(order)
            # interleaved shard, truncated to the common length: disjoint
            # across ranks, equal-sized, and a pure function of (seed, epoch)
            self._order = order[self.shard_rank::self.num_shards][
                :self.samples_per_epoch]
            self._order_key = key
        return self._order

    def __iter__(self):
        return self

    def __next__(self) -> Any:
        order = self._epoch_order()
        if self.cursor >= len(order):
            self.epoch += 1
            self.cursor = 0
            order = self._epoch_order()
        sample = self.dataset[int(order[self.cursor])]
        self.cursor += 1
        return sample

    # -- loader protocol (see runtime/dataloader.py) -----------------------
    def reseed(self, offset: int):
        """Fresh deterministic order: seed = base seed + offset, epoch
        traversal restarted."""
        self.seed = self._base_seed + int(offset)
        self.cursor = 0
        self.order_version += 1

    def state_dict(self) -> Dict[str, int]:
        return {"seed": self.seed, "epoch": self.epoch,
                "cursor": self.cursor}

    def load_state_dict(self, state: Dict[str, int]):
        self.seed = int(state.get("seed", self.seed))
        self.epoch = int(state.get("epoch", self.epoch))
        self.cursor = int(state.get("cursor", self.cursor))
        self.order_version += 1

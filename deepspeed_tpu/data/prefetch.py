"""Background host→device prefetch: a bounded queue whose worker thread
pulls batches from the wrapped loader and runs the engine's sharded
``device_put`` *before* the training loop asks for them.

With depth ≥ 2 this double-buffers the input path: while the compiled
step for batch N runs, the worker is already staging batch N+1's host
copy and device transfer, so the step-profiler's ``dataloader`` and
``h2d`` phases collapse to a queue pop (see
``benchmarks/data/input_pipeline_bench.py``).

Resume correctness: the worker captures ``loader.state_dict()``
immediately after pulling each item and enqueues the pair
``(device_batch, state)``. When the consumer pops batch *k*, the state
that rides with it is exactly "the loader just after producing batch
*k*" — i.e. the correct resume point once batch *k* has been consumed —
regardless of how far ahead the worker has run. ``state_dict()`` returns
that last-delivered snapshot, so checkpoints taken between steps restore
without replaying or skipping prefetched-but-unconsumed batches.
"""

import copy
import queue
import threading
from typing import Any, Callable, Dict, Optional

_END = object()  # worker→consumer: wrapped loader raised StopIteration


class DevicePrefetcher:
    """Wrap a loader-protocol iterator with a bounded prefetch queue.

    ``put_fn`` is the host→device transfer (the engine passes its
    ``_put_batch``); ``None`` leaves batches on host. The wrapper itself
    speaks the loader protocol (``state_dict``/``load_state_dict``/
    ``reseed``/``order_version``/``seed``) by delegating to the wrapped
    loader — mutating calls halt the worker first so the underlying
    iterator is never touched from two threads.
    """

    def __init__(self, loader, put_fn: Optional[Callable] = None,
                 depth: int = 2):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.loader = loader
        self.put_fn = put_fn
        self.depth = depth
        self._queue: queue.Queue = queue.Queue(maxsize=depth)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._error: Optional[BaseException] = None
        self._delivered_state: Optional[Dict[str, Any]] = None
        self._last_order_version = getattr(loader, "order_version", 0)
        # starvation accounting for Perf/* counters
        self._gets = 0
        self._starved_gets = 0
        self._depth_sum = 0
        self._depth_max = 0

    # -- worker ------------------------------------------------------------
    def _worker(self, it):
        try:
            while not self._stop.is_set():
                try:
                    item = next(it)
                except StopIteration:
                    self._put_blocking(_END)
                    return
                state = None
                if hasattr(self.loader, "state_dict"):
                    state = copy.deepcopy(self.loader.state_dict())
                if self.put_fn is not None:
                    item = self.put_fn(item)
                if not self._put_blocking((item, state)):
                    return
        except BaseException as e:  # propagate into the consumer
            self._error = e
            self._put_blocking(_END)

    def _put_blocking(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _ensure_worker(self):
        if getattr(self.loader, "order_version", 0) != self._last_order_version:
            self._halt()
        if self._thread is None or not self._thread.is_alive():
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            self._stop.clear()
            self._queue = queue.Queue(maxsize=self.depth)
            self._last_order_version = getattr(self.loader,
                                               "order_version", 0)
            self._thread = threading.Thread(
                target=self._worker, args=(iter(self.loader),),
                name="ds-prefetch", daemon=True)
            self._thread.start()

    def _halt(self):
        """Stop the worker and discard anything it staged."""
        self._stop.set()
        if self._thread is not None:
            # drain so a blocked put() observes the stop event
            while self._thread.is_alive():
                try:
                    self._queue.get_nowait()
                except queue.Empty:
                    self._thread.join(timeout=0.1)
            self._thread = None
        with self._queue.mutex:
            self._queue.queue.clear()
        self._stop.clear()

    # -- iterator ----------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        self._ensure_worker()
        depth = self._queue.qsize()
        self._gets += 1
        self._depth_sum += depth
        self._depth_max = max(self._depth_max, depth)
        if depth == 0:
            self._starved_gets += 1
            # the consumer is about to block on the producer: the feed-
            # health signal the flight recorder keeps (stdlib-only import,
            # swallows subscriber errors — never breaks the iterator)
            from deepspeed_tpu.telemetry.bus import (
                KIND_PREFETCH_STARVED,
                publish,
            )

            publish(KIND_PREFETCH_STARVED, severity="warning",
                    starved_gets=self._starved_gets, gets=self._gets)
        got = self._queue.get()
        if got is _END:
            self._thread = None
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            raise StopIteration
        item, state = got
        if state is not None:
            self._delivered_state = state
        return item

    def counters(self) -> Dict[str, float]:
        """Prefetch health counters, exported as ``Perf/*`` gauges by the
        step profiler (see docs/observability.md)."""
        gets = max(self._gets, 1)
        return {
            "prefetch_depth": float(self.depth),
            "prefetch_gets": float(self._gets),
            "prefetch_starved_gets": float(self._starved_gets),
            "prefetch_queue_depth_avg": self._depth_sum / gets,
            "prefetch_queue_depth_max": float(self._depth_max),
        }

    def stop(self):
        self._halt()

    # -- loader protocol ---------------------------------------------------
    @property
    def order_version(self) -> int:
        return getattr(self.loader, "order_version", 0)

    @property
    def seed(self):
        return getattr(self.loader, "seed", None)

    @property
    def batch_size(self):
        return getattr(self.loader, "batch_size", None)

    def reseed(self, offset: int):
        self._halt()
        self._delivered_state = None
        self.loader.reseed(offset)
        self._last_order_version = getattr(self.loader, "order_version", 0)

    def state_dict(self) -> Dict[str, Any]:
        if self._delivered_state is not None:
            return copy.deepcopy(self._delivered_state)
        return copy.deepcopy(self.loader.state_dict())

    def load_state_dict(self, state: Dict[str, Any]):
        self._halt()
        self._delivered_state = None
        self.loader.load_state_dict(state)
        self._last_order_version = getattr(self.loader, "order_version", 0)

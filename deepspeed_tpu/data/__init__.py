"""Input data pipeline: deterministic sharded streaming, sequence packing,
and double-buffered host->device prefetch (docs/data.md).

The training input path this package replaces is the synchronous host loop
in ``runtime/dataloader.py`` + ``runtime/engine.py``: ``next(data_iter)``
followed by a blocking ``device_put`` inside the step. Here the three
stages are separated so each is independently testable and the transfer
overlaps compute:

* :class:`ShardedSampleStream` — deterministic, seed+epoch-keyed sample
  order, disjointly sharded across data-parallel processes, resumable via
  ``state_dict`` and reshuffled by the sentinel's ``reseed`` path.
* :class:`SequencePacker` — greedy first-fit bin packing of variable
  length documents into fixed ``[B, S]`` batches with ``segment_ids`` and
  per-segment position resets (the exactness contract the model's
  segment-aware masking completes; see docs/data.md).
* :class:`DevicePrefetcher` — a bounded background queue whose worker
  runs the engine's sharded ``device_put``, so h2d of batch N+1 overlaps
  compute of batch N.
* :class:`PackedDataPipeline` — the loader-protocol object tying the
  stream and packer together (``state_dict``/``load_state_dict``/
  ``reseed``/``order_version``, same contract as ``DeepSpeedDataLoader``).

Config-gated under the ``data_pipeline`` block (``runtime/config.py``),
default-off: without it the engine's input path is byte-identical to the
historical loop.
"""

from deepspeed_tpu.data.packing import SequencePacker, pack_documents
from deepspeed_tpu.data.pipeline import PackedDataPipeline
from deepspeed_tpu.data.prefetch import DevicePrefetcher
from deepspeed_tpu.data.streaming import ShardedSampleStream

__all__ = [
    "DevicePrefetcher",
    "PackedDataPipeline",
    "SequencePacker",
    "ShardedSampleStream",
    "pack_documents",
]

"""PackedDataPipeline: the loader-protocol object tying the sharded
sample stream and the sequence packer into an infinite batch iterator.

This is the host-side stage the engine swaps in for
``DeepSpeedDataLoader`` when the ``data_pipeline`` config block is
enabled. It speaks the exact loader protocol the engine, checkpointing,
and sentinel already rely on (``state_dict``/``load_state_dict``/
``reseed``/``order_version``/``seed``/``batch_size``), so the
``RepeatingLoader`` wrapper, the checkpoint ``meta["dataloader"]`` path
and the rollback-reseed path all compose unchanged.

Curriculum hook: ``seqlen_fn`` (wired by the engine to the
``CurriculumScheduler``'s quantized difficulty) is polled at each batch
boundary. A changed target seq-len flushes nothing silently — pending
documents are re-queued into a packer of the new shape, so the number of
distinct compiled shapes stays bounded by the schedule's step count, not
by the data.
"""

import copy
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from deepspeed_tpu.data.packing import SequencePacker
from deepspeed_tpu.data.streaming import ShardedSampleStream


class PackedDataPipeline:
    """Infinite iterator of packed ``[B, S]`` batch dicts.

    With ``pack_sequences=False`` it degrades to fixed-shape collation:
    every batch is ``batch_size`` consecutive stream samples stacked (and
    right-padded/truncated to ``seq_length``), with segment/position
    fields still emitted so the model-side masking stays uniform.
    """

    def __init__(self, dataset, *, batch_size: int, seq_length: int,
                 pack_sequences: bool = True, pad_token_id: int = 0,
                 shuffle: bool = True, seed: int = 0, shard_rank: int = 0,
                 num_shards: int = 1,
                 seqlen_fn: Optional[Callable[[], int]] = None):
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        if seq_length < 2:
            raise ValueError(f"seq_length must be >= 2, got {seq_length}")
        self.batch_size = batch_size
        self.seq_length = seq_length
        self.pack_sequences = pack_sequences
        self.pad_token_id = pad_token_id
        self.seqlen_fn = seqlen_fn
        self.stream = ShardedSampleStream(
            dataset, shuffle=shuffle, seed=seed,
            shard_rank=shard_rank, num_shards=num_shards)
        self._packer = SequencePacker(batch_size, seq_length,
                                      pad_id=pad_token_id)
        # batches finished early by a seq-len change, delivered before any
        # new packing happens
        self._ready: List[Dict[str, np.ndarray]] = []
        self._last_order_version = self.stream.order_version

    # -- loader protocol ---------------------------------------------------
    @property
    def order_version(self) -> int:
        return self.stream.order_version

    @property
    def seed(self) -> int:
        return self.stream.seed

    def reseed(self, offset: int):
        """Sentinel rollback path: fresh sample order, and the pending
        half-packed rows are dropped — replaying the exact stream that
        diverged once would diverge again."""
        self._packer.reset()
        self._ready = []
        self.stream.reseed(offset)
        self._last_order_version = self.stream.order_version

    def state_dict(self) -> Dict[str, Any]:
        return {
            "stream": self.stream.state_dict(),
            "packer": self._packer.state_dict(),
            "ready": [
                {k: v.tolist() for k, v in b.items()} for b in self._ready
            ],
        }

    def load_state_dict(self, state: Dict[str, Any]):
        saved_shards = (state.get("stream") or {}).get("num_shards")
        self.stream.load_state_dict(state["stream"])
        if (saved_shards is not None
                and int(saved_shards) != self.stream.num_shards):
            # elastic re-stride: the stream resumed at the new geometry
            # (see ShardedSampleStream.load_state_dict). The half-packed
            # rows and ready batches belong to ONE old-rank's pipeline;
            # every new rank loads the same state, so exactly one of them
            # (rank 0) may carry the pending work forward — anywhere else
            # it would be delivered num_shards times
            if self.stream.shard_rank == 0:
                self._packer.load_state_dict(state["packer"])
                self._ready = [
                    {k: np.asarray(v, dtype=np.int32) for k, v in b.items()}
                    for b in state.get("ready", [])
                ]
            else:
                self._packer.reset()
                self._ready = []
        else:
            self._packer.load_state_dict(state["packer"])
            self._ready = [
                {k: np.asarray(v, dtype=np.int32) for k, v in b.items()}
                for b in state.get("ready", [])
            ]
        self._last_order_version = self.stream.order_version

    # -- iteration ---------------------------------------------------------
    def _sync_order_version(self):
        # the stream was reseeded/restored out-of-band (e.g. via a direct
        # handle): half-packed state belongs to the dead order
        if self.stream.order_version != self._last_order_version:
            self._packer.reset()
            self._ready = []
            self._last_order_version = self.stream.order_version

    def _apply_seqlen(self):
        if self.seqlen_fn is None:
            return
        target = int(self.seqlen_fn())
        target = max(2, min(self.seq_length, target))
        if target == self._packer.seq_len:
            return
        # finish the pending rows at the OLD shape (no samples are lost,
        # no token silently truncated by the shape change)...
        pending = self._packer.reset()
        self._packer = SequencePacker(self.batch_size, target,
                                      pad_id=self.pad_token_id)
        # ...by re-queuing the displaced documents into the new packer
        for doc in pending:
            batch = self._packer.add(doc)
            if batch is not None:
                self._ready.append(batch)

    def __iter__(self):
        return self

    def __next__(self) -> Dict[str, np.ndarray]:
        self._sync_order_version()
        self._apply_seqlen()
        if self._ready:
            return self._ready.pop(0)
        if not self.pack_sequences:
            return self._collate_fixed()
        while True:
            batch = self._packer.add(next(self.stream))
            if batch is not None:
                return batch

    def _collate_fixed(self) -> Dict[str, np.ndarray]:
        B, S = self.batch_size, self._packer.seq_len
        input_ids = np.full((B, S), self.pad_token_id, dtype=np.int32)
        segment_ids = np.zeros((B, S), dtype=np.int32)
        positions = np.zeros((B, S), dtype=np.int32)
        for r in range(B):
            sample = next(self.stream)
            if isinstance(sample, dict):
                sample = sample["input_ids"]
            tokens = np.asarray(sample, dtype=np.int32).reshape(-1)[:S]
            n = len(tokens)
            input_ids[r, :n] = tokens
            segment_ids[r, :n] = 1
            positions[r, :n] = np.arange(n, dtype=np.int32)
        return {
            "input_ids": input_ids,
            "labels": input_ids.copy(),
            "segment_ids": segment_ids,
            "positions": positions,
        }

"""Sequence packing: bin-pack variable-length documents into fixed [B, S]
batches with segment ids and per-segment position resets.

Packed batch format (all ``int32``, all ``[B, S]``):

* ``input_ids`` — document tokens back to back, ``pad_id`` in the slack;
* ``labels`` — identical to ``input_ids`` (the model's loss shift derives
  next-token targets and zero-weights the positions that would cross a
  segment boundary — see ``models/transformer_lm.py _shifted_targets``);
* ``segment_ids`` — 1-based per-row document index, 0 marks padding;
* ``positions`` — position WITHIN the document (reset to 0 at each
  segment start), used for both learned and rotary embeddings.

Exactness condition (docs/data.md): with (a) attention restricted to
*causal AND same-segment*, (b) positions reset per segment, and (c) loss
weights zeroing any position whose next token belongs to a different
segment, the packed forward is mathematically identical to running each
document alone — the weighted-mean cross entropy over a packed batch
equals the token-weighted mean of the per-document losses.

The packer is a deterministic greedy first-fit streamer: documents arrive
in stream order, land in the first open row with space, and a document
that fits no row flushes the batch and seeds the next one. Determinism
(no reordering, no lookahead) is what makes mid-epoch resume exact: the
pending rows are part of ``state_dict``.
"""

from typing import Any, Dict, List, Optional

import numpy as np


def _as_tokens(doc) -> np.ndarray:
    """Accept a raw token sequence or a dict sample with ``input_ids``."""
    if isinstance(doc, dict):
        doc = doc["input_ids"]
    arr = np.asarray(doc, dtype=np.int32).reshape(-1)
    if arr.size == 0:
        raise ValueError("cannot pack an empty document")
    return arr


class SequencePacker:
    """Greedy first-fit packing of documents into ``[batch_size, seq_len]``.

    ``add(doc)`` returns a finished batch dict when the incoming document
    forced a flush, else ``None``. ``flush()`` emits the pending partial
    rows (used at explicit boundaries, e.g. a curriculum seq-len change).
    """

    def __init__(self, batch_size: int, seq_len: int, pad_id: int = 0):
        if batch_size < 1 or seq_len < 2:
            raise ValueError(
                f"need batch_size >= 1 and seq_len >= 2, got "
                f"{batch_size}x{seq_len}")
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.pad_id = pad_id
        self._rows: List[List[np.ndarray]] = []

    # -- state -------------------------------------------------------------
    def pending_documents(self) -> List[np.ndarray]:
        """Documents buffered in partial rows, in placement order."""
        return [doc for row in self._rows for doc in row]

    def state_dict(self) -> Dict[str, Any]:
        # plain lists of ints: must survive the checkpoint meta's msgpack
        return {
            "seq_len": self.seq_len,
            "rows": [[doc.tolist() for doc in row] for row in self._rows],
        }

    def load_state_dict(self, state: Dict[str, Any]):
        self.seq_len = int(state.get("seq_len", self.seq_len))
        self._rows = [
            [np.asarray(doc, dtype=np.int32) for doc in row]
            for row in state.get("rows", [])
        ]

    def reset(self) -> List[np.ndarray]:
        """Drop pending rows, returning the displaced documents."""
        pending = self.pending_documents()
        self._rows = []
        return pending

    # -- packing -----------------------------------------------------------
    def _row_used(self, row: List[np.ndarray]) -> int:
        return sum(len(d) for d in row)

    def add(self, doc) -> Optional[Dict[str, np.ndarray]]:
        tokens = _as_tokens(doc)[:self.seq_len]
        for row in self._rows:
            if self._row_used(row) + len(tokens) <= self.seq_len:
                row.append(tokens)
                return None
        if len(self._rows) < self.batch_size:
            self._rows.append([tokens])
            return None
        batch = self._build(self._rows)
        self._rows = [[tokens]]
        return batch

    def flush(self) -> Optional[Dict[str, np.ndarray]]:
        if not self._rows:
            return None
        batch = self._build(self._rows)
        self._rows = []
        return batch

    def _build(self, rows) -> Dict[str, np.ndarray]:
        B, S = self.batch_size, self.seq_len
        input_ids = np.full((B, S), self.pad_id, dtype=np.int32)
        segment_ids = np.zeros((B, S), dtype=np.int32)
        positions = np.zeros((B, S), dtype=np.int32)
        for r, row in enumerate(rows):
            off = 0
            for seg, doc in enumerate(row, start=1):
                n = len(doc)
                input_ids[r, off:off + n] = doc
                segment_ids[r, off:off + n] = seg
                positions[r, off:off + n] = np.arange(n, dtype=np.int32)
                off += n
        return {
            "input_ids": input_ids,
            "labels": input_ids.copy(),
            "segment_ids": segment_ids,
            "positions": positions,
        }


def pack_documents(docs, batch_size: int, seq_len: int,
                   pad_id: int = 0) -> List[Dict[str, np.ndarray]]:
    """One-shot convenience: pack a finite document list into batches
    (including a final partial batch). Same greedy first-fit order as the
    streaming packer."""
    packer = SequencePacker(batch_size, seq_len, pad_id=pad_id)
    out = []
    for doc in docs:
        batch = packer.add(doc)
        if batch is not None:
            out.append(batch)
    tail = packer.flush()
    if tail is not None:
        out.append(tail)
    return out

"""Communication op logging.

Parity with reference ``deepspeed/utils/comms_logging.py:56`` (CommsLogger:
per-op counts, message sizes, summary table). Difference, by design: inside a
jitted SPMD program ops cannot be timed individually (XLA schedules them), so
trace-time logging records op/shape/bytes, and real latency comes from the
standalone comm benchmarks (benchmarks/communication in the reference;
``deepspeed_tpu/benchmarks/comm_bench.py`` here).
"""

import threading
from collections import defaultdict
from typing import Dict, Optional

import numpy as np

from deepspeed_tpu.utils.logging import log_dist


def _nbytes(x) -> int:
    try:
        return int(np.prod(x.shape)) * x.dtype.itemsize
    except Exception:
        return 0


def _nelems(x) -> int:
    try:
        return int(np.prod(x.shape))
    except Exception:
        return 0


def wire_factor(op_name: str, world: Optional[int]) -> float:
    """Bytes sent per device over the interconnect, as a multiple of the
    op's INPUT payload, under the standard ring accounting the comm
    benchmarks already use (``benchmarks/communication/run_all.py``
    busbw factors):

    - ``all_reduce``: 2(w-1)/w  (reduce-scatter + all-gather rounds)
    - ``reduce_scatter`` / ``all_to_all``: (w-1)/w of the full input
    - ``all_gather``: (w-1) x the local shard (the input here IS the shard)
    - ``broadcast``: lowered as masked psum in ``comm.py`` → allreduce cost
    - ``ppermute``: every device forwards its full payload once

    ``world=None`` (axis size unknown at the call site) conservatively
    charges the full payload; ``world=1`` is free — nothing crosses a wire.
    """
    if world is None:
        return 1.0
    w = int(world)
    if w <= 1:
        return 0.0
    base = op_name.split(".")[0]
    if base in ("all_reduce", "broadcast"):
        return 2.0 * (w - 1) / w
    if base in ("reduce_scatter", "all_to_all"):
        return (w - 1) / w
    if base == "all_gather":
        return float(w - 1)
    return 1.0  # ppermute / unknown: payload crosses once


class CommsLogger:
    def __init__(self, enabled: bool = False, verbose: bool = False,
                 prof_all: bool = True, prof_ops=None, debug: bool = False):
        self.enabled = enabled
        self.verbose = verbose
        self.prof_all = prof_all
        self.prof_ops = prof_ops or []
        self.debug = debug
        self._lock = threading.Lock()
        # op name -> {"count", "bytes" (logical payload), "wire_bytes"
        # (ring-accounted bytes sent per device in the WIRE dtype),
        # "wire_dtype", "msg_sizes": {size: count}}
        self.comms_dict: Dict[str, Dict] = defaultdict(
            lambda: {"count": 0, "bytes": 0, "wire_bytes": 0,
                     "wire_dtype": None, "msg_sizes": defaultdict(int)}
        )
        # interconnect-level rollup: wire bytes tagged "ici" (intra-slice
        # fabric) vs "dcn" (inter-slice network) by the hierarchical
        # exchange (comm/bucketed.py hierarchical_all_reduce). Untagged
        # records (the flat single-level exchanges) land in neither.
        self.level_bytes: Dict[str, int] = defaultdict(int)

    def configure(self, config) -> None:
        self.enabled = config.enabled
        self.verbose = config.verbose
        self.prof_all = config.prof_all
        self.prof_ops = list(config.prof_ops)
        self.debug = config.debug

    def _should_log(self, op_name: str) -> bool:
        if not self.enabled:
            return False
        return self.prof_all or op_name in self.prof_ops

    def append(self, op_name: str, tensor, axis: Optional[str],
               log_name: Optional[str] = None, wire_dtype=None,
               world: Optional[int] = None,
               level: Optional[str] = None) -> None:
        """Record one collective at trace time.

        ``bytes`` counts the logical input payload in the tensor's own
        dtype (reference CommsLogger behaviour). ``wire_bytes`` is the new
        on-the-wire estimate: the payload re-expressed in ``wire_dtype``
        (what actually crosses the interconnect — int8 for the quantized
        path) scaled by :func:`wire_factor` for the op's ring cost at axis
        size ``world``. ``level`` ("ici" | "dcn") additionally rolls the
        wire bytes into the per-interconnect counters the hierarchical
        exchange exports (``Comm/ici_bytes`` / ``Comm/dcn_bytes``).
        """
        name = log_name or op_name
        if not self._should_log(name):
            return
        size = _nbytes(tensor)
        if wire_dtype is not None:
            try:
                wire_payload = _nelems(tensor) * np.dtype(wire_dtype).itemsize
            except Exception:
                wire_payload = size
        else:
            wire_payload = size
        wire = int(round(wire_payload * wire_factor(op_name, world)))
        with self._lock:
            rec = self.comms_dict[name]
            rec["count"] += 1
            rec["bytes"] += size
            rec["wire_bytes"] += wire
            if wire_dtype is not None:
                rec["wire_dtype"] = str(np.dtype(wire_dtype))
            rec["msg_sizes"][size] += 1
            if level is not None:
                self.level_bytes[str(level)] += wire
        if self.verbose:
            log_dist(
                f"comm op: {name} | axis: {axis} | msg size: {size} bytes"
                f" | wire: {wire} bytes",
                ranks=[0],
            )

    def counters(self) -> Dict[str, float]:
        """Flat cumulative counters for ``Monitor`` export (``Comm/*``):
        per-op ``<name>_count`` / ``<name>_bytes`` / ``<name>_wire_bytes``
        plus ``total_wire_bytes``. Trace-time semantics: these grow per
        *trace*, not per executed step (see module docstring)."""
        out: Dict[str, float] = {}
        total_wire = 0
        with self._lock:
            for name, rec in sorted(self.comms_dict.items()):
                key = name.replace("/", "_").replace(" ", "_")
                out[f"{key}_count"] = float(rec["count"])
                out[f"{key}_bytes"] = float(rec["bytes"])
                out[f"{key}_wire_bytes"] = float(rec["wire_bytes"])
                total_wire += rec["wire_bytes"]
            # per-interconnect rollups (docs/observability.md): always
            # exported so dashboards can alert on dcn_bytes == 0 when a
            # hierarchical config silently fell back to the flat path
            out["ici_bytes"] = float(self.level_bytes.get("ici", 0))
            out["dcn_bytes"] = float(self.level_bytes.get("dcn", 0))
        out["total_wire_bytes"] = float(total_wire)
        return out

    def total_wire_bytes(self) -> int:
        with self._lock:
            return sum(rec["wire_bytes"] for rec in self.comms_dict.values())

    def log_summary(self) -> str:
        lines = ["Comm. Op            Count    Total Bytes    Wire Bytes"]
        with self._lock:
            for name, rec in sorted(self.comms_dict.items()):
                wire = rec["wire_bytes"]
                dt = f" ({rec['wire_dtype']})" if rec["wire_dtype"] else ""
                lines.append(f"{name:<20}{rec['count']:<9}{rec['bytes']:<15}"
                             f"{wire}{dt}")
                for size, cnt in sorted(rec["msg_sizes"].items()):
                    lines.append(f"    msg size {size:>12} B  x{cnt}")
        summary = "\n".join(lines)
        log_dist(summary, ranks=[0])
        return summary

    def reset(self) -> None:
        with self._lock:
            self.comms_dict.clear()
            self.level_bytes.clear()


# process-global instance, configured by the engine from the comms_logger block
comms_logger = CommsLogger()

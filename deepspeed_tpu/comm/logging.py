"""Communication op logging.

Parity with reference ``deepspeed/utils/comms_logging.py:56`` (CommsLogger:
per-op counts, message sizes, summary table). Difference, by design: inside a
jitted SPMD program ops cannot be timed individually (XLA schedules them), so
trace-time logging records op/shape/bytes, and real latency comes from the
standalone comm benchmarks (benchmarks/communication in the reference;
``deepspeed_tpu/benchmarks/comm_bench.py`` here).
"""

import threading
from collections import defaultdict
from typing import Dict, Optional

import numpy as np

from deepspeed_tpu.utils.logging import log_dist


def _nbytes(x) -> int:
    try:
        return int(np.prod(x.shape)) * x.dtype.itemsize
    except Exception:
        return 0


class CommsLogger:
    def __init__(self, enabled: bool = False, verbose: bool = False,
                 prof_all: bool = True, prof_ops=None, debug: bool = False):
        self.enabled = enabled
        self.verbose = verbose
        self.prof_all = prof_all
        self.prof_ops = prof_ops or []
        self.debug = debug
        self._lock = threading.Lock()
        # op name -> {"count": int, "bytes": int, "msg_sizes": {size: count}}
        self.comms_dict: Dict[str, Dict] = defaultdict(
            lambda: {"count": 0, "bytes": 0, "msg_sizes": defaultdict(int)}
        )

    def configure(self, config) -> None:
        self.enabled = config.enabled
        self.verbose = config.verbose
        self.prof_all = config.prof_all
        self.prof_ops = list(config.prof_ops)
        self.debug = config.debug

    def _should_log(self, op_name: str) -> bool:
        if not self.enabled:
            return False
        return self.prof_all or op_name in self.prof_ops

    def append(self, op_name: str, tensor, axis: Optional[str], log_name: Optional[str] = None) -> None:
        """Record one collective at trace time."""
        name = log_name or op_name
        if not self._should_log(name):
            return
        size = _nbytes(tensor)
        with self._lock:
            rec = self.comms_dict[name]
            rec["count"] += 1
            rec["bytes"] += size
            rec["msg_sizes"][size] += 1
        if self.verbose:
            log_dist(
                f"comm op: {name} | axis: {axis} | msg size: {size} bytes",
                ranks=[0],
            )

    def log_summary(self) -> str:
        lines = ["Comm. Op            Count    Total Bytes"]
        with self._lock:
            for name, rec in sorted(self.comms_dict.items()):
                lines.append(f"{name:<20}{rec['count']:<9}{rec['bytes']}")
                for size, cnt in sorted(rec["msg_sizes"].items()):
                    lines.append(f"    msg size {size:>12} B  x{cnt}")
        summary = "\n".join(lines)
        log_dist(summary, ranks=[0])
        return summary

    def reset(self) -> None:
        with self._lock:
            self.comms_dict.clear()


# process-global instance, configured by the engine from the comms_logger block
comms_logger = CommsLogger()

"""Bucketed gradient exchange (GAS-boundary bucketing, T3-style).

The engine's compressed step keeps PER-WORKER gradients through the
accumulation window and exchanges once at the optimizer boundary
(``runtime/engine.py`` ``_compressed_apply_core``). Historically that
exchange was one collective per gradient leaf, issued in a serial chain:
each int8 exchange is a quantize -> all_to_all -> sum -> requantize ->
all_gather pipeline whose phases depend on each other, so leaf N+1's
quantize cannot start until leaf N's all_gather returns.

This module re-buckets the exchange ("T3: Transparent Tracking &
Triggering", PAPERS.md): leaves are packed — in deterministic tree order —
into size-bounded buckets and exchanged one collective per bucket. The
buckets are mutually independent dataflow chains, so XLA's latency-hiding
scheduler is free to overlap bucket N+1's compute phases (quantize /
dequant-sum) with bucket N's in-flight collectives, and small leaves
amortize collective launch latency by riding in a shared payload.

Three entry points, all trace-level (call inside ``shard_map``/``jit``
over a named mesh axis):

- :func:`assign_buckets` / :func:`plan_for_tree` — deterministic bucket
  assignment by leaf size (greedy, fixed tree order; a byte budget of 0
  degenerates to one leaf per bucket, a huge budget to one monolithic
  bucket).
- :func:`bucketed_all_reduce` — fp32/bf16-wire bucketed psum. With an
  fp32 wire this is BIT-FOR-BIT identical to the per-leaf exchange
  (psum is elementwise; concatenation order cannot change any element's
  reduction).
- :func:`bucketed_quantized_all_reduce` — the int8 EQuARX path
  (``comm.compressed.quantized_all_reduce``) per bucket, with the
  worker/server error-feedback residuals carried PER BUCKET on the flat
  concatenated payload and per-bucket wire accounting
  (``<log_name>.bucket<i>`` payload + ``.scales`` sideband).
- :func:`hierarchical_all_reduce` — the two-level ICI/DCN exchange
  ("Scale MLPerf-0.6 models on Google TPU-v3 Pods" posture with the
  EQuARX inter-slice wire, PAPERS.md): bf16 reduce-scatter within each
  slice over ICI, int8 quantized exchange of the 1/P reduced shard
  across slices over DCN, bf16 all-gather back within the slice. DCN
  moves ~2(G-1)/G x N/P int8 bytes instead of 2(W-1)/W x 2N bf16 bytes.
"""

import dataclasses
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deepspeed_tpu.comm.compressed import (quantized_all_reduce,
                                           server_shard_length)
from deepspeed_tpu.comm.logging import comms_logger


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    """Deterministic leaf -> bucket assignment for one gradient tree.

    ``bucket_leaves[b]`` holds the flat-leaf indices (``jax.tree.flatten``
    order) exchanged in bucket ``b``; concatenation inside a bucket follows
    that order. The plan depends only on leaf sizes and the byte budget, so
    every rank computes the identical plan from the identical param tree —
    no coordination needed.
    """

    bucket_leaves: Tuple[Tuple[int, ...], ...]
    leaf_sizes: Tuple[int, ...]

    @property
    def num_buckets(self) -> int:
        return len(self.bucket_leaves)

    def bucket_sizes(self) -> Tuple[int, ...]:
        """Element count of each bucket's flat concatenated payload."""
        return tuple(sum(self.leaf_sizes[i] for i in idxs)
                     for idxs in self.bucket_leaves)


def assign_buckets(leaf_sizes: Sequence[int], bucket_bytes: int,
                   itemsize: int = 4) -> BucketPlan:
    """Greedy fixed-order packing of leaves into ``bucket_bytes`` buckets.

    Leaves keep tree order (reproducible across ranks and runs). A bucket
    closes when adding the next leaf would exceed the budget; a single
    leaf larger than the budget gets a bucket of its own. ``bucket_bytes
    <= 0`` yields one leaf per bucket (the legacy per-leaf exchange
    expressed as a plan). ``itemsize`` is the accumulation dtype's width —
    gradients exchange from f32 accumulators, hence the default 4.
    """
    buckets, cur, cur_bytes = [], [], 0
    for i, n in enumerate(leaf_sizes):
        nbytes = int(n) * itemsize
        if cur and (bucket_bytes <= 0 or cur_bytes + nbytes > bucket_bytes):
            buckets.append(tuple(cur))
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
    if cur:
        buckets.append(tuple(cur))
    return BucketPlan(tuple(buckets), tuple(int(n) for n in leaf_sizes))


def plan_for_tree(tree: Any, bucket_mb: float, itemsize: int = 4
                  ) -> BucketPlan:
    """Bucket plan for a pytree of arrays / ShapeDtypeStructs."""
    sizes = [int(np.prod(leaf.shape)) if leaf.shape else 1
             for leaf in jax.tree.leaves(tree)]
    plan = assign_buckets(sizes, int(bucket_mb * 1024 * 1024), itemsize)
    # plans are built at trace/compile time, never per step — a plan
    # change mid-run (retrace) is exactly what forensics wants to see
    from deepspeed_tpu.telemetry.bus import KIND_BUCKET_PLAN, publish

    publish(KIND_BUCKET_PLAN, num_buckets=plan.num_buckets,
            num_leaves=len(sizes), bucket_mb=float(bucket_mb),
            total_bytes=int(sum(sizes)) * int(itemsize))
    return plan


def _concat_bucket(leaves, idxs, dtype=None):
    parts = [leaves[i].ravel() if dtype is None
             else leaves[i].astype(dtype).ravel() for i in idxs]
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts)


def _split_bucket(flat, leaves, idxs, out):
    off = 0
    for i in idxs:
        n = leaves[i].size
        out[i] = flat[off:off + n].reshape(
            leaves[i].shape).astype(leaves[i].dtype)
        off += n


def bucketed_all_reduce(tree: Any, axis: str,
                        plan: Optional[BucketPlan] = None, *,
                        wire_dtype=None, mean: bool = False,
                        log_name: str = "bucketed_all_reduce") -> Any:
    """Bucketed sum (or mean) all-reduce of a gradient tree.

    One ``psum`` per bucket; ``wire_dtype`` (e.g. ``jnp.bfloat16``) casts
    the concatenated payload before the collective and back after, halving
    wire bytes at ~3 decimal digits of mantissa. With the native (f32)
    wire the result is bit-for-bit the per-leaf exchange. ``plan=None``
    degenerates to one bucket per leaf. Wire bytes log under
    ``<log_name>.bucket<i>`` (one record per bucket, mirroring the
    quantized path) so benchmarks can meter each bucket's payload.
    """
    leaves, treedef = jax.tree.flatten(tree)
    if plan is None:
        plan = assign_buckets([l.size for l in leaves], 0)
    w = int(lax.psum(1, axis))
    out = [None] * len(leaves)
    for b, idxs in enumerate(plan.bucket_leaves):
        flat = _concat_bucket(leaves, idxs)
        payload = (flat if wire_dtype is None
                   or flat.dtype == jnp.dtype(wire_dtype)
                   else flat.astype(wire_dtype))
        comms_logger.append("all_reduce", payload, axis,
                            log_name=f"{log_name}.bucket{b}", world=w)
        reduced = lax.psum(payload, axis).astype(flat.dtype)
        if mean:
            reduced = reduced / w
        _split_bucket(reduced, leaves, idxs, out)
    return jax.tree.unflatten(treedef, out)


def hierarchy_groups(world: int, num_slices: int
                     ) -> Tuple[Tuple[Tuple[int, ...], ...],
                                Tuple[Tuple[int, ...], ...]]:
    """ICI/DCN ``axis_index_groups`` for a dp axis of ``world`` ranks laid
    out over ``num_slices`` slices.

    Assumes the slice dimension is the SLOW (outer) dimension of the axis:
    rank = slice_idx * per_slice + ici_idx. That is exactly what
    ``mesh_utils.create_hybrid_device_mesh`` produces (the dcn mesh shape
    stacks outside each per-slice mesh — ``parallel/mesh.py:_arrange``),
    and what ``tpu.grad_exchange.dcn_slices`` emulates on the virtual CPU
    mesh. ICI groups are the contiguous per-slice runs; DCN groups take
    one rank at the same ICI position from every slice.
    """
    if num_slices < 1 or world % num_slices:
        raise ValueError(
            f"cannot split a dp axis of {world} ranks into {num_slices} "
            f"equal slices")
    per = world // num_slices
    ici = tuple(tuple(s * per + i for i in range(per))
                for s in range(num_slices))
    dcn = tuple(tuple(s * per + i for s in range(num_slices))
                for i in range(per))
    return ici, dcn


def hierarchical_all_reduce(tree: Any, axis: str, num_slices: int,
                            plan: Optional[BucketPlan] = None, *,
                            block: int = 512, wire_dtype=jnp.bfloat16,
                            mean: bool = False,
                            log_name: str = "hierarchical_grad_exchange"
                            ) -> Any:
    """Two-level ICI/DCN sum (or mean) all-reduce of a gradient tree.

    Per bucket, with W ranks in ``num_slices`` slices of P ranks each:

    1. ``psum_scatter`` the bucket within each slice (ICI, ``wire_dtype``
       — bf16 by default): every rank ends with its slice's sum of a
       1/P shard.
    2. :func:`quantized_all_reduce` of the shard ACROSS slices (DCN,
       int8 + per-block fp32 scales) via ``axis_index_groups`` — the
       EQuARX wire format on the expensive interconnect, at 1/P of the
       tensor. No error feedback: the deferred exchange is stateless
       (one exchange per optimizer step; residuals would need optimizer
       state the bf16/fp32 deferred family deliberately does not carry).
    3. ``all_gather`` the globally reduced shard back within each slice
       (ICI, ``wire_dtype``).

    Wire accounting tags the intra-slice legs ``level="ici"`` and the
    inter-slice leg ``level="dcn"`` (``Comm/ici_bytes`` /
    ``Comm/dcn_bytes``). ``num_slices=1`` degenerates to a single-level
    scatter/gather psum (no DCN leg, everything metered as ICI).
    """
    leaves, treedef = jax.tree.flatten(tree)
    if plan is None:
        plan = assign_buckets([l.size for l in leaves], 0)
    w = int(lax.psum(1, axis))
    ici_groups, dcn_groups = hierarchy_groups(w, num_slices)
    per_slice = w // num_slices
    out = [None] * len(leaves)
    for b, idxs in enumerate(plan.bucket_leaves):
        flat = _concat_bucket(leaves, idxs)
        n = flat.size
        pad = (-n) % per_slice
        if pad:
            flat = jnp.concatenate(
                [flat, jnp.zeros((pad,), flat.dtype)])
        payload = (flat if wire_dtype is None
                   or flat.dtype == jnp.dtype(wire_dtype)
                   else flat.astype(wire_dtype))
        comms_logger.append("reduce_scatter", payload, axis,
                            log_name=f"{log_name}.bucket{b}.ici",
                            world=per_slice, level="ici")
        shard = lax.psum_scatter(
            payload, axis, scatter_dimension=0, tiled=True,
            axis_index_groups=list(map(list, ici_groups))
        ).astype(flat.dtype)
        if num_slices > 1:
            shard = quantized_all_reduce(
                shard, axis, block=block,
                axis_index_groups=list(map(list, dcn_groups)),
                log_name=f"{log_name}.bucket{b}.dcn", level="dcn")
        gathered = (shard if wire_dtype is None
                    or shard.dtype == jnp.dtype(wire_dtype)
                    else shard.astype(wire_dtype))
        comms_logger.append("all_gather", gathered, axis,
                            log_name=f"{log_name}.bucket{b}.ici",
                            world=per_slice, level="ici")
        full = lax.all_gather(
            gathered, axis, tiled=True,
            axis_index_groups=list(map(list, ici_groups))
        ).astype(flat.dtype)
        if pad:
            full = full[:n]
        if mean:
            full = full / w
        _split_bucket(full, leaves, idxs, out)
    return jax.tree.unflatten(treedef, out)


def bucketed_quantized_all_reduce(
        tree: Any, axis: str, plan: Optional[BucketPlan] = None, *,
        block: int = 512,
        worker_errors: Optional[Sequence[jnp.ndarray]] = None,
        server_errors: Optional[Sequence[jnp.ndarray]] = None,
        log_name: str = "quantized_all_reduce"
) -> Tuple[Any, Tuple[jnp.ndarray, ...], Tuple[jnp.ndarray, ...]]:
    """Per-bucket int8 EQuARX exchange with per-bucket error feedback.

    ``worker_errors[b]`` (``[bucket_len]`` f32) is added into bucket
    ``b``'s payload before quantization; ``server_errors[b]``
    (``[server_shard_length(bucket_len, W, block)]`` f32) compensates the
    phase-2 requantization. Either may be ``None`` for a cold start.
    Returns ``(sum_tree, new_worker_errors, new_server_errors)`` — the SUM
    over the axis (divide by W for the mean), residuals as per-bucket
    tuples in bucket order. Wire bytes log under
    ``<log_name>.bucket<i>`` / ``...bucket<i>.scales`` so the comm
    benchmarks can report each bucket's payload and sideband.
    """
    leaves, treedef = jax.tree.flatten(tree)
    if plan is None:
        plan = assign_buckets([l.size for l in leaves], 0)
    w = int(lax.psum(1, axis))
    out = [None] * len(leaves)
    new_we, new_se = [], []
    for b, idxs in enumerate(plan.bucket_leaves):
        flat = _concat_bucket(leaves, idxs, dtype=jnp.float32)
        if worker_errors is not None:
            flat = flat + worker_errors[b]
        se = (server_errors[b] if server_errors is not None
              else jnp.zeros((server_shard_length(flat.size, w, block),),
                             jnp.float32))
        reduced, err, new_server = quantized_all_reduce(
            flat, axis, block=block, return_error=True, server_error=se,
            log_name=f"{log_name}.bucket{b}")
        _split_bucket(reduced, leaves, idxs, out)
        new_we.append(err)
        new_se.append(new_server)
    return jax.tree.unflatten(treedef, out), tuple(new_we), tuple(new_se)

"""Quantized (compressed) collectives.

Counterpart of the reference's compressed-communication backends
(``runtime/comm/nccl.py:51`` compressed_allreduce — error-compensated 1-bit
over NCCL; cupy bit-packing) re-designed for XLA/ICI in the EQuARX style
(see PAPERS.md): both all-reduce phases move int8 payloads with per-block
scales instead of fp32, cutting collective bytes ~4x. The 1-bit optimizer
variants live in ``runtime/fp16/onebit``; this is the generic tensor path.

Quantization is the shared symmetric per-group int8 from
``ops/quantizer.py`` (one implementation for MoQ, serving, and the wire).

Scheme (inside shard_map over a named axis, W ranks):

1. quantize the local tensor blockwise (int8 symmetric, per-block scale)
2. reduce-scatter: each rank receives every rank's int8 copy of ITS shard
   (``all_to_all`` on the quantized payload), dequantizes, and sums in f32
3. re-quantize the reduced shard and ``all_gather`` it; dequantize

Two rounds of quantization error; per-block scaling keeps relative error
~1/127 per round. With ``return_error=True`` the caller gets the local
(worker) residual for 1-bit-Adam-style error feedback on the next step.
"""

from typing import Tuple, Union

import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.comm.logging import comms_logger
from deepspeed_tpu.ops.quantizer import dequantize, quantize_blockwise


def _quantize_blocks(flat: jnp.ndarray, block: int
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    # shared blockwise int8 (ops/quantizer.py) — the same format the int8
    # KV cache stores, so wire and cache cannot drift
    return quantize_blockwise(flat, block)


def server_shard_length(n: int, w: int, block: int = 512) -> int:
    """Length of one rank's reduced shard inside :func:`quantized_all_reduce`
    (the flat tensor padded to a ``w * block`` multiple, split ``w`` ways) —
    the shape a caller must allocate for the phase-2 error-feedback buffer."""
    return (n + ((-n) % (w * block))) // w


def quantized_all_reduce(x: jnp.ndarray, axis: str, block: int = 512,
                         return_error: bool = False,
                         server_error: jnp.ndarray = None,
                         log_name: str = "quantized_all_reduce",
                         axis_index_groups=None,
                         level=None
                         ) -> Union[jnp.ndarray,
                                    Tuple[jnp.ndarray, jnp.ndarray],
                                    Tuple[jnp.ndarray, jnp.ndarray,
                                          jnp.ndarray]]:
    """Sum-all-reduce with int8 wire format (use inside shard_map/jit).

    Returns the reduced tensor in ``x``'s shape/dtype (expect ~1e-2
    relative error), plus — with ``return_error=True`` — the local phase-1
    quantization residual ``x - dequant(quant(x))`` to carry as error
    feedback into the next step's tensor (the 1-bit Adam pattern,
    runtime/fp16/onebit/adam.py). The residual is returned in float32
    regardless of ``x``'s dtype: error feedback must accumulate in full
    precision (a bf16 round-trip would drop most of the residual's
    mantissa and defeat the compensation).

    ``server_error`` enables the SECOND round of compensation (reference
    compressed_allreduce's server_error, runtime/comm/nccl.py:51): pass this
    rank's ``[server_shard_length(x.size, W, block)]`` f32 residual from the
    previous step; it is added into the reduced shard before phase-2
    requantization and the new residual is returned as a third output
    ``(out, worker_err, new_server_error)``. Without it, phase-2
    requantization noise (~1/127 relative per step) goes uncompensated.

    ``log_name`` labels the wire accounting (payload under ``log_name``,
    scale sideband under ``<log_name>.scales``) so callers issuing many
    exchanges — e.g. the bucketed reducer in ``comm/bucketed.py`` — can
    meter each one separately.

    ``axis_index_groups`` restricts the reduction to disjoint equal-size
    subgroups of the axis (jax semantics: each rank reduces with its own
    group only) — the hierarchical exchange uses this for the inter-slice
    DCN leg without adding a mesh axis. ``level`` ("ici"/"dcn") tags the
    wire accounting with the interconnect this exchange crosses.
    """
    if axis_index_groups is not None:
        sizes = {len(g) for g in axis_index_groups}
        if len(sizes) != 1:
            raise ValueError(
                f"axis_index_groups must be equal-size, got sizes {sizes}")
        w = sizes.pop()
    else:
        w = int(lax.psum(1, axis))  # static axis size at trace time
    shape, dtype = x.shape, x.dtype
    flat = x.astype(jnp.float32).ravel()
    n = flat.size
    pad = (-n) % (w * block)
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    per = flat.size // w  # this rank's shard length, a block multiple

    # phase 1: quantize full tensor, all_to_all so rank r holds every
    # rank's int8 copy of shard r
    q, s = _quantize_blocks(flat, block)
    # trace-time wire accounting: the int8 payload and its fp32 per-block
    # scale sideband are what actually cross the interconnect (the logical
    # tensor never does) — log both under distinct names so the comm
    # benchmarks can report payload vs sideband
    comms_logger.append("all_to_all", q, axis,
                        log_name=log_name, world=w, level=level)
    comms_logger.append("all_to_all", s, axis,
                        log_name=f"{log_name}.scales", world=w, level=level)
    q_recv = lax.all_to_all(q.reshape(w, per), axis,
                            split_axis=0, concat_axis=0, tiled=False,
                            axis_index_groups=axis_index_groups)
    s_recv = lax.all_to_all(s.reshape(w, per // block), axis,
                            split_axis=0, concat_axis=0, tiled=False,
                            axis_index_groups=axis_index_groups)
    # q_recv: [W, per] — W ranks' int8 copies of MY shard; dequant + sum
    contribs = (q_recv.reshape(w, per // block, block).astype(jnp.float32)
                * s_recv[..., None])
    reduced = jnp.sum(contribs, axis=0).reshape(per)
    if server_error is not None:
        reduced = reduced + server_error

    # phase 2: re-quantize the reduced shard, all_gather, dequantize
    q2, s2 = _quantize_blocks(reduced, block)
    comms_logger.append("all_gather", q2, axis,
                        log_name=log_name, world=w, level=level)
    comms_logger.append("all_gather", s2, axis,
                        log_name=f"{log_name}.scales", world=w, level=level)
    q_all = lax.all_gather(q2, axis, tiled=True,
                           axis_index_groups=axis_index_groups)
    s_all = lax.all_gather(s2, axis, tiled=True,
                           axis_index_groups=axis_index_groups)
    out = dequantize(q_all, s_all)
    if pad:
        out = out[:n]
    out = out.reshape(shape).astype(dtype)
    if not return_error and server_error is None:
        return out
    err = flat - dequantize(q, s)
    if pad:
        err = err[:n]
    if server_error is None:
        return out, err.reshape(shape)
    new_server_error = reduced - dequantize(q2, s2)
    return out, err.reshape(shape), new_server_error


def quantization_error(x: jnp.ndarray, block: int = 512) -> jnp.ndarray:
    """Residual ``x - dequant(quant(x))`` for error-feedback loops
    (float32 — see :func:`quantized_all_reduce`)."""
    flat = x.astype(jnp.float32).ravel()
    n = flat.size
    pad = (-n) % block
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    q, s = _quantize_blocks(flat, block)
    err = flat - dequantize(q, s)
    if pad:
        err = err[:n]
    return err.reshape(x.shape)

"""``deepspeed_tpu.comm`` — the communication facade.

Parity with reference ``deepspeed/comm/comm.py:223-760`` (torch.distributed-
compatible verb surface + init_distributed + env discovery), re-expressed for
XLA SPMD. Two layers:

1. **In-program collectives** (this module's functional API) — used inside
   ``shard_map``/``jit`` with a named mesh axis. Each verb lowers to the
   corresponding ``jax.lax`` collective and records itself with the
   CommsLogger at trace time:

   =====================  ==============================
   reference verb          XLA lowering
   =====================  ==============================
   all_reduce              lax.psum / pmax / pmin
   all_gather(_base)       lax.all_gather(tiled=True)
   reduce_scatter(_base)   lax.psum_scatter
   all_to_all_single       lax.all_to_all
   send/recv (pipeline)    lax.ppermute
   broadcast               psum of masked value
   =====================  ==============================

2. **Host-level process management** — ``init_distributed`` wraps
   ``jax.distributed.initialize`` (multi-host rendezvous ≈ the reference's
   torch.distributed.init_process_group at comm/torch.py:32), and
   rank/world-size queries map to ``jax.process_index``/device counts.

The 1-bit compressed-allreduce path (reference runtime/comm/nccl.py:51) is
provided by :mod:`deepspeed_tpu.comm.compressed`.
"""

import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.comm.logging import comms_logger
from deepspeed_tpu.utils.logging import log_dist, logger


class ReduceOp:
    SUM = "sum"
    AVG = "avg"
    MAX = "max"
    MIN = "min"
    PROD = "prod"


# ---------------------------------------------------------------------------
# In-program collectives (use inside shard_map / jit with named axes)
# ---------------------------------------------------------------------------
def _axis_world(axis: str):
    """Static size of a bound mesh axis at trace time (``psum(1, axis)`` is
    constant-folded to the axis size), or None when called with the axis
    unbound — the comms logger then falls back to payload-only accounting."""
    try:
        return int(lax.psum(1, axis))
    except Exception:
        return None


def all_reduce(x, axis: str, op: str = ReduceOp.SUM):
    """reference comm/comm.py:503 all_reduce."""
    comms_logger.append("all_reduce", x, axis, world=_axis_world(axis))
    if op == ReduceOp.SUM:
        return lax.psum(x, axis)
    if op == ReduceOp.AVG:
        return lax.pmean(x, axis)
    if op == ReduceOp.MAX:
        return lax.pmax(x, axis)
    if op == ReduceOp.MIN:
        return lax.pmin(x, axis)
    if op == ReduceOp.PROD:
        # Exact, dtype-preserving product: gather then reduce (no log/exp trick,
        # which is inexact and NaNs on negatives).
        gathered = lax.all_gather(x, axis)
        return jnp.prod(gathered, axis=0)
    raise ValueError(f"unsupported reduce op {op}")


def all_gather(x, axis: str, gather_dim: int = 0, tiled: bool = True):
    """reference comm/comm.py all_gather/_base; tiled=True concatenates along
    ``gather_dim`` (the _base flat-buffer form)."""
    comms_logger.append("all_gather", x, axis, world=_axis_world(axis))
    return lax.all_gather(x, axis, axis=gather_dim, tiled=tiled)


def reduce_scatter(x, axis: str, scatter_dim: int = 0):
    """reference comm/comm.py reduce_scatter(_base) → psum_scatter."""
    comms_logger.append("reduce_scatter", x, axis, world=_axis_world(axis))
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_dim, tiled=True)


def all_to_all_single(x, axis: str, split_dim: int = 0, concat_dim: int = 0):
    """reference comm/comm.py:392 all_to_all_single (MoE dispatch path)."""
    comms_logger.append("all_to_all", x, axis, world=_axis_world(axis))
    return lax.all_to_all(x, axis, split_axis=split_dim, concat_axis=concat_dim,
                          tiled=True)


def ppermute(x, axis: str, perm):
    """Point-to-point ring/pipeline transfer (reference pipe/p2p.py send/recv
    :48-161 collapses to one collective-permute on TPU)."""
    comms_logger.append("ppermute", x, axis, world=_axis_world(axis))
    return lax.ppermute(x, axis, perm)


def send_recv_next(x, axis: str, axis_size: int):
    """Send to rank+1 on ``axis`` (pipeline forward activations)."""
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    return ppermute(x, axis, perm)


def send_recv_prev(x, axis: str, axis_size: int):
    """Send to rank-1 on ``axis`` (pipeline backward grads)."""
    perm = [(i, (i - 1) % axis_size) for i in range(axis_size)]
    return ppermute(x, axis, perm)


def broadcast(x, axis: str, root: int = 0):
    """reference comm/comm.py:223 broadcast: every rank gets root's value."""
    comms_logger.append("broadcast", x, axis, world=_axis_world(axis))
    idx = lax.axis_index(axis)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis)


def axis_index(axis: str):
    return lax.axis_index(axis)


# ---------------------------------------------------------------------------
# Host-level process management
# ---------------------------------------------------------------------------
_initialized = False


def init_distributed(
    dist_backend: str = "xla",
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    auto_mpi_discovery: bool = True,
    **kwargs,
):
    """Multi-host rendezvous (reference comm/comm.py:577 init_distributed).

    Single-host (or already-initialised) is a no-op. Env discovery mirrors the
    reference's MPI/launcher env probing (comm/comm.py:640-760): honours
    COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID, the OMPI_* rank vars,
    and the JAX-native auto-detection on TPU pods.
    """
    global _initialized
    if _initialized:
        return
    # DS_TPU_* is the deepspeed_tpu launcher's protocol (launcher/runner.py)
    coordinator_address = (coordinator_address
                           or os.environ.get("DS_TPU_COORDINATOR")
                           or os.environ.get("COORDINATOR_ADDRESS"))
    num_processes = (num_processes or _env_int("DS_TPU_NUM_PROCS")
                     or _env_int("NUM_PROCESSES"))
    if process_id is None:
        process_id = _env_int("DS_TPU_PROC_ID")
    process_id = process_id if process_id is not None else _env_int("PROCESS_ID")
    if auto_mpi_discovery and process_id is None:
        # scheduler-provided rank identity: OpenMPI, then Slurm (reference
        # probes MPI/AzureML/SageMaker env the same way, comm/comm.py:640)
        ompi_rank = _env_int("OMPI_COMM_WORLD_RANK")
        if ompi_rank is not None:
            process_id = ompi_rank
            num_processes = num_processes or _env_int("OMPI_COMM_WORLD_SIZE")
        elif (_env_int("SLURM_PROCID") is not None
              and os.environ.get("SLURM_STEP_ID") is not None
              and (_env_int("SLURM_STEP_NUM_TASKS")
                   or _env_int("SLURM_NTASKS") or 1) > 1):
            # only inside an actual srun step (SLURM_STEP_ID) with more
            # than one task: a bare `python train.py` in an sbatch/salloc
            # shell carries SLURM_PROCID=0 + SLURM_NTASKS but must stay a
            # single-host no-op, not hang in rendezvous (jax's Slurm
            # cluster detection supplies the coordinator when none given)
            process_id = _env_int("SLURM_PROCID")
            num_processes = num_processes or _env_int(
                "SLURM_STEP_NUM_TASKS") or _env_int("SLURM_NTASKS")
    multi_host = coordinator_address is not None or (
        num_processes is not None and num_processes > 1
    )
    if not multi_host:
        # single-host no-op; do NOT latch _initialized so a later call with
        # real coordinator args still performs the rendezvous
        return
    # enable jax's cross-host device-transfer server (PjRt DCN path) so
    # host-level cross-mesh device_puts — the pipeline engine's inter-stage
    # transfers — work across hosts. Must be configured BEFORE the backend
    # initialises. DS_TPU_TRANSFER_ADDR overrides the advertised address
    # (set it empty to disable).
    addr = os.environ.get("DS_TPU_TRANSFER_ADDR")
    if addr is None:
        # the reachable local IP is the one that routes to the coordinator
        # (gethostbyname(gethostname()) is a loopback trap on hosts whose
        # /etc/hosts maps the hostname to 127.0.x.1): a connected UDP
        # socket picks the right interface without sending anything
        import socket

        addr = ""
        host = (coordinator_address or "").strip()
        if host.startswith("["):          # [v6]:port or [v6]
            host = host[1:].split("]", 1)[0]
        elif host.count(":") == 1:        # host:port
            host = host.rpartition(":")[0]
        # else: port-less hostname/IPv4, or bare IPv6 — use as-is
        if host:
            try:
                family = (socket.AF_INET6 if ":" in host
                          else socket.AF_INET)
                probe = socket.socket(family, socket.SOCK_DGRAM)
                try:
                    probe.connect((host, 9))
                    ip = probe.getsockname()[0]
                    # bracket IPv6 or the host:port split is ambiguous
                    addr = f"[{ip}]:0" if ":" in ip else f"{ip}:0"
                finally:
                    probe.close()
            except OSError:
                addr = ""
    if addr:
        try:
            jax.config.update("jax_cross_host_transfer_socket_address", addr)
        except Exception as e:
            # missing flag (old jax) or malformed address: cross-host
            # device_puts (pipeline inter-stage) will not work — say so
            # instead of hanging silently later
            logger.warning(
                f"cross-host transfer server not configured ({e}); "
                "host-level cross-mesh transfers (pipeline pp across "
                "hosts) will be unavailable")
    elif os.environ.get("DS_TPU_TRANSFER_ADDR") is None:
        # not explicitly disabled, yet no address could be derived (e.g.
        # pod auto-detection with no coordinator given, or probe failure)
        logger.warning(
            "could not derive a cross-host transfer address; pipeline "
            "inter-stage transfers across hosts will be unavailable — "
            "set DS_TPU_TRANSFER_ADDR=<this_host_ip>:0 to enable them")

    # the CPU backend compiles cross-process programs only when a CPU
    # collectives implementation is configured (gloo); without it every
    # multi-process jit — including the virtual-mesh tests — aborts with
    # "Multiprocess computations aren't implemented on the CPU backend".
    # Must be set BEFORE backend init; harmless for TPU/GPU platforms.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception as e:  # old jax without the option, or no gloo build
        logger.warning(
            f"could not enable gloo CPU collectives ({e}); multi-process "
            "runs on the CPU backend will not work")

    # log_dist is unusable before the rendezvous: it queries
    # jax.process_index(), which initialises the XLA backend and makes
    # jax.distributed.initialize fail — use the raw logger here so a
    # hanging rendezvous still records what it attempted
    logger.info(
        f"Initializing distributed JAX: coordinator={coordinator_address} "
        f"procs={num_processes} id={process_id}"
    )
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )
    _initialized = True
    log_dist(
        f"Distributed JAX ready: {jax.process_count()} processes, "
        f"{jax.device_count()} devices",
        ranks=[-1],
    )


def _env_int(name: str) -> Optional[int]:
    v = os.environ.get(name)
    return int(v) if v is not None else None


def is_initialized() -> bool:
    return _initialized


def get_rank() -> int:
    """Host process rank (reference get_rank; device-level rank is a mesh
    coordinate, see MeshTopology.coord_of)."""
    return jax.process_index()


def get_world_size() -> int:
    """Number of devices (reference world_size counts GPUs, one per process;
    on TPU one process drives many chips so this counts chips)."""
    return jax.device_count()


def get_local_device_count() -> int:
    return jax.local_device_count()


def barrier():
    """reference comm/comm.py barrier; on JAX: a tiny global psum, blocked on."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("deepspeed_tpu_barrier")


def log_summary():
    return comms_logger.log_summary()

"""deepspeed_tpu.comm — XLA/ICI communication backend.

See reference ``deepspeed/comm/__init__.py`` (re-exports the comm facade).
"""

from deepspeed_tpu.comm.comm import (  # noqa: F401
    ReduceOp,
    all_gather,
    all_reduce,
    all_to_all_single,
    axis_index,
    barrier,
    broadcast,
    get_local_device_count,
    get_rank,
    get_world_size,
    init_distributed,
    is_initialized,
    log_summary,
    ppermute,
    reduce_scatter,
    send_recv_next,
    send_recv_prev,
)
from deepspeed_tpu.comm.logging import CommsLogger, comms_logger  # noqa: F401

"""Process-local telemetry: event bus, crash-forensics flight recorder,
and HBM memory accounting (docs/observability.md "Telemetry events").

Import layering matters here: ``bus``, ``flight_recorder`` and
``crash_report`` are stdlib-only (no jax) so supervisors — the elastic
agent, the launcher, worker wrapper scripts — can import them without
initializing a backend, the same discipline ``runtime/sentinel.py``
established. ``memory`` touches jax only inside its functions.
"""

from deepspeed_tpu.telemetry.bus import TelemetryBus, publish, telemetry_bus
from deepspeed_tpu.telemetry.crash_report import (
    TELEMETRY_DIR_ENV,
    load_blackbox,
    sweep_blackbox_dumps,
    verify_blackbox,
)
from deepspeed_tpu.telemetry.flight_recorder import (
    BLACKBOX_SCHEMA,
    FlightRecorder,
    install_crash_handlers,
)

__all__ = [
    "TelemetryBus",
    "telemetry_bus",
    "publish",
    "FlightRecorder",
    "install_crash_handlers",
    "BLACKBOX_SCHEMA",
    "TELEMETRY_DIR_ENV",
    "sweep_blackbox_dumps",
    "load_blackbox",
    "verify_blackbox",
]

"""Rank-tagged in-process telemetry bus.

Every subsystem that makes a *discrete decision* — the sentinel skipping
a batch, a checkpoint falling back to an older tag, the ring KV cache
declining a demand, the bucketed gradient exchange building a plan, the
prefetcher starving, the serving scheduler admitting/evicting a lane —
publishes a structured event here. Subscribers (the flight recorder,
tests) see them in publish order.

Design constraints, in priority order:

1. **Telemetry must never break training.** ``publish`` swallows
   subscriber exceptions (warning once per subscriber) and never raises.
2. **Cheap enough for hot paths.** One lock, one dict build, one deque
   append per subscriber — microseconds. No jax import, no host sync:
   payload values must already be host-side Python scalars (publishers
   own that contract; the bus never materializes device arrays).
3. **Supervisor-importable.** stdlib only, like ``runtime/sentinel.py``.

The process-global ``telemetry_bus`` is the instance everything uses;
``TelemetryBus`` exists separately for test isolation.
"""

import os
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional

# Event kinds published by the repo's subsystems (one flat namespace,
# dotted by subsystem). Not an enum: third-party publishers may add their
# own kinds and the bus does not gatekeep.
KIND_SENTINEL_SKIP = "sentinel.skip"
KIND_SENTINEL_ROLLBACK = "sentinel.rollback"
KIND_SENTINEL_DIVERGED = "sentinel.diverged"
KIND_WATCHDOG_FIRE = "sentinel.watchdog_fire"
KIND_CKPT_COMMIT = "checkpoint.commit"
KIND_CKPT_FALLBACK = "checkpoint.fallback"
KIND_RING_DECLINE = "ring.decline"
KIND_BUCKET_PLAN = "comm.bucket_plan"
KIND_COMM_HIERARCHY = "comm.hierarchy_plan"
KIND_PREFETCH_STARVED = "data.prefetch_starved"
KIND_SERVE_ADMIT = "serve.admit"
KIND_SERVE_EVICT = "serve.evict"
KIND_SERVE_FIRST_TOKEN = "serve.first_token"
KIND_SERVE_PREFIX_HIT = "serve.prefix_hit"
KIND_SERVE_PREFIX_MISS = "serve.prefix_miss"
KIND_SERVE_PREFIX_EVICT = "serve.prefix_evict"
KIND_SERVE_SHED = "serve.shed"
KIND_SERVE_DEADLINE_SHED = "serve.deadline_shed"
KIND_SERVE_REPLICA_DOWN = "serve.replica_down"
KIND_SERVE_REPLICA_UP = "serve.replica_up"
KIND_SERVE_FAILOVER = "serve.failover"
KIND_SERVE_DRAIN = "serve.drain"
KIND_SERVE_STATS = "serve.stats"
KIND_SERVE_KV_TRANSFER = "serve.kv_transfer"
KIND_SERVE_SPEC_ACCEPT = "serve.spec_accept"
KIND_SHUTDOWN = "shutdown.graceful"
KIND_ELASTIC_RESHARD = "elastic.reshard"
# cluster health plane (runtime/health.py): peer liveness over the
# out-of-band heartbeat mesh, step-time straggler detection, step-skew
# desync, and SDC parameter-digest mismatches
KIND_HEALTH_PEER_DOWN = "health.peer_down"
KIND_HEALTH_PEER_UP = "health.peer_up"
KIND_HEALTH_STRAGGLER = "health.straggler"
KIND_HEALTH_DESYNC = "health.desync"
KIND_HEALTH_SDC = "health.sdc"
KIND_HEALTH_ABORT = "health.abort"


def _default_rank() -> int:
    # jax-free rank guess for processes that never call set_rank (the
    # engine overrides this with jax.process_index() at init)
    for var in ("DS_TPU_RANK", "JAX_PROCESS_INDEX", "RANK"):
        v = os.environ.get(var)
        if v and v.isdigit():
            return int(v)
    return 0


class TelemetryBus:
    """Thread-safe pub/sub fan-out of structured telemetry events."""

    def __init__(self, rank: Optional[int] = None):
        self._rank = _default_rank() if rank is None else int(rank)
        self._lock = threading.Lock()
        self._subscribers: List[Callable[[Dict[str, Any]], None]] = []
        self._broken: set = set()
        self._counts: Dict[str, int] = {}

    @property
    def rank(self) -> int:
        return self._rank

    def set_rank(self, rank: int) -> None:
        self._rank = int(rank)

    @staticmethod
    def _ref(fn):
        """Bound methods are held weakly: the global bus outlives every
        engine, and a strong ref to ``recorder.on_event`` would pin each
        dead engine's recorder (and its monitor's open csv handles)
        forever. Plain functions/closures stay strong — a weak ref to a
        lambda would die instantly."""
        if hasattr(fn, "__self__") and hasattr(fn, "__func__"):
            # builtin bound methods (list.append) have __self__ but no
            # __func__ and WeakMethod rejects them — those stay strong
            return weakref.WeakMethod(fn)
        return fn

    @staticmethod
    def _deref(ref):
        return ref() if isinstance(ref, weakref.WeakMethod) else ref

    def subscribe(self, fn: Callable[[Dict[str, Any]], None]):
        """Register ``fn(event_dict)``; returns ``fn`` for unsubscribe."""
        ref = self._ref(fn)
        with self._lock:
            if ref not in self._subscribers:
                self._subscribers.append(ref)
        return fn

    def unsubscribe(self, fn) -> None:
        ref = self._ref(fn)
        with self._lock:
            if ref in self._subscribers:
                self._subscribers.remove(ref)
            self._broken.discard(id(fn))

    def publish(self, kind: str, step: Optional[int] = None,
                severity: str = "info", **payload) -> Dict[str, Any]:
        """Publish one event; returns the event dict (tests inspect it)."""
        ev: Dict[str, Any] = {
            "ts": time.time(),
            "kind": str(kind),
            "rank": self._rank,
            "severity": severity,
        }
        if step is not None:
            ev["step"] = int(step)
        if payload:
            ev.update(payload)
        with self._lock:
            self._counts[ev["kind"]] = self._counts.get(ev["kind"], 0) + 1
            subscribers = []
            dead = []
            for ref in self._subscribers:
                fn = self._deref(ref)
                if fn is None:
                    dead.append(ref)  # its recorder was GC'd
                else:
                    subscribers.append(fn)
            for ref in dead:
                self._subscribers.remove(ref)
        for fn in subscribers:
            try:
                fn(ev)
            except Exception as e:
                if id(fn) not in self._broken:
                    self._broken.add(id(fn))
                    # local import: utils.logging is jax-free, but keep
                    # the module importable even if logging setup changes
                    from deepspeed_tpu.utils.logging import logger

                    logger.warning(
                        "telemetry subscriber %r raised %s: %s — muting "
                        "further warnings from it", fn, type(e).__name__, e)
        return ev

    def counts(self) -> Dict[str, int]:
        """Cumulative publish count per kind (for dumps and tests)."""
        with self._lock:
            return dict(self._counts)

    def reset(self) -> None:
        """Drop subscribers and counts (test isolation only)."""
        with self._lock:
            self._subscribers.clear()
            self._broken.clear()
            self._counts.clear()


# The process-global bus. Module-level publishers (ring declines, bucket
# plans, prefetch starvation) and the engine's flight recorder all share
# this instance; its rank tag is set once by the engine.
telemetry_bus = TelemetryBus()


def publish(kind: str, step: Optional[int] = None, severity: str = "info",
            **payload) -> Dict[str, Any]:
    """Publish on the process-global bus (the one-liner publishers use)."""
    return telemetry_bus.publish(kind, step=step, severity=severity,
                                 **payload)

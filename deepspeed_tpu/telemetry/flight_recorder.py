"""Crash-forensics flight recorder.

A bounded in-memory ring of the last N optimizer steps — phase timings,
loss / grad-norm (only when the engine already materialized them on
host), ``Comm/*`` wire bytes, feed-health counters, live memory
watermarks — plus the last M bus events. On a fatal path it dumps one
atomic, crc32-stamped ``blackbox-rank{k}.json`` that survives the
process: the per-rank evidence the elastic agent and launcher sweep into
a run-level crash report (``crash_report.py``).

Zero-added-syncs discipline (the step-profiler bar): per-step phase
spans here are **host dispatch times** (``perf_counter`` around the same
``with`` blocks the profiler fences) — no fence is ever issued by this
module. Inside the profiler's fenced window those spans coincide with
true device time; outside it they are the honest host-side view. Loss
and grad-norm are recorded only when some already-paid-for host
materialization (monitor export, sentinel verdict) produced them — the
recorder itself never pulls a device value.

Dump triggers (docs/observability.md "Flight recorder" trigger matrix):

* ``DivergenceError`` (exit 13) — explicit dump in the engine before the
  raise (the usual worker exit is a *caught* DivergenceError +
  ``sys.exit(13)``, which never reaches ``sys.excepthook``);
* ``HangWatchdog`` abort (exit 14) — dump inside the ``on_fire``
  callback, because the abort is ``os._exit`` which skips ``atexit``;
* SIGTERM (or any configured signal) — chained handler, previous handler
  (e.g. the graceful-shutdown flag-setter) still runs after the dump;
* unhandled exceptions — ``sys.excepthook`` chain;
* ``atexit`` backstop — dumps only when a fatal reason was armed but the
  corresponding dump never happened (e.g. an exit path we don't hook).

stdlib-only, like ``runtime/sentinel.py``: supervisors import this
module to read dumps without dragging in jax.
"""

import atexit
import contextlib
import json
import os
import signal as signal_module
import socket
import sys
import threading
import time
import traceback
import zlib
from collections import deque
from typing import Any, Callable, Dict, List, Optional

BLACKBOX_SCHEMA = "ds-tpu-blackbox/1"


def _canonical_bytes(payload: Dict[str, Any]) -> bytes:
    """Deterministic serialization the crc is computed over. ``default=
    str`` so an odd payload value degrades to its repr instead of killing
    the dump on the crash path."""
    return json.dumps(payload, sort_keys=True, separators=(",", ":"),
                      default=str).encode("utf-8")


def blackbox_crc(payload: Dict[str, Any]) -> int:
    """crc32 over the canonical payload *without* its ``crc32`` field."""
    body = {k: v for k, v in payload.items() if k != "crc32"}
    return zlib.crc32(_canonical_bytes(body)) & 0xFFFFFFFF


class FlightRecorder:
    """Bounded step/event ring with an atomic crash dump.

    All mutating methods are thread-safe: the hang watchdog dumps from
    its daemon thread while the training loop records steps.
    """

    def __init__(self, ring_steps: int = 64, ring_events: int = 256,
                 dump_dir: Optional[str] = None, rank: int = 0,
                 bus=None, clock: Callable[[], float] = time.time):
        if ring_steps < 1:
            raise ValueError(f"ring_steps must be >= 1, got {ring_steps}")
        if ring_events < 1:
            raise ValueError(f"ring_events must be >= 1, got {ring_events}")
        self.rank = int(rank)
        self.dump_dir = dump_dir
        self._clock = clock
        self._lock = threading.Lock()
        self._steps: deque = deque(maxlen=ring_steps)
        self._events: deque = deque(maxlen=ring_events)
        self._static: Dict[str, Any] = {}
        self._flush_hooks: List[Callable[[], None]] = []
        self._bus = bus
        self._dumped_path: Optional[str] = None
        self._pending_fatal: Optional[Dict[str, Any]] = None
        # current-step accumulator (begin_step/phase/record_step)
        self._cur_step: Optional[int] = None
        self._step_t0 = 0.0
        self._phase_acc: Dict[str, float] = {}
        if bus is not None:
            bus.subscribe(self.on_event)

    # -- static context ----------------------------------------------------
    def set_static(self, **info) -> None:
        """Attach run-constant context (world size, batch triad, model
        id, config digests) reproduced verbatim in every dump."""
        with self._lock:
            self._static.update(info)

    def add_flush_hook(self, fn: Callable[[], None]) -> None:
        """Run ``fn()`` right before a dump (the CsvMonitor durability
        hook: flush counter CSVs so the crash doesn't truncate them)."""
        with self._lock:
            self._flush_hooks.append(fn)

    # -- per-step recording ------------------------------------------------
    def begin_step(self, step: int) -> None:
        """Anchor the current step's host clock; idempotent per step."""
        if self._cur_step == step:
            return
        self._cur_step = step
        self._step_t0 = time.perf_counter()
        self._phase_acc = {}

    def phase(self, name: str, inner=None):
        """Context manager accumulating host dispatch time for ``name``
        into the current step record; wraps ``inner`` (the step
        profiler's fenced phase context or its shared nullcontext) so
        the engine keeps one ``with`` per phase."""
        return self._phase_ctx(name, inner)

    @contextlib.contextmanager
    def _phase_ctx(self, name: str, inner):
        t0 = time.perf_counter()
        try:
            if inner is not None:
                with inner:
                    yield
            else:
                yield
        finally:
            dt = time.perf_counter() - t0
            self._phase_acc[name] = self._phase_acc.get(name, 0.0) + dt

    def record_step(self, step: int, loss: Optional[float] = None,
                    grad_norm: Optional[float] = None,
                    comm: Optional[Dict[str, float]] = None,
                    feed: Optional[Dict[str, float]] = None,
                    mem: Optional[Dict[str, int]] = None,
                    **extra) -> Dict[str, Any]:
        """Append one step record to the ring and close the accumulator.

        Callers pass only values that are ALREADY host-side (see module
        docstring); ``None`` fields are omitted from the record.
        """
        rec: Dict[str, Any] = {"step": int(step), "ts": self._clock()}
        # any open accumulator belongs to this record: the engine bumps
        # global_steps inside the optimizer step, so the step id at
        # record time is begin time's id + 1 — match on "open", not "=="
        if self._cur_step is not None:
            rec["total_s"] = time.perf_counter() - self._step_t0
            if self._phase_acc:
                rec["phases_s"] = dict(self._phase_acc)
        if loss is not None:
            rec["loss"] = float(loss)
        if grad_norm is not None:
            rec["grad_norm"] = float(grad_norm)
        if comm:
            rec["comm"] = {str(k): v for k, v in comm.items()}
        if feed:
            rec["feed"] = {str(k): float(v) for k, v in feed.items()}
        if mem:
            rec["mem"] = {str(k): v for k, v in mem.items()}
        if extra:
            rec.update(extra)
        with self._lock:
            self._steps.append(rec)
        self._cur_step = None
        self._phase_acc = {}
        return rec

    # -- bus fan-in --------------------------------------------------------
    def on_event(self, event: Dict[str, Any]) -> None:
        with self._lock:
            self._events.append(dict(event))

    # -- introspection (tests, crash report) -------------------------------
    def steps(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._steps)

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    @property
    def dumped_path(self) -> Optional[str]:
        return self._dumped_path

    # -- fatal-path dump ---------------------------------------------------
    def arm(self, reason: str, exit_code: Optional[int] = None) -> None:
        """Mark a fatal reason so the ``atexit`` backstop dumps if no
        explicit dump happens before the interpreter exits."""
        with self._lock:
            self._pending_fatal = {"reason": reason, "exit_code": exit_code}

    def payload(self, reason: str, exit_code: Optional[int] = None,
                exc: Optional[BaseException] = None) -> Dict[str, Any]:
        """The dump body, crc-stamped. Pure (no I/O) so tests can check
        the schema without touching disk."""
        with self._lock:
            body: Dict[str, Any] = {
                "schema": BLACKBOX_SCHEMA,
                "rank": self.rank,
                "reason": reason,
                "exit_code": exit_code,
                "ts": self._clock(),
                "host": socket.gethostname(),
                "pid": os.getpid(),
                "static": dict(self._static),
                "steps": list(self._steps),
                "events": list(self._events),
            }
        if self._bus is not None:
            body["event_counts"] = self._bus.counts()
        if exc is not None:
            body["exception"] = {
                "type": type(exc).__name__,
                "message": str(exc),
                "traceback": traceback.format_exception(
                    type(exc), exc, exc.__traceback__),
            }
        body["crc32"] = blackbox_crc(body)
        return body

    def dump(self, reason: str, exit_code: Optional[int] = None,
             exc: Optional[BaseException] = None,
             force: bool = False) -> Optional[str]:
        """Write ``blackbox-rank{k}.json`` atomically (tmp + rename).

        Idempotent: the FIRST fatal reason wins (a SIGTERM arriving while
        the divergence dump is on disk must not overwrite the evidence)
        unless ``force``. Returns the path, or None when ``dump_dir`` is
        unset or the write failed — a dump failure must never mask the
        original crash.
        """
        if self.dump_dir is None:
            return None
        if self._dumped_path is not None and not force:
            return self._dumped_path
        for hook in list(self._flush_hooks):
            try:
                hook()
            except Exception:
                pass  # a broken flush hook must not block the dump
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            path = os.path.join(self.dump_dir,
                                f"blackbox-rank{self.rank}.json")
            tmp = f"{path}.tmp.{os.getpid()}"
            body = self.payload(reason, exit_code=exit_code, exc=exc)
            with open(tmp, "w") as f:
                json.dump(body, f, indent=1, default=str)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            self._dumped_path = path
            with self._lock:
                self._pending_fatal = None
            return path
        except Exception as e:
            try:
                from deepspeed_tpu.utils.logging import logger

                logger.warning("flight recorder dump failed: %s", e)
            except Exception:
                pass
            return None

    def retract_dump(self) -> None:
        """Remove a dump that turned out not to be a crash.

        The SIGTERM handler dumps immediately (at signal time nobody
        knows whether the grace save will succeed); when the graceful
        shutdown then commits its checkpoint and exits cleanly, that
        blackbox is stale evidence — left behind it would pollute the
        next crash sweep of the same telemetry dir. Best-effort: a
        failure to unlink must not break the clean exit."""
        path, self._dumped_path = self._dumped_path, None
        with self._lock:
            self._pending_fatal = None
        if path is not None:
            try:
                os.remove(path)
            except OSError:
                pass

    def _atexit_dump(self) -> None:
        pending = self._pending_fatal
        if pending is not None and self._dumped_path is None:
            self.dump(pending["reason"], exit_code=pending.get("exit_code"))

    def close(self) -> None:
        """Unsubscribe from the bus (engine teardown / tests)."""
        if self._bus is not None:
            self._bus.unsubscribe(self.on_event)
            self._bus = None


def install_crash_handlers(recorder: FlightRecorder,
                           signals=("SIGTERM",),
                           excepthook: bool = True,
                           use_atexit: bool = True) -> Callable[[], None]:
    """Hook ``recorder.dump`` into the process's fatal paths.

    Chains, never replaces: the previous ``sys.excepthook`` and any
    previous signal handler (e.g. the engine's graceful-shutdown
    flag-setter) run *after* the dump. Signal handlers install only on
    the main thread (the ``signal`` module's requirement — same guard as
    the engine's graceful shutdown). Returns an ``uninstall()`` callable
    restoring what was replaced; ``atexit`` registrations stay (they are
    no-ops once nothing fatal is armed).
    """
    restorers: List[Callable[[], None]] = []

    if excepthook:
        prev_hook = sys.excepthook

        def _hook(exc_type, exc, tb):
            code = getattr(exc, "exit_code", 1)
            try:
                recorder.dump("unhandled_exception", exit_code=code, exc=exc)
            except Exception:
                pass
            prev_hook(exc_type, exc, tb)

        sys.excepthook = _hook

        def _restore_hook(h=_hook, p=prev_hook):
            if sys.excepthook is h:
                sys.excepthook = p

        restorers.append(_restore_hook)

    on_main = threading.current_thread() is threading.main_thread()
    if on_main:
        for name in signals:
            signum = getattr(signal_module, str(name), None)
            if signum is None:
                continue

            prev = signal_module.getsignal(signum)

            def _handler(sig, frame, _name=str(name), _prev=prev):
                try:
                    recorder.dump(f"signal:{_name}", exit_code=128 + sig)
                except Exception:
                    pass
                if callable(_prev):
                    _prev(sig, frame)
                elif _prev == signal_module.SIG_DFL:
                    # preserve default semantics: re-deliver with the
                    # default handler restored so the process still dies
                    signal_module.signal(sig, signal_module.SIG_DFL)
                    os.kill(os.getpid(), sig)

            signal_module.signal(signum, _handler)

            def _restore_sig(snum=signum, h=_handler, p=prev):
                if signal_module.getsignal(snum) is h:
                    try:
                        signal_module.signal(snum, p)
                    except (ValueError, TypeError):
                        pass

            restorers.append(_restore_sig)

    if use_atexit:
        atexit.register(recorder._atexit_dump)

        def _restore_atexit():
            try:
                atexit.unregister(recorder._atexit_dump)
            except Exception:
                pass

        restorers.append(_restore_atexit)

    def uninstall():
        for r in restorers:
            r()

    return uninstall

"""HBM memory accounting.

Three sources, in decreasing order of authority:

1. **Compiled-step ``memory_analysis()``** — XLA's own accounting of the
   already-compiled step executable (``CompiledMemoryStats``): argument /
   output / temp bytes, donation-aliased bytes, generated-code size.
   Captured ONCE at compile (the ``cost_analysis`` pattern in
   ``profiling/flops_profiler``); lowering with avals of the live state
   is a compile-cache hit, so this never recompiles.
2. **Live ``device.memory_stats()`` watermarks** — the PJRT allocator's
   ``bytes_in_use`` / ``peak_bytes_in_use``. A host-local runtime query,
   NOT a device sync, but still sampled only where the step profiler has
   already paid a fence (zero added syncs on the healthy path). Returns
   None on backends without an allocator report (CPU) — every consumer
   gates on that.
3. **The ``device_kind`` HBM table** — the denominator: how much HBM the
   detected chip actually has, same keying as the peak-FLOPs table in
   ``profiling/step_profiler.py``.

jax is imported inside functions only: the telemetry package must stay
importable by supervisors that never initialize a backend.
"""

from typing import Any, Dict, Optional, Tuple

# HBM capacity per jax device in GiB, keyed by device_kind substrings
# (first match wins — newest/most-specific first; same convention as
# HW_PEAK_BF16_TFLOPS). v2/v3 are per-core because a jax device is one
# core there (half the chip's HBM); v4+ are per-chip. Sources: Google TPU
# system-architecture pages. No CPU entry: host RAM is not HBM and
# ``hbm_bytes`` reports None so callers can say so explicitly.
DEVICE_HBM_GIB = (
    ("v6e", 32.0),
    ("v6 lite", 32.0),
    ("v5p", 95.0),
    ("v5e", 16.0),
    ("v5 lite", 16.0),
    ("v5", 95.0),
    ("v4", 32.0),
    ("v3", 16.0),
    ("v2", 8.0),
)

_GIB = 1024 ** 3


def hbm_bytes(device=None, override_gib: Optional[float] = None
              ) -> Tuple[Optional[int], str]:
    """``(hbm_bytes_or_None, source)`` for ``device`` (default:
    ``jax.devices()[0]``). None means "no HBM figure for this backend"
    (CPU, unknown kinds) — the honest answer, not a guess."""
    if override_gib:
        return int(override_gib * _GIB), "config override"
    kind = ""
    try:
        if device is None:
            import jax

            device = jax.devices()[0]
        kind = str(getattr(device, "device_kind", device)).lower()
    except Exception:  # pragma: no cover - backend-less host
        return None, "no backend"
    for sub, gib in DEVICE_HBM_GIB:
        if sub in kind:
            return int(gib * _GIB), f"device_kind={kind!r}"
    return None, f"no HBM table entry for device_kind={kind!r}"


def live_memory_stats(device=None) -> Optional[Dict[str, int]]:
    """Current allocator watermarks for ``device``, or None when the
    backend exposes none (``memory_stats()`` is None on CPU). Host-local
    query; no device sync."""
    try:
        if device is None:
            import jax

            device = jax.local_devices()[0]
        stats = device.memory_stats()
    except Exception:  # pragma: no cover - backend-less host
        return None
    if not stats:
        return None
    keep = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
            "largest_alloc_size", "bytes_reserved", "num_allocs")
    out = {k: int(v) for k, v in stats.items()
           if k in keep and isinstance(v, (int, float))}
    return out or None


def compiled_memory_analysis(fn, *args) -> Dict[str, float]:
    """XLA memory analysis of ``fn(*args)`` (args may be avals).

    Mirrors ``flops_profiler.cost_analysis``: jit (no-op when ``fn`` is
    already jitted), lower, compile — a cache hit for an already-compiled
    step — then read ``CompiledMemoryStats``. Returns bytes::

        {"argument_bytes", "output_bytes", "temp_bytes", "alias_bytes",
         "generated_code_bytes", "peak_working_set_bytes"}

    ``peak_working_set_bytes`` = arguments + outputs + temps − aliased
    (donated inputs reuse their buffers for outputs): the analytic
    per-device HBM ceiling of running this program, excluding whatever
    else the process keeps resident.
    """
    import jax

    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    compiled = jitted.lower(*args).compile()
    ma = compiled.memory_analysis()
    if ma is None:  # pragma: no cover - backend without the API
        raise RuntimeError("backend returned no memory_analysis()")
    arg = float(getattr(ma, "argument_size_in_bytes", 0) or 0)
    out = float(getattr(ma, "output_size_in_bytes", 0) or 0)
    tmp = float(getattr(ma, "temp_size_in_bytes", 0) or 0)
    alias = float(getattr(ma, "alias_size_in_bytes", 0) or 0)
    code = float(getattr(ma, "generated_code_size_in_bytes", 0) or 0)
    return {
        "argument_bytes": arg,
        "output_bytes": out,
        "temp_bytes": tmp,
        "alias_bytes": alias,
        "generated_code_bytes": code,
        "peak_working_set_bytes": max(0.0, arg + out + tmp - alias),
    }


def memory_analysis_of_call(jitted_fn, *concrete_args) -> Dict[str, float]:
    """``compiled_memory_analysis`` with avals derived from concrete
    arguments (the pipeline engine holds live stage inputs, not avals)."""
    import jax

    avals = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
        if hasattr(x, "shape") else x, concrete_args)
    return compiled_memory_analysis(jitted_fn, *avals)


def summarize_program_memory(programs: Dict[str, Dict[str, float]]
                             ) -> Dict[str, float]:
    """Flatten per-program memory dicts into one counter dict.

    Programs run sequentially (fwd/bwd then apply; pipeline stages in
    schedule order), so the honest headline is the MAX working set over
    programs, not the sum — plus prefixed per-program detail and a summed
    generated-code size (all executables stay loaded).
    """
    out: Dict[str, float] = {}
    peak = 0.0
    code = 0.0
    for name, mem in programs.items():
        for k, v in mem.items():
            out[f"{name}_{k}"] = float(v)
        peak = max(peak, float(mem.get("peak_working_set_bytes", 0.0)))
        code += float(mem.get("generated_code_bytes", 0.0))
    out["peak_working_set_bytes"] = peak
    out["generated_code_bytes_total"] = code
    return out


def format_bytes(n: Optional[Any]) -> str:
    """Human GiB/MiB formatting for reports (None-safe)."""
    if n is None:
        return "n/a"
    n = float(n)
    if n >= _GIB:
        return f"{n / _GIB:.2f} GiB"
    if n >= 1024 ** 2:
        return f"{n / 1024 ** 2:.1f} MiB"
    return f"{int(n)} B"

"""Run-level crash report: sweep per-rank blackbox dumps into one file.

After a worker (or a whole pod) dies, each rank's flight recorder has
left a ``blackbox-rank{k}.json`` in the telemetry dir. The elastic agent
(single-host supervision) and the launcher (multi-host fan-out) call
``sweep_blackbox_dumps`` to merge them into ``crash-report.json``: crc
verification per dump, a per-rank summary table, and a cross-rank merged
event tail ordered by wall clock — "what was happening in the last N
steps when rank 3 died with exit 13", answerable from one file.

stdlib-only: supervisors import this without a jax backend.
"""

import glob
import json
import os
import re
import time
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.telemetry.flight_recorder import (
    BLACKBOX_SCHEMA,
    blackbox_crc,
)

# Workers and supervisors rendezvous on the telemetry dir via this env
# var (the agent/launcher export it; the engine's TelemetryConfig reads
# it as the dump_dir default).
TELEMETRY_DIR_ENV = "DS_TPU_TELEMETRY_DIR"

CRASH_REPORT_SCHEMA = "ds-tpu-crash-report/1"
_RANK_RE = re.compile(r"blackbox-rank(\d+)\.json$")


def verify_blackbox(payload: Dict[str, Any]) -> bool:
    """Recompute the crc stamp; False means a torn/tampered dump."""
    stamp = payload.get("crc32")
    if stamp is None:
        return False
    return int(stamp) == blackbox_crc(payload)


def load_blackbox(path: str) -> Tuple[Optional[Dict[str, Any]], str]:
    """``(payload_or_None, status)`` — status is "ok", "crc_mismatch",
    or the parse error. A torn dump still returns its parseable payload
    (flagged) because partial evidence beats none on the crash path."""
    try:
        with open(path) as f:
            payload = json.load(f)
    except Exception as e:
        return None, f"unreadable: {type(e).__name__}: {e}"
    if payload.get("schema") != BLACKBOX_SCHEMA:
        return payload, f"unknown schema {payload.get('schema')!r}"
    return payload, "ok" if verify_blackbox(payload) else "crc_mismatch"


def _rank_summary(payload: Dict[str, Any], status: str) -> Dict[str, Any]:
    steps = payload.get("steps") or []
    last = steps[-1] if steps else {}
    out = {
        "status": status,
        "reason": payload.get("reason"),
        "exit_code": payload.get("exit_code"),
        "ts": payload.get("ts"),
        "host": payload.get("host"),
        "pid": payload.get("pid"),
        "steps_recorded": len(steps),
        "last_step": last.get("step"),
        "last_loss": last.get("loss"),
        "last_grad_norm": last.get("grad_norm"),
        "event_counts": payload.get("event_counts") or {},
    }
    exc = payload.get("exception")
    if exc:
        out["exception"] = {"type": exc.get("type"),
                            "message": exc.get("message")}
    return out


def sweep_blackbox_dumps(telemetry_dir: str,
                         out_path: Optional[str] = None,
                         event_tail: int = 80
                         ) -> Optional[Dict[str, Any]]:
    """Merge every ``blackbox-rank*.json`` under ``telemetry_dir`` into
    one run-level ``crash-report.json`` (atomic write).

    Returns the report dict, or None when no dumps exist (a clean exit
    leaves no blackbox — sweeping is safe to call unconditionally).
    """
    paths = sorted(glob.glob(os.path.join(telemetry_dir,
                                          "blackbox-rank*.json")))
    if not paths:
        return None
    ranks: Dict[str, Dict[str, Any]] = {}
    merged_events: List[Dict[str, Any]] = []
    reasons: Dict[str, int] = {}
    exit_codes: Dict[str, int] = {}
    for path in paths:
        m = _RANK_RE.search(os.path.basename(path))
        rank = m.group(1) if m else os.path.basename(path)
        payload, status = load_blackbox(path)
        if payload is None:
            ranks[rank] = {"status": status, "path": path}
            continue
        summary = _rank_summary(payload, status)
        summary["path"] = path
        ranks[rank] = summary
        reason = str(payload.get("reason"))
        reasons[reason] = reasons.get(reason, 0) + 1
        code = str(payload.get("exit_code"))
        exit_codes[code] = exit_codes.get(code, 0) + 1
        for ev in (payload.get("events") or []):
            ev = dict(ev)
            ev.setdefault("rank", payload.get("rank"))
            merged_events.append(ev)
    merged_events.sort(key=lambda e: e.get("ts", 0.0))
    last_steps = [r.get("last_step") for r in ranks.values()
                  if r.get("last_step") is not None]
    # the first rank to die (earliest dump ts) usually holds the root
    # cause; straggler ranks die later of collective timeouts
    first_rank = None
    first_ts = None
    for rank, r in ranks.items():
        ts = r.get("ts")
        if ts is not None and (first_ts is None or ts < first_ts):
            first_ts, first_rank = ts, rank
    report = {
        "schema": CRASH_REPORT_SCHEMA,
        "generated_ts": time.time(),
        "telemetry_dir": os.path.abspath(telemetry_dir),
        "num_ranks": len(ranks),
        "reasons": reasons,
        "exit_codes": exit_codes,
        "first_fatal_rank": first_rank,
        "last_step_min": min(last_steps) if last_steps else None,
        "last_step_max": max(last_steps) if last_steps else None,
        "ranks": ranks,
        "events_tail": merged_events[-event_tail:],
    }
    out_path = out_path or os.path.join(telemetry_dir, "crash-report.json")
    tmp = f"{out_path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=1, default=str)
    os.replace(tmp, out_path)
    report["path"] = out_path
    return report

"""Checkpoint inspection, conversion, and resharding.

Capability surface of reference ``deepspeed/checkpoint/`` (DeepSpeedCheckpoint
``deepspeed_checkpoint.py:37``, universal checkpoints
``universal_checkpoint.py:13``, 2D/3D reshapers ``reshape_meg_2d.py``,
``reshape_3d_utils.py``). TPU re-design: engine checkpoints already store
logically-global arrays, so "reshape across dp/tp/pp changes" is a no-op at
load; this package adds (a) the universal per-parameter fp32 format for
cross-framework/optimizer-state portability, (b) TP merge/split math for
importing externally sharded (Megatron-style) checkpoints, and (c) a
checkpoint inspector.
"""

from deepspeed_tpu.checkpoint.deepspeed_checkpoint import (  # noqa: F401
    DeepSpeedCheckpoint,
)
from deepspeed_tpu.checkpoint.reshape_utils import (  # noqa: F401
    merge_tp_slices,
    reshape_tp_degree,
    split_tp_param,
)
from deepspeed_tpu.checkpoint.universal_checkpoint import (  # noqa: F401
    convert_to_universal,
    load_universal_into_engine,
    load_universal_state,
)

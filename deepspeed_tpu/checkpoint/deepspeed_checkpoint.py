"""Checkpoint inspector (reference ``checkpoint/deepspeed_checkpoint.py:37``).

The reference class enumerates ``mp_rank_*`` / ``zero_pp_rank_*`` file grids
and exposes tp/pp/dp degrees plus per-layer file maps so reshape tools can
walk them. Our checkpoints are logically-global (one model-states file, one
optim-states file per tag), so the inspector's job is simpler: resolve tags,
enumerate contents, and expose flat ``name -> array`` views of module and
optimizer state.
"""

import os
from typing import Any, Dict, List, Optional

import numpy as np
from flax import serialization

from deepspeed_tpu.utils.tree import flatten_dots as _flatten


class DeepSpeedCheckpoint:
    """Inspect a deepspeed_tpu checkpoint directory."""

    MODEL_FILE = "mp_rank_00_model_states.msgpack"
    OPTIM_FILE = "zero_pp_rank_0_mp_rank_00_optim_states.msgpack"

    def __init__(self, ckpt_dir: str, tag: Optional[str] = None):
        self.ckpt_dir = ckpt_dir
        self.tag = tag or self._read_latest()
        self.tag_dir = os.path.join(ckpt_dir, str(self.tag))
        if not os.path.isdir(self.tag_dir):
            raise FileNotFoundError(f"no checkpoint tag dir {self.tag_dir}")
        self._model_state = None
        self._optim_state = None

    def _read_latest(self) -> str:
        latest = os.path.join(self.ckpt_dir, "latest")
        if not os.path.exists(latest):
            raise FileNotFoundError(
                f"no 'latest' file in {self.ckpt_dir}; pass tag explicitly")
        with open(latest) as f:
            return f.read().strip()

    # ------------------------------------------------------------------
    # layout queries (reference exposes tp/pp/dp degrees; ours are logical)
    # ------------------------------------------------------------------
    @property
    def tp_degree(self) -> int:
        return 1  # files are unsharded; TP is a runtime property

    @property
    def pp_degree(self) -> int:
        return 1

    @property
    def dp_degree(self) -> int:
        return 1

    def list_tags(self) -> List[str]:
        return sorted(
            d for d in os.listdir(self.ckpt_dir)
            if os.path.isdir(os.path.join(self.ckpt_dir, d)))

    def list_files(self) -> List[str]:
        return sorted(os.listdir(self.tag_dir))

    # ------------------------------------------------------------------
    # content access
    # ------------------------------------------------------------------
    def _load(self, fname: str) -> Dict[str, Any]:
        path = os.path.join(self.tag_dir, fname)
        with open(path, "rb") as f:
            return serialization.msgpack_restore(f.read())

    def module_state(self) -> Dict[str, np.ndarray]:
        """Flat ``name -> array`` view of model weights."""
        if self._model_state is None:
            self._model_state = self._load(self.MODEL_FILE)
        return _flatten(self._model_state.get("module", self._model_state))

    def optimizer_state(self) -> Dict[str, np.ndarray]:
        if self._optim_state is None:
            self._optim_state = self._load(self.OPTIM_FILE)
        return _flatten(self._optim_state.get("optimizer",
                                              self._optim_state))

    def parameter_names(self) -> List[str]:
        return sorted(self.module_state().keys())

    def num_parameters(self) -> int:
        return int(sum(int(np.prod(v.shape))
                       for v in self.module_state().values()
                       if hasattr(v, "shape")))

    def show_summary(self) -> str:
        lines = [f"checkpoint {self.ckpt_dir} tag={self.tag}",
                 f"  files: {self.list_files()}",
                 f"  params: {self.num_parameters():,}"]
        for name, arr in sorted(self.module_state().items()):
            shape = getattr(arr, "shape", ())
            dtype = getattr(arr, "dtype", type(arr).__name__)
            lines.append(f"  {name}: {tuple(shape)} {dtype}")
        return "\n".join(lines)

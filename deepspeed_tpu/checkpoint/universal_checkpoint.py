"""Universal checkpoints: per-parameter fp32 files, reshard-on-load.

Reference: ``checkpoint/universal_checkpoint.py:13`` + ``ds_to_universal``
workflow — ZeRO fragments are stitched into per-parameter fp32 "hp" files
(weight + optimizer moments) that any (dp, tp, pp) layout can load. Here the
engine checkpoint is already logically global, so conversion is a re-keying:
one ``.npy`` per parameter/moment plus a JSON manifest. The value of the
format on TPU is portability (inspectable single-param files, partial loads,
cross-model surgery) and exact optimizer-state resume across mesh changes.

Layout::

    <out_dir>/zero/<param.name>/fp32.npy
    <out_dir>/zero/<param.name>/exp_avg.npy        (when present)
    <out_dir>/zero/<param.name>/exp_avg_sq.npy     (when present)
    <out_dir>/universal_manifest.json
"""

import json
import os
from typing import Any, Dict, Optional

import jax
import numpy as np
from flax import serialization, traverse_util


def _flat(tree) -> Dict[tuple, Any]:
    # keep_empty_nodes: optax states contain EmptyState leaves that must
    # survive the flatten/unflatten round-trip for from_state_dict to match
    return traverse_util.flatten_dict(tree, keep_empty_nodes=True)


def _param_dir(out_dir: str, name: str) -> str:
    return os.path.join(out_dir, "zero", name)


def convert_to_universal(ckpt_dir: str, out_dir: str,
                         tag: Optional[str] = None) -> Dict[str, Any]:
    """Convert an engine checkpoint into the universal layout."""
    from deepspeed_tpu.checkpoint.deepspeed_checkpoint import \
        DeepSpeedCheckpoint

    ds = DeepSpeedCheckpoint(ckpt_dir, tag)
    module = ds.module_state()

    # optimizer moments: locate adam-style exp_avg/exp_avg_sq subtrees whose
    # flat param paths mirror the module tree
    optim = {}
    try:
        optim = ds.optimizer_state()
    except FileNotFoundError:
        pass
    moments: Dict[str, Dict[str, np.ndarray]] = {}
    for key, arr in optim.items():
        # optax ScaleByAdamState paths look like "...mu.<param path>" /
        # "...nu.<param path>"
        for tag_name, moment in (("mu", "exp_avg"), ("nu", "exp_avg_sq")):
            marker = f".{tag_name}."
            if marker in key:
                pname = key.split(marker, 1)[1]
                moments.setdefault(pname, {})[moment] = arr

    # optax step counter (ScaleByAdamState.count): needed so bias
    # correction resumes at the right step, not at the fresh engine's
    step_count = None
    for key, arr in optim.items():
        if key == "count" or key.endswith(".count"):
            step_count = int(np.asarray(arr))
            break

    manifest = {"tag": str(ds.tag), "parameters": {},
                "step_count": step_count}
    for name, arr in module.items():
        pdir = _param_dir(out_dir, name)
        os.makedirs(pdir, exist_ok=True)
        arr32 = np.asarray(arr, dtype=np.float32)
        np.save(os.path.join(pdir, "fp32.npy"), arr32)
        entry = {"shape": list(arr32.shape), "files": ["fp32.npy"]}
        for moment, marr in moments.get(name, {}).items():
            np.save(os.path.join(pdir, f"{moment}.npy"),
                    np.asarray(marr, dtype=np.float32))
            entry["files"].append(f"{moment}.npy")
        manifest["parameters"][name] = entry

    with open(os.path.join(out_dir, "universal_manifest.json"), "w") as f:
        json.dump(manifest, f, indent=2)
    return manifest


def load_universal_state(universal_dir: str) -> Dict[str, Dict[str, np.ndarray]]:
    """Load the universal layout into ``name -> {fp32, exp_avg, ...}``."""
    with open(os.path.join(universal_dir, "universal_manifest.json")) as f:
        manifest = json.load(f)
    out = {}
    for name, entry in manifest["parameters"].items():
        pdir = _param_dir(universal_dir, name)
        out[name] = {
            os.path.splitext(fname)[0]: np.load(os.path.join(pdir, fname))
            for fname in entry["files"]
        }
    return out


def load_universal_into_engine(engine, universal_dir: str,
                               load_optimizer_states: bool = True,
                               strict: bool = True) -> int:
    """Load universal weights (and adam moments) into a live engine.

    The engine's current shardings re-distribute each array at device_put —
    this IS the "reshard on load across (dp, tp, pp) changes" capability of
    reference universal checkpoints, with XLA doing the distribution.
    Returns the number of parameters loaded.
    """
    state = load_universal_state(universal_dir)
    params_sd = serialization.to_state_dict(engine._params)
    flat = _flat(params_sd)
    loaded = 0
    for path, cur in flat.items():
        if cur is traverse_util.empty_node:
            continue
        name = ".".join(path)
        if name not in state:
            if strict:
                raise KeyError(f"universal checkpoint missing param {name}")
            continue
        arr = state[name]["fp32"]
        if tuple(arr.shape) != tuple(np.shape(cur)):
            raise ValueError(
                f"shape mismatch for {name}: checkpoint {arr.shape} vs "
                f"model {np.shape(cur)}")
        # .dtype attr, never np.asarray: leaves may be sharded jax.Arrays
        # spanning non-addressable devices on multi-host meshes
        flat[path] = arr.astype(getattr(cur, "dtype", np.float32))
        loaded += 1
    restored = serialization.from_state_dict(
        engine._params, traverse_util.unflatten_dict(flat))
    engine._params = jax.jit(
        lambda t: t, out_shardings=engine._param_shardings)(restored)

    if load_optimizer_states and engine._opt_state is not None:
        with open(os.path.join(universal_dir,
                               "universal_manifest.json")) as f:
            step_count = json.load(f).get("step_count")
        opt_sd = serialization.to_state_dict(engine._opt_state)
        opt_flat = _flat(opt_sd)
        for path, cur in opt_flat.items():
            if cur is traverse_util.empty_node:
                continue
            key = ".".join(path)
            if step_count is not None and (key == "count"
                                           or key.endswith(".count")):
                opt_flat[path] = np.asarray(
                    step_count, dtype=getattr(cur, "dtype", np.int32))
                continue
            for tag_name, moment in (("mu", "exp_avg"), ("nu", "exp_avg_sq")):
                marker = f".{tag_name}."
                if marker in key:
                    pname = key.split(marker, 1)[1]
                    if pname in state and moment in state[pname]:
                        arr = state[pname][moment]
                        opt_flat[path] = arr.astype(
                            getattr(cur, "dtype", np.float32)).reshape(
                                np.shape(cur))
        restored_opt = serialization.from_state_dict(
            engine._opt_state, traverse_util.unflatten_dict(opt_flat))
        engine._opt_state = jax.jit(
            lambda t: t, out_shardings=engine._opt_shardings)(restored_opt)
    return loaded


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(
        description="Convert a deepspeed_tpu checkpoint to universal format")
    p.add_argument("checkpoint_dir")
    p.add_argument("output_dir")
    p.add_argument("--tag", default=None)
    args = p.parse_args(argv)
    manifest = convert_to_universal(args.checkpoint_dir, args.output_dir,
                                    args.tag)
    print(f"wrote {len(manifest['parameters'])} parameters to "
          f"{args.output_dir}")


if __name__ == "__main__":
    main()

"""Tensor-parallel merge/split math for checkpoint import/export.

Reference analogue: ``checkpoint/reshape_meg_2d.py`` + the qkv merge/split
logic in ``runtime/state_dict_factory.py:214`` (MegatronSDLoader). Used to
(a) import Megatron/DeepSpeed TP-sharded checkpoints into the logically-
global format, and (b) export global weights back out at a requested TP
degree. Strategies:

* ``column`` — output-dim sharding (Megatron ColumnParallelLinear): slices
  concatenate on the output axis.
* ``row`` — input-dim sharding (RowParallelLinear): slices concatenate on
  the input axis.
* ``qkv`` — fused attention projection: each slice holds [q_i; k_i; v_i],
  so a plain concat would interleave wrongly; merge splits each slice into
  its q/k/v thirds first, then concatenates per-projection.
* ``replicate`` — layernorms/biases of row-parallel layers: all slices are
  identical; merge takes slice 0, split copies.
"""

from typing import List, Sequence

import numpy as np


def _check_same_rank(slices: Sequence[np.ndarray]):
    if not slices:
        raise ValueError("no slices given")
    shapes = {s.ndim for s in slices}
    if len(shapes) != 1:
        raise ValueError("slices differ in rank")


def merge_tp_slices(slices: Sequence[np.ndarray], strategy: str = "column",
                    axis: int = None) -> np.ndarray:
    """Merge per-TP-rank weight slices into one global array."""
    slices = [np.asarray(s) for s in slices]
    _check_same_rank(slices)
    if strategy == "replicate":
        return slices[0]
    if strategy == "column":
        ax = 0 if axis is None else axis
        return np.concatenate(slices, axis=ax)
    if strategy == "row":
        ax = (slices[0].ndim - 1) if axis is None else axis
        return np.concatenate(slices, axis=ax)
    if strategy == "qkv":
        ax = 0 if axis is None else axis
        parts = {0: [], 1: [], 2: []}
        for s in slices:
            if s.shape[ax] % 3:
                raise ValueError(
                    f"qkv slice axis {ax} size {s.shape[ax]} not divisible "
                    f"by 3")
            q, k, v = np.split(s, 3, axis=ax)
            parts[0].append(q)
            parts[1].append(k)
            parts[2].append(v)
        return np.concatenate(
            [np.concatenate(parts[i], axis=ax) for i in range(3)], axis=ax)
    raise ValueError(f"unknown merge strategy {strategy!r}")


def split_tp_param(param: np.ndarray, degree: int,
                   strategy: str = "column",
                   axis: int = None) -> List[np.ndarray]:
    """Split one global array into ``degree`` per-TP-rank slices (inverse of
    :func:`merge_tp_slices`)."""
    param = np.asarray(param)
    if strategy == "replicate":
        return [param.copy() for _ in range(degree)]
    if strategy == "column":
        ax = 0 if axis is None else axis
        return list(np.split(param, degree, axis=ax))
    if strategy == "row":
        ax = (param.ndim - 1) if axis is None else axis
        return list(np.split(param, degree, axis=ax))
    if strategy == "qkv":
        ax = 0 if axis is None else axis
        q, k, v = np.split(param, 3, axis=ax)
        qs = np.split(q, degree, axis=ax)
        ks = np.split(k, degree, axis=ax)
        vs = np.split(v, degree, axis=ax)
        return [np.concatenate([qs[i], ks[i], vs[i]], axis=ax)
                for i in range(degree)]
    raise ValueError(f"unknown split strategy {strategy!r}")


def reshape_tp_degree(slices: Sequence[np.ndarray], new_degree: int,
                      strategy: str = "column",
                      axis: int = None) -> List[np.ndarray]:
    """Re-shard from one TP degree to another (reference reshape_meg_2d
    ``reshape_tp_dimension``): merge to global, split at the new degree."""
    merged = merge_tp_slices(slices, strategy, axis)
    return split_tp_param(merged, new_degree, strategy, axis)

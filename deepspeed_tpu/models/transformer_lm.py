"""TPU-first decoder-only transformer LM (GPT-2 family).

This is the in-repo model zoo counterpart of the reference's transformer stack
(reference ``csrc/transformer/`` fused training kernel +
``deepspeed/ops/transformer/transformer.py:459`` DeepSpeedTransformerLayer).
Design is idiomatic JAX, not a translation:

* bf16 compute / fp32 params (mixed precision by dtype policy, not patching)
* einsum attention — XLA fuses bias/gelu/residual into the MXU matmuls,
  which is what the reference's hand-fused CUDA kernels exist to do
* optional ``lax.scan`` over layers: O(1) compile time and natural remat
* static shapes only; causal mask via iota comparison (no dynamic slicing)
* weights carry stable path names so parallelism rules (TP/FSDP specs,
  see deepspeed_tpu/runtime/zero/sharding.py) can address them by regex
"""

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    mlp_ratio: int = 4
    layer_norm_epsilon: float = 1e-5  # HF GPT-2 default
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    # --- architecture family knobs (GPT-2 defaults) ------------------------
    # GPT-J/NeoX/OPT/LLaMA-family variants are the same block with these
    # toggled; the HF injection policies (module_inject/hf.py) set them
    intermediate_size: Optional[int] = None  # None -> mlp_ratio * n_embd
    norm: str = "layernorm"            # "layernorm" | "rmsnorm" (LLaMA)
    activation: str = "gelu_tanh"      # "gelu_tanh"|"gelu"|"relu"|"silu"
                                       # |"quick_gelu" (CLIP)
    causal: bool = True                # False = bidirectional (encoders)
    gated_mlp: bool = False            # SwiGLU: act(gate) * up (LLaMA)
    use_bias: bool = True              # biases on dense + norm layers
    attn_bias: Optional[bool] = None   # override for attention projections
                                       # (GPT-J: biasless attn, biased MLP)
    alibi: bool = False                # ALiBi attention bias (BLOOM)
    embed_layernorm: bool = False      # LN right after wte (BLOOM)
    rotary: bool = False               # rotary embeddings (ops/rotary.py)
    rotary_pct: float = 1.0            # fraction of head_dim rotated (NeoX)
    rotary_interleaved: bool = False   # GPT-J even/odd pairing
    rope_theta: float = 10000.0
    learned_positions: bool = True     # wpe table (off for rotary models)
    tie_word_embeddings: bool = True
    lm_head_bias: bool = False         # GPT-J's untied head carries a bias
    parallel_residual: bool = False    # x + attn(ln_1 x) + mlp(ln_2 x)
    n_kv_head: Optional[int] = None    # grouped-query attention; None = MHA
    remat: bool = False
    # "full" recomputes everything (min memory); "selective" saves matmul
    # outputs and recomputes only elementwise ops — the TPU sweet spot:
    # MXU work is saved, cheap VPU work is redone
    remat_policy: str = "full"
    scan_layers: bool = True
    # Pallas flash kernel path (ops/pallas). True | False | "auto" —
    # auto picks per shape from the measured crossover: XLA einsum wins at
    # short seq (the whole [T,T] score matrix tiles well), flash wins from
    # FLASH_AUTO_MIN_SEQ up (benchmarks/flash_sweep.py: GPT-2 125M on one
    # v5e chip — seq 128: 56 vs 45 TFLOPS for XLA; 512: 49 vs 45 flash;
    # 2048: 47 vs 25; 4096: 48 vs 12)
    use_flash_attention: Any = False
    # opt into LIVE flash block autotuning (ops/pallas/autotune.py): first
    # compile at a new (seq, head_dim, dtype, device) benchmarks the
    # candidate grid and persists the winner to the on-disk cache. Off =
    # cached/pretuned blocks still apply; only the benchmarking is gated.
    flash_autotune: bool = False
    # chunked online-softmax attention (ops/chunked_attention.py): bounded
    # O(T * chunk) score memory in plain XLA — the long-context path where
    # the flash kernel's VMEM ceiling binds (seq > 8192 on the current
    # toolchain). An int sets the KV chunk size and takes precedence over
    # the flash path; None disables.
    attention_chunk: Optional[int] = None
    # ZeRO-Infinity parameter tier (ops/streaming.py): layer-stack params
    # live in host memory; the scan streams one layer into HBM per step.
    # Pair with ds_config zero_optimization.offload_param (engine places
    # the shardings in pinned_host). Requires scan_layers.
    param_offload: bool = False
    # sequence/context parallelism over the sp mesh axis
    # (parallel/sequence.py): "none" | "ring" | "ulysses"
    sequence_parallel: str = "none"
    # fused LM-head + cross entropy (ops/cross_entropy.py
    # fused_linear_cross_entropy): never materializes the [tokens, vocab]
    # logits. True | False | "auto". The chunked head scan has a real
    # cost — measured on one v5e chip: ~0.7% at seq 1024 (1.3B A/B) and
    # 1.5x step time at seq 16k/125M where full remat + chunked attention
    # mean logits were not the binding buffer anyway — so "auto" engages
    # only when the slab (tokens x vocab x itemsize, global batch) reaches
    # 4 GB. There it WINS: a 256k-vocab model (seq 4096) measures 2.5%
    # faster at micro 2 (4.3 GB slab) and 7% at micro 4 (8.6 GB) than the
    # dense head, with identical losses.
    # An int >= 1 forces it with that token chunk size (default 2048);
    # 0/False disable.
    fused_head_ce: Any = "auto"
    # block-sparse attention (ops.sparse_attention): a SparsityConfig
    # restricting attention to its block layout — causality is enforced
    # on top regardless of the layout's symmetry. Populated from the
    # DeepSpeed "sparse_attention" config block (see models/bert.py for
    # the encoder-side story).
    sparse_attention: Any = None
    # layout-aware KV cache for decode: window(+leading-global) layouts
    # retain only the G + (w+1)*block slots the layout can ever attend
    # (a block-granular ring), reproducing the TRAINING sparse math
    # exactly while cutting cache memory n_positions/(G+(w+1)*block)-fold.
    # "auto" engages when the layout is expressible (sliding-window,
    # leading-global longformer) and the ring is smaller than the dense
    # cache; True demands it (ValueError if the layout cannot express
    # it — e.g. BigBird's random links); False always decodes dense.
    sparse_kv_cache: Any = "auto"
    # weight-only int8 serving (reference int8 GEMM inference kernels,
    # csrc/transformer/inference/csrc/pt_binding.cpp:1535): block matmul
    # kernels are STORED as {"q": int8, "scale": f32[out]} and dequantized
    # per layer INSIDE the scan body (nn.map_variables), where XLA fuses
    # the convert into the consuming dots — per-token HBM weight traffic
    # stays int8. Dequantizing the whole stacked [L, ...] tree outside the
    # layer scan instead materializes a full bf16 copy per decode step
    # (measured 2x SLOWER than bf16 at 1.3B). Inference-only flag, set by
    # init_inference(dtype="int8"); composes with tp>1 (the {q, scale}
    # leaves shard like the dense kernel they replace, see
    # runtime/zero/sharding.py _quantized_leaf_spec).
    quantized_weights: bool = False
    # int8 KV cache for decode (serving capacity lever, see
    # serving/disagg.py): cache leaves are STORED int8 with one f32 scale
    # per (row, slot, kv-head) — the same symmetric blockwise format as
    # the compressed wire (ops/quantizer.quantize_blockwise, block =
    # head_dim) — and dequantized on read inside the attention einsum.
    # Per-slot HBM drops from 2*D*2 bytes (bf16) to 2*(D + 4) bytes,
    # ~1.94x more lanes at D=128 under the same budget (~3.88x vs fp32).
    # None keeps the cache in the compute dtype; "int8" quantizes. The
    # cache PROTOCOL (leaf shapes minus dtype, splice axes, slot clocks)
    # is unchanged, so the scheduler's jitted _splice and the prefix
    # cache work as-is.
    kv_cache_dtype: Any = None
    # extra STORAGE blocks in the ring KV cache beyond the w_blk + 1 the
    # window visibility needs (sparse_attention_utils.ring_storage_len).
    # Semantically invisible — visibility is positional — but >= 1 makes
    # the speculative-decode verify pass (an unaligned multi-token
    # mid-stream write) exact; the continuous-batching scheduler demands
    # it when spec decoding a ring model.
    kv_cache_slack_blocks: int = 0
    # stochastic transformer (reference op_builder/stochastic_transformer.py,
    # ops/transformer/transformer.py:110 stochastic_mode): whole-block
    # stochastic depth. When training under a progressive-layer-drop
    # schedule the engine feeds ``pld_theta`` (computed IN-GRAPH from the
    # step counter — no per-step host transfer) and each layer i survives
    # with p_i = 1 - (i/L)(1 - theta), gated by an explicit per-layer key
    # from the scan's split rng stream. ``jax.remat`` replays the same key
    # at recompute, so gradients stay exact — the determinism the CUDA
    # kernel's stochastic mode gives up, for free.
    stochastic_mode: bool = False
    # MoE (reference deepspeed/moe/): 0 experts = dense MLP everywhere
    moe_num_experts: int = 0
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.0
    moe_eval_capacity_factor: float = 1.0
    moe_min_capacity: int = 4
    moe_drop_tokens: bool = True
    moe_aux_loss_coef: float = 0.01
    moe_noisy_gate_policy: Optional[str] = None
    moe_use_rts: bool = True
    moe_gated_experts: bool = False  # SwiGLU experts (Mixtral-style)

    def __post_init__(self):
        if self.sequence_parallel not in ("none", "ring", "ulysses"):
            raise ValueError(
                f"sequence_parallel must be 'none', 'ring', or 'ulysses'; "
                f"got {self.sequence_parallel!r}")
        if self.norm not in ("layernorm", "rmsnorm"):
            raise ValueError(f"unknown norm {self.norm!r}")
        if self.activation not in _ACTIVATIONS:
            raise ValueError(f"unknown activation {self.activation!r}")
        if self.n_kv_head is not None and self.n_head % self.n_kv_head:
            raise ValueError(
                f"n_head ({self.n_head}) must be divisible by n_kv_head "
                f"({self.n_kv_head})")
        if self.param_offload and not self.scan_layers:
            raise ValueError(
                "param_offload streams layer slices out of the scan; it "
                "requires scan_layers=True")
        if self.use_flash_attention not in (True, False, "auto"):
            raise ValueError(
                f"use_flash_attention must be True, False or 'auto'; got "
                f"{self.use_flash_attention!r}")
        if self.sparse_attention is not None and self.alibi:
            raise ValueError(
                "sparse_attention does not compose with alibi (the "
                "block-sparse path has no positional-bias hook); a silent "
                "dense fallback would change the model's math, so this is "
                "rejected up front")
        if self.attention_chunk is not None and (
                not isinstance(self.attention_chunk, int)
                or self.attention_chunk <= 0):
            raise ValueError(
                f"attention_chunk must be a positive int or None; got "
                f"{self.attention_chunk!r}")
        if self.kv_cache_dtype not in (None, "int8"):
            raise ValueError(
                f"kv_cache_dtype must be None or 'int8'; got "
                f"{self.kv_cache_dtype!r}")
        if not isinstance(self.kv_cache_slack_blocks, int) or \
                self.kv_cache_slack_blocks < 0:
            raise ValueError(
                f"kv_cache_slack_blocks must be a non-negative int; got "
                f"{self.kv_cache_slack_blocks!r}")
        if self.sparse_kv_cache not in ("auto", True, False):
            raise ValueError(
                f"sparse_kv_cache must be 'auto', True or False; got "
                f"{self.sparse_kv_cache!r}")
        if self.sparse_kv_cache is True:
            from deepspeed_tpu.ops.sparse_attention.sparse_attention_utils \
                import ring_decode_params

            if (self.sparse_attention is None
                    or ring_decode_params(self.sparse_attention) is None):
                raise ValueError(
                    "sparse_kv_cache=True needs a ring-expressible layout "
                    "(causal sliding-window, or longformer with leading "
                    "global blocks); BigBird's random links cannot be "
                    "served from a bounded ring — use 'auto' to fall back "
                    "to the dense cache")

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head

    @property
    def kv_heads(self) -> int:
        return self.n_kv_head or self.n_head

    @property
    def ffn_dim(self) -> int:
        return self.intermediate_size or self.mlp_ratio * self.n_embd

    @property
    def rotary_dim(self) -> int:
        rd = round(self.rotary_pct * self.head_dim)
        return rd - rd % 2

    @property
    def is_moe(self) -> bool:
        return self.moe_num_experts > 0


# GPT-2 sizes (reference benchmarks target 125M / 1.3B; BASELINE.md configs 2-5)
GPT2_SIZES = {
    "gpt2-125m": dict(n_embd=768, n_layer=12, n_head=12),
    "gpt2-350m": dict(n_embd=1024, n_layer=24, n_head=16),
    "gpt2-760m": dict(n_embd=1536, n_layer=24, n_head=16),
    "gpt2-1.3b": dict(n_embd=2048, n_layer=24, n_head=16),
    "gpt2-2.7b": dict(n_embd=2560, n_layer=32, n_head=32),
    "gpt2-6.7b": dict(n_embd=4096, n_layer=32, n_head=32),
}


def gpt2_config(name: str, **overrides) -> GPTConfig:
    base = dict(GPT2_SIZES[name])
    base.update(overrides)
    return GPTConfig(**base)


def _norm(cfg, name):
    if cfg.norm == "rmsnorm":
        return nn.RMSNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                          param_dtype=cfg.param_dtype, name=name)
    return nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype, use_bias=cfg.use_bias,
                        name=name)


_ACTIVATIONS = {
    "gelu_tanh": lambda x: nn.gelu(x, approximate=True),
    "gelu": lambda x: nn.gelu(x, approximate=False),
    "relu": nn.relu,
    "silu": nn.silu,
    # CLIP's x * sigmoid(1.702 x)
    "quick_gelu": lambda x: x * nn.sigmoid(1.702 * x),
}


class VocabEmbed(nn.Embed):
    """``nn.Embed`` with an explicit vocab-parallel lookup when the table is
    tensor-parallel vocab-sharded.

    A row-gather over a tp-sharded operand (and the scatter-add in its
    backward) cannot be partitioned by GSPMD — it falls back to
    "involuntary full rematerialization", replicating the table every step.
    The fix is the Megatron VocabParallelEmbedding masked-lookup+allreduce
    (reference analogue ``deepspeed/module_inject/replace_module.py:18``
    slices the same weights at inference), expressed as a ``shard_map``
    island: each tp shard gathers from its LOCAL vocab slice, zeroes rows
    it does not own, and one psum merges — O(B*T*C) memory, no ``[B, T,
    vocab]`` one-hot buffer (earlier rounds paid ~0.8 GB per micro batch
    at 50k vocab for that lowering), and the backward is a LOCAL
    scatter-add per shard, exactly the partitioning GSPMD could not infer.
    Replicated tables keep the native gather.
    """

    def __call__(self, inputs):
        from deepspeed_tpu.parallel.mesh import get_default_topology

        topo = get_default_topology()
        tp = topo.size("tp")
        if tp > 1 and self.num_embeddings % tp == 0:
            if topo.size("pp") == 1:
                return _vocab_parallel_lookup(inputs, self.embedding, topo,
                                              self.dtype)
            # pipeline stages jit over per-stage SUB-meshes; a shard_map
            # bound to the full topology mesh cannot run there. Fall back
            # to the one-hot contraction, which GSPMD partitions cleanly
            # on whatever mesh the stage runs (Megatron masked-lookup
            # expressed as a dot; [B, T, vocab] operand is the cost)
            onehot = jax.nn.one_hot(inputs, self.num_embeddings,
                                    dtype=self.dtype)
            return jnp.dot(onehot, self.embedding.astype(self.dtype))
        # tp == 1, or an indivisible vocab dim (sharding rules strip the
        # spec, the table stays replicated): native gather partitions fine
        return super().__call__(inputs)


def _vocab_parallel_lookup(ids, embedding, topo, dtype):
    """Masked local-gather + psum over the tp axis (shard_map island)."""
    from jax.sharding import PartitionSpec as P

    tp = topo.size("tp")
    vocab, _ = embedding.shape
    shard = vocab // tp
    # shard_map needs the batch dims evenly divisible by their mesh axes;
    # when they are not (e.g. batch-1 serving on a dp>1 mesh, where the
    # array is replicated anyway), declare them unsharded
    b0 = topo.batch_spec()[0]
    b_axes = b0 if isinstance(b0, tuple) else ((b0,) if b0 else ())
    b_size = int(np.prod([topo.size(a) for a in b_axes])) if b_axes else 1
    if ids.shape[0] % max(b_size, 1) != 0:
        b0 = None
    # mirror engine._put_batch: the sequence dim rides sp when it divides
    sp = topo.size("sp")
    t_ax = "sp" if (sp > 1 and ids.shape[1] % sp == 0) else None

    def lookup(ids_l, emb_l):
        lo = jax.lax.axis_index("tp") * shard
        local = ids_l - lo
        valid = (local >= 0) & (local < shard)
        rows = jnp.take(emb_l, jnp.where(valid, local, 0), axis=0)
        rows = jnp.where(valid[..., None], rows.astype(dtype),
                         jnp.zeros((), dtype))
        # exactly one shard owns each id, so the bf16 psum is exact
        return jax.lax.psum(rows, "tp")

    return jax.shard_map(
        lookup, mesh=topo.mesh,
        in_specs=(P(b0, t_ax), P("tp", None)),
        out_specs=P(b0, t_ax, None),
        check_vma=False,
    )(ids, embedding)


class CausalSelfAttention(nn.Module):
    config: GPTConfig

    @nn.compact
    def __call__(self, x, *, mask=None, segment_ids=None, positions=None,
                 deterministic=True, decode=False):
        cfg = self.config
        B, T, C = x.shape
        H, D = cfg.n_head, cfg.head_dim
        Hkv = cfg.kv_heads
        bias = cfg.use_bias if cfg.attn_bias is None else cfg.attn_bias

        # packed-sequence masking (deepspeed_tpu/data/): position i attends
        # j iff j <= i AND seg[i] == seg[j]. Supported on the flash and
        # einsum paths; the others either cannot express the per-row block
        # structure (sparse layouts, ALiBi's absolute-position bias) or do
        # not see it yet (sp/chunked fall through to einsum below).
        if segment_ids is not None:
            if decode:
                raise NotImplementedError(
                    "packed-sequence segment_ids are a training-path "
                    "feature; decode caches are per-sequence")
            if cfg.sparse_attention is not None:
                raise NotImplementedError(
                    "segment_ids with a block-sparse layout would silently "
                    "change the layout's visibility; unpack the batch or "
                    "disable sparse_attention")
            if cfg.alibi:
                raise NotImplementedError(
                    "ALiBi's absolute-position bias is not segment-aware; "
                    "packed batches require rotary or learned positions")

        qkv = nn.Dense((H + 2 * Hkv) * D, use_bias=bias,
                       dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                       name="c_attn")(x)
        q = qkv[..., : H * D].reshape(B, T, H, D)
        k = qkv[..., H * D:(H + Hkv) * D].reshape(B, T, Hkv, D)
        v = qkv[..., (H + Hkv) * D:].reshape(B, T, Hkv, D)

        def rope(t, positions):
            from deepspeed_tpu.ops.rotary import apply_rotary_pos_emb

            return apply_rotary_pos_emb(
                t, positions, base=cfg.rope_theta,
                rotary_dim=cfg.rotary_dim,
                interleaved=cfg.rotary_interleaved)

        def repeat_kv(t):
            return (t if Hkv == H
                    else jnp.repeat(t, H // Hkv, axis=2))

        if decode:
            if not cfg.causal:
                raise NotImplementedError(
                    "decode path requires a causal model")
            # int8 KV cache (GPTConfig.kv_cache_dtype): values are stored
            # quantized with per-(row, slot, kv-head) f32 scales and
            # dequantized on read — XLA fuses the int8->f32 convert +
            # scale multiply into the attention einsums, so per-step HBM
            # cache traffic stays int8
            kv_int8 = cfg.kv_cache_dtype == "int8"
            kv_store_dtype = jnp.int8 if kv_int8 else cfg.dtype

            def quantize_kv(t):
                from deepspeed_tpu.ops.quantizer import quantize_blockwise

                q, s = quantize_blockwise(t, D)
                return q, s[..., 0]          # [B, T, Hkv, 1] -> [B, T, Hkv]

            def read_kv(ck, cv, ks, vs):
                if not kv_int8:
                    return ck.value, cv.value
                from deepspeed_tpu.ops.quantizer import dequantize_blockwise

                return (dequantize_blockwise(ck.value, ks.value, cfg.dtype),
                        dequantize_blockwise(cv.value, vs.value, cfg.dtype))
            # layout-aware compact KV cache: when the sparse layout is a
            # causal window (+ leading globals), decode retains ONLY the
            # slots the layout can ever attend — a block-granular ring —
            # and reproduces the TRAINING block-sparse visibility exactly
            # (the dense-cache path below attends strictly more keys than
            # a window-trained model saw). See GPTConfig.sparse_kv_cache.
            from deepspeed_tpu.ops.sparse_attention. \
                sparse_attention_utils import ring_engaged, ring_storage_len

            ring = ring_engaged(cfg)
            if ring is not None:
                w_blk, g_tok, blk = ring
                ring_len = ring_storage_len(cfg, ring)
                S = g_tok + ring_len
                if T > ring_len:
                    raise ValueError(
                        f"ring KV prefill got {T} tokens in one pass but "
                        f"the ring retains only {ring_len} positions: keys "
                        "a mid-prompt query still needs would be evicted "
                        "before it attends, and the corrupted attention "
                        "outputs would poison every later layer's cache "
                        "(and with it every generated token). Prefill long "
                        "prompts in block-aligned chunks instead — "
                        "InferenceEngine.generate and the continuous-"
                        "batching scheduler do this automatically "
                        "(inference/engine.py prefill_chunk_spans).")
                cached_k = self.variable(
                    "cache", "cached_key", jnp.zeros,
                    (B, S, Hkv, D), kv_store_dtype)
                cached_v = self.variable(
                    "cache", "cached_value", jnp.zeros,
                    (B, S, Hkv, D), kv_store_dtype)
                k_scale = v_scale = None
                if kv_int8:
                    k_scale = self.variable(
                        "cache", "cached_key_scale", jnp.zeros,
                        (B, S, Hkv), jnp.float32)
                    v_scale = self.variable(
                        "cache", "cached_value_scale", jnp.zeros,
                        (B, S, Hkv), jnp.float32)
                cache_valid = self.variable(
                    "cache", "valid", jnp.zeros, (B, S), jnp.bool_)
                # PER-ROW slot positions and write index: continuous-
                # batching admissions splice a freshly prefilled [1, ...]
                # cache into one batch lane, so every row carries its own
                # clock (lockstep generate just advances them together)
                slot_pos = self.variable(
                    "cache", "slot_pos",
                    lambda: jnp.full((B, S), -1, jnp.int32))
                cache_index = self.variable(
                    "cache", "cache_index",
                    lambda: jnp.zeros((B,), jnp.int32))
                idx = cache_index.value                       # [B]
                pos = idx[:, None] + jnp.arange(T)[None, :]   # [B, T]
                if cfg.rotary:
                    q, k = rope(q, pos), rope(k, pos)
                # every token of a (guarded, <= ring_len) pass lands in its
                # ring slot; leading-global tokens ALSO land in their
                # dedicated slot (the ring copy is masked out of
                # visibility below, so nothing double-counts)
                ring_slot = g_tok + pos % ring_len            # [B, T]
                glob_slot = jnp.where(pos < g_tok, pos, S)    # S -> dropped
                write_valid = (mask.astype(jnp.bool_) if mask is not None
                               else jnp.ones((B, T), jnp.bool_))
                if kv_int8:
                    (kc, ksc), (vc, vsc) = quantize_kv(k), quantize_kv(v)
                else:
                    kc, vc = k.astype(cfg.dtype), v.astype(cfg.dtype)
                rows = jnp.arange(B)[:, None]
                for slots in (ring_slot, glob_slot):
                    cached_k.value = cached_k.value.at[rows, slots].set(
                        kc, mode="drop")
                    cached_v.value = cached_v.value.at[rows, slots].set(
                        vc, mode="drop")
                    if kv_int8:
                        k_scale.value = k_scale.value.at[rows, slots].set(
                            ksc, mode="drop")
                        v_scale.value = v_scale.value.at[rows, slots].set(
                            vsc, mode="drop")
                    cache_valid.value = cache_valid.value.at[
                        rows, slots].set(write_valid, mode="drop")
                    slot_pos.value = slot_pos.value.at[rows, slots].set(
                        pos, mode="drop")
                cache_index.value = idx + T
                k_all, v_all = read_kv(cached_k, cached_v, k_scale, v_scale)

                G = H // Hkv
                qg = q.reshape(B, T, Hkv, G, D)
                scale = 1.0 / np.sqrt(D)
                att = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k_all) * scale
                q_pos = pos[:, :, None]                       # [B, T, 1]
                ps = slot_pos.value[:, None, :]               # [B, 1, S]
                s_idx = jnp.arange(S)[None, None, :]
                is_glob = s_idx < g_tok
                in_window = (ps // blk) >= (q_pos // blk) - w_blk
                visible = ((ps >= 0) & (ps <= q_pos)
                           & (is_glob | (in_window & (ps >= g_tok))))
                visible = (visible[:, None, None]             # [B,1,1,T,S]
                           & cache_valid.value[:, None, None, None, :])
                att = jnp.where(visible, att, jnp.finfo(att.dtype).min)
                # NaN-safe: an all-pad chunk row (ragged left-padded batch)
                # has an empty visible set; its output is masked out later
                # but must not produce NaN
                att = jax.nn.softmax(
                    att.astype(jnp.float32), axis=-1,
                    where=visible).astype(cfg.dtype)
                y = jnp.einsum("bhgqk,bkhd->bqhgd", att, v_all)
                y = y.reshape(B, T, C)
                return nn.Dense(C, use_bias=bias, dtype=cfg.dtype,
                                param_dtype=cfg.param_dtype,
                                name="c_proj")(y)
            # KV-cache append + attend (the reference's softmax_context
            # kernel with its inference_context.h cache management,
            # csrc/transformer/inference/). Chunk-aware: prefill writes T
            # tokens at once, decode steps write one. Ragged batches:
            # LEFT-padded prompts pass ``mask``, and a per-slot validity
            # cache excludes pad slots from every later step's attention
            # (reference inference_context.h masked decode). Left padding
            # keeps valid keys physically contiguous, so rotary (relative
            # offsets) and ALiBi (row-constant shift under softmax) stay
            # exact without per-sequence position bookkeeping here.
            cached_k = self.variable(
                "cache", "cached_key", jnp.zeros,
                (B, cfg.n_positions, Hkv, D), kv_store_dtype)
            cached_v = self.variable(
                "cache", "cached_value", jnp.zeros,
                (B, cfg.n_positions, Hkv, D), kv_store_dtype)
            k_scale = v_scale = None
            if kv_int8:
                k_scale = self.variable(
                    "cache", "cached_key_scale", jnp.zeros,
                    (B, cfg.n_positions, Hkv), jnp.float32)
                v_scale = self.variable(
                    "cache", "cached_value_scale", jnp.zeros,
                    (B, cfg.n_positions, Hkv), jnp.float32)
            cache_valid = self.variable(
                "cache", "valid", jnp.zeros,
                (B, cfg.n_positions), jnp.bool_)
            # PER-ROW write index (see ring branch): continuous-batching
            # admissions splice a [1, ...] cache into one batch lane, so
            # each row advances its own clock
            cache_index = self.variable(
                "cache", "cache_index",
                lambda: jnp.zeros((B,), jnp.int32))
            idx = cache_index.value                         # [B]
            pos = idx[:, None] + jnp.arange(T)[None, :]     # [B, T]
            if cfg.rotary:
                # rotate before the cache write: cached keys are
                # position-baked, exactly like the reference's KV cache
                # after its apply_rotary_pos_emb kernel
                q, k = rope(q, pos), rope(k, pos)
            rows = jnp.arange(B)[:, None]
            if kv_int8:
                (kc, ksc), (vc, vsc) = quantize_kv(k), quantize_kv(v)
                k_scale.value = k_scale.value.at[rows, pos].set(
                    ksc, mode="drop")
                v_scale.value = v_scale.value.at[rows, pos].set(
                    vsc, mode="drop")
            else:
                kc, vc = k.astype(cfg.dtype), v.astype(cfg.dtype)
            cached_k.value = cached_k.value.at[rows, pos].set(
                kc, mode="drop")
            cached_v.value = cached_v.value.at[rows, pos].set(
                vc, mode="drop")
            write_valid = (mask.astype(jnp.bool_) if mask is not None
                           else jnp.ones((B, T), jnp.bool_))
            cache_valid.value = cache_valid.value.at[rows, pos].set(
                write_valid, mode="drop")
            cache_index.value = idx + T
            k_all, v_all = read_kv(cached_k, cached_v, k_scale, v_scale)

            # grouped attention: query heads contract directly against the
            # un-repeated KV cache ([B, max, Hkv, D] stays in place — no
            # [B, max, H, D] repeat materializes per step)
            G = H // Hkv
            qg = q.reshape(B, T, Hkv, G, D)
            scale = 1.0 / np.sqrt(D)
            att = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k_all) * scale
            q_pos = pos[:, :, None]                         # [B, T, 1]
            k_pos = jnp.arange(cfg.n_positions)[None, :]    # [1, max]
            if cfg.alibi:
                slopes = jnp.asarray(alibi_slopes(H)).reshape(Hkv, G)
                att = att + (slopes[:, :, None, None]
                             * k_pos[None].astype(att.dtype))
            visible = (k_pos[None] <= q_pos)                # [B, T, max]
            visible = (visible[:, None, None]               # [B,1,1,T,max]
                       & cache_valid.value[:, None, None, None, :])
            att = jnp.where(visible, att, jnp.finfo(att.dtype).min)
            att = jax.nn.softmax(att.astype(jnp.float32), axis=-1).astype(
                cfg.dtype)
            y = jnp.einsum("bhgqk,bkhd->bqhgd", att, v_all)
            y = y.reshape(B, T, C)
            return nn.Dense(C, use_bias=bias, dtype=cfg.dtype,
                            param_dtype=cfg.param_dtype, name="c_proj")(y)

        if cfg.rotary:
            # packed batches pass per-segment-reset positions so each
            # document sees the same rotary phases it would alone
            pos = (positions if positions is not None
                   else jnp.arange(T)[None, :])
            q = rope(q, pos)
            k = rope(k, pos)
        k = repeat_kv(k)
        v = repeat_kv(v)

        # block-sparse path (explicit opt-in; wins over sp/chunked/flash).
        # Taken UNCONDITIONALLY when configured — a silent dense fallback
        # would change the model's math between configs. Attention-prob
        # dropout does not exist on this path (the layout already drops
        # most of the matrix; output dropout below still applies), and
        # ALiBi is rejected at config time.
        if cfg.sparse_attention is not None:
            from deepspeed_tpu.ops.sparse_attention import SparseSelfAttention

            sa = SparseSelfAttention(cfg.sparse_attention,
                                     max_seq_length=cfg.n_positions)
            kpm = None
            if mask is not None:
                kpm = jnp.where(mask, 0.0, jnp.finfo(jnp.float32).min)
            y = sa(q, k, v, key_padding_mask=kpm, causal=cfg.causal)
            y = y.reshape(B, T, C)
            y = nn.Dense(C, use_bias=bias, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="c_proj")(y)
            return nn.Dropout(cfg.dropout)(y, deterministic=deterministic)

        # like the flash path, sp attention has no attention-prob dropout
        # (and no ALiBi bias hook)
        if (cfg.sequence_parallel != "none" and mask is None
                and segment_ids is None and not cfg.alibi
                and (cfg.dropout == 0.0 or deterministic)):
            from deepspeed_tpu.parallel.mesh import get_default_topology
            from deepspeed_tpu.parallel.sequence import (
                ring_attention,
                ulysses_attention,
            )

            if get_default_topology().size("sp") > 1:
                attn_fn = {"ring": ring_attention,
                           "ulysses": ulysses_attention}[cfg.sequence_parallel]
                y = attn_fn(q, k, v, causal=cfg.causal)
                y = y.reshape(B, T, C)
                y = nn.Dense(C, use_bias=bias, dtype=cfg.dtype,
                             param_dtype=cfg.param_dtype, name="c_proj")(y)
                return nn.Dropout(cfg.dropout)(y, deterministic=deterministic)

        # chunked path: same gating as flash (no mask/ALiBi/attn-dropout),
        # divisibility by the chunk instead of 128-alignment. Selected by
        # explicit attention_chunk (wins over flash) or by "auto" past the
        # flash kernel's VMEM ceiling (FLASH_MAX_SEQ).
        auto_chunk = None
        if cfg.use_flash_attention == "auto" and T > FLASH_MAX_SEQ:
            # largest standard chunk that divides T (an odd long T still
            # routes here rather than into the flash VMEM wall)
            auto_chunk = next(
                (c for c in (CHUNKED_AUTO_CHUNK, 512, 256, 128)
                 if T % c == 0), None)
        eff_chunk = cfg.attention_chunk or auto_chunk
        if (eff_chunk and mask is None and segment_ids is None
                and not cfg.alibi
                and (cfg.dropout == 0.0 or deterministic)
                and T % eff_chunk == 0 and T > eff_chunk):
            from deepspeed_tpu.ops.chunked_attention import chunked_attention

            y = chunked_attention(q, k, v, causal=cfg.causal,
                                  chunk=eff_chunk)
            y = y.reshape(B, T, C)
            y = nn.Dense(C, use_bias=bias, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="c_proj")(y)
            return nn.Dropout(cfg.dropout)(y, deterministic=deterministic)

        # flash path needs 128-aligned seq (TPU tile constraint), no padding
        # mask, and no attention dropout (the kernel has none). "auto"
        # selects by the measured seq-length crossover (see GPTConfig).
        # "auto" never picks flash past its VMEM ceiling (FLASH_MAX_SEQ) —
        # an un-chunkable long T falls through to einsum rather than
        # compiling the kernel into the wall
        want_flash = (FLASH_AUTO_MIN_SEQ <= T <= FLASH_MAX_SEQ
                      if cfg.use_flash_attention == "auto"
                      else cfg.use_flash_attention)
        use_flash = (want_flash and mask is None
                     and T % 128 == 0 and not cfg.alibi
                     and (cfg.dropout == 0.0 or deterministic))
        if use_flash:
            from deepspeed_tpu.ops.pallas.flash_attention import flash_attention

            y = flash_attention(q, k, v, causal=cfg.causal,
                                segment_ids=segment_ids,
                                autotune=True if cfg.flash_autotune
                                else None)
        else:
            scale = 1.0 / np.sqrt(D)
            att = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
            if cfg.alibi:
                # bias slopes_h * k_pos (HF BLOOM formula; equivalent to
                # slopes * (k - q) under softmax's row-shift invariance)
                slopes = jnp.asarray(alibi_slopes(H))
                att = att + (slopes[None, :, None, None]
                             * jnp.arange(T, dtype=att.dtype)[None, None,
                                                              None, :])
            if cfg.causal:
                tri = jnp.tril(jnp.ones((T, T), dtype=bool))
                att = jnp.where(tri[None, None, :, :], att,
                                jnp.finfo(att.dtype).min)
            if mask is not None:
                att = jnp.where(mask[:, None, None, :], att, jnp.finfo(att.dtype).min)
            if segment_ids is not None:
                # NaN-safe: the causal diagonal is always same-segment, so
                # no row's visible set is ever empty
                same = (segment_ids[:, None, :, None]
                        == segment_ids[:, None, None, :])
                att = jnp.where(same, att, jnp.finfo(att.dtype).min)
            att = jax.nn.softmax(att.astype(jnp.float32), axis=-1).astype(cfg.dtype)
            att = nn.Dropout(cfg.dropout)(att, deterministic=deterministic)
            y = jnp.einsum("bhqk,bkhd->bqhd", att, v)
        y = y.reshape(B, T, C)
        y = nn.Dense(C, use_bias=bias, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="c_proj")(y)
        y = nn.Dropout(cfg.dropout)(y, deterministic=deterministic)
        return y


class MLP(nn.Module):
    config: GPTConfig

    @nn.compact
    def __call__(self, x, *, deterministic=True):
        cfg = self.config
        act = _ACTIVATIONS[cfg.activation]
        h = nn.Dense(cfg.ffn_dim, use_bias=cfg.use_bias, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="c_fc")(x)
        if cfg.gated_mlp:
            # SwiGLU (LLaMA family): act(gate) * up — both column-parallel
            g = nn.Dense(cfg.ffn_dim, use_bias=cfg.use_bias, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="c_gate")(x)
            h = act(g) * h
        else:
            h = act(h)
        h = nn.Dense(cfg.n_embd, use_bias=cfg.use_bias, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="c_proj")(h)
        h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        return h


class Block(nn.Module):
    """Pre-LN transformer block; MLP becomes an expert-parallel MoE layer when
    the config asks for experts (reference moe/layer.py MoE drop-in).
    Returns ``(x, l_aux)`` — l_aux is 0 for the dense path."""

    config: GPTConfig

    @nn.compact
    def __call__(self, x, *, mask=None, segment_ids=None, positions=None,
                 deterministic=True, decode=False, pld_keep=None):
        cfg = self.config
        x_in = x
        a = CausalSelfAttention(cfg, name="attn")(
            _norm(cfg, "ln_1")(x),
            mask=mask, segment_ids=segment_ids, positions=positions,
            deterministic=deterministic, decode=decode)
        if cfg.parallel_residual:
            # GPT-J/NeoX form: attention and MLP both read the pre-residual
            # stream; GPT-J's single shared LN is expressed by loading
            # identical weights into ln_1/ln_2 (module_inject/hf.py)
            h = _norm(cfg, "ln_2")(x)
        else:
            x = x + a
            h = _norm(cfg, "ln_2")(x)
        if cfg.is_moe:
            from deepspeed_tpu.moe.layer import MoE

            y, l_aux, _ = MoE(
                d_model=cfg.n_embd,
                d_hidden=cfg.ffn_dim,
                num_experts=cfg.moe_num_experts,
                k=cfg.moe_top_k,
                capacity_factor=cfg.moe_capacity_factor,
                eval_capacity_factor=cfg.moe_eval_capacity_factor,
                min_capacity=cfg.moe_min_capacity,
                noisy_gate_policy=cfg.moe_noisy_gate_policy,
                drop_tokens=cfg.moe_drop_tokens,
                use_rts=cfg.moe_use_rts,
                gated_experts=cfg.moe_gated_experts,
                dtype=cfg.dtype,
                param_dtype=cfg.param_dtype,
                name="mlp",
            )(h, deterministic=deterministic)
        else:
            y = MLP(cfg, name="mlp")(h, deterministic=deterministic)
            l_aux = jnp.float32(0.0)
        x = x + y + a if cfg.parallel_residual else x + y
        if cfg.stochastic_mode and pld_keep is not None and not deterministic:
            # whole-block stochastic depth (PLD form: identity skip, no
            # 1/keep rescale — inference uses all layers unscaled). The
            # gate key comes from the per-layer split "dropout" stream, so
            # remat recompute reproduces the same draw exactly.
            gate = jax.random.bernoulli(self.make_rng("dropout"), pld_keep)
            x = jnp.where(gate, x, x_in)
            l_aux = jnp.where(gate, l_aux, jnp.zeros_like(l_aux))
        return x, l_aux


# measured crossover for use_flash_attention="auto"
# (benchmarks/flash_sweep.py, v5e chip): XLA einsum attention wins below
# this sequence length, the Pallas flash kernel at and above it
FLASH_AUTO_MIN_SEQ = 512
# above this, the flash kernel's per-head VMEM working set exceeds the
# 16 MB scoped-vmem ceiling (measured at 16384); "auto" falls back to the
# chunked online-softmax path (ops/chunked_attention.py)
FLASH_MAX_SEQ = 8192
CHUNKED_AUTO_CHUNK = 1024


def alibi_slopes(n_head: int) -> np.ndarray:
    """Per-head ALiBi slopes (BLOOM; HF build_alibi_tensor math exactly,
    reference BLOOMLayerPolicy replace_policy.py:444 serves these models
    through its fused kernels)."""
    import math

    closest = 2 ** math.floor(math.log2(n_head))
    base = 2.0 ** (-(2.0 ** -(math.log2(closest) - 3)))
    slopes = [base ** i for i in range(1, closest + 1)]
    if closest != n_head:
        extra_base = 2.0 ** (-(2.0 ** -(math.log2(2 * closest) - 3)))
        n_extra = min(closest, n_head - closest)
        slopes += [extra_base ** i for i in range(1, 2 * n_extra, 2)]
    return np.asarray(slopes, np.float32)


def quantize_block_params(tree):
    """2-D ``kernel`` leaves -> {"q": int8, "scale": f32[out]} (symmetric
    per-output-column). The storage format of ``quantized_weights``; also
    the ``trans_out_fn`` that makes ``model.init`` produce this structure
    natively so shape/sharding trees stay consistent."""
    from collections.abc import Mapping

    from deepspeed_tpu.ops.quantizer import quantize_weight_per_column

    def walk(t):
        if isinstance(t, Mapping):   # plain dict OR flax FrozenDict
            out = {}
            for k, v in t.items():
                if (k == "kernel" and hasattr(v, "ndim")
                        and v.ndim in (2, 3)
                        and jnp.issubdtype(v.dtype, jnp.floating)):
                    if v.ndim == 2:
                        q, s = quantize_weight_per_column(v, num_bits=8)
                    else:  # scan-stacked [n_layer, in, out]
                        q, s = jax.vmap(lambda w: quantize_weight_per_column(
                            w, num_bits=8))(v)
                    out[k] = {"q": q, "scale": s}
                else:
                    out[k] = walk(v)
            return out
        return t

    return walk(tree)


def dequantize_block_params(tree, dtype):
    """Trace-level inverse of :func:`quantize_block_params`: runs INSIDE
    the layer scan on one layer's slice, so the int8->compute convert
    fuses into that layer's matmuls."""

    from collections.abc import Mapping

    def walk(t):
        if isinstance(t, Mapping):   # plain dict OR flax FrozenDict
            if set(t) == {"q", "scale"}:
                q, s = t["q"], t["scale"]
                sb = s[:, None, :] if q.ndim == 3 else s[None, :]
                return q.astype(dtype) * sb.astype(dtype)
            return {k: walk(v) for k, v in t.items()}
        return t

    return walk(tree)


def _maybe_quantized_block(block_cls, cfg):
    """Wrap a block class so its params live int8-at-rest (see
    GPTConfig.quantized_weights).

    init=False on purpose: with init=True, flax's map_variables runs the
    wrapped function ONCE with the raw (still-quantized) params whenever
    any other collection is mutable — i.e. on every KV-cache-creating
    decode apply — and Dense then chokes on the {q, scale} dict. The
    trade-off is that ``model.init`` cannot create params through the
    transform: initialize a dense twin (quantized_weights=False) and
    convert with :func:`quantize_block_params`, which is what
    ``InferenceEngine._materialize`` does."""
    if not cfg.quantized_weights:
        return block_cls
    import functools

    return nn.map_variables(
        block_cls, "params",
        trans_in_fn=functools.partial(dequantize_block_params,
                                      dtype=cfg.dtype))


def pld_keep_probability(layer_idx, n_layer: int, theta):
    """Depth schedule for PLD stochastic depth: layer i survives with
    ``p_i = 1 - (i/L)(1 - theta)`` — deeper layers drop more. Shared by
    the GPT trunk (scan + loop forms) and the BERT encoder so the schedule
    cannot drift between them. ``layer_idx`` may be a python int or a
    traced scan counter; ``theta`` a float or traced scalar."""
    frac = (layer_idx.astype(jnp.float32)
            if hasattr(layer_idx, "astype") else float(layer_idx)) / n_layer
    return 1.0 - frac * (1.0 - theta)


def _remat_policy(name: str):
    import jax

    if name == "selective":
        # non-batched dots (the param matmuls) + flash-attention outputs:
        # saving o/lse (O(seq) memory) avoids re-running the fwd kernel to
        # rebuild backward residuals — attention probs are never saved
        return jax.checkpoint_policies.save_from_both_policies(
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            jax.checkpoint_policies.save_only_these_names(
                "attn_out", "attn_lse"),
        )
    if name == "save_dots":
        # checkpoint every dot product (param matmuls AND attention
        # score/value einsums): the backward never re-runs a matmul, at
        # the cost of keeping the [T, T] attention dots live on the dense
        # path — the cheapest-recompute / highest-memory selective point
        return jax.checkpoint_policies.dots_saveable
    if name == "save_nothing_but_flash":
        # keep ONLY the flash kernel's o/lse residuals (O(seq) per layer,
        # tagged via checkpoint_name in ops/pallas/flash_attention.py) so
        # backward skips the fwd kernel re-run; everything else — all
        # param matmuls included — is recomputed. On the einsum path no
        # tensor carries these names, so it degenerates to `full`.
        return jax.checkpoint_policies.save_only_these_names(
            "attn_out", "attn_lse")
    if name == "full":
        return None  # save nothing, recompute all
    raise ValueError(f"unknown remat_policy {name!r}")


class ScannedBlocks(nn.Module):
    """All transformer blocks as one scanned module: params get a leading
    ``n_layer`` axis, compile time is layer-count independent, and remat
    applies per scan step (the activation-checkpointing sweet spot on TPU)."""

    config: GPTConfig

    @nn.compact
    def __call__(self, x, *, mask=None, segment_ids=None, positions=None,
                 deterministic=True, decode=False, pld_theta=None):
        cfg = self.config
        use_pld = (cfg.stochastic_mode and pld_theta is not None
                   and not deterministic)

        def call_block(block, x, mask, segment_ids, positions, layer_idx):
            # deterministic/decode ride the closure so remat never sees
            # them as traced booleans
            pld_keep = (pld_keep_probability(layer_idx, cfg.n_layer,
                                             pld_theta) if use_pld else None)
            return block(x, mask=mask, segment_ids=segment_ids,
                         positions=positions, deterministic=deterministic,
                         decode=decode, pld_keep=pld_keep)

        if cfg.remat:
            call_block = nn.remat(call_block, prevent_cse=False,
                                  policy=_remat_policy(cfg.remat_policy))

        def body(block, carry, layer_idx):
            # None entries are valid (empty) pytree leaves in the carry
            x, mask, segment_ids, positions = carry
            x, l_aux = call_block(block, x, mask, segment_ids, positions,
                                  layer_idx)
            return (x, mask, segment_ids, positions), l_aux

        block_cls = _maybe_quantized_block(Block, cfg)
        if cfg.param_offload:
            # ZeRO-Infinity param tier: the scan's per-iteration slice of
            # the (host-resident) layer stack is copied into HBM right
            # before use — one layer's working set in device memory at a
            # time (ops/streaming.py; reference partition_parameters.py:537
            # remote_device="cpu" + coordinator fetch_sub_module)
            from deepspeed_tpu.ops.streaming import stream_tree_to_device

            block_cls = nn.map_variables(
                block_cls, "params", trans_in_fn=stream_tree_to_device,
                init=True)  # composes: stream int8-at-rest, dequant inner

        scanned = nn.scan(
            body,
            variable_axes={"params": 0, "cache": 0},
            split_rngs={"params": True, "dropout": True, "gating": True},
            in_axes=0,
            length=cfg.n_layer,
            metadata_params={nn.PARTITION_NAME: "layers"},
        )
        (x, _, _, _), l_aux = scanned(
            block_cls(cfg, name="block"),
            (x, mask, segment_ids, positions), jnp.arange(cfg.n_layer))
        return x, jnp.sum(l_aux)


def gpt_tp_rules(path: str, shape) -> "PartitionSpec":
    """Megatron-style tensor-parallel PartitionSpecs for GPT params
    (reference delegates training TP to a user mpu, engine.py:189; inference
    TP slices the same weights in module_inject/replace_module.py:18 —
    column-parallel qkv/fc1, row-parallel proj/fc2, vocab-parallel embedding).
    Consumed by ZeroShardingRules; dims not divisible by the tp axis are
    stripped there."""
    from jax.sharding import PartitionSpec

    ndim = len(shape)

    def dim(i):
        spec = [None] * ndim
        spec[i] = "tp"
        return PartitionSpec(*spec)

    if path.endswith(("attn/c_attn/kernel", "mlp/c_fc/kernel",
                      "mlp/c_gate/kernel",
                      "attn/c_attn/bias", "mlp/c_fc/bias",
                      "mlp/c_gate/bias")):
        return dim(-1)  # column parallel
    if path.endswith(("attn/c_proj/kernel", "mlp/c_proj/kernel")):
        return dim(-2)  # row parallel
    if path.endswith("wte/embedding"):
        return dim(0)   # vocab parallel (logits shard over vocab)
    if path.endswith(("lm_head/kernel", "lm_head")):
        return dim(-1)  # vocab-parallel untied head
    # expert-parallel MoE params (ep axis + Megatron tp inside each expert)
    from deepspeed_tpu.moe.layer import moe_param_spec

    return moe_param_spec(path, shape)


class GPT(nn.Module):
    """Decoder-only LM. ``__call__(batch)`` returns mean cross-entropy loss
    when ``batch["labels"]`` is present, else logits — the model contract the
    engine trains against (see runtime/engine.py)."""

    config: GPTConfig

    # engine reads this for TP sharding (runtime/zero/sharding.py)
    tp_rules = staticmethod(gpt_tp_rules)

    def param_offload_filter(self, path: str) -> bool:
        """Which param leaves the engine may place in host memory: exactly
        the ones this model streams back per-layer — the scanned stack
        under ``h`` (runtime/engine.py offload_param)."""
        return self.config.param_offload and path.startswith("['h']")

    @nn.compact
    def __call__(self, input_ids, labels=None, attention_mask=None,
                 segment_ids=None, positions=None, deterministic=True,
                 decode=False, pld_theta=None):
        cfg = self.config
        B, T = input_ids.shape
        wte = VocabEmbed(cfg.vocab_size, cfg.n_embd, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="wte")
        x = wte(input_ids)
        if cfg.embed_layernorm:  # BLOOM word_embeddings_layernorm
            x = _norm(cfg, "ln_embed")(x)
        if cfg.learned_positions:
            wpe = nn.Embed(cfg.n_positions, cfg.n_embd, dtype=cfg.dtype,
                           param_dtype=cfg.param_dtype, name="wpe")
            if decode:
                # per-sequence position counters tracked alongside the
                # per-layer KV caches: with LEFT-padded ragged prompts the
                # learned position of a token is its count of valid
                # predecessors, not its physical cache slot
                position = self.variable("cache", "position",
                                         lambda: jnp.zeros((B,), jnp.int32))
                if attention_mask is not None:
                    am = attention_mask.astype(jnp.int32)
                    offs = jnp.clip(jnp.cumsum(am, axis=1) - 1, 0)
                    pos = position.value[:, None] + offs
                    position.value = position.value + jnp.sum(am, axis=1)
                else:
                    pos = position.value[:, None] + jnp.arange(T)[None, :]
                    position.value = position.value + T
            else:
                # packed batches reset positions at each document start so
                # every document sees the embeddings it would alone
                pos = (positions if positions is not None
                       else jnp.arange(T)[None, :])
            x = x + wpe(pos)
        x = nn.Dropout(cfg.dropout)(x, deterministic=deterministic)

        if cfg.scan_layers:
            x, l_aux = ScannedBlocks(cfg, name="h")(
                x, mask=attention_mask, segment_ids=segment_ids,
                positions=positions, deterministic=deterministic,
                decode=decode, pld_theta=pld_theta)
        else:
            l_aux = jnp.float32(0.0)
            use_pld = (cfg.stochastic_mode and pld_theta is not None
                       and not deterministic)

            def call_block(block, x, mask, segment_ids, positions, pld_keep):
                # closure keeps deterministic/decode static under remat
                return block(x, mask=mask, segment_ids=segment_ids,
                             positions=positions,
                             deterministic=deterministic,
                             decode=decode, pld_keep=pld_keep)

            if cfg.remat:
                call_block = nn.remat(call_block, prevent_cse=False,
                                      policy=_remat_policy(cfg.remat_policy))
            loop_block_cls = _maybe_quantized_block(Block, cfg)
            for i in range(cfg.n_layer):
                keep = (pld_keep_probability(i, cfg.n_layer, pld_theta)
                        if use_pld else None)
                x, aux_i = call_block(loop_block_cls(cfg, name=f"h_{i}"), x,
                                      attention_mask, segment_ids, positions,
                                      keep)
                l_aux = l_aux + aux_i

        x = _norm(cfg, "ln_f")(x)
        # LM head (tied to wte, or a separate lm_head when untied): bf16
        # operands + fp32 accumulation keeps the MXU at full rate (a plain
        # fp32 matmul here runs ~8x slower and is ~1/3 of the model's flops
        # at this vocab size)
        if cfg.tie_word_embeddings:
            head_w = wte.embedding.astype(cfg.dtype)  # [V, C]
            head_dims = (((x.ndim - 1,), (1,)), ((), ()))
        else:
            head_w = self.param(
                "lm_head",
                nn.initializers.normal(0.02), (cfg.n_embd, cfg.vocab_size),
                cfg.param_dtype).astype(cfg.dtype)    # [C, V]
            head_dims = (((x.ndim - 1,), (0,)), ((), ()))
        head_b = (self.param("lm_head_bias", nn.initializers.zeros,
                             (cfg.vocab_size,), cfg.param_dtype)
                  if cfg.lm_head_bias else None)
        if labels is None:
            logits = jax.lax.dot_general(
                x.astype(cfg.dtype), head_w, head_dims,
                preferred_element_type=jnp.float32)
            if head_b is not None:
                logits = logits + head_b.astype(logits.dtype)
            return logits
        # training path: the shift is expressed by zero-weighting the last
        # position instead of slicing, which keeps every tensor tile-aligned
        # (a [b, t-1, V] slice forces padded-tile reductions and a copy)
        fused = cfg.fused_head_ce
        if fused == "auto":
            # NOTE: B*T here is whatever the model was TRACED with — the
            # global batch under plain pjit, but the per-shard batch when
            # applied inside a shard_map/pipeline stage. Losses match
            # either way; only the 4 GB engage point is topology-dependent
            # (per-device logits are 1/dp of this under pjit). Force
            # fused_head_ce=True/int to pin the behavior across topologies.
            logits_bytes = (B * T * cfg.vocab_size
                            * jnp.dtype(cfg.dtype).itemsize)
            fused = logits_bytes >= (4 << 30)
        if fused:
            # fused head+CE: [tokens, vocab] logits never materialize —
            # the head runs chunk-by-chunk inside the loss vjp
            from deepspeed_tpu.ops.cross_entropy import (
                fused_linear_cross_entropy)

            targets, wts = _shifted_targets(labels, attention_mask,
                                            segment_ids)
            flat = x.astype(cfg.dtype).reshape(-1, cfg.n_embd)
            # bool first: True is an int and would read as chunk=1
            chunk = (fused if isinstance(fused, int)
                     and not isinstance(fused, bool) else 2048)
            loss = fused_linear_cross_entropy(
                cfg.tie_word_embeddings, chunk, flat, head_w, head_b,
                targets.reshape(-1), wts.reshape(-1))
        else:
            # unfused: materialize compute-dtype logits, fused CE math
            # (f32 reductions inside the fusion, bf16 cotangent)
            logits = jax.lax.dot_general(
                x.astype(cfg.dtype), head_w, head_dims)
            if head_b is not None:
                logits = logits + head_b.astype(logits.dtype)
            loss = cross_entropy_loss(logits, labels, attention_mask,
                                      segment_ids)
        if cfg.is_moe:
            # load-balance aux loss, averaged over layers (reference adds the
            # per-MoE-layer l_aux into the training loss with a coefficient)
            loss = loss + cfg.moe_aux_loss_coef * l_aux / cfg.n_layer
        return loss


def _shifted_targets(labels, mask=None, segment_ids=None):
    """Next-token targets + f32 weights: target for position i is
    labels[i+1]; the last position gets a dummy target with zero weight —
    all tensors stay tile-aligned (no [b, t-1] slicing).

    With ``segment_ids`` (packed batches, deepspeed_tpu/data/), a position
    whose next token belongs to a DIFFERENT segment — a document's last
    token predicting the next document's first, or any pad (segment 0)
    position — is zero-weighted too. This is the third leg of the packing
    exactness condition (docs/data.md): the weighted mean then equals the
    token-count-weighted mean of the per-document losses."""
    b, t = labels.shape
    targets = jnp.concatenate(
        [labels[:, 1:], jnp.zeros((b, 1), labels.dtype)], axis=1)
    if mask is not None:
        w = mask.astype(jnp.float32)
        w = jnp.concatenate(
            [w[:, 1:], jnp.zeros((b, 1), jnp.float32)], axis=1)
    else:
        w = jnp.concatenate(
            [jnp.ones((b, t - 1), jnp.float32),
             jnp.zeros((b, 1), jnp.float32)], axis=1)
    if segment_ids is not None:
        seg_next = jnp.concatenate(
            [segment_ids[:, 1:], jnp.zeros((b, 1), segment_ids.dtype)],
            axis=1)
        w = w * ((segment_ids == seg_next)
                 & (segment_ids != 0)).astype(jnp.float32)
    return targets, w


def cross_entropy_loss(logits, labels, mask=None, segment_ids=None):
    """Mean next-token cross entropy with shift (f32 reductions fused over
    compute-dtype logits; see ops/cross_entropy.py)."""
    from deepspeed_tpu.ops.cross_entropy import softmax_cross_entropy

    b, t = labels.shape
    targets, w = _shifted_targets(labels, mask, segment_ids)
    flat = logits.reshape(b * t, logits.shape[-1])
    return softmax_cross_entropy(flat, targets.reshape(b * t),
                                 w.reshape(b * t))


def num_params(config: GPTConfig) -> int:
    """Approximate parameter count (for flops accounting); tracks the
    architecture-family knobs (GQA, gated MLP, untied head, biases)."""
    cfg = config
    C, L, V = cfg.n_embd, cfg.n_layer, cfg.vocab_size
    D, H, Hkv, F = cfg.head_dim, cfg.n_head, cfg.kv_heads, cfg.ffn_dim
    b = 1 if cfg.use_bias else 0
    ab = b if cfg.attn_bias is None else (1 if cfg.attn_bias else 0)
    attn = C * (H + 2 * Hkv) * D + ab * (H + 2 * Hkv) * D + C * C + ab * C
    mlp = (3 if cfg.gated_mlp else 2) * C * F + b * (
        (2 if cfg.gated_mlp else 1) * F + C)
    norm_p = C * (2 if (cfg.norm == "layernorm" and cfg.use_bias) else 1)
    per_layer = attn + mlp + 2 * norm_p
    total = V * C + L * per_layer + norm_p
    if cfg.learned_positions:
        total += cfg.n_positions * C
    if not cfg.tie_word_embeddings:
        total += C * V
    if cfg.lm_head_bias:
        total += V
    return total


def train_flops_per_token(config: GPTConfig) -> float:
    """6N + attention flops per token (standard accounting)."""
    N = num_params(config) - config.vocab_size * config.n_embd  # non-embedding
    return 6.0 * N

"""GPT expressed as a pipeline layer list.

Parity with the reference's Megatron-GPT2 pipeline examples (layers =
embedding, N transformer blocks, final norm + head; reference
pipe/module.py consumers): each layer maps hidden -> hidden so the
PipelineEngine can cut the list at any boundary.
"""

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from deepspeed_tpu.models.transformer_lm import (
    Block,
    GPTConfig,
    VocabEmbed,
    cross_entropy_loss,
)
from deepspeed_tpu.runtime.pipe.module import LayerSpec, PipelineModule


class GPTEmbed(nn.Module):
    """input_ids -> hidden."""

    config: GPTConfig

    @nn.compact
    def __call__(self, input_ids, *, deterministic: bool = True):
        cfg = self.config
        T = input_ids.shape[1]
        wte = VocabEmbed(cfg.vocab_size, cfg.n_embd, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="wte")
        wpe = nn.Embed(cfg.n_positions, cfg.n_embd, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype, name="wpe")
        x = wte(input_ids) + wpe(jnp.arange(T)[None, :])
        return nn.Dropout(cfg.dropout)(x, deterministic=deterministic)


class GPTPipeBlock(nn.Module):
    """hidden -> hidden (drops the MoE aux loss — pipeline GPT is dense;
    reference pipeline examples are dense too).

    ``layer_idx`` is the block's GLOBAL ordinal among the transformer
    blocks: under a progressive-layer-drop schedule the keep probability
    depends on absolute depth (deeper blocks drop more), which must not
    change when the pipeline is cut differently."""

    config: GPTConfig
    layer_idx: int = 0

    @nn.compact
    def __call__(self, x, *, deterministic: bool = True, pld_theta=None):
        pld_keep = None
        if (pld_theta is not None and self.config.stochastic_mode
                and not deterministic):
            from deepspeed_tpu.models.transformer_lm import (
                pld_keep_probability)

            pld_keep = pld_keep_probability(
                self.layer_idx, self.config.n_layer, pld_theta)
        x, _ = Block(self.config, name="block")(
            x, deterministic=deterministic, pld_keep=pld_keep)
        return x


class GPTHead(nn.Module):
    """hidden -> logits (untied unembedding; the tied variant is expressed
    with TiedLayerSpec over GPTEmbed/GPTHead sharing 'embed')."""

    config: GPTConfig

    @nn.compact
    def __call__(self, x, *, deterministic: bool = True):
        cfg = self.config
        x = nn.LayerNorm(dtype=cfg.dtype, name="ln_f")(x)
        return nn.Dense(cfg.vocab_size, use_bias=False, dtype=jnp.float32,
                        param_dtype=cfg.param_dtype, name="lm_head")(
            x.astype(jnp.float32))


def gpt_pipeline(config: GPTConfig, num_stages: Optional[int] = None,
                 partition_method: str = "uniform") -> PipelineModule:
    """LayerSpec list for a GPT LM + next-token loss."""
    assert not config.is_moe, "pipeline GPT is dense (use the SPMD MoE path)"
    layers = [LayerSpec(GPTEmbed, config)]
    layers += [LayerSpec(GPTPipeBlock, config, layer_idx=i)
               for i in range(config.n_layer)]
    layers += [LayerSpec(GPTHead, config)]

    def loss_fn(logits, labels):
        return cross_entropy_loss(logits, labels)

    from deepspeed_tpu.models.transformer_lm import gpt_tp_rules

    return PipelineModule(layers, num_stages=num_stages, loss_fn=loss_fn,
                          partition_method=partition_method,
                          tp_rules=gpt_tp_rules)

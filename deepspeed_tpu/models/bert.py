"""TPU-first BERT encoder for the pretraining benchmark path.

Capability counterpart of the reference's BERT story (BASELINE config 1;
reference docs/_tutorials/bert-pretraining.md, tests/unit/modeling.py HF copy).
Idiomatic JAX encoder: bf16 compute, einsum attention, scan-over-layers,
MLM head tied to the token embedding.
"""

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.transformer_lm import VocabEmbed


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    layer_norm_eps: float = 1e-12   # HF BERT default
    approximate_gelu: bool = True   # tanh gelu; HF BERT uses exact erf gelu
    use_mlm_bias: bool = False      # HF cls.predictions.bias on the decoder
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False
    # "full" recomputes everything; "selective" saves the non-batched
    # param matmul outputs (attention einsums are still recomputed — the
    # policy's flash-attention checkpoint names only exist in the GPT
    # trunk; see transformer_lm._remat_policy)
    remat_policy: str = "full"
    scan_layers: bool = True
    # a SparsityConfig (ops.sparse_attention): restricts attention to the
    # config's block layout (default impl: static K/V-block gather + MXU
    # einsums; "kernel": "pallas" selects the streaming kernel). Populated
    # from the DeepSpeed "sparse_attention" config block by
    # sparse_attention_utils.apply_sparse_attention.
    sparse_attention: Any = None
    # stochastic transformer (reference op_builder/stochastic_transformer.py):
    # whole-layer stochastic depth driven by the engine's PLD schedule; see
    # transformer_lm.GPTConfig.stochastic_mode for the key/remat story
    stochastic_mode: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


BERT_SIZES = {
    "bert-base": dict(hidden_size=768, num_hidden_layers=12,
                      num_attention_heads=12, intermediate_size=3072),
    "bert-large": dict(hidden_size=1024, num_hidden_layers=24,
                       num_attention_heads=16, intermediate_size=4096),
}


def bert_config(name: str, **overrides) -> BertConfig:
    base = dict(BERT_SIZES[name])
    base.update(overrides)
    return BertConfig(**base)


class BertSelfAttention(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, mask=None, deterministic=True):
        cfg = self.config
        B, T, C = x.shape
        H, D = cfg.num_attention_heads, cfg.head_dim
        qkv = nn.Dense(3 * C, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                       name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, H, D)
        k = k.reshape(B, T, H, D)
        v = v.reshape(B, T, H, D)
        if cfg.sparse_attention is not None:
            # block-sparse path (SparseSelfAttention; default "gather" impl
            # materializes [B, H, nL, block, W*block] score buffers).
            # Attention-probability dropout is not applied on this path —
            # the layout already drops most of the attention matrix; output
            # dropout below still applies.
            from deepspeed_tpu.ops.sparse_attention import SparseSelfAttention

            sa = SparseSelfAttention(
                cfg.sparse_attention,
                max_seq_length=cfg.max_position_embeddings)
            kpm = None
            if mask is not None:
                kpm = jnp.where(mask, 0.0, jnp.finfo(jnp.float32).min)
            y = sa(q, k, v, key_padding_mask=kpm).reshape(B, T, C)
        else:
            att = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
            if mask is not None:
                att = jnp.where(mask[:, None, None, :], att,
                                jnp.finfo(att.dtype).min)
            att = jax.nn.softmax(att.astype(jnp.float32), axis=-1).astype(cfg.dtype)
            att = nn.Dropout(cfg.dropout)(att, deterministic=deterministic)
            y = jnp.einsum("bhqk,bkhd->bqhd", att, v).reshape(B, T, C)
        y = nn.Dense(C, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     name="output")(y)
        y = nn.Dropout(cfg.dropout)(y, deterministic=deterministic)
        return y


class BertLayer(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, mask=None, deterministic=True, pld_keep=None):
        cfg = self.config
        x_in = x
        # Post-LN like original BERT
        a = BertSelfAttention(cfg, name="attention")(x, mask, deterministic)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype, name="ln_attn")(x + a)
        h = nn.Dense(cfg.intermediate_size, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="intermediate")(x)
        h = nn.gelu(h, approximate=cfg.approximate_gelu)
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="output")(h)
        h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype, name="ln_out")(x + h)
        if cfg.stochastic_mode and pld_keep is not None and not deterministic:
            # whole-layer stochastic depth (PLD identity skip; same
            # remat-exact per-layer key story as transformer_lm.Block)
            gate = jax.random.bernoulli(self.make_rng("dropout"), pld_keep)
            x = jnp.where(gate, x, x_in)
        return x


class BertEncoder(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, mask=None, deterministic=True, pld_theta=None):
        cfg = self.config
        L = cfg.num_hidden_layers
        use_pld = (cfg.stochastic_mode and pld_theta is not None
                   and not deterministic)

        def keep_of(layer_idx):
            if not use_pld:
                return None
            from deepspeed_tpu.models.transformer_lm import \
                pld_keep_probability

            return pld_keep_probability(layer_idx, L, pld_theta)

        if cfg.scan_layers:
            layer_cls = BertLayer
            if cfg.remat:
                from deepspeed_tpu.models.transformer_lm import _remat_policy

                layer_cls = nn.remat(BertLayer, prevent_cse=False,
                                     policy=_remat_policy(cfg.remat_policy))

            def body(layer, carry, layer_idx):
                x, mask = carry
                return (layer(x, mask, deterministic,
                              keep_of(layer_idx)), mask), None

            scanned = nn.scan(
                body,
                variable_axes={"params": 0},
                split_rngs={"params": True, "dropout": True},
                in_axes=0,
                length=L,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )
            (x, _), _ = scanned(layer_cls(cfg, name="layer"), (x, mask),
                                jnp.arange(L))
            return x
        layer_cls = BertLayer
        if cfg.remat:
            from deepspeed_tpu.models.transformer_lm import _remat_policy

            layer_cls = nn.remat(BertLayer, prevent_cse=False,
                                 policy=_remat_policy(cfg.remat_policy))
        for i in range(cfg.num_hidden_layers):
            x = layer_cls(cfg, name=f"layer_{i}")(x, mask, deterministic,
                                                  keep_of(i))
        return x


def bert_tp_rules(path: str, shape):
    """Megatron-style TP specs for BERT params (see gpt_tp_rules)."""
    from jax.sharding import PartitionSpec

    ndim = len(shape)

    def dim(i):
        spec = [None] * ndim
        spec[i] = "tp"
        return PartitionSpec(*spec)

    if path.endswith(("attention/qkv/kernel", "attention/qkv/bias",
                      "intermediate/kernel", "intermediate/bias")):
        return dim(-1)  # column parallel
    if path.endswith("output/kernel"):  # both attention/output and FFN output
        return dim(-2)  # row parallel
    if path.endswith("word_embeddings/embedding"):
        return dim(0)
    return None


class BertForPreTraining(nn.Module):
    """BERT with MLM head (tied embeddings). ``__call__`` returns masked-LM
    loss when ``labels`` given (-100 = ignore), else logits."""

    config: BertConfig

    tp_rules = staticmethod(bert_tp_rules)

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 labels=None, deterministic=True, pld_theta=None):
        cfg = self.config
        B, T = input_ids.shape
        tok = VocabEmbed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="word_embeddings")
        pos = nn.Embed(cfg.max_position_embeddings, cfg.hidden_size,
                       dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                       name="position_embeddings")
        typ = nn.Embed(cfg.type_vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype, name="token_type_embeddings")
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = tok(input_ids) + pos(jnp.arange(T)[None, :]) + typ(token_type_ids)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype, name="embeddings_ln")(x)
        x = nn.Dropout(cfg.dropout)(x, deterministic=deterministic)

        x = BertEncoder(cfg, name="encoder")(x, attention_mask, deterministic,
                                             pld_theta=pld_theta)

        # MLM transform + tied decoder
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="mlm_dense")(x)
        h = nn.gelu(h, approximate=cfg.approximate_gelu)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype, name="mlm_ln")(h)
        # bf16 operands + fp32 accumulation: full MXU rate on the vocab
        # projection (fp32 matmul would run ~8x slower)
        logits = jax.lax.dot_general(
            h.astype(cfg.dtype), tok.embedding.astype(cfg.dtype),
            (((h.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if cfg.use_mlm_bias:
            logits = logits + self.param(
                "mlm_bias", nn.initializers.zeros, (cfg.vocab_size,),
                cfg.param_dtype).astype(logits.dtype)

        if labels is None:
            return logits
        return masked_lm_loss(logits, labels)


def masked_lm_loss(logits, labels):
    """Mean CE over positions where labels != -100."""
    logits = logits.astype(jnp.float32)
    valid = labels != -100
    safe_labels = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
    m = valid.astype(jnp.float32)
    return jnp.sum(nll * m) / jnp.maximum(jnp.sum(m), 1.0)

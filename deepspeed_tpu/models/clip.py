"""CLIP text + vision encoders.

Capability counterpart of the reference's CLIP path: ``HFCLIPLayerPolicy``
(``module_inject/replace_policy.py:186``) injects fused kernels into HF CLIP
encoder layers, and ``DSClipEncoder`` (``model_implementations/``) wraps the
text tower for stable-diffusion serving.

TPU re-design: both towers REUSE the GPT trunk's :class:`Block` — a CLIP
encoder layer is the same pre-LN attention+MLP block with ``quick_gelu`` and
(for vision) bidirectional attention — so every trunk feature (scan-over-
layers, remat, flash attention, TP rules) applies unchanged. Only the
embeddings, pooling, and projections are CLIP-specific.
"""

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.transformer_lm import (
    GPTConfig,
    ScannedBlocks,
    _norm,
    gpt_tp_rules,
)


@dataclasses.dataclass(frozen=True)
class CLIPTextConfig:
    vocab_size: int = 49408
    hidden_size: int = 512
    num_hidden_layers: int = 12
    num_attention_heads: int = 8
    intermediate_size: int = 2048
    max_position_embeddings: int = 77
    layer_norm_eps: float = 1e-5
    hidden_act: str = "quick_gelu"
    projection_dim: int = 512
    eos_token_id: int = 49407
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = True

    def trunk(self) -> GPTConfig:
        return GPTConfig(
            vocab_size=self.vocab_size,
            n_positions=self.max_position_embeddings,
            n_embd=self.hidden_size,
            n_layer=self.num_hidden_layers,
            n_head=self.num_attention_heads,
            intermediate_size=self.intermediate_size,
            layer_norm_epsilon=self.layer_norm_eps,
            activation=self.hidden_act,
            causal=True,  # CLIP text attends causally
            dtype=self.dtype, param_dtype=self.param_dtype,
            scan_layers=self.scan_layers, dropout=0.0)


@dataclasses.dataclass(frozen=True)
class CLIPVisionConfig:
    image_size: int = 224
    patch_size: int = 32
    num_channels: int = 3
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    layer_norm_eps: float = 1e-5
    hidden_act: str = "quick_gelu"
    projection_dim: int = 512
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = True

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    def trunk(self) -> GPTConfig:
        return GPTConfig(
            vocab_size=1,  # unused by the trunk blocks
            n_positions=self.num_patches + 1,
            n_embd=self.hidden_size,
            n_layer=self.num_hidden_layers,
            n_head=self.num_attention_heads,
            intermediate_size=self.intermediate_size,
            layer_norm_epsilon=self.layer_norm_eps,
            activation=self.hidden_act,
            causal=False,  # vision attends bidirectionally
            dtype=self.dtype, param_dtype=self.param_dtype,
            scan_layers=self.scan_layers, dropout=0.0)


class CLIPTextModel(nn.Module):
    """Text tower: returns (last_hidden_state, pooled, projected)."""

    config: CLIPTextConfig

    tp_rules = staticmethod(gpt_tp_rules)

    @nn.compact
    def __call__(self, input_ids, deterministic=True):
        cfg = self.config
        trunk = cfg.trunk()
        B, T = input_ids.shape
        tok = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype, name="token_embedding")
        pos = nn.Embed(cfg.max_position_embeddings, cfg.hidden_size,
                       dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                       name="position_embedding")
        x = tok(input_ids) + pos(jnp.arange(T)[None, :])
        x, _ = ScannedBlocks(trunk, name="h")(
            x, deterministic=deterministic)
        x = _norm(trunk, "ln_f")(x)
        # EOS pooling. HF semantics: legacy configs (eos_token_id == 2, all
        # original OpenAI checkpoints) pool at argmax(input_ids) — the
        # highest token id is the real EOT 49407; newer configs pool at the
        # first position equal to eos_token_id.
        if cfg.eos_token_id == 2:
            eos_pos = jnp.argmax(input_ids, axis=1)
        else:
            eos_pos = jnp.argmax(
                (input_ids == cfg.eos_token_id).astype(jnp.int32), axis=1)
        pooled = x[jnp.arange(B), eos_pos]
        proj = nn.Dense(cfg.projection_dim, use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype,
                        name="text_projection")(pooled)
        return x, pooled, proj


class CLIPVisionModel(nn.Module):
    """Vision tower: returns (last_hidden_state, pooled, projected)."""

    config: CLIPVisionConfig

    tp_rules = staticmethod(gpt_tp_rules)

    @nn.compact
    def __call__(self, pixel_values, deterministic=True):
        """pixel_values: [batch, H, W, channels] (NHWC)."""
        cfg = self.config
        trunk = cfg.trunk()
        B = pixel_values.shape[0]
        patches = nn.Conv(
            cfg.hidden_size,
            kernel_size=(cfg.patch_size, cfg.patch_size),
            strides=(cfg.patch_size, cfg.patch_size),
            use_bias=False, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            name="patch_embedding")(pixel_values.astype(cfg.dtype))
        patches = patches.reshape(B, -1, cfg.hidden_size)
        cls = self.param("class_embedding", nn.initializers.normal(0.02),
                         (cfg.hidden_size,), cfg.param_dtype)
        cls = jnp.broadcast_to(cls.astype(cfg.dtype),
                               (B, 1, cfg.hidden_size))
        x = jnp.concatenate([cls, patches], axis=1)
        pos = nn.Embed(cfg.num_patches + 1, cfg.hidden_size, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype, name="position_embedding")
        x = x + pos(jnp.arange(x.shape[1])[None, :])
        x = _norm(trunk, "pre_layernorm")(x)
        x, _ = ScannedBlocks(trunk, name="h")(x, deterministic=deterministic)
        pooled = _norm(trunk, "post_layernorm")(x[:, 0])
        proj = nn.Dense(cfg.projection_dim, use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype,
                        name="visual_projection")(pooled)
        return x, pooled, proj


class CLIPModel(nn.Module):
    """Two-tower CLIP: contrastive text/image embeddings + logits."""

    text_config: CLIPTextConfig
    vision_config: CLIPVisionConfig
    logit_scale_init: float = 2.6592

    # nested tower paths keep the trunk suffixes, so the GPT TP rules apply
    tp_rules = staticmethod(gpt_tp_rules)

    @nn.compact
    def __call__(self, input_ids, pixel_values, deterministic=True):
        _, _, t = CLIPTextModel(self.text_config, name="text_model")(
            input_ids, deterministic=deterministic)
        _, _, v = CLIPVisionModel(self.vision_config, name="vision_model")(
            pixel_values, deterministic=deterministic)
        t = t / jnp.linalg.norm(t.astype(jnp.float32), axis=-1,
                                keepdims=True)
        v = v / jnp.linalg.norm(v.astype(jnp.float32), axis=-1,
                                keepdims=True)
        scale = jnp.exp(self.param(
            "logit_scale",
            nn.initializers.constant(self.logit_scale_init), ()))
        logits_per_text = scale * t @ v.T
        return logits_per_text, logits_per_text.T

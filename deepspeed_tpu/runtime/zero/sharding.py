"""ZeRO as sharding rules.

This module IS the TPU-native ZeRO (reference ``deepspeed/runtime/zero/``,
~8k LoC of hooks/buckets/streams): each stage is a set of PartitionSpecs
over the ``fsdp`` mesh axis, applied to the param / grad-accumulation /
optimizer-state pytrees of the compiled train step. XLA then emits exactly
the collectives the reference implements by hand:

=========  =======================================  =============================
stage      reference mechanism                       sharding expression
=========  =======================================  =============================
0 (DDP)    bucketed grad allreduce                   grads replicated -> psum
           (engine.py:2180-2298)
1          optimizer-state partitions + allgather    opt state sharded over fsdp
           of updated fp16 (stage_1_and_2.py:1744)   (XLA: reduce-scatter grads
                                                     into the update, all-gather
                                                     new params out)
2          + gradient partitions via bucketed        + grad-accum buffer sharded
           reduce-scatter (stage_1_and_2.py:938)     over fsdp
3          + param partitions, allgather-on-use,     + params sharded over fsdp;
           prefetch coordinator                      XLA schedules per-layer
           (partition_parameters.py:806,             all-gathers (the prefetch
           partitioned_param_coordinator.py:237)     coordinator, for free)
=========  =======================================  =============================

``param_persistence_threshold`` (stage3, zero/config.py) maps to ``min_size``:
small params stay replicated.
"""

from typing import Any, Callable, Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.parallel.mesh import MeshTopology, shard_largest_dim_spec
from deepspeed_tpu.utils.tree import path_str as _path_str


def _spec_for_shape(shape, topo: MeshTopology, min_size: int = 0,
                    tp_spec: Optional[PartitionSpec] = None) -> PartitionSpec:
    """FSDP sharding for one array shape, composed with an optional TP spec
    (TP dims win; fsdp takes the largest remaining divisible dim)."""
    fsdp_size = topo.size("fsdp")
    if tp_spec is not None and any(a is not None for a in tp_spec):
        if fsdp_size <= 1:
            return tp_spec
        # shard largest dim not already taken by tp
        taken = {i for i, a in enumerate(tp_spec) if a is not None}
        candidates = [
            i for i, d in enumerate(shape)
            if i not in taken and d % fsdp_size == 0
        ]
        if not candidates or int(np.prod(shape)) < max(min_size, fsdp_size):
            return tp_spec
        best = max(candidates, key=lambda i: shape[i])
        spec = list(tp_spec) + [None] * (len(shape) - len(tp_spec))
        spec[best] = "fsdp"
        return PartitionSpec(*spec)
    return shard_largest_dim_spec(shape, "fsdp", fsdp_size, min_size=min_size)


class ZeroShardingRules:
    """Builds NamedSharding trees for params / grads / optimizer state given a
    ZeRO stage and mesh, optionally composed with tensor-parallel rules
    (a ``path, shape -> PartitionSpec`` callable, see parallel/tensor_parallel)."""

    def __init__(self, topo: MeshTopology, stage: int,
                 param_persistence_threshold: int = 0,
                 tp_rules: Optional[Callable] = None):
        self.topo = topo
        self.stage = stage
        self.persistence_threshold = param_persistence_threshold
        self.tp_rules = tp_rules

    # -- per-leaf specs ----------------------------------------------------
    def _tp_spec(self, path, shape) -> Optional[PartitionSpec]:
        if self.tp_rules is None:
            return None
        spec = self.tp_rules(path, shape)
        if spec is None:
            spec = self._quantized_leaf_spec(path, shape)
        if spec is None:
            return None
        # validate: strip axes whose dim is not divisible by the mesh axis size
        cleaned = []
        for i, axis in enumerate(spec):
            if axis is None:
                cleaned.append(None)
                continue
            size = self.topo.size(axis) if isinstance(axis, str) else int(
                np.prod([self.topo.size(a) for a in axis])
            )
            # size-1 axes collapse to replicated; indivisible dims cannot shard
            cleaned.append(axis if size > 1 and shape[i] % size == 0 else None)
        if all(a is None for a in cleaned):
            return None
        return PartitionSpec(*cleaned)

    def _quantized_leaf_spec(self, path, shape) -> Optional[PartitionSpec]:
        """TP specs for int8 weight-only ``{q, scale}`` leaves, derived from
        the dense kernel rule they replace (reference composes int8 with MP
        the same way: GroupQuantizer quantizes the already-sliced weight,
        replace_module.py:139 after slicing at :18). ``q`` has the kernel's
        shape, so it inherits the kernel's spec verbatim; ``scale`` is
        per-output-column (the kernel shape minus the contraction dim), so
        its spec is the kernel spec with dim -2 dropped — column-parallel
        kernels shard their scales on the same output axis, row-parallel
        kernels keep scales replicated. Both are exact: dequant is an
        elementwise per-column product, so sharded q × broadcast scale
        equals the sharded dense kernel."""
        if path.endswith("/q"):
            return self.tp_rules(path[:-len("/q")], shape)
        if path.endswith("/scale"):
            kshape = tuple(shape[:-1]) + (1,) + (shape[-1],)
            kspec = self.tp_rules(path[:-len("/scale")], kshape)
            if kspec is None:
                return None
            ks = list(kspec) + [None] * (len(kshape) - len(kspec))
            del ks[-2]  # the contraction dim the scale does not carry
            return PartitionSpec(*ks)
        return None

    def param_spec(self, path, shape) -> PartitionSpec:
        tp = self._tp_spec(path, shape)
        if self.stage >= 3:
            return _spec_for_shape(
                shape, self.topo, min_size=self.persistence_threshold, tp_spec=tp
            )
        return tp if tp is not None else PartitionSpec()

    def grad_accum_spec(self, path, shape) -> PartitionSpec:
        tp = self._tp_spec(path, shape)
        if self.stage >= 2:
            return _spec_for_shape(shape, self.topo, tp_spec=tp)
        return tp if tp is not None else PartitionSpec()

    def opt_state_spec(self, param_path: Optional[str], shape) -> PartitionSpec:
        """Spec for an optimizer-state leaf. ``param_path`` is the path of the
        param this leaf mirrors (mu/nu), or None for non-param-shaped state.
        Stage >= 1 shards param-shaped state over fsdp (the reference's
        optimizer-state partitioning, stage_1_and_2.py:634) composed with the
        param's TP spec; stage 0 mirrors the param spec exactly."""
        if not shape:
            return PartitionSpec()
        tp = self._tp_spec(param_path, shape) if param_path is not None else None
        if self.stage >= 1:
            return _spec_for_shape(shape, self.topo, tp_spec=tp)
        return tp if tp is not None else PartitionSpec()

    # -- pytree builders ---------------------------------------------------
    def param_sharding_tree(self, params_shapes) -> Any:
        """``params_shapes``: pytree of ShapeDtypeStruct (from eval_shape)."""
        mesh = self.topo.mesh

        def leaf(path, leaf_shape):
            spec = self.param_spec(path, leaf_shape.shape)
            return NamedSharding(mesh, spec)

        return _tree_map_with_path(leaf, params_shapes)

    def grad_sharding_tree(self, params_shapes) -> Any:
        mesh = self.topo.mesh

        def leaf(path, leaf_shape):
            spec = self.grad_accum_spec(path, leaf_shape.shape)
            return NamedSharding(mesh, spec)

        return _tree_map_with_path(leaf, params_shapes)

    def opt_sharding_tree(self, opt_state_shapes, params_shapes=None) -> Any:
        """Optimizer-state leaves that mirror a parameter (optax mu/nu subtrees
        carry the param pytree, so their paths END with the param's path) get
        that param's rule; everything else (counts, scalars) follows the plain
        shape rule."""
        mesh = self.topo.mesh
        param_paths = []
        if params_shapes is not None:
            flat = jax.tree_util.tree_flatten_with_path(params_shapes)[0]
            param_paths = [
                (_path_str(path), leaf.shape) for path, leaf in flat
            ]

        def leaf(path_s, leaf_shape):
            # path_s is already stringified by _tree_map_with_path
            matched = None
            for ppath, pshape in param_paths:
                if path_s.endswith(ppath) and tuple(pshape) == tuple(leaf_shape.shape):
                    matched = ppath
                    break
            spec = self.opt_state_spec(matched, leaf_shape.shape)
            return NamedSharding(mesh, spec)

        return _tree_map_with_path(leaf, opt_state_shapes)


def _tree_map_with_path(fn, tree):
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: fn(_path_str(path), leaf), tree
    )

"""ZeRO public API surface.

Reference ``deepspeed/runtime/zero/`` exports ``zero.Init`` and
``GatheredParameters`` (partition_parameters.py:537 / :1512). In the TPU
framework parameters are *logically global* arrays whose shards live where the
PartitionSpec says — so "gathering" is a device_get / resharding, not a
collective the user orchestrates. The classes below keep the API shape for
ported user code.
"""

import contextlib

from deepspeed_tpu.runtime.checkpoint_engine import _to_host
from deepspeed_tpu.runtime.zero.sharding import ZeroShardingRules  # noqa: F401


class Init(contextlib.AbstractContextManager):
    """reference zero.Init (partition_parameters.py:537): construct a model
    with params partitioned from the start. The TPU engine always materializes
    params via jit with sharded out_shardings (engine._init_state), so this
    context is a documented no-op kept for API parity; ``remote_device`` and
    ``config_dict_or_path`` are accepted and recorded.
    """

    def __init__(self, module=None, data_parallel_group=None, mem_efficient_linear=True,
                 remote_device=None, pin_memory=False, config_dict_or_path=None,
                 config=None, enabled=True, dtype=None, mpu=None):
        self.enabled = enabled
        self.remote_device = remote_device

    def __exit__(self, *exc):
        return False


class GatheredParameters(contextlib.AbstractContextManager):
    """reference GatheredParameters (partition_parameters.py:1512): inside the
    context, the given params are available unpartitioned. Here: materializes
    replicated host copies in ``.params``."""

    def __init__(self, params, modifier_rank=None, fwd_module=None, enabled=True):
        self._src = params
        self.enabled = enabled
        self.params = None

    def __enter__(self):
        if self.enabled:
            self.params = gather_params(self._src)
        else:
            self.params = self._src
        return self

    def __exit__(self, *exc):
        return False


def gather_params(params):
    """Fully-replicated host copy of a (possibly sharded) param pytree —
    the all-gather the reference does explicitly (partition_parameters.py:806)."""
    return _to_host(params)

"""ZeRO-Infinity parameter NVMe tier: layer-wise SSD-resident training.

Reference counterpart: ``swap_tensor/partitioned_param_swapper.py:35``
(AsyncPartitionedParameterSwapper) + ``zero/partition_parameters.py:537``
(``remote_device="nvme"``) — parameters live on local SSD and are fetched
into a bounded buffer pool right before use.

TPU re-design. The pinned-host tier (``offload_param.device: "cpu"``,
ops/streaming.py) needs the full streamed stack addressable in host memory
while the compiled scan runs — host RAM is its capacity ceiling. The NVMe
tier removes that ceiling by executing the model as a HOST-DRIVEN LAYER
SWEEP over a :class:`~deepspeed_tpu.runtime.pipe.module.PipelineModule`'s
LayerSpec list (the same decomposition the pipeline engine consumes):

* All transformer blocks share ONE compiled forward and ONE compiled
  recompute-backward program (identical shapes), so compile time is
  per-layer-class, not per-layer.
* Per-layer state on disk: fp32 master + Adam m/v + a compute-dtype copy.
  The forward fetches only the compute copy (2 bytes/param); the backward
  fetches master+m+v, updates them with the fused host Adam
  (``update_tensor`` — the PipelinedOptimizerSwapper path), and writes all
  four blobs back. Full parameters, gradients, and optimizer state NEVER
  exist in host RAM or HBM — the resident working set is a rotating
  3-slot pool (reference swap_out_and_release's buffer rings).
* Prefetch: while layer ``l`` computes, the aio threadpool reads layer
  ``l+1`` (forward) / ``l-1`` (backward) into the next slot — the
  one-scan-iteration-ahead pipeline of PipelinedOptimizerSwapper applied
  to parameters.
* First/last (embedding/head) layers stay device-resident like the
  reference's persistent parameters (param_persistence_threshold).

Engine integration: ``zero_optimization.offload_param.device: "nvme"``
with a PipelineModule model routes ``initialize()`` here.
"""

import os
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam
from deepspeed_tpu.runtime.swap_tensor.swapper import AsyncTensorSwapper
from deepspeed_tpu.utils.logging import log_dist


class _LayerStore:
    """Disk-backed per-layer blobs with a rotating prefetch pool.

    Blob kinds per streamed layer: ``c`` compute-dtype params, ``p`` fp32
    master, ``m``/``v`` Adam moments. Reads go through ``prefetch`` /
    ``get`` so the next layer's IO overlaps the current layer's compute.
    """

    def __init__(self, nvme_dir: str, num_threads: int = 4):
        self.swapper = AsyncTensorSwapper(
            os.path.join(nvme_dir, "param_nvme"), num_threads=num_threads)
        self._pending: Dict[str, np.ndarray] = {}

    def write(self, name: str, arr: np.ndarray) -> None:
        self.swapper.swap_out(name, arr)

    def prefetch(self, name: str) -> None:
        if name in self._pending:
            return
        self._pending[name] = self.swapper.swap_in(name)

    def get(self, name: str) -> np.ndarray:
        if name not in self._pending:
            self.prefetch(name)
        self.swapper.wait()
        return self._pending.pop(name)

    def barrier(self) -> None:
        self.swapper.wait()


class NVMeParamEngine:
    """Training engine for SSD-resident parameters (layer sweep).

    ``module`` is a PipelineModule (embed, N blocks, head + loss_fn);
    training is bf16/fp32 (no fp16 loss scaling — same constraint the
    pinned-host tier documents).
    """

    def __init__(self, module, config, sample_batch=None, seed: int = 0):
        self.module = module
        self._config = config
        off = config.zero_config.offload_param or {}
        nvme_dir = off.get("nvme_path") or "/tmp/ds_tpu_nvme"
        self.store = _LayerStore(nvme_dir)
        opt_type = (config.optimizer.type or "adamw").lower()
        if opt_type not in ("adam", "adamw", "fusedadam", "cpuadam"):
            raise NotImplementedError(
                "offload_param nvme tier runs the fused host Adam "
                f"(reference DeepSpeedCPUAdam); optimizer type "
                f"{config.optimizer.type!r} is not supported here")
        self._lr_schedule = None
        if config.scheduler.type is not None:
            from deepspeed_tpu.runtime.lr_schedules import (
                schedule_fn_from_config,
            )

            self._lr_schedule = schedule_fn_from_config(
                config.scheduler.type, config.scheduler.params)
        opt_p = dict(config.optimizer.params or {})
        betas = opt_p.get("betas", (0.9, 0.999))
        self.cpu_adam = DeepSpeedCPUAdam(
            lr=float(opt_p.get("lr", 1e-3)),
            betas=(float(betas[0]), float(betas[1])),
            eps=float(opt_p.get("eps", 1e-8)),
            weight_decay=float(opt_p.get("weight_decay", 0.0)),
            adamw_mode=opt_type != "adam")
        config._resolve_batch_triad(1)  # single-replica layer sweep
        self.train_micro_batch_size_per_gpu = \
            config.train_micro_batch_size_per_gpu
        # gas > 1 accumulates streamed-layer grads ON DISK ("g" blobs,
        # read-add-write per micro step) so the RSS bound survives — see
        # train_batch; resident (embed/head) grads accumulate in RAM
        self.gradient_accumulation_steps = \
            config.gradient_accumulation_steps
        self.global_steps = 0
        self._rng = jax.random.PRNGKey(seed)
        self._initialized = False
        self._specs = list(module.layer_specs)
        self._mods = [s.build() for s in self._specs]
        # first and last layer (embed / head+loss) stay device-resident
        self._n_stream = len(self._specs) - 2
        self._fwd_cache: Dict[int, Any] = {}
        self._bwd_cache: Dict[int, Any] = {}

    # ------------------------------------------------------------------
    def _layer_key(self, idx: int):
        """Compile cache key: the module instance itself (flax modules are
        frozen dataclasses — equal-config layers hash equal and share one
        compiled program; a same-class layer with DIFFERENT fields gets its
        own, so the cache can never run layer B with layer A's closure)."""
        return self._mods[idx]

    def _init_state(self, batch):
        """Layer-by-layer init: only one layer's params are ever resident
        (the zero.Init capacity property, partition_parameters.py:806)."""
        t0 = time.time()
        x = jnp.asarray(batch["input_ids"])
        self._treedefs: List[Any] = []
        self._shapes: List[List[tuple]] = []
        self._dtypes: List[List[Any]] = []
        self._sizes: List[int] = []
        total = 0
        for i, mod in enumerate(self._mods):
            rng = jax.random.fold_in(self._rng, i)
            params = mod.init(rng, x, deterministic=True)["params"]
            x = mod.apply({"params": params}, x, deterministic=True)
            leaves, treedef = jax.tree.flatten(params)
            self._treedefs.append(treedef)
            self._shapes.append([l.shape for l in leaves])
            self._dtypes.append([l.dtype for l in leaves])
            flat = np.concatenate(
                [np.asarray(l, np.float32).ravel() for l in leaves])
            self._sizes.append(flat.size)
            total += flat.size
            if 0 < i <= self._n_stream:  # streamed block
                li = i - 1
                self.store.write(f"p{li}", flat)
                self.store.write(f"c{li}", self._to_compute(flat, li))
                self.store.write(f"m{li}", np.zeros_like(flat))
                self.store.write(f"v{li}", np.zeros_like(flat))
                # bound the write backlog: the aio queue holds a ref to
                # every queued buffer, so an un-barriered init would keep
                # the WHOLE model in RAM (measured: 8.2 GB RSS for a 4.8 GB
                # stack) — exactly what this tier exists to avoid
                self.store.barrier()
                del params
            else:
                # resident: device params + host master + host moments
                if i == 0:
                    self._embed_params = jax.device_put(params)
                else:
                    self._head_params = jax.device_put(params)
        self.store.barrier()
        self._resident_masters = {}
        self._compute_dtype = self._dtypes[1][0] if self._n_stream else \
            self._dtypes[0][0]
        self._initialized = True
        log_dist(
            f"NVMe param tier: {self._n_stream} streamed layers, "
            f"{total / 1e6:.1f}M params total, host window = 3 layer "
            f"slots ({self._sizes[1] * 16 / 1e6:.1f} MB incl. moments)",
            ranks=[0])
        log_dist(f"nvme init in {time.time() - t0:.1f}s", ranks=[0])

    def _to_compute(self, flat_f32: np.ndarray, li: int) -> np.ndarray:
        dt = self._dtypes[li + 1][0]
        return flat_f32.astype(dt) if dt != np.float32 else flat_f32

    def _unflatten(self, flat: np.ndarray, idx: int):
        """flat blob -> device param tree for layer ``idx`` (spec index)."""
        leaves, off = [], 0
        for shape, dtype in zip(self._shapes[idx], self._dtypes[idx]):
            n = int(np.prod(shape))
            leaves.append(flat[off:off + n].reshape(shape).astype(dtype))
            off += n
        return jax.tree.unflatten(self._treedefs[idx], leaves)

    # ------------------------------------------------------------------
    def _block_fwd(self, idx):
        key = self._layer_key(idx)
        if key not in self._fwd_cache:
            mod = self._mods[idx]

            def f(params, x):
                return mod.apply({"params": params}, x, deterministic=True)

            self._fwd_cache[key] = jax.jit(f)
        return self._fwd_cache[key]

    def _block_bwd(self, idx):
        """Recompute-vjp: (params, x, g_out) -> (g_params_flat, g_x)."""
        key = self._layer_key(idx)
        if key not in self._bwd_cache:
            mod = self._mods[idx]

            def b(params, x, g):
                _, vjp = jax.vjp(
                    lambda p, xx: mod.apply({"params": p}, xx,
                                            deterministic=True), params, x)
                gp, gx = vjp(g)
                flat = jnp.concatenate([
                    l.astype(jnp.float32).ravel()
                    for l in jax.tree.leaves(gp)])
                return flat, gx

            self._bwd_cache[key] = jax.jit(b)
        return self._bwd_cache[key]

    def _loss_and_head_bwd(self):
        if not hasattr(self, "_head_fn"):
            mod = self._mods[-1]
            loss_fn = self.module.loss_fn

            def f(params, x, labels):
                def run(p, xx):
                    out = mod.apply({"params": p}, xx, deterministic=True)
                    return (loss_fn(out, labels) if loss_fn is not None
                            else out)

                loss, vjp = jax.vjp(run, params, x)
                gp, gx = vjp(jnp.float32(1.0))
                return loss, gp, gx

            self._head_fn = jax.jit(f)
        return self._head_fn

    def _embed_bwd(self):
        if not hasattr(self, "_embed_fn"):
            mod = self._mods[0]

            def f(params, ids, g):
                _, vjp = jax.vjp(
                    lambda p: mod.apply({"params": p}, ids,
                                        deterministic=True), params)
                (gp,) = vjp(g)
                return gp

            self._embed_fn = jax.jit(f)
        return self._embed_fn

    # ------------------------------------------------------------------
    def train_batch(self, data_iter):
        """One optimizer step = ``gradient_accumulation_steps`` micro
        sweeps. Streamed-layer grads accumulate ON DISK (``g`` blobs,
        read-add-write per micro) so host RSS stays window-bounded;
        the last micro folds the accumulated grad into the fused host
        Adam in the same pass."""
        gas = self.gradient_accumulation_steps
        losses = []
        for mi in range(gas):
            losses.append(self._micro_sweep(
                next(data_iter), first=mi == 0, last=mi == gas - 1,
                inv_gas=1.0 / gas))
        self.global_steps += 1
        return jnp.mean(jnp.stack(losses))

    def _micro_sweep(self, batch, first, last, inv_gas):
        if not self._initialized:
            self._init_state(batch)
        ids = jnp.asarray(batch["input_ids"])
        labels = jnp.asarray(batch["labels"])
        S = self._n_stream

        # ---- forward sweep: fetch compute copies, keep layer inputs ----
        x = self._block_fwd(0)(self._embed_params, ids)
        acts = []
        self.store.prefetch("c0")
        for li in range(S):
            # get BEFORE prefetching the next layer: wait() is global, so
            # a prefetch queued first would be waited on too — the next
            # layer's read must instead overlap THIS layer's compute
            p_dev = jax.device_put(self._unflatten(
                self.store.get(f"c{li}"), li + 1))
            if li + 1 < S:
                self.store.prefetch(f"c{li + 1}")
            acts.append(x)
            x = self._block_fwd(li + 1)(p_dev, x)
            del p_dev

        # ---- head + loss + its backward (resident) ----
        if last:
            if self._lr_schedule is not None:
                self.cpu_adam.lr = float(
                    self._lr_schedule(self.global_steps))
            self.cpu_adam.step_count += 1  # once per step, pre-update
        loss, g_head, gx = self._loss_and_head_bwd()(
            self._head_params, x, labels)
        self._accumulate_resident("head", self._head_params, g_head,
                                  first, last, inv_gas)

        # ---- backward sweep: reverse prefetch; grads to disk, Adam on
        # the boundary micro ----
        bwd_kinds = (("c", "p", "m", "v") if last else ("c",)) + \
            (("g",) if not first else ())
        if S:
            for kind in bwd_kinds:
                self.store.prefetch(f"{kind}{S - 1}")
        for li in reversed(range(S)):
            p_dev = jax.device_put(self._unflatten(
                self.store.get(f"c{li}"), li + 1))
            fetched = {k: self.store.get(f"{k}{li}")
                       for k in bwd_kinds if k != "c"}
            if li - 1 >= 0:  # after the gets (global wait, see fwd sweep)
                for kind in bwd_kinds:
                    self.store.prefetch(f"{kind}{li - 1}")
            g_flat, gx = self._block_bwd(li + 1)(p_dev, acts[li], gx)
            del p_dev
            g = np.asarray(g_flat, np.float32) * inv_gas
            if "g" in fetched:
                g = g + fetched["g"]
            if last:
                master, m, v = fetched["p"], fetched["m"], fetched["v"]
                self.cpu_adam.update_tensor(master, g, m, v)
                self.store.write(f"p{li}", master)
                self.store.write(f"m{li}", m)
                self.store.write(f"v{li}", v)
                self.store.write(f"c{li}", self._to_compute(master, li))
                del master, m, v
            else:
                self.store.write(f"g{li}", g)
            del g, fetched
        self.store.barrier()
        if last and not first and S:
            # the accumulated-grad blobs are dead once folded into Adam —
            # keep them out of checkpoints and off the disk budget
            for li in range(S):
                self.store.swapper.remove(f"g{li}")

        g_embed = self._embed_bwd()(self._embed_params, ids, gx)
        self._accumulate_resident("embed", self._embed_params, g_embed,
                                  first, last, inv_gas)
        if last:
            if "embed" in self._resident_masters:
                self._embed_params = self._resident_masters["embed"]["dev"]
            if "head" in self._resident_masters:
                self._head_params = self._resident_masters["head"]["dev"]
        return loss

    def _accumulate_resident(self, name: str, params, grads, first, last,
                             inv_gas) -> None:
        """RAM-accumulated grads + host Adam on the boundary micro for the
        device-resident (embed/head) layers."""
        st = self._resident_masters.setdefault(name, {})
        leaves = jax.tree.leaves(params)
        if "p" not in st:
            st["p"] = np.concatenate(
                [np.asarray(l, np.float32).ravel() for l in leaves])
            st["m"] = np.zeros_like(st["p"])
            st["v"] = np.zeros_like(st["p"])
        g = np.concatenate([
            np.asarray(l, np.float32).ravel()
            for l in jax.tree.leaves(grads)]) * inv_gas
        if first:
            st["g"] = g
        else:
            st["g"] += g
        if last:
            self.cpu_adam.update_tensor(st["p"], st.pop("g"),
                                        st["m"], st["v"])
            idx = 0 if name == "embed" else len(self._mods) - 1
            st["dev"] = jax.device_put(self._unflatten(st["p"], idx))

    # ------------------------------------------------------------------
    # checkpointing: the SSD store IS the state — snapshot blobs + the
    # resident (embed/head) masters + counters (reference nvme checkpoints
    # likewise persist the swap files' content)
    # ------------------------------------------------------------------
    def save_checkpoint(self, save_dir, tag=None, client_state=None):
        import pickle
        import shutil

        assert self._initialized, "cannot checkpoint before first batch"
        tag = tag or f"global_step{self.global_steps}"
        out = os.path.join(save_dir, str(tag))
        os.makedirs(out, exist_ok=True)
        self.store.barrier()
        blob_dir = self.store.swapper.swap_dir
        for f in os.listdir(blob_dir):
            shutil.copy2(os.path.join(blob_dir, f), os.path.join(out, f))
        residents = {
            f"{name}.{k}": st[k]
            for name, st in self._resident_masters.items()
            for k in ("p", "m", "v")
        }
        np.savez(os.path.join(out, "resident_masters.npz"), **residents)
        with open(os.path.join(out, "nvme_engine_states.pkl"), "wb") as f:
            pickle.dump({
                "global_steps": self.global_steps,
                "step_count": self.cpu_adam.step_count,
                "swap_meta": self.store.swapper._meta,
                "client_state": client_state or {},
            }, f)
        from deepspeed_tpu.runtime import checkpoint_manifest

        checkpoint_manifest.write_latest(save_dir, tag)
        return True

    def load_checkpoint(self, load_dir, tag=None):
        import pickle
        import shutil

        assert self._initialized, (
            "run one train_batch before load_checkpoint so layer "
            "templates exist")
        if tag is None:
            with open(os.path.join(load_dir, "latest")) as f:
                tag = f.read().strip()
        src = os.path.join(load_dir, str(tag))
        with open(os.path.join(src, "nvme_engine_states.pkl"), "rb") as f:
            meta = pickle.load(f)
        self.store.barrier()
        blob_dir = self.store.swapper.swap_dir
        for f_ in os.listdir(src):
            if f_.endswith(".swp"):
                shutil.copy2(os.path.join(src, f_),
                             os.path.join(blob_dir, f_))
        self.store.swapper._meta = dict(meta["swap_meta"])
        data = np.load(os.path.join(src, "resident_masters.npz"))
        for name, st in self._resident_masters.items():
            for k in ("p", "m", "v"):
                st[k] = np.array(data[f"{name}.{k}"], copy=True)
            idx = 0 if name == "embed" else len(self._mods) - 1
            st["dev"] = jax.device_put(self._unflatten(st["p"], idx))
        if "embed" in self._resident_masters:
            self._embed_params = self._resident_masters["embed"]["dev"]
        if "head" in self._resident_masters:
            self._head_params = self._resident_masters["head"]["dev"]
        self.global_steps = int(meta["global_steps"])
        self.cpu_adam.step_count = int(meta["step_count"])
        return tag, meta.get("client_state", {})

    # ------------------------------------------------------------------
    @property
    def topology(self):
        from deepspeed_tpu.parallel.mesh import get_default_topology

        return get_default_topology()

"""ZeRO-Offload: optimizer states + master weights on HOST memory
(reference ``runtime/zero/stage_1_and_2.py`` cpu_offload path +
``ops/adam/cpu_adam.py`` DeepSpeedCPUAdam; ZeRO-Infinity's NVMe tier via
``swap_tensor``).

Device HBM holds ONLY compute-dtype parameters; fp32 masters and Adam
moments live in host numpy and are updated by the multithreaded native
kernel (ops/native). Each step: grads device->host, fused host Adam,
masters host->device (cast + resharded). HBM cost per param drops from
16 bytes (fp32 master + m + v + grad) to just the compute bytes — the
ZeRO-Offload trade: PCIe/DMA traffic for memory headroom.

With ``nvme_dir`` set, the Adam moments are additionally swapped to local
SSD between steps through the aio threadpool (ZeRO-Infinity pattern), so
host RAM holds only masters.
"""

from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam
from deepspeed_tpu.utils.logging import log_dist


class HostOffloadOptimizer:
    def __init__(self, params, param_shardings, opt_params: dict,
                 compute_dtype, gradient_clipping: float = 0.0,
                 lr_schedule: Optional[Callable] = None,
                 nvme_dir: Optional[str] = None, adamw_mode: bool = True):
        opt_params = dict(opt_params or {})
        betas = opt_params.get("betas", (0.9, 0.999))
        self.cpu_adam = DeepSpeedCPUAdam(
            lr=float(opt_params.get("lr", 1e-3)),
            betas=(float(betas[0]), float(betas[1])),
            eps=float(opt_params.get("eps", 1e-8)),
            weight_decay=float(opt_params.get("weight_decay", 0.0)),
            adamw_mode=adamw_mode)
        self.lr_schedule = lr_schedule
        self.gradient_clipping = gradient_clipping
        self.compute_dtype = compute_dtype

        host = jax.device_get(params)
        leaves, self._treedef = jax.tree.flatten(host)
        # explicit copy: device_get may hand back read-only buffers, and
        # the native kernel updates masters in place
        self.masters: List[np.ndarray] = [
            np.array(l, dtype=np.float32, copy=True) for l in leaves]
        self._shapes = [l.shape for l in leaves]
        # per-leaf dtypes: mixed trees (bf16 kernels + fp32 norms) must
        # round-trip without a blanket cast
        self._dtypes = [l.dtype for l in leaves]
        self._shard_leaves = (jax.tree.leaves(param_shardings)
                              if param_shardings is not None
                              else [None] * len(leaves))
        self._swapper = None
        if nvme_dir:
            from deepspeed_tpu.runtime.swap_tensor import (
                PipelinedOptimizerSwapper,
            )

            self._swapper = PipelinedOptimizerSwapper(nvme_dir)
        nbytes = sum(m.nbytes for m in self.masters)
        log_dist(
            f"ZeRO-Offload: {len(self.masters)} tensors, "
            f"{nbytes / 1e6:.1f} MB fp32 masters on host"
            + (f", moments swapped to {nvme_dir}" if nvme_dir else ""),
            ranks=[0])

    # ------------------------------------------------------------------
    def _swap_in_moments(self):
        if self._swapper is None or self.cpu_adam.step_count == 0:
            return
        for i in range(len(self.masters)):
            self.cpu_adam._m[i] = self._swapper.swap_in(f"m{i}")
            self.cpu_adam._v[i] = self._swapper.swap_in(f"v{i}")
        self._swapper.wait()

    def _swap_out_moments(self):
        if self._swapper is None or not self.cpu_adam._m:
            return
        for i in range(len(self.masters)):
            self._swapper.swap_out(f"m{i}", self.cpu_adam._m[i])
            self._swapper.swap_out(f"v{i}", self.cpu_adam._v[i])
        self._swapper.wait()
        self.cpu_adam._m.clear()
        self.cpu_adam._v.clear()

    # ------------------------------------------------------------------
    def step(self, acc_grads, loss_scale: float = 1.0,
             global_step: int = 0, current_params=None, lr_override=None):
        """Host optimizer step. Returns (new device params tree, overflow,
        grad_norm). On overflow the masters are untouched and
        ``current_params`` (when given) is returned as-is — no redundant
        full-model re-upload. ``lr_override``: absolute lr for this step
        (write-through param_groups["lr"], engine.set_lr)."""
        if lr_override is not None:
            self.cpu_adam.lr = float(lr_override)
        elif self.lr_schedule is not None:
            self.cpu_adam.lr = float(self.lr_schedule(global_step))

        host_grads = jax.device_get(acc_grads)
        flat_grads = [
            np.asarray(g, dtype=np.float32).reshape(-1) / loss_scale
            for g in jax.tree.leaves(host_grads)]

        sq = sum(float(np.dot(g, g)) for g in flat_grads)
        grad_norm = float(np.sqrt(sq))
        overflow = not np.isfinite(grad_norm)

        if overflow and current_params is not None:
            return current_params, overflow, grad_norm
        if not overflow:
            if self.gradient_clipping and self.gradient_clipping > 0:
                factor = min(1.0,
                             self.gradient_clipping / (grad_norm + 1e-6))
                if factor < 1.0:
                    flat_grads = [g * factor for g in flat_grads]
            flat_masters = [m.reshape(-1) for m in self.masters]
            if self._swapper is not None:
                # pipelined moment swap: sub-group N+1's disk read and
                # N-1's write overlap N's fused Adam (reference
                # pipelined_optimizer_swapper.py:27)
                ca = self.cpu_adam
                ca.step_count += 1

                def upd(i, m, v):
                    ca.update_tensor(flat_masters[i], flat_grads[i], m, v)

                self._swapper.run_step(
                    [m.size for m in flat_masters], upd,
                    first_step=(ca.step_count == 1))
            else:
                self.cpu_adam.step(flat_masters, flat_grads)

        device_leaves = []
        for m, shape, dtype, shard in zip(self.masters, self._shapes,
                                          self._dtypes,
                                          self._shard_leaves):
            # one transfer: cast on HOST (jax registers bf16 with numpy)
            # then device_put straight into the target sharding
            host = m.reshape(shape).astype(np.dtype(dtype), copy=False)
            arr = jax.device_put(host, shard) if shard is not None \
                else jnp.asarray(host)
            device_leaves.append(arr)
        return (jax.tree.unflatten(self._treedef, device_leaves),
                overflow, grad_norm)

    def refresh_masters(self, params) -> None:
        """Re-seed the fp32 masters from a (restored) device param tree —
        required after loading model weights without optimizer states,
        since step() always rebuilds device params FROM the masters."""
        host = jax.device_get(params)
        for i, leaf in enumerate(jax.tree.leaves(host)):
            self.masters[i][...] = np.asarray(leaf, dtype=np.float32)

    # ------------------------------------------------------------------
    # checkpoint surface (engine save/load)
    # ------------------------------------------------------------------
    def state_dict(self):
        self._swap_in_moments()
        # moments are stored ONLY when they exist (no sentinel values — a
        # zeros(1) placeholder would collide with genuine size-1 params)
        sd = {
            "step_count": self.cpu_adam.step_count,
            "masters": {str(i): m for i, m in enumerate(self.masters)},
            "exp_avg": {str(i): m for i, m in self.cpu_adam._m.items()},
            "exp_avg_sq": {str(i): v
                           for i, v in self.cpu_adam._v.items()},
        }
        # restore the nvme-tier invariant (host RAM holds only masters)
        self._swap_out_moments()
        return sd

    def load_state_dict(self, sd):
        self.cpu_adam.step_count = int(sd["step_count"])
        # drop resident moments first so a pre-first-step checkpoint
        # (no stored moments) cannot leave stale state behind
        self.cpu_adam._m.clear()
        self.cpu_adam._v.clear()
        for i in range(len(self.masters)):
            self.masters[i][...] = np.asarray(
                sd["masters"][str(i)], dtype=np.float32).reshape(
                    self.masters[i].shape)
            key = str(i)
            if key in sd.get("exp_avg", {}):
                self.cpu_adam._m[i] = np.asarray(
                    sd["exp_avg"][key], dtype=np.float32).reshape(-1).copy()
                self.cpu_adam._v[i] = np.asarray(
                    sd["exp_avg_sq"][key],
                    dtype=np.float32).reshape(-1).copy()
        self._swap_out_moments()

"""Tiled linear layers (reference ``runtime/zero/tiling.py:27`` TiledLinear).

The reference splits one huge Linear into a grid of smaller Linears so
ZeRO-3 can fetch/release weight tiles one at a time. On TPU the analogous
memory pressure is XLA temp buffers for giant [in, out] matmuls; tiling by
input splits turns one matmul into an accumulation of smaller ones that
the scheduler can stream. Output splits shard the bias/activation side.
"""

from typing import Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp


class TiledLinear(nn.Module):
    """Drop-in Dense replacement computing y = sum_i x_i @ W_ij per output
    tile j. Weight tiles are separate parameters (``tile_i_j``), so
    sharding rules and ZeRO-3 partitioning see small, independently
    fetchable arrays (the reference's core trick)."""

    features: int
    in_splits: int = 1
    out_splits: int = 1
    use_bias: bool = True
    dtype: Optional[jnp.dtype] = None
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        in_features = x.shape[-1]
        if in_features % self.in_splits:
            raise ValueError(
                f"in_features {in_features} not divisible by in_splits "
                f"{self.in_splits}")
        if self.features % self.out_splits:
            raise ValueError(
                f"features {self.features} not divisible by out_splits "
                f"{self.out_splits}")
        in_tile = in_features // self.in_splits
        out_tile = self.features // self.out_splits
        dtype = self.dtype or x.dtype

        x_tiles = jnp.split(x, self.in_splits, axis=-1)
        # init variance must use the FULL fan-in (sum over in_splits tiles
        # behaves like one Dense): scale lecun by 1/in_splits
        tile_init = nn.initializers.variance_scaling(
            1.0 / self.in_splits, "fan_in", "truncated_normal")
        out_tiles = []
        for j in range(self.out_splits):
            acc = None
            for i in range(self.in_splits):
                w = self.param(
                    f"tile_{i}_{j}", tile_init,
                    (in_tile, out_tile), self.param_dtype)
                part = x_tiles[i].astype(dtype) @ w.astype(dtype)
                acc = part if acc is None else acc + part
            if self.use_bias:
                b = self.param(f"bias_{j}", nn.initializers.zeros,
                               (out_tile,), self.param_dtype)
                acc = acc + b.astype(dtype)
            out_tiles.append(acc)
        return jnp.concatenate(out_tiles, axis=-1)

    @staticmethod
    def from_dense_kernel(kernel, in_splits: int, out_splits: int):
        """Split a dense [in, out] kernel into the tile param dict
        (reference copy_params_from)."""
        import numpy as np

        kernel = np.asarray(kernel)
        rows = np.split(kernel, in_splits, axis=0)
        out = {}
        for i, row in enumerate(rows):
            for j, tile in enumerate(np.split(row, out_splits, axis=1)):
                out[f"tile_{i}_{j}"] = tile
        return out

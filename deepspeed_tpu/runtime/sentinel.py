"""Training health sentinel: anomaly detection, graduated response, hang
watchdog (docs/recovery.md "Divergence and hang recovery").

PR 1 made crashes survivable; this module covers the runs that *stay up
while going wrong*. Pod-scale TPU training treats NaN bursts, loss spikes,
and wedged collectives as routine events to absorb, not fatal ones — the
engine detects them host-side off values the step already returns (no
extra device sync), and repairs them with the manifest/newest-valid-tag
machinery from ``checkpoint_manifest.py``:

* :class:`TrainingSentinel` — per-step verdicts from a non-finite check
  (any dtype, not just the fp16 loss-scale path) plus rolling-window
  z-score/ratio spike detection on loss and grad norm; consecutive
  anomalies first burn a bounded skip budget, then escalate to rollback,
  then — once the rollback budget is spent — to :class:`DivergenceError`.
* :class:`HangWatchdog` — a daemon-thread heartbeat armed around each
  step; on timeout it dumps every Python thread stack and either warns or
  aborts the process with its own exit code.
* :class:`DivergenceError` — carries a distinct exit code so the elastic
  agent can tell "diverged" (restarting replays the failure) from
  "crashed" (restarting is the fix) and stop restart-looping.

Deliberately jax-free (stdlib + the config object's attributes) so
supervisors and agent-side tooling can import it without a runtime.
"""

import math
import os
import sys
import threading
import time
import traceback
from collections import deque
from typing import Callable, Dict, Optional, Tuple

from deepspeed_tpu.runtime import constants as C
from deepspeed_tpu.utils.logging import logger

# verdicts returned by TrainingSentinel.observe
VERDICT_OK = "ok"
VERDICT_ANOMALY = "anomaly"
VERDICT_ROLLBACK = "rollback"
VERDICT_DIVERGED = "diverged"


class DivergenceError(RuntimeError):
    """Training diverged past its rollback budget. Carries ``exit_code``
    (default :data:`constants.DIVERGENCE_EXIT_CODE_DEFAULT`) so worker
    scripts can ``sys.exit(e.exit_code)`` and the elastic agent can stop
    restart-looping into the same divergence."""

    def __init__(self, message: str,
                 exit_code: int = C.DIVERGENCE_EXIT_CODE_DEFAULT):
        super().__init__(message)
        self.exit_code = int(exit_code)


def _finite(value: Optional[float]) -> bool:
    return value is not None and math.isfinite(value)


class TrainingSentinel:
    """Host-side anomaly detector with a graduated response policy.

    ``observe()`` is called once per optimizer step with the loss and
    grad norm the step already materialized. It returns a
    ``(verdict, reason)`` pair; the ENGINE owns the repair actions
    (the sentinel never touches device state):

    * ``ok`` — healthy step, windows updated;
    * ``anomaly`` — bad step inside the skip budget: the engine has
      already cond-skipped the update (non-finite) or should simply move
      to the next batch (spike);
    * ``rollback`` — consecutive anomalies exceeded ``skip_budget`` and
      rollbacks remain in budget: restore the newest manifest-valid
      checkpoint and (optionally) reseed the data order;
    * ``diverged`` — the rollback budget is spent too; raise
      :class:`DivergenceError`.

    Spike detection only engages once ``min_window`` healthy samples are
    banked, so warmup noise cannot trip it; anomalous samples are never
    added to the windows, so a NaN burst cannot poison the baseline it is
    judged against.
    """

    def __init__(self, cfg):
        self.cfg = cfg
        window = int(cfg.window)
        self._losses = deque(maxlen=window)
        self._grad_norms = deque(maxlen=window)
        self._consecutive = 0
        self.stats: Dict[str, int] = {
            "nonfinite_steps": 0,
            "loss_spikes": 0,
            "grad_spikes": 0,
            "batch_skips": 0,
            "rollbacks": 0,
            "divergences": 0,
            "watchdog_fires": 0,
        }

    # -- detection -----------------------------------------------------
    def _spike(self, value: float, window, zscore_thr: float,
               ratio_thr: float) -> Optional[str]:
        if len(window) < max(int(self.cfg.min_window), 2):
            return None
        mean = sum(window) / len(window)
        if ratio_thr and ratio_thr > 0 and mean > 0 \
                and value > ratio_thr * mean:
            return f"{value:.4g} > {ratio_thr:g}x window mean {mean:.4g}"
        if zscore_thr and zscore_thr > 0:
            var = sum((x - mean) ** 2 for x in window) / len(window)
            std = math.sqrt(var)
            if std > 0 and (value - mean) / std > zscore_thr:
                return (f"z-score {(value - mean) / std:.1f} > "
                        f"{zscore_thr:g} (mean {mean:.4g}, std {std:.4g})")
        return None

    def observe(self, loss: Optional[float], grad_norm: Optional[float] = None,
                update_skipped: bool = False, fp16: bool = False,
                step: int = 0) -> Tuple[str, str]:
        """Judge one optimizer step. ``update_skipped`` is the in-graph
        overflow gate's decision; under fp16 a routine loss-scale overflow
        (finite loss) belongs to the loss scaler and does NOT count
        against the sentinel budget."""
        anomaly = None
        # None means "not observed this step" (e.g. no grad norm under a
        # compressed optimizer), never an anomaly by itself
        nonfinite = ((loss is not None and not math.isfinite(loss))
                     or (grad_norm is not None
                         and not math.isfinite(grad_norm))
                     or (update_skipped and not fp16))
        if nonfinite and getattr(self.cfg, "check_nonfinite", True):
            self.stats["nonfinite_steps"] += 1
            anomaly = f"non-finite loss/grads at step {step} (loss={loss})"
        elif _finite(loss):
            reason = self._spike(loss, self._losses,
                                 self.cfg.loss_spike_zscore,
                                 self.cfg.loss_spike_ratio)
            if reason is not None:
                self.stats["loss_spikes"] += 1
                anomaly = f"loss spike at step {step}: {reason}"
            elif _finite(grad_norm):
                reason = self._spike(grad_norm, self._grad_norms,
                                     self.cfg.grad_spike_zscore,
                                     self.cfg.grad_spike_ratio)
                if reason is not None:
                    self.stats["grad_spikes"] += 1
                    anomaly = f"grad-norm spike at step {step}: {reason}"

        if update_skipped and (anomaly is not None or not fp16):
            self.stats["batch_skips"] += 1

        if anomaly is None:
            self._consecutive = 0
            if _finite(loss):
                self._losses.append(float(loss))
            if _finite(grad_norm):
                self._grad_norms.append(float(grad_norm))
            return VERDICT_OK, ""

        self._consecutive += 1
        if self._consecutive <= int(self.cfg.skip_budget):
            return VERDICT_ANOMALY, (
                f"{anomaly} [{self._consecutive}/{self.cfg.skip_budget} "
                f"consecutive before rollback]")
        if self.stats["rollbacks"] >= int(self.cfg.rollback_budget):
            self.stats["divergences"] += 1
            return VERDICT_DIVERGED, (
                f"{anomaly}; skip budget ({self.cfg.skip_budget}) and "
                f"rollback budget ({self.cfg.rollback_budget}) exhausted")
        return VERDICT_ROLLBACK, (
            f"{anomaly}; {self._consecutive} consecutive anomalies "
            f"exceed skip budget {self.cfg.skip_budget}")

    # -- state transitions driven by the engine ------------------------
    def note_rollback(self):
        """A rollback happened: the restored state predates the window, so
        the baseline restarts clean (stale samples would mis-judge the
        post-restore loss level)."""
        self.stats["rollbacks"] += 1
        self._consecutive = 0
        self._losses.clear()
        self._grad_norms.clear()

    def note_watchdog_fire(self, dump: str = ""):
        self.stats["watchdog_fires"] += 1

    def counters(self) -> Dict[str, int]:
        return dict(self.stats)


def dump_thread_stacks() -> str:
    """Format the current stack of every Python thread (the hang
    post-mortem: WHERE each thread is stuck, e.g. blocked in a collective
    or a host transfer)."""
    frames = sys._current_frames()
    chunks = []
    for t in threading.enumerate():
        chunks.append(f"--- thread {t.name} (ident={t.ident}, "
                      f"daemon={t.daemon}) ---")
        frame = frames.get(t.ident)
        if frame is None:
            chunks.append("  <no frame>")
        else:
            chunks.append("".join(traceback.format_stack(frame)).rstrip())
    return "\n".join(chunks)


class HangWatchdog:
    """Daemon-thread heartbeat: ``arm()`` before dispatching a step (and
    again at every sign of progress — re-arming IS the heartbeat),
    ``disarm()`` when the step completes. If the deadline passes while
    armed, the watchdog dumps all thread stacks and either warns (and
    pushes the deadline so it doesn't spam) or aborts the process with
    ``exit_code`` via ``os._exit`` — a hung collective cannot be unwound
    with an exception from another thread.

    ``clock``/``abort_fn``/``poll_once()`` are test seams: drive a fake
    monotonic clock and call ``poll_once()`` directly, no sleeping.
    """

    def __init__(self, timeout_s: float, action: str = "warn",
                 exit_code: int = C.SENTINEL_HANG_EXIT_CODE_DEFAULT,
                 poll_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 on_fire: Optional[Callable[[str], None]] = None,
                 abort_fn: Optional[Callable[[int], None]] = None):
        if action not in ("warn", "abort"):
            raise ValueError(f"HangWatchdog action must be 'warn' or "
                             f"'abort', got {action!r}")
        self.timeout_s = float(timeout_s)
        self.action = action
        self.exit_code = int(exit_code)
        self._clock = clock
        self._poll_s = poll_s if poll_s is not None else min(
            1.0, max(0.02, self.timeout_s / 10.0))
        self._on_fire = on_fire
        self._abort = abort_fn if abort_fn is not None else os._exit
        self._lock = threading.Lock()
        self._deadline: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.fired = 0
        self.last_dump: Optional[str] = None

    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="ds-tpu-hang-watchdog", daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop.wait(self._poll_s):
            self.poll_once()

    def arm(self):
        """(Re)start the countdown — call at every sign of step progress."""
        with self._lock:
            self._deadline = self._clock() + self.timeout_s

    def disarm(self):
        with self._lock:
            self._deadline = None

    @property
    def armed(self) -> bool:
        """True while a step is in flight (between arm and disarm) — the
        cluster health plane samples this into every beat so survivors
        of a peer loss can report WHERE they were stuck."""
        with self._lock:
            return self._deadline is not None

    def stop(self):
        self._stop.set()
        self.disarm()
        if self._thread is not None:
            self._thread.join(timeout=max(self._poll_s * 4, 1.0))
            self._thread = None

    def poll_once(self) -> bool:
        """One deadline check (the daemon loop body; also the test seam).
        Returns True when the watchdog fired."""
        with self._lock:
            deadline = self._deadline
            if deadline is None or self._clock() < deadline:
                return False
            # warn: push the deadline one timeout out so a persistent hang
            # re-warns periodically instead of spamming every poll; abort:
            # clear it (the process is going down)
            self._deadline = (self._clock() + self.timeout_s
                              if self.action == "warn" else None)
        self.fired += 1
        dump = dump_thread_stacks()
        self.last_dump = dump
        logger.error(
            "hang watchdog: no step progress within %.1fs (action=%s). "
            "Thread stacks:\n%s", self.timeout_s, self.action, dump)
        if self._on_fire is not None:
            try:
                self._on_fire(dump)
            except Exception:  # never let telemetry mask the dump
                logger.exception("hang watchdog on_fire callback failed")
        if self.action == "abort":
            logger.error("hang watchdog: aborting with exit code %d",
                         self.exit_code)
            self._abort(self.exit_code)
        return True

"""Optimizer construction from the DeepSpeed config.

Parity with reference ``engine._configure_basic_optimizer`` (engine.py:1186):
the JSON ``optimizer`` block (type + params) builds the underlying update
rule. TPU re-design: optimizers are optax gradient transformations living
**sharded on the mesh** (their state shards with ZeRO stage, see
runtime/zero/sharding.py) instead of per-rank fused CUDA kernels. The fused
multi-tensor Adam of the reference (csrc/adam/multi_tensor_adam.cu) is the
Pallas kernel in ops/pallas/fused_adam.py, reachable via type "FusedAdam"
with ``tpu.use_pallas_optimizer``; plain optax compiles to fully-fused XLA
loops already, which is the honest default.
"""

from typing import Any, Callable, Dict, Optional, Union

import optax

from deepspeed_tpu.runtime import constants as C
from deepspeed_tpu.utils.logging import logger


def _normalize_betas(params: Dict[str, Any]):
    betas = params.get("betas", (0.9, 0.999))
    return float(betas[0]), float(betas[1])


def is_compressed_optimizer(opt_type: Optional[str]) -> bool:
    """True for the 1-bit family (compressed-communication optimizers)."""
    return (opt_type or "").lower() in (
        C.ONEBIT_ADAM_OPTIMIZER, C.ZERO_ONE_ADAM_OPTIMIZER,
        C.ONEBIT_LAMB_OPTIMIZER)


def build_optimizer(
    opt_type: Optional[str],
    opt_params: Optional[Dict[str, Any]] = None,
    learning_rate: Union[float, Callable, None] = None,
    use_pallas: bool = False,
    compression_axis: Optional[str] = None,
    compression_axis_size: Optional[int] = None,
) -> optax.GradientTransformation:
    """Map a DeepSpeed optimizer block to an optax transformation.

    ``learning_rate`` may be a float or a trace-safe schedule fn; when None,
    the lr from the params block is used. ``use_pallas`` routes FusedAdam to
    the single-pass Pallas kernel. For the 1-bit family pass
    ``compression_axis``/``compression_axis_size`` (the data-parallel mesh
    axis the sign-compressed exchange runs over — the engine does this; the
    returned transformation must be called inside shard_map with PER-WORKER
    gradients, see runtime/fp16/onebit).
    """
    opt_params = dict(opt_params or {})
    lr = learning_rate if learning_rate is not None else opt_params.get("lr", 1e-3)
    b1, b2 = _normalize_betas(opt_params)
    eps = float(opt_params.get("eps", 1e-8))
    wd = float(opt_params.get("weight_decay", 0.0))

    name = (opt_type or C.ADAMW_OPTIMIZER).lower()

    # the Pallas kernel implements decoupled (AdamW) decay only; coupled-L2
    # Adam (adam_w_mode=False) falls through to the optax path
    if use_pallas and name in (C.ADAM_OPTIMIZER, C.FUSED_ADAM_OPTIMIZER,
                               C.ADAMW_OPTIMIZER) and bool(
                                   opt_params.get("adam_w_mode", True)):
        from deepspeed_tpu.ops.pallas.fused_adam import fused_adamw

        return fused_adamw(lr, b1=b1, b2=b2, eps=eps, weight_decay=wd)

    if name in (C.ADAM_OPTIMIZER, C.FUSED_ADAM_OPTIMIZER, C.CPU_ADAM_OPTIMIZER):
        # reference FusedAdam defaults to adam_w_mode=True (ops/adam/fused_adam.py:15)
        adam_w_mode = bool(opt_params.get("adam_w_mode", True))
        if adam_w_mode:
            return optax.adamw(lr, b1=b1, b2=b2, eps=eps, weight_decay=wd)
        tx = optax.adam(lr, b1=b1, b2=b2, eps=eps)
        if wd:
            tx = optax.chain(optax.add_decayed_weights(wd), tx)
        return tx
    if name == C.ADAMW_OPTIMIZER:
        return optax.adamw(lr, b1=b1, b2=b2, eps=eps, weight_decay=wd)
    if name in (C.ADAGRAD_OPTIMIZER, C.CPU_ADAGRAD_OPTIMIZER):
        return optax.adagrad(lr, eps=float(opt_params.get("eps", 1e-10)))
    if name in (C.LAMB_OPTIMIZER, C.FUSED_LAMB_OPTIMIZER):
        return optax.lamb(lr, b1=b1, b2=b2, eps=eps, weight_decay=wd)
    if name == C.SGD_OPTIMIZER:
        return optax.sgd(lr, momentum=opt_params.get("momentum", 0.0),
                         nesterov=bool(opt_params.get("nesterov", False)))
    if name in (C.ONEBIT_ADAM_OPTIMIZER, C.ZERO_ONE_ADAM_OPTIMIZER,
                C.ONEBIT_LAMB_OPTIMIZER):
        # Compressed-communication optimizers (reference runtime/fp16/onebit/
        # adam.py:10 + runtime/comm/nccl.py:51): sign-compressed momentum
        # exchange over the data-parallel axis. The engine passes the mesh
        # axis; without one (standalone build_optimizer call) there is no
        # axis to exchange over, so fall back to the uncompressed update
        # rule with a warning.
        if compression_axis is not None and compression_axis_size is not None:
            from deepspeed_tpu.runtime.fp16.onebit import (
                onebit_adam,
                onebit_lamb,
                zero_one_adam,
            )

            # reference OnebitAdam calls the warmup length freeze_step
            warmup = int(opt_params.get(
                "freeze_step", opt_params.get("warmup_steps", 100)))
            if name == C.ONEBIT_LAMB_OPTIMIZER:
                return onebit_lamb(
                    lr, b1=b1, b2=b2, eps=eps, weight_decay=wd,
                    warmup_steps=warmup, axis=compression_axis,
                    axis_size=compression_axis_size)
            if name == C.ZERO_ONE_ADAM_OPTIMIZER:
                if "freeze_step" in opt_params:
                    logger.warning(
                        "ZeroOneAdam has no full-precision warmup stage "
                        "(0/1 Adam compresses from step 1; the variance "
                        "refresh period governs accuracy) — freeze_step "
                        "is ignored")
                return zero_one_adam(
                    lr, b1=b1, b2=b2, eps=eps, weight_decay=wd,
                    var_update_period=int(opt_params.get(
                        "var_update_period", 16)),
                    axis=compression_axis,
                    axis_size=compression_axis_size)
            return onebit_adam(
                lr, b1=b1, b2=b2, eps=eps, weight_decay=wd,
                warmup_steps=warmup, axis=compression_axis,
                axis_size=compression_axis_size)
        logger.warning(
            "%s: no mesh axis provided; using the uncompressed inner "
            "optimizer (the engine wires the compressed exchange)", opt_type,
        )
        if "lamb" in name:
            return optax.lamb(lr, b1=b1, b2=b2, eps=eps, weight_decay=wd)
        return optax.adamw(lr, b1=b1, b2=b2, eps=eps, weight_decay=wd)
    raise ValueError(f"Unknown optimizer type: {opt_type!r}")

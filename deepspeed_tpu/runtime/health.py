"""Cluster health plane: out-of-band peer liveness, coordinated abort,
straggler detection, and SDC parameter-digest probes (docs/recovery.md
"Cluster health & SDC defense").

PR 19 made multi-process training real, and with it a failure class no
single-process defense can see: one stalled, preempted, or
silently-corrupted peer leaves every OTHER process blocked inside an XLA
collective. The hang watchdog (``runtime/sentinel.py``) eventually fires
on each survivor independently — N staggered timeouts, no shared
diagnosis, N uncoordinated restarts. This module is the shared
diagnosis:

* :class:`ClusterHealthPlane` runs an **out-of-band TCP heartbeat
  mesh** between the training processes. Everything lives on daemon
  threads and plain sockets — never through XLA collectives — so the
  plane stays live while the main thread is wedged inside one (the same
  reasoning that makes the hang watchdog a daemon thread with
  ``os._exit``: a hung collective cannot be unwound from another
  thread).
* Each beat carries ``(rank, step, watchdog_armed, step_time_ewma,
  param_digest?)``. Peers are tracked with the healthy→suspect→down
  silence schedule shared with the serving fleet
  (``utils/health_state.SilenceSchedule`` — extracted from
  ``serving/fleet.FleetHealth``).
* A peer declared **down** mid-step makes every survivor perform a
  coordinated abort with ``PEER_LOSS_EXIT_CODE_DEFAULT`` (15): the
  elastic agent sees ONE world-level failure inside the silence budget
  and relaunches the world together from the newest manifest-valid tag
  (``elasticity/elastic_agent.py``; a permanently-gone peer routes
  through the agent's topology-event path).
* Rolling per-host **step-time skew** vs. the fleet median emits
  ``health.straggler`` (the per-host skew sensitivity of pod-scale runs;
  "Scale MLPerf-0.6 models on Google TPU-v3 Pods").
* Every K steps an **SDC probe** (:func:`param_digest`) digests the
  locally-addressable bits of the fully-replicated param leaves and the
  digests are cross-checked over the heartbeat mesh — replicas must be
  bit-identical, so any divergence is silent data corruption on some
  host. A mismatch dumps the flight-recorder blackbox and routes to the
  sentinel's rollback path (in-process, or via abort + relaunch from the
  newest manifest-valid tag — ``tpu.cluster_health.sdc_action``).

The plane is transport + policy only: no jax import at module scope (the
digest helpers import it lazily), so supervisors and tests can import it
as cheaply as ``sentinel.py``. ``clock`` / ``abort_fn`` / ``poll_once``
/ ``send_beats`` are the same test seams ``HangWatchdog`` exposes.
"""

import json
import os
import socket
import statistics
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.runtime import constants as C
from deepspeed_tpu.telemetry.bus import (
    KIND_HEALTH_ABORT,
    KIND_HEALTH_DESYNC,
    KIND_HEALTH_PEER_DOWN,
    KIND_HEALTH_PEER_UP,
    KIND_HEALTH_SDC,
    KIND_HEALTH_STRAGGLER,
    telemetry_bus,
)
from deepspeed_tpu.utils.health_state import (
    DOWN,
    HEALTHY,
    RECOVERING,
    HealthConfig,
    SilenceSchedule,
)
from deepspeed_tpu.utils.logging import logger

# how many of our own digests we keep for cross-checking against beats
# that arrive late (a peer's digest for step S may land after we already
# probed S+K)
_DIGEST_HISTORY = 32


def _parse_peer(addr: str) -> Tuple[str, int]:
    host, _, port = addr.rpartition(":")
    return host, int(port)


class ClusterHealthPlane:
    """Out-of-band liveness + SDC cross-check for one training process.

    Parameters
    ----------
    rank / world_size:
        this process's index and the process count (NOT device counts —
        the mesh is between host processes).
    config:
        a ``ClusterHealthConfig`` (``runtime/config.py``) or anything
        with the same fields.
    watchdog_probe:
        callable -> bool; sampled into each beat as ``watchdog_armed``
        so a surviving operator can see WHICH hosts were mid-step when
        a peer vanished (the shared diagnosis the N independent
        watchdogs cannot produce).
    on_abort:
        called with ``(reason, detail_dict)`` right before ``abort_fn``
        — the engine dumps its flight-recorder blackbox here (an
        ``os._exit`` abort skips atexit, same as the hang watchdog).
    clock / abort_fn:
        test seams; defaults ``time.monotonic`` / ``os._exit``.
    """

    def __init__(self, rank: int, world_size: int, config,
                 watchdog_probe: Optional[Callable[[], bool]] = None,
                 on_abort: Optional[Callable[[str, Dict[str, Any]], None]]
                 = None,
                 clock: Callable[[], float] = time.monotonic,
                 abort_fn: Optional[Callable[[int], None]] = None,
                 bus=None):
        if world_size < 1:
            raise ValueError(f"world_size must be >= 1, got {world_size}")
        if not 0 <= rank < world_size:
            raise ValueError(f"rank {rank} outside world {world_size}")
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.config = config
        self._clock = clock
        self._bus = bus if bus is not None else telemetry_bus
        self._abort_fn = abort_fn if abort_fn is not None else os._exit
        self._on_abort = on_abort
        self._watchdog_probe = watchdog_probe or (lambda: False)
        if config.peers:
            if len(config.peers) != self.world_size:
                raise ValueError(
                    f"tpu.cluster_health.peers has {len(config.peers)} "
                    f"entries for a world of {self.world_size}")
            self.peers = [_parse_peer(p) for p in config.peers]
        else:
            self.peers = [(config.host, int(config.port_base) + r)
                          for r in range(self.world_size)]
        self._schedule = SilenceSchedule(
            self.world_size,
            HealthConfig(suspect_after_s=config.suspect_after_s,
                         down_after_s=config.down_after_s,
                         recover_probes=config.recover_probes),
            clock=clock, on_transition=self._on_transition)

        self._lock = threading.Lock()
        self._step = 0
        self._last_step_ts: Optional[float] = None
        self._step_time_ewma = 0.0
        # own digests: step -> digest (bounded FIFO); peer latest digests:
        # rank -> (digest_step, digest)
        self._digests: Dict[int, int] = {}
        self._peer_digests: Dict[int, Tuple[int, int]] = {}
        self._peer_info: Dict[int, Dict[str, Any]] = {}
        self._sdc_reported: set = set()       # digest steps already flagged
        self._sdc_pending: Optional[Dict[str, Any]] = None
        self._desync_active: set = set()      # ranks currently skewed
        self._straggling = False
        self._counters = {
            "beats_sent": 0, "beats_received": 0, "peers_down": 0,
            "peers_up": 0, "stragglers": 0, "desyncs": 0,
            "sdc_mismatches": 0, "aborts": 0,
        }
        self._aborted = False
        self._stop = threading.Event()
        self._server: Optional[socket.socket] = None
        self._threads: List[threading.Thread] = []

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> None:
        """Bind the beat server and start the daemon sender/receiver."""
        if self._threads or self.world_size < 2:
            return
        host, port = self.peers[self.rank]
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, port))
        srv.listen(2 * self.world_size)
        srv.settimeout(0.2)  # bounded accept so stop() is honored
        self._server = srv
        for name, target in (("ds-tpu-health-recv", self._serve),
                             ("ds-tpu-health-send", self._beat_loop)):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        logger.info(
            "cluster health plane up: rank %d/%d listening on %s:%d "
            "(beat %.2fs, suspect %.1fs, down %.1fs)", self.rank,
            self.world_size, host, port, self.config.beat_interval_s,
            self.config.suspect_after_s, self.config.down_after_s)

    def stop(self) -> None:
        self._stop.set()
        srv, self._server = self._server, None
        if srv is not None:
            try:
                srv.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=1.0)
        self._threads = []

    # ------------------------------------------------------------------
    # daemon loops (never on the main thread; never through collectives)
    # ------------------------------------------------------------------
    def _serve(self) -> None:
        while not self._stop.is_set():
            srv = self._server
            if srv is None:
                return
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # closed by stop()
            try:
                conn.settimeout(1.0)
                chunks = []
                while True:
                    data = conn.recv(4096)
                    if not data:
                        break
                    chunks.append(data)
                if chunks:
                    self._on_beat(json.loads(b"".join(chunks).decode()))
            except (OSError, ValueError, KeyError):
                pass  # malformed/raced beat: silence is what kills a peer
            finally:
                try:
                    conn.close()
                except OSError:
                    pass

    def _beat_loop(self) -> None:
        interval = float(self.config.beat_interval_s)
        while not self._stop.wait(interval):
            self.send_beats()
            self.poll_once()

    # ------------------------------------------------------------------
    # beats
    # ------------------------------------------------------------------
    def _build_beat(self) -> Dict[str, Any]:
        try:
            armed = bool(self._watchdog_probe())
        except Exception:
            armed = False
        with self._lock:
            beat = {
                "rank": self.rank,
                "step": self._step,
                "watchdog_armed": armed,
                "step_time_ewma": self._step_time_ewma,
            }
            if self._digests:
                dstep = max(self._digests)
                beat["digest_step"] = dstep
                beat["param_digest"] = self._digests[dstep]
        return beat

    def send_beats(self) -> None:
        """One fan-out of the current beat to every peer (the sender
        loop body; also the test seam). Send failures are deliberately
        ignored: a dead peer is detected by OUR silence schedule on ITS
        beats, not by connect errors here."""
        payload = (json.dumps(self._build_beat()) + "\n").encode()
        timeout = min(1.0, float(self.config.beat_interval_s))
        for r, (host, port) in enumerate(self.peers):
            if r == self.rank:
                continue
            try:
                with socket.create_connection((host, port),
                                              timeout=timeout) as s:
                    s.sendall(payload)
            except OSError:
                pass
        with self._lock:
            self._counters["beats_sent"] += 1
        # sending IS our own sign of life
        self._schedule.heartbeat(self.rank)

    def _on_beat(self, beat: Dict[str, Any]) -> None:
        r = int(beat["rank"])
        if r == self.rank or not 0 <= r < self.world_size:
            return
        with self._lock:
            self._counters["beats_received"] += 1
            self._peer_info[r] = {
                "step": int(beat.get("step", 0)),
                "watchdog_armed": bool(beat.get("watchdog_armed", False)),
                "step_time_ewma": float(beat.get("step_time_ewma", 0.0)),
            }
            own_step = self._step
        self._schedule.heartbeat(r)
        self._check_desync(r, int(beat.get("step", 0)), own_step)
        if beat.get("param_digest") is not None:
            self._note_peer_digest(r, int(beat["digest_step"]),
                                   int(beat["param_digest"]))

    # ------------------------------------------------------------------
    # engine-facing surface (main thread)
    # ------------------------------------------------------------------
    def notify_step(self, step: int) -> None:
        """Step-boundary hook: advances the step the beats report and
        folds the inter-step wall time into the straggler EWMA."""
        now = self._clock()
        alpha = float(self.config.ewma_alpha)
        with self._lock:
            self._step = int(step)
            if self._last_step_ts is not None:
                dt = max(now - self._last_step_ts, 0.0)
                self._step_time_ewma = (
                    dt if self._step_time_ewma == 0.0 else
                    alpha * dt + (1.0 - alpha) * self._step_time_ewma)
            self._last_step_ts = now

    def submit_digest(self, step: int, digest: Optional[int]) -> None:
        """Record our param digest for ``step`` (rides on the next beat)
        and cross-check it against any peer digest already received for
        the same step."""
        if digest is None:
            return
        with self._lock:
            self._digests[int(step)] = int(digest)
            while len(self._digests) > _DIGEST_HISTORY:
                del self._digests[min(self._digests)]
            peer_view = dict(self._peer_digests)
        for r, (dstep, d) in peer_view.items():
            if dstep == int(step):
                self._compare_digest(r, dstep, d)

    def take_sdc_fault(self) -> Optional[Dict[str, Any]]:
        """Pop the pending SDC mismatch (``sdc_action: rollback`` path):
        the engine polls this at the step boundary and routes a non-None
        result through the sentinel's rollback."""
        with self._lock:
            fault, self._sdc_pending = self._sdc_pending, None
        return fault

    def counters(self) -> Dict[str, int]:
        """Cumulative ``Health/*`` counters for the monitor export."""
        with self._lock:
            return dict(self._counters)

    def peer_states(self) -> Dict[int, str]:
        return self._schedule.states()

    def peer_info(self) -> Dict[int, Dict[str, Any]]:
        with self._lock:
            return {r: dict(v) for r, v in self._peer_info.items()}

    # ------------------------------------------------------------------
    # detection
    # ------------------------------------------------------------------
    def poll_once(self) -> None:
        """One sweep of the silence schedule + straggler evaluation (the
        sender loop body; also the test seam)."""
        self._schedule.sweep()
        self._check_straggler()

    def _on_transition(self, i: int, frm: str, to: str, reason: str,
                       probes: int) -> None:
        if i == self.rank:
            return
        if to == DOWN:
            with self._lock:
                self._counters["peers_down"] += 1
                step = self._step
            # NB: the hook runs under the schedule's (non-reentrant)
            # lock — no schedule calls from here; ``reason`` already
            # carries the silence duration
            self._bus.publish(
                KIND_HEALTH_PEER_DOWN, step=step, severity="warning",
                peer=i, previous=frm, reason=reason)
            logger.error(
                "cluster health: peer %d is DOWN (%s) at step %d",
                i, reason, step)
            if self.config.abort_on_peer_loss:
                self._coordinated_abort(
                    "peer_loss", peer=i, cause=reason, step=step)
        elif to == HEALTHY and frm in (RECOVERING, DOWN):
            with self._lock:
                self._counters["peers_up"] += 1
            self._bus.publish(KIND_HEALTH_PEER_UP, peer=i, probes=probes)

    def _check_desync(self, r: int, peer_step: int, own_step: int) -> None:
        thr = int(self.config.step_skew_threshold)
        if thr <= 0:
            return
        skewed = abs(peer_step - own_step) > thr
        with self._lock:
            was = r in self._desync_active
            if skewed and not was:
                self._desync_active.add(r)
                self._counters["desyncs"] += 1
            elif not skewed and was:
                self._desync_active.discard(r)
        if skewed and not was:  # edge-only, like serve.replica_down
            self._bus.publish(
                KIND_HEALTH_DESYNC, step=own_step, severity="warning",
                peer=r, peer_step=peer_step, skew=peer_step - own_step)

    def _check_straggler(self) -> None:
        ratio = float(self.config.straggler_ratio)
        if ratio <= 0:
            return
        with self._lock:
            own = self._step_time_ewma
            ewmas = [own] if own > 0 else []
            ewmas += [v["step_time_ewma"] for v in self._peer_info.values()
                      if v["step_time_ewma"] > 0]
            step = self._step
        if own <= 0 or len(ewmas) < int(self.config.straggler_min_peers):
            return
        median = statistics.median(ewmas)
        lagging = median > 0 and own > ratio * median
        with self._lock:
            was, self._straggling = self._straggling, lagging
            if lagging and not was:
                self._counters["stragglers"] += 1
        if lagging and not was:  # edge-only self-report: each host judges
            self._bus.publish(  # its OWN skew, so the fleet gets one
                KIND_HEALTH_STRAGGLER, step=step,  # event per straggler
                severity="warning", own_ewma_s=round(own, 4),
                fleet_median_s=round(median, 4),
                ratio=round(own / median, 3))
            logger.warning(
                "cluster health: this host is a straggler (step ewma "
                "%.3fs vs fleet median %.3fs)", own, median)

    # ------------------------------------------------------------------
    # SDC digest cross-check
    # ------------------------------------------------------------------
    def _note_peer_digest(self, r: int, dstep: int, digest: int) -> None:
        with self._lock:
            self._peer_digests[r] = (dstep, digest)
            ours = self._digests.get(dstep)
        if ours is not None:
            self._compare_digest(r, dstep, digest)

    def _compare_digest(self, r: int, dstep: int, theirs: int) -> None:
        with self._lock:
            ours = self._digests.get(dstep)
            if ours is None or ours == theirs:
                return
            if dstep in self._sdc_reported:  # one verdict per probe step
                return
            self._sdc_reported.add(dstep)
            self._counters["sdc_mismatches"] += 1
            detail = {"peer": r, "digest_step": dstep, "ours": ours,
                      "theirs": theirs}
        self._bus.publish(KIND_HEALTH_SDC, step=dstep, severity="fatal",
                          **detail)
        logger.error(
            "cluster health: SDC digest mismatch at step %d vs peer %d "
            "(ours=%#010x theirs=%#010x) — a replicated parameter is no "
            "longer bit-identical across hosts", dstep, r, ours, theirs)
        if self.config.sdc_action == "abort":
            self._coordinated_abort("sdc", **detail)
        else:
            with self._lock:
                self._sdc_pending = dict(detail, kind="sdc")

    # ------------------------------------------------------------------
    # coordinated abort
    # ------------------------------------------------------------------
    def abort(self, reason: str, **detail) -> None:
        """Public escalation hook (the engine uses it when an SDC
        rollback has no target); same once-only coordinated abort the
        silence schedule triggers."""
        self._coordinated_abort(reason, **detail)

    def _coordinated_abort(self, reason: str, **detail) -> None:
        """Every survivor runs this within the silence budget of the
        same peer event, so the per-host elastic agents see ONE
        world-level failure (exit code 15 from every process) instead of
        N staggered hang timeouts. ``os._exit``, like the watchdog: the
        main thread may be unrecoverably parked in a collective."""
        with self._lock:
            if self._aborted:
                return
            self._aborted = True
            self._counters["aborts"] += 1
        code = int(self.config.exit_code)
        self._bus.publish(KIND_HEALTH_ABORT, severity="fatal",
                          reason=reason, exit_code=code, **detail)
        logger.error(
            "cluster health: coordinated abort (%s) — exiting with code "
            "%d so the elastic agent relaunches the world together "
            "(detail: %s)", reason, code, detail)
        if self._on_abort is not None:
            try:
                self._on_abort(reason, dict(detail))
            except Exception:  # forensics must not block the abort
                logger.exception("cluster health on_abort callback failed")
        self._abort_fn(code)


# ---------------------------------------------------------------------------
# SDC parameter digest (the only jax-touching code in this module; kept
# lazy so supervisors import the plane jax-free)
# ---------------------------------------------------------------------------
def _bitcast_digest_fn(dtype):
    """Jitted per-device digest: bitcast to the same-width uint, widen to
    uint32, wrapping sum. A plain sum is permutation-invariant but ANY
    single bit flip changes it (short of an exact 2^32 collision), which
    is the failure model — and it is cheap enough to run every K steps."""
    import jax
    import jax.numpy as jnp

    width = dtype.itemsize * 8
    uint = {8: jnp.uint8, 16: jnp.uint16, 32: jnp.uint32,
            64: jnp.uint64}[width]

    def digest(x):
        bits = jax.lax.bitcast_convert_type(x, uint)
        return jnp.sum(bits.astype(jnp.uint32) if width != 64 else
                       (bits & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32))

    return jax.jit(digest)


_DIGEST_FNS: Dict[Any, Any] = {}


def param_digest(params) -> Optional[int]:
    """Digest the locally-addressable bits of every **fully replicated**
    float param leaf, mod 2**32. Returns None when no leaf qualifies
    (e.g. every leaf ZeRO-sharded). Only feed this from engines whose
    replication spans processes: the pipeline engine deliberately does
    NOT, because each stage's params replicate over that stage's own
    sub-mesh and digests from different stage owners would trivially
    differ.

    Per-process by construction: each process sums over its OWN devices'
    shards — no collective. Replication means every process must compute
    the same value, so a cross-mesh mismatch is bit-level divergence
    (SDC) on some host. A ``psum`` here would average the evidence away:
    every host would agree on the corrupted total.
    """
    import jax
    import numpy as np

    total = 0
    found = False
    for leaf in jax.tree.leaves(params):
        if not isinstance(leaf, jax.Array):
            continue
        if not np.issubdtype(leaf.dtype, np.floating) and \
                leaf.dtype.name != "bfloat16":
            continue
        try:
            if not leaf.sharding.is_fully_replicated:
                continue
        except (AttributeError, ValueError):
            continue
        found = True
        fn = _DIGEST_FNS.get(leaf.dtype)
        if fn is None:
            fn = _DIGEST_FNS[leaf.dtype] = _bitcast_digest_fn(leaf.dtype)
        for shard in leaf.addressable_shards:
            # every local copy is digested, so a flip on any ONE device
            # shows up even before it skews training
            total = (total + int(fn(shard.data))) % (1 << 32)
    return total if found else None


def build_plane(config, rank: Optional[int] = None,
                world_size: Optional[int] = None, **kwargs
                ) -> Optional[ClusterHealthPlane]:
    """Engine helper: resolve ``tpu.cluster_health`` auto-enablement
    against the live process count and return a started-able plane, or
    None when the plane should stay off (single process, disabled)."""
    import jax

    rank = jax.process_index() if rank is None else int(rank)
    world_size = (jax.process_count() if world_size is None
                  else int(world_size))
    if not config.resolve_enabled(world_size):
        return None
    return ClusterHealthPlane(rank, world_size, config, **kwargs)

"""Loss scaling for fp16 training.

Parity with reference ``deepspeed/runtime/fp16/loss_scaler.py`` (LossScaler
:54 static, DynamicLossScaler :77). TPU re-design: the scaler state is a
jittable pytree (arrays only) threaded through the compiled train step; the
static policy lives in LossScaleConfig, closed over at trace time. The
overflow check and skip-update decision happen inside the step via
``lax.cond`` (reference does it host-side between CUDA kernels — see
SURVEY.md §7 hard part (c)).
"""

import dataclasses
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class LossScaleState(NamedTuple):
    """Device state (pytree of arrays)."""

    scale: jnp.ndarray          # f32 scalar, current loss scale
    good_steps: jnp.ndarray     # i32 scalar, consecutive non-overflow steps
    hysteresis: jnp.ndarray     # i32 scalar, remaining tolerated overflows


@dataclasses.dataclass(frozen=True)
class LossScaleConfig:
    """Static policy (trace-time constants)."""

    dynamic: bool = False
    scale_window: int = 1000
    min_scale: float = 1.0
    max_hysteresis: int = 1
    scale_factor: float = 2.0


def init_loss_scale(fp16_config=None, enabled: bool = True
                    ) -> Tuple[LossScaleState, LossScaleConfig]:
    """Build initial (state, policy) from an Fp16Config (runtime/config.py)."""
    if fp16_config is None or not enabled:
        state = LossScaleState(
            scale=jnp.float32(1.0), good_steps=jnp.int32(0), hysteresis=jnp.int32(1)
        )
        return state, LossScaleConfig()
    dynamic = fp16_config.dynamic_loss_scale
    init_scale = (2.0 ** fp16_config.initial_scale_power if dynamic
                  else float(fp16_config.loss_scale))
    state = LossScaleState(
        scale=jnp.float32(init_scale),
        good_steps=jnp.int32(0),
        hysteresis=jnp.int32(fp16_config.hysteresis),
    )
    cfg = LossScaleConfig(
        dynamic=dynamic,
        scale_window=int(fp16_config.loss_scale_window),
        min_scale=float(fp16_config.min_loss_scale),
        max_hysteresis=int(fp16_config.hysteresis),
        scale_factor=2.0,
    )
    return state, cfg


def has_overflow(grads) -> jnp.ndarray:
    """Global inf/nan check over a grad pytree (reference CheckOverflow,
    runtime/utils.py; the cross-rank allreduce of the flag is implicit in
    SPMD — every device computes the same reduction)."""
    leaves = jax.tree_util.tree_leaves(grads)
    if not leaves:
        return jnp.bool_(False)
    flags = [jnp.logical_not(jnp.all(jnp.isfinite(g))) for g in leaves]
    return jnp.any(jnp.stack(flags))


def update_loss_scale(state: LossScaleState, overflow,
                      cfg: LossScaleConfig) -> LossScaleState:
    """Dynamic loss-scale update (reference DynamicLossScaler.update_scale):
    on overflow consume hysteresis then halve; after ``scale_window`` clean
    steps, double."""
    if not cfg.dynamic:
        return state

    def on_overflow(s):
        new_hyst = s.hysteresis - 1
        drop = new_hyst <= 0
        new_scale = jnp.where(
            drop, jnp.maximum(s.scale / cfg.scale_factor, cfg.min_scale), s.scale
        )
        return LossScaleState(
            scale=new_scale,
            good_steps=jnp.int32(0),
            hysteresis=jnp.where(drop, jnp.int32(cfg.max_hysteresis), new_hyst),
        )

    def on_good(s):
        grew = (s.good_steps + 1) >= cfg.scale_window
        return LossScaleState(
            scale=jnp.where(grew, s.scale * cfg.scale_factor, s.scale),
            good_steps=jnp.where(grew, jnp.int32(0), s.good_steps + 1),
            hysteresis=jnp.int32(cfg.max_hysteresis),
        )

    return jax.lax.cond(overflow, on_overflow, on_good, state)

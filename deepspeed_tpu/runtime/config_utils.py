"""Config helpers.

Parity with reference ``deepspeed/runtime/config_utils.py`` (get_scalar_param,
pydantic-style DeepSpeedConfigModel at :161) using plain dataclasses — no
pydantic dependency; unknown keys warn instead of failing, matching the
reference's permissive "extra field" behavior.
"""

import dataclasses
import json
from typing import Any, Dict

from deepspeed_tpu.utils.logging import logger


def get_scalar_param(param_dict: Dict, param_name: str, param_default_value):
    return param_dict.get(param_name, param_default_value)


def dict_raise_error_on_duplicate_keys(ordered_pairs):
    """Reject duplicate keys in the user JSON (reference config_utils.py)."""
    d = dict((k, v) for k, v in ordered_pairs)
    if len(d) != len(ordered_pairs):
        counter = {}
        for k, _ in ordered_pairs:
            counter[k] = counter.get(k, 0) + 1
        keys = [k for k, v in counter.items() if v > 1]
        raise ValueError(f"Duplicate keys in DeepSpeed config: {keys}")
    return d


class ConfigModel:
    """Minimal stand-in for the reference's pydantic DeepSpeedConfigModel:
    dataclass subclasses get ``from_dict`` with unknown-key warnings and
    deprecated-alias support via ``_aliases = {old: new}``."""

    _aliases: Dict[str, str] = {}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]):
        if d is None:
            d = {}
        if not isinstance(d, dict):
            raise TypeError(f"{cls.__name__} config block must be a dict, got {type(d)}")
        field_names = {f.name for f in dataclasses.fields(cls)}
        kwargs = {}
        for key, value in d.items():
            key = cls._aliases.get(key, key)
            if key in field_names:
                kwargs[key] = value
            else:
                logger.warning("%s: ignoring unknown config key %r", cls.__name__, key)
        inst = cls(**kwargs)
        if hasattr(inst, "__post_init__validate__"):
            inst.__post_init__validate__()
        return inst

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def __repr__(self):
        return f"{self.__class__.__name__}({self.to_dict()})"


def pretty_json(d: Dict) -> str:
    return json.dumps(d, indent=2, sort_keys=True, default=str)

"""Checkpoint durability + integrity primitives.

At pod scale the dominant checkpoint failure mode is the environment, not
the code: slice preemption mid-write, host loss before a page-cache flush,
flaky network filesystems (see "Scale MLPerf-0.6 models on Google TPU-v3
Pods", PAPERS.md). This module is the single place that knows how to make a
file durably land and how to prove later that a whole tag directory landed:

* ``atomic_write_bytes`` — tmp file + flush + fsync + ``os.replace`` +
  parent-directory fsync, with exponential-backoff retry on transient
  ``OSError``;
* per-tag ``manifest.json`` (file list + byte sizes + crc32) written by
  ``CheckpointEngine.commit`` and checked by ``verify_tag_dir`` before a
  load trusts the tag;
* ``find_valid_tags`` / ``latest_valid_tag`` — the fallback scan used when
  the newest tag is torn, and by the elastic agent to tell relaunched
  workers which tag is known-good (``DS_TPU_LAST_VALID_TAG``).

Deliberately dependency-light (no jax/flax): the elastic agent imports it
in the supervisor process where pulling in a TPU runtime would be wrong.
"""

import errno
import json
import os
import time
import zlib
from typing import Callable, Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

MANIFEST_NAME = "manifest.json"
# v1: {version, tag, files}. v2 adds an optional "topology" block (world
# size, zero stage, axis sizes, per-leaf partition specs) so an elastic
# resume on a different device count is DETECTED and resharded instead of
# failing. v1 manifests stay loadable: no topology block means the saved
# topology is unknowable, so only same-topology resume is supported
# (runtime/reshard.py raises a clear error naming TOPOLOGY_FIELDS when a
# topology change was expected).
MANIFEST_VERSION = 2
# fields of the v2 topology block, named in back-compat error messages
TOPOLOGY_FIELDS = ("world_size", "zero_stage", "axis_sizes",
                   "partition_specs")
LATEST_NAME = "latest"
LAST_VALID_TAG_ENV = "DS_TPU_LAST_VALID_TAG"

# Transient-IO retry policy. Read at call time (not bound as argument
# defaults) so tests and deployments can tune them on the module.
IO_RETRIES = 3
IO_BACKOFF_S = 0.1

# OSErrors that no amount of retrying will fix — surface them immediately.
_PERMANENT_ERRNOS = frozenset({errno.ENOSPC, errno.EDQUOT, errno.EROFS})


def _fsync_dir(path: str):
    """fsync a DIRECTORY so a rename into it survives power loss (POSIX
    does not promise the dirent is durable until the dir itself is
    synced). Best-effort: some filesystems refuse O_RDONLY dir fsync."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def retry_io(fn: Callable, what: str, retries: Optional[int] = None,
             backoff_s: Optional[float] = None):
    """Run ``fn()`` retrying transient ``OSError`` with exponential backoff.

    Returns ``(result, attempts_failed)`` so callers can export a retry
    counter. Non-OSError exceptions and permanently-fatal errnos (ENOSPC,
    EROFS, ...) propagate immediately.
    """
    retries = IO_RETRIES if retries is None else retries
    backoff_s = IO_BACKOFF_S if backoff_s is None else backoff_s
    failures = 0
    while True:
        try:
            return fn(), failures
        except OSError as e:
            if e.errno in _PERMANENT_ERRNOS or failures >= retries:
                raise
            failures += 1
            delay = backoff_s * (2 ** (failures - 1))
            logger.warning(
                "transient IO failure (%s): %s; retry %d/%d in %.2fs",
                what, e, failures, retries, delay)
            if delay > 0:
                time.sleep(delay)


def payload_digest(payload: bytes) -> Dict[str, object]:
    """Size + crc32 of an in-memory payload (manifest entry shape)."""
    return {"bytes": len(payload), "crc32": f"{zlib.crc32(payload):08x}"}


def file_digest(path: str, chunk_size: int = 1 << 20) -> Dict[str, object]:
    """Streamed size + crc32 of a file on disk."""
    crc = 0
    size = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(chunk_size)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
            size += len(chunk)
    return {"bytes": size, "crc32": f"{crc:08x}"}


def atomic_write_bytes(path: str, payload: bytes,
                       retries: Optional[int] = None,
                       backoff_s: Optional[float] = None) -> int:
    """Durably write ``payload`` to ``path``: write a sibling tmp file,
    flush + fsync it, ``os.replace`` over the target, fsync the parent
    dir. Transient OSErrors retry the whole open/write/replace cycle.
    Returns the number of failed attempts (for retry counters)."""
    parent = os.path.dirname(path) or "."
    os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"

    def _once():
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(parent)

    try:
        _, failures = retry_io(_once, what=path, retries=retries,
                               backoff_s=backoff_s)
    finally:
        # a failed attempt may leave the tmp file; never leave it to be
        # mistaken for checkpoint data
        try:
            if os.path.exists(tmp):
                os.unlink(tmp)
        except OSError:
            pass
    return failures


# ---------------------------------------------------------------------------
# manifest
# ---------------------------------------------------------------------------
def manifest_path(tag_dir: str) -> str:
    return os.path.join(tag_dir, MANIFEST_NAME)


def write_manifest(tag_dir: str, tag: str,
                   files: Dict[str, Dict[str, object]],
                   topology: Optional[Dict] = None) -> str:
    """Write ``tag_dir/manifest.json`` naming every file of the tag with
    its size and crc32, plus (v2) the topology the state was laid out for.
    Written durably LAST, so its presence certifies the whole tag: a crash
    at any earlier point leaves a tag without a manifest, which loads
    treat as never-committed."""
    doc = {
        "version": MANIFEST_VERSION,
        "tag": str(tag),
        "files": {name: dict(entry) for name, entry in sorted(files.items())},
    }
    if topology is not None:
        doc["topology"] = topology
    payload = json.dumps(doc, indent=2, sort_keys=True).encode()
    path = manifest_path(tag_dir)
    atomic_write_bytes(path, payload)
    return path


def read_manifest(tag_dir: str) -> Optional[Dict]:
    """Parsed manifest, or None when absent/unreadable (legacy tag)."""
    try:
        with open(manifest_path(tag_dir)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def manifest_topology(tag_dir: str) -> Optional[Dict]:
    """The tag's saved topology block, or None for v1/absent manifests
    (pre-topology-metadata checkpoints: same-topology resume only)."""
    manifest = read_manifest(tag_dir)
    if manifest is None:
        return None
    return manifest.get("topology")


def verify_tag_dir(tag_dir: str, check_data: bool = True
                   ) -> Optional[List[str]]:
    """Check a tag directory against its manifest.

    Returns ``[]`` when every listed file exists with the recorded size
    (and crc32 when ``check_data``), a list of human-readable problems on
    mismatch, or ``None`` when there is no manifest to check (pre-manifest
    checkpoint — the caller decides whether to trust it)."""
    manifest = read_manifest(tag_dir)
    if manifest is None:
        return None
    problems = []
    for name, entry in manifest.get("files", {}).items():
        path = os.path.join(tag_dir, name)
        if not os.path.exists(path):
            problems.append(f"missing file: {name}")
            continue
        size = os.path.getsize(path)
        if size != entry.get("bytes"):
            problems.append(
                f"size mismatch: {name} has {size} bytes, manifest says "
                f"{entry.get('bytes')}")
            continue
        if check_data:
            crc = file_digest(path)["crc32"]
            if crc != entry.get("crc32"):
                problems.append(
                    f"crc mismatch: {name} is {crc}, manifest says "
                    f"{entry.get('crc32')}")
    return problems


def find_valid_tags(base_dir: str, check_data: bool = True,
                    exclude=()) -> List[str]:
    """Tags under ``base_dir`` whose manifest verifies, newest first
    (manifest mtime — commit order — with dir name as tiebreaker)."""
    if not os.path.isdir(base_dir):
        return []
    candidates = []
    for name in os.listdir(base_dir):
        if name in exclude:
            continue
        tag_dir = os.path.join(base_dir, name)
        if not os.path.isdir(tag_dir):
            continue
        mpath = manifest_path(tag_dir)
        if not os.path.exists(mpath):
            continue
        if verify_tag_dir(tag_dir, check_data=check_data) == []:
            candidates.append((os.path.getmtime(mpath), name))
    return [name for _, name in sorted(candidates, reverse=True)]


def latest_valid_tag(base_dir: str, check_data: bool = True,
                     exclude=()) -> Optional[str]:
    tags = find_valid_tags(base_dir, check_data=check_data, exclude=exclude)
    return tags[0] if tags else None


# ---------------------------------------------------------------------------
# 'latest' pointer
# ---------------------------------------------------------------------------
def write_latest(save_dir: str, tag: str):
    """Atomically + durably update the ``latest`` pointer: a crash mid-
    write can never leave a truncated pointer wedging recovery."""
    atomic_write_bytes(os.path.join(save_dir, LATEST_NAME),
                       str(tag).encode())


def read_latest(load_dir: str) -> Optional[str]:
    try:
        with open(os.path.join(load_dir, LATEST_NAME)) as f:
            tag = f.read().strip()
        return tag or None
    except OSError:
        return None

"""Activation checkpointing — the TPU-native form of reference
``deepspeed/runtime/activation_checkpointing/checkpointing.py``.

The reference's ``CheckpointFunction`` (:499) re-runs the forward in the
backward pass with manually stashed RNG states, optionally partitioning the
stored activations across model-parallel ranks (:373) or moving them to CPU.
On TPU all of that maps onto ``jax.checkpoint`` (remat):

* recompute-in-backward  → ``jax.checkpoint(fn, policy=...)``
* ``partition_activations`` → saved residuals inherit the pjit shardings of
  the inputs, so under tensor parallelism they are *already* partitioned;
  the flag is accepted and simply documents intent.
* ``cpu_checkpointing`` → offload policy (``save_and_offload_only_these_names``
  on named residuals / ``offload_dot_with_no_batch_dims``): residuals live in
  host memory between forward and backward.
* Megatron RNG-state tracker (:122-241) → explicit key splitting; a small
  named-key tracker is provided for porting Megatron-style dropout code.

``configure()`` / ``is_configured()`` / ``checkpoint()`` keep the reference's
module-level API so engine and user code can be written identically.
"""

import threading
from typing import Any, Callable, Optional

import jax

_CONFIG = None
_LOCK = threading.Lock()


def policy_from_config(ac_config=None, remat: str = "full"):
    """Map a config block to a ``jax.checkpoint`` rematerialization policy.

    ``remat`` mirrors the tpu.remat config key: ``none`` (save everything —
    checkpointing disabled), ``full`` (save nothing, recompute all), or
    ``selective`` (save matmul outputs, recompute elementwise — the right
    default on TPU where recomputing MXU work is expensive but VPU work is
    cheap).
    """
    cp = jax.checkpoint_policies
    if ac_config is not None and getattr(ac_config, "cpu_checkpointing",
                                         False):
        # keep dot outputs, but in host memory between fwd and bwd
        return cp.offload_dot_with_no_batch_dims("device", "pinned_host")
    if remat == "none":
        return cp.everything_saveable
    if remat == "selective":
        return cp.dots_with_no_batch_dims_saveable
    if remat == "full":
        return cp.nothing_saveable
    raise ValueError(f"unknown remat policy {remat!r}")


class _ActCkptState:
    def __init__(self, ac_config=None, remat: str = "full"):
        self.config = ac_config
        self.remat = remat
        self.policy = policy_from_config(ac_config, remat)
        self.profile = bool(getattr(ac_config, "profile", False))
        self.number_checkpoints = getattr(ac_config, "number_checkpoints",
                                          None)


def configure(deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None,
              remat: str = "full"):
    """Module-level setup (reference checkpointing.py ``configure``).

    Accepts either an engine config object carrying an
    ``activation_checkpointing`` block or the reference's keyword flags.
    """
    global _CONFIG
    ac = None
    if deepspeed_config is not None:
        ac = getattr(deepspeed_config, "activation_checkpointing", None)
    if ac is None:
        from deepspeed_tpu.runtime.config import \
            ActivationCheckpointingConfig
        ac = ActivationCheckpointingConfig()
    if partition_activations is not None:
        ac.partition_activations = partition_activations
    if contiguous_checkpointing is not None:
        ac.contiguous_memory_optimization = contiguous_checkpointing
    if num_checkpoints is not None:
        ac.number_checkpoints = num_checkpoints
    if checkpoint_in_cpu is not None:
        ac.cpu_checkpointing = checkpoint_in_cpu
    if profile is not None:
        ac.profile = profile
    with _LOCK:
        _CONFIG = _ActCkptState(ac, remat)
    return _CONFIG


def is_configured() -> bool:
    return _CONFIG is not None


def reset():
    global _CONFIG
    with _LOCK:
        _CONFIG = None


def checkpoint(function: Callable, *args, policy=None, static_argnums=(),
               prevent_cse: bool = False, **kwargs) -> Any:
    """Run ``function`` under remat (reference ``CheckpointFunction.apply``).

    Unlike the reference this returns the value of a *traced, differentiable*
    call: ``jax.grad`` through it recomputes the forward instead of reading
    stored activations.
    """
    state = _CONFIG or _ActCkptState()
    fn = jax.checkpoint(
        function,
        policy=policy if policy is not None else state.policy,
        prevent_cse=prevent_cse,
        static_argnums=static_argnums,
    )
    return fn(*args, **kwargs)


def checkpoint_wrapper(function: Callable, policy=None,
                       prevent_cse: bool = False,
                       static_argnums=()) -> Callable:
    """Decorator form: returns a remat-wrapped callable honoring the
    configured policy at call time."""

    def wrapped(*args, **kwargs):
        return checkpoint(function, *args, policy=policy,
                          prevent_cse=prevent_cse,
                          static_argnums=static_argnums, **kwargs)

    return wrapped


# ``CheckpointFunction`` in the reference is a torch.autograd.Function; here
# the callable itself is the whole mechanism.
CheckpointFunction = checkpoint_wrapper


# ---------------------------------------------------------------------------
# RNG tracker (reference checkpointing.py:122-241 Megatron CudaRNGStatesTracker)
# ---------------------------------------------------------------------------
class RNGStateTracker:
    """Named deterministic RNG streams for porting Megatron-style code.

    JAX RNG is functional, so "checkpoint and restore generator state" is
    simply "re-split the same key": forked streams are reproducible by
    construction, which is exactly the property the reference's state
    save/restore machinery exists to guarantee.
    """

    def __init__(self):
        self._keys = {}
        self._counts = {}

    def add(self, name: str, seed_or_key):
        if name in self._keys:
            raise ValueError(f"rng state {name!r} already added")
        key = (jax.random.PRNGKey(seed_or_key)
               if isinstance(seed_or_key, int) else seed_or_key)
        self._keys[name] = key
        self._counts[name] = 0

    def get_states(self):
        return dict(self._keys), dict(self._counts)

    def set_states(self, states):
        self._keys, self._counts = dict(states[0]), dict(states[1])

    def fork(self, name: str = "model-parallel-rng"):
        """Next key in the named stream (call-counted, deterministic)."""
        if name not in self._keys:
            raise KeyError(f"rng state {name!r} was never added")
        count = self._counts[name]
        self._counts[name] = count + 1
        return jax.random.fold_in(self._keys[name], count)

    def reset(self):
        self._keys.clear()
        self._counts.clear()


_RNG_TRACKER = RNGStateTracker()


def get_rng_tracker() -> RNGStateTracker:
    return _RNG_TRACKER


def model_parallel_reconfigure(seed: int,
                               tp_rank: Optional[int] = None) -> None:
    """Seed the tracker with per-TP-rank decorrelated streams (reference
    ``model_parallel_cuda_manual_seed``): same ``seed`` everywhere, dropout
    stream offset by the tensor-parallel coordinate."""
    _RNG_TRACKER.reset()
    base = jax.random.PRNGKey(seed)
    _RNG_TRACKER.add("default", base)
    mp_key = jax.random.fold_in(base, 2718 + (tp_rank or 0))
    _RNG_TRACKER.add("model-parallel-rng", mp_key)

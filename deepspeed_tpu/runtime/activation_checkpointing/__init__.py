from deepspeed_tpu.runtime.activation_checkpointing.checkpointing import (  # noqa: F401
    CheckpointFunction,
    checkpoint,
    checkpoint_wrapper,
    configure,
    get_rng_tracker,
    is_configured,
    model_parallel_reconfigure,
    policy_from_config,
    reset,
)

"""Checkpoint re-layout across device-count changes (elastic resume).

A ZeRO-partitioned state tree checkpointed at N devices is re-laid-out for
N' in three phases:

1. **detect** — read the saved topology block from the tag's manifest
   (``checkpoint_manifest.manifest_topology``) and diff it against the live
   :class:`MeshTopology` (``layout.topology_matches``). No block (a v1
   manifest) means the saved topology is unknowable: only same-topology
   resume is safe, and an *expected* topology change becomes a clear error
   instead of silent corruption.
2. **gather / verify** — checkpoints store LOGICAL (global) arrays
   (``checkpoint_engine._to_host`` gathers shards at save), so the gather
   already happened at save time; what remains is verifying each loaded
   leaf's global shape against the per-leaf record saved alongside the
   partition specs, so a leaf that drifted (truncated file, wrong tag)
   fails here with a named path instead of inside ``device_put``.
3. **place** — re-partition every leaf against the NEW topology's sharding
   tree (a jit identity with ``out_shardings``, exactly the engine's
   normal load path — resharding is a property of placement, not a
   separate copy pass).

The caller (``DeepSpeedEngine.load_checkpoint``) stitches the phases into
an ``elastic.reshard`` telemetry event with per-phase timings.
"""

import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from deepspeed_tpu.parallel.mesh import MeshTopology
from deepspeed_tpu.runtime import checkpoint_manifest as cm
from deepspeed_tpu.runtime import layout
from deepspeed_tpu.runtime.constants import ELASTIC_PREV_WORLD_ENV
from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.utils.tree import flatten_dots


class ReshardError(RuntimeError):
    """A topology-changed load that cannot proceed safely."""


@dataclass
class ReshardDecision:
    """Outcome of the detect phase for one (tag, live-topology) pair."""

    saved: Optional[Dict[str, Any]]  # manifest topology block (None = v1)
    mismatches: List[str] = field(default_factory=list)
    detect_s: float = 0.0

    @property
    def needed(self) -> bool:
        return bool(self.mismatches)

    @property
    def saved_world(self) -> Optional[int]:
        if self.saved is None:
            return None
        ws = self.saved.get("world_size")
        return None if ws is None else int(ws)

    def describe(self) -> str:
        if self.saved is None:
            return "no saved topology metadata (pre-v2 manifest)"
        if not self.mismatches:
            return "saved topology matches live topology"
        return "topology changed: " + ", ".join(self.mismatches)


def decide(load_dir: str, tag: str, topology: MeshTopology,
           zero_stage: Optional[int] = None,
           expect_reshard: Optional[bool] = None) -> ReshardDecision:
    """Detect phase. ``expect_reshard`` is the elastic agent's signal
    (``DS_TPU_ELASTIC_PREV_WORLD`` differing from the live world): when a
    reshard is expected but the manifest predates topology metadata, the
    load must fail loudly — the fields needed to verify the re-layout
    simply are not there."""
    t0 = time.monotonic()
    saved = cm.manifest_topology(os.path.join(load_dir, str(tag)))
    if expect_reshard is None:
        prev = os.environ.get(ELASTIC_PREV_WORLD_ENV)
        expect_reshard = (prev is not None
                          and int(prev) != topology.num_devices)
    if saved is None:
        if expect_reshard:
            raise ReshardError(
                f"checkpoint tag {tag!r} at {load_dir} predates topology "
                f"metadata (manifest version < {cm.MANIFEST_VERSION}: "
                f"missing fields "
                f"{', '.join(cm.TOPOLOGY_FIELDS)}). A topology-changed "
                f"resume needs them to verify the re-layout; only "
                f"same-topology resume is supported for this checkpoint. "
                f"Re-save once on the original topology to upgrade it.")
        return ReshardDecision(saved=None,
                               detect_s=time.monotonic() - t0)
    mismatches = layout.topology_matches(saved, topology,
                                         zero_stage=zero_stage)
    return ReshardDecision(saved=saved, mismatches=mismatches,
                           detect_s=time.monotonic() - t0)


def verify_state_dict(state_sd: Dict[str, Any],
                      saved_specs: Dict[str, Dict[str, Any]],
                      label: str) -> Tuple[int, float]:
    """Gather/verify phase: every loaded leaf whose path has a saved
    per-leaf record must match the recorded GLOBAL shape (the checkpoint
    stores logical arrays, so the shapes are topology-independent — a
    mismatch means the payload is not what the manifest described).
    Returns (leaves verified, elapsed seconds); raises ReshardError with
    the offending paths on mismatch."""
    t0 = time.monotonic()
    flat = flatten_dots(state_sd)
    bad: List[str] = []
    checked = 0
    for key, leaf in flat.items():
        rec = saved_specs.get(key.replace(".", "/"))
        if rec is None or "shape" not in rec:
            continue
        checked += 1
        want = tuple(int(d) for d in rec["shape"])
        got = tuple(np.shape(leaf))
        if want != got:
            bad.append(f"{key}: saved {want}, loaded {got}")
    if bad:
        raise ReshardError(
            f"{label} state does not match the saved partition record for "
            f"{len(bad)} leaf/leaves: " + "; ".join(bad[:5])
            + ("; ..." if len(bad) > 5 else ""))
    return checked, time.monotonic() - t0


def gather_tree(tree: Any) -> Any:
    """Gather device arrays (sharded or not) to host numpy copies — the
    logical view a checkpoint stores, and the interchange format between
    two topologies (used by the N -> N' -> N round-trip tests and any
    in-process re-layout that skips the filesystem)."""
    return jax.tree.map(
        lambda x: np.array(jax.device_get(x), copy=True), tree)


def place_tree(tree: Any, shardings: Any) -> Tuple[Any, float]:
    """Place phase: partition host (or differently-sharded device) leaves
    against a sharding tree. The jit identity with ``out_shardings`` is the
    engine's own load-path placement — XLA moves/reshards each leaf.
    Returns (placed tree, elapsed seconds); the placed tree is block_until_
    ready so the timing covers the actual transfer."""
    t0 = time.monotonic()
    placed = jax.jit(lambda t: t, out_shardings=shardings)(tree)
    jax.block_until_ready(placed)
    return placed, time.monotonic() - t0


def reshard_tree(tree: Any, shardings: Any) -> Tuple[Any, Dict[str, float]]:
    """Gather + place in one call: re-lay-out a live tree (sharded for one
    topology) against another topology's sharding tree. The explicit host
    hop is what makes cross-MESH movement legal — a jit identity cannot
    consume arrays committed to a different mesh's devices."""
    t0 = time.monotonic()
    host = gather_tree(tree)
    gather_s = time.monotonic() - t0
    placed, place_s = place_tree(host, shardings)
    return placed, {"gather_s": gather_s, "place_s": place_s,
                    "total_s": gather_s + place_s}

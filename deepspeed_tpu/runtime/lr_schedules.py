"""Learning-rate schedules.

Parity with reference ``deepspeed/runtime/lr_schedules.py`` (854 LoC):
LRRangeTest (:308), OneCycle (:415), WarmupLR (:704), WarmupDecayLR (:800).

TPU re-design: each schedule is a pure, **trace-safe** ``step -> lr`` function
(built from ``jnp.where`` so it runs inside the jitted train step — the lr is
computed on device each step instead of being fed from host), wrapped in a
stateful object exposing the reference's ``step()/get_lr()/state_dict()``
surface for host-side parity.
"""

import math
from typing import Any, Callable, Dict, List, Optional

import jax.numpy as jnp

LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR]


# ---------------------------------------------------------------------------
# Pure schedule functions (jit-safe: step may be a traced array)
# ---------------------------------------------------------------------------
def lr_range_test_fn(lr_range_test_min_lr: float = 1e-3,
                     lr_range_test_step_size: int = 2000,
                     lr_range_test_step_rate: float = 1.0,
                     lr_range_test_staircase: bool = False,
                     **_) -> Callable:
    """reference lr_schedules.py:308 — continuous/staircase LR ramp."""

    def fn(step):
        step = jnp.asarray(step, jnp.float32)
        interval = (jnp.floor(step / lr_range_test_step_size)
                    if lr_range_test_staircase
                    else step / lr_range_test_step_size)
        return lr_range_test_min_lr * (1.0 + interval * lr_range_test_step_rate)

    return fn


def one_cycle_fn(cycle_min_lr: float, cycle_max_lr: float,
                 cycle_first_step_size: int = 2000,
                 cycle_second_step_size: Optional[int] = None,
                 cycle_first_stair_count: int = 0,
                 cycle_second_stair_count: Optional[int] = None,
                 decay_step_size: int = 0,
                 decay_lr_rate: float = 0.0,
                 **_) -> Callable:
    """reference lr_schedules.py:415 — triangular cycle + optional decay."""
    second = (cycle_second_step_size if cycle_second_step_size is not None
              else cycle_first_step_size)
    total_cycle = cycle_first_step_size + second

    def fn(step):
        step = jnp.asarray(step, jnp.float32)
        up = cycle_min_lr + (cycle_max_lr - cycle_min_lr) * (
            step / cycle_first_step_size
        )
        down = cycle_max_lr - (cycle_max_lr - cycle_min_lr) * (
            (step - cycle_first_step_size) / second
        )
        if decay_step_size > 0:
            decay_steps = (step - total_cycle) / decay_step_size
            tail = cycle_min_lr / (1.0 + decay_steps * decay_lr_rate)
        else:
            tail = jnp.float32(cycle_min_lr)
        return jnp.where(
            step <= cycle_first_step_size, up,
            jnp.where(step <= total_cycle, down, tail),
        )

    return fn


def warmup_lr_fn(warmup_min_lr: float = 0.0, warmup_max_lr: float = 0.001,
                 warmup_num_steps: int = 1000, warmup_type: str = "log",
                 **_) -> Callable:
    """reference lr_schedules.py:704 — log/linear warmup then constant."""
    log_denom = math.log(max(warmup_num_steps, 2))

    def fn(step):
        step = jnp.asarray(step, jnp.float32)
        if warmup_type == "log":
            gamma = jnp.log(step + 1.0) / log_denom
        else:
            gamma = step / max(warmup_num_steps, 1)
        gamma = jnp.clip(gamma, 0.0, 1.0)
        warm = warmup_min_lr + (warmup_max_lr - warmup_min_lr) * gamma
        return jnp.where(step < warmup_num_steps, warm, warmup_max_lr)

    return fn


def warmup_decay_lr_fn(total_num_steps: int, warmup_min_lr: float = 0.0,
                       warmup_max_lr: float = 0.001, warmup_num_steps: int = 1000,
                       warmup_type: str = "log", **_) -> Callable:
    """reference lr_schedules.py:800 — warmup then linear decay to 0."""
    warm = warmup_lr_fn(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)

    def fn(step):
        step = jnp.asarray(step, jnp.float32)
        frac = (total_num_steps - step) / max(1, total_num_steps - warmup_num_steps)
        decay = warmup_max_lr * jnp.clip(frac, 0.0, 1.0)
        return jnp.where(step < warmup_num_steps, warm(step), decay)

    return fn


_FACTORIES = {
    LR_RANGE_TEST: lr_range_test_fn,
    ONE_CYCLE: one_cycle_fn,
    WARMUP_LR: warmup_lr_fn,
    WARMUP_DECAY_LR: warmup_decay_lr_fn,
}


def schedule_fn_from_config(sched_type: str, params: Dict[str, Any]) -> Callable:
    if sched_type not in _FACTORIES:
        raise ValueError(
            f"Unknown scheduler type {sched_type!r}; valid: {VALID_LR_SCHEDULES}"
        )
    return _FACTORIES[sched_type](**params)


# ---------------------------------------------------------------------------
# Stateful wrappers (reference object API)
# ---------------------------------------------------------------------------
class LRScheduler:
    """step()/get_lr()/get_last_lr()/state_dict() surface of the reference
    schedulers, driving a pure schedule function."""

    def __init__(self, schedule_fn: Callable, last_batch_iteration: int = -1):
        self.schedule_fn = schedule_fn
        self.last_batch_iteration = last_batch_iteration
        self._last_lr: List[float] = self.get_lr()

    def get_lr(self) -> List[float]:
        return [float(self.schedule_fn(max(0, self.last_batch_iteration)))]

    def get_last_lr(self) -> List[float]:
        return list(self._last_lr)

    def step(self, last_batch_iteration: Optional[int] = None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration
        self._last_lr = self.get_lr()

    def state_dict(self) -> Dict[str, Any]:
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd: Dict[str, Any]):
        self.last_batch_iteration = sd["last_batch_iteration"]
        self._last_lr = self.get_lr()


class WarmupLR(LRScheduler):
    def __init__(self, warmup_min_lr=0.0, warmup_max_lr=0.001,
                 warmup_num_steps=1000, warmup_type="log",
                 last_batch_iteration=-1, **_):
        super().__init__(
            warmup_lr_fn(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type),
            last_batch_iteration,
        )


class WarmupDecayLR(LRScheduler):
    def __init__(self, total_num_steps, warmup_min_lr=0.0, warmup_max_lr=0.001,
                 warmup_num_steps=1000, warmup_type="log",
                 last_batch_iteration=-1, **_):
        super().__init__(
            warmup_decay_lr_fn(total_num_steps, warmup_min_lr, warmup_max_lr,
                               warmup_num_steps, warmup_type),
            last_batch_iteration,
        )


class OneCycle(LRScheduler):
    def __init__(self, cycle_min_lr, cycle_max_lr, **kwargs):
        last = kwargs.pop("last_batch_iteration", -1)
        super().__init__(one_cycle_fn(cycle_min_lr, cycle_max_lr, **kwargs), last)


class LRRangeTest(LRScheduler):
    def __init__(self, **kwargs):
        last = kwargs.pop("last_batch_iteration", -1)
        super().__init__(lr_range_test_fn(**kwargs), last)


def build_lr_scheduler(sched_type: str, params: Dict[str, Any]) -> LRScheduler:
    return LRScheduler(schedule_fn_from_config(sched_type, params))

from deepspeed_tpu.runtime.swap_tensor.swapper import (  # noqa: F401
    AsyncTensorSwapper,
    PipelinedOptimizerSwapper,
    OptimizerStateSwapper,
)

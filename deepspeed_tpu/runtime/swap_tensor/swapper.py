"""Tensor swapping to local SSD (reference ``runtime/swap_tensor/``:
AsyncPartitionedParameterSwapper, PartitionedOptimizerSwapper ~1970 LoC).

TPU re-design: swapping is a host-side concern — arrays move
device -> host -> file via the aio threadpool, overlapped with compute by
queueing writes right after the values are produced and reads right before
they are needed. ``AsyncTensorSwapper`` is the generic array<->file engine;
``OptimizerStateSwapper`` applies it to an optimizer-state pytree between
steps (the ZeRO-Infinity "NVMe tier" for optimizer states).
"""

import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.ops.aio import AioHandle
from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.utils.tree import flatten_dots, unflatten_dots


class AsyncTensorSwapper:
    """Swap named numpy arrays to files under a swap dir
    (reference async_swapper.py:17)."""

    def __init__(self, swap_dir: str, num_threads: int = 4):
        self.swap_dir = swap_dir
        os.makedirs(swap_dir, exist_ok=True)
        self.handle = AioHandle(num_threads)
        self._meta: Dict[str, Tuple[tuple, Any]] = {}

    def _path(self, name: str) -> str:
        import hashlib

        # readable prefix + hash of the ORIGINAL name: sanitization maps
        # '.', '/', '_' onto one character, so distinct names could
        # otherwise share a file
        safe = name.replace("/", "_").replace(".", "_")[:80]
        digest = hashlib.sha1(name.encode()).hexdigest()[:10]
        return os.path.join(self.swap_dir, f"{safe}.{digest}.swp")

    def swap_out(self, name: str, array: np.ndarray,
                 handle: Optional[AioHandle] = None) -> None:
        arr = np.ascontiguousarray(array)
        self._meta[name] = (arr.shape, arr.dtype)
        (handle or self.handle).async_pwrite(arr, self._path(name))

    def swap_in(self, name: str, out: Optional[np.ndarray] = None,
                handle: Optional[AioHandle] = None) -> np.ndarray:
        if name not in self._meta:
            raise KeyError(f"{name} was never swapped out")
        shape, dtype = self._meta[name]
        if out is None:
            out = np.empty(shape, dtype=dtype)
        (handle or self.handle).async_pread(out, self._path(name))
        return out

    def wait(self) -> None:
        self.handle.wait()

    def remove(self, name: str) -> None:
        """Drop a blob (file + metadata). Callers must ensure no queued op
        still targets it (wait() first)."""
        self._meta.pop(name, None)
        path = self._path(name)
        if os.path.exists(path):
            os.remove(path)

    def swapped_names(self):
        return sorted(self._meta)

    def bytes_on_disk(self) -> int:
        return sum(os.path.getsize(self._path(n)) for n in self._meta
                   if os.path.exists(self._path(n)))


class PipelinedOptimizerSwapper(AsyncTensorSwapper):
    """Double-buffered moment swapping (reference
    ``swap_tensor/pipelined_optimizer_swapper.py:27``): while sub-group N's
    host optimizer math runs, sub-group N+1's moment READ and sub-group
    N-1's WRITE are in flight on separate aio handles, so disk time hides
    behind compute instead of serializing with it.

    ``run_step(sizes, update_fn, first_step, num_groups)`` drives one full
    optimizer step: ``update_fn(i, m, v)`` is called for every tensor index
    with its moment buffers resident. The plain
    :class:`AsyncTensorSwapper` surface (``swap_in``/``swap_out`` on the
    shared handle) stays available for checkpointing.
    """

    def __init__(self, swap_dir: str, num_threads: int = 4):
        super().__init__(swap_dir, num_threads)
        self.read_handles = (AioHandle(num_threads), AioHandle(num_threads))
        self.write_handles = (AioHandle(num_threads), AioHandle(num_threads))
        # two group-slots of reusable moment buffers: fresh np.empty every
        # step page-faults the whole state and doubles the compute time
        self._pool: Dict[Tuple[int, int], Tuple[np.ndarray, np.ndarray]] = {}
        self._dirty = False

    def _pooled(self, slot: int, k: int, size: int):
        buf = self._pool.get((slot, k))
        if buf is None or buf[0].size < size:
            buf = (np.empty(size, np.float32), np.empty(size, np.float32))
            self._pool[(slot, k)] = buf
        return buf[0][:size], buf[1][:size]

    def flush(self) -> None:
        """Drain writes deferred past the end of the last ``run_step``."""
        if self._dirty:
            for w in self.write_handles:
                w.wait()
            self._dirty = False

    def wait(self) -> None:
        self.flush()
        super().wait()

    def swap_in(self, name: str, out=None, handle=None):
        # checkpoint reads via the plain surface must not race the
        # deferred tail writes of the same files
        if handle is None:
            self.flush()
        return super().swap_in(name, out, handle)

    def swap_out(self, name: str, array, handle=None):
        # two in-flight writes to one file complete in nondeterministic
        # order — checkpoint writes must drain the deferred tail first
        if handle is None:
            self.flush()
        return super().swap_out(name, array, handle)

    def run_step(self, sizes, update_fn, first_step: bool,
                 num_groups: int = 4) -> None:
        n = len(sizes)
        num_groups = max(1, min(num_groups, n))
        bounds = np.linspace(0, n, num_groups + 1).astype(int)
        groups = [range(bounds[g], bounds[g + 1])
                  for g in range(num_groups)]
        buffers = {}

        def issue_reads(g, h):
            for k, i in enumerate(groups[g]):
                m, v = self._pooled(g % 3, k, sizes[i])
                if first_step:  # moments not on disk yet
                    m[...] = 0.0
                    v[...] = 0.0
                else:
                    self.swap_in(f"m{i}", m, handle=h)
                    self.swap_in(f"v{i}", v, handle=h)
                buffers[i] = (m, v)

        def issue_writes(g, h):
            for i in groups[g]:
                m, v = buffers.pop(i)
                self.swap_out(f"m{i}", m, handle=h)
                self.swap_out(f"v{i}", v, handle=h)

        # While group g computes, group g+1's READ and group g-1's WRITE
        # are both in flight (three live buffer slots make that legal:
        # read target, compute, write source). Slot (g+1)%3 was last used
        # by group g-2, whose writes — issued two iterations ago — are
        # waited just before the slot is reused, so that wait is almost
        # always free. The final group's writes drain during the next
        # step's device forward/backward window, waited only at the next
        # run_step / flush — the reference PipelinedOptimizerSwapper's
        # async write-behind (pipelined_optimizer_swapper.py:27).
        self.flush()
        issue_reads(0, self.read_handles[0])
        self.read_handles[0].wait()
        for g in range(num_groups):
            if g + 1 < num_groups:
                self.write_handles[g % 2].wait()  # slot (g+1)%3 free?
                issue_reads(g + 1, self.read_handles[(g + 1) % 2])
            for i in groups[g]:
                m, v = buffers[i]
                update_fn(i, m, v)
            issue_writes(g, self.write_handles[g % 2])
            if g + 1 < num_groups and not first_step:
                self.read_handles[(g + 1) % 2].wait()
        self._dirty = True


class OptimizerStateSwapper:
    """Swap a whole optimizer-state pytree (reference
    optimizer_utils.py:27 PartitionedOptimizerSwapper).

    ``swap_out_tree(state)`` writes every array leaf and returns a
    skeleton; ``swap_in_tree()`` reconstructs the pytree. The caller
    overlap-pattern is: swap_out right after step N's apply, swap_in right
    before step N+1's apply.
    """

    def __init__(self, swap_dir: str, num_threads: int = 4):
        self.swapper = AsyncTensorSwapper(swap_dir, num_threads)
        self._skeleton = None

    def swap_out_tree(self, state) -> None:
        import jax

        host = jax.device_get(state)
        flat = flatten_dots(host, keep_empty_nodes=True)
        self._skeleton = {}
        for name, leaf in flat.items():
            if hasattr(leaf, "shape") and getattr(leaf, "size", 0) > 0:
                self.swapper.swap_out(name, np.asarray(leaf))
                self._skeleton[name] = None  # swapped marker
            else:
                self._skeleton[name] = leaf  # scalars/empties stay resident
        self.swapper.wait()
        logger.info(
            f"optimizer state swapped out: "
            f"{self.swapper.bytes_on_disk() / 1e6:.1f} MB on disk")

    def swap_in_tree(self):
        if self._skeleton is None:
            raise RuntimeError("nothing swapped out")
        flat = {}
        for name, leaf in self._skeleton.items():
            if leaf is None:
                flat[name] = self.swapper.swap_in(name)
            else:
                flat[name] = leaf
        self.swapper.wait()
        return unflatten_dots(flat)

"""Tensor swapping to local SSD (reference ``runtime/swap_tensor/``:
AsyncPartitionedParameterSwapper, PartitionedOptimizerSwapper ~1970 LoC).

TPU re-design: swapping is a host-side concern — arrays move
device -> host -> file via the aio threadpool, overlapped with compute by
queueing writes right after the values are produced and reads right before
they are needed. ``AsyncTensorSwapper`` is the generic array<->file engine;
``OptimizerStateSwapper`` applies it to an optimizer-state pytree between
steps (the ZeRO-Infinity "NVMe tier" for optimizer states).
"""

import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.ops.aio import AioHandle
from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.utils.tree import flatten_dots, unflatten_dots


class AsyncTensorSwapper:
    """Swap named numpy arrays to files under a swap dir
    (reference async_swapper.py:17)."""

    def __init__(self, swap_dir: str, num_threads: int = 4):
        self.swap_dir = swap_dir
        os.makedirs(swap_dir, exist_ok=True)
        self.handle = AioHandle(num_threads)
        self._meta: Dict[str, Tuple[tuple, Any]] = {}

    def _path(self, name: str) -> str:
        import hashlib

        # readable prefix + hash of the ORIGINAL name: sanitization maps
        # '.', '/', '_' onto one character, so distinct names could
        # otherwise share a file
        safe = name.replace("/", "_").replace(".", "_")[:80]
        digest = hashlib.sha1(name.encode()).hexdigest()[:10]
        return os.path.join(self.swap_dir, f"{safe}.{digest}.swp")

    def swap_out(self, name: str, array: np.ndarray) -> None:
        arr = np.ascontiguousarray(array)
        self._meta[name] = (arr.shape, arr.dtype)
        self.handle.async_pwrite(arr, self._path(name))

    def swap_in(self, name: str,
                out: Optional[np.ndarray] = None) -> np.ndarray:
        if name not in self._meta:
            raise KeyError(f"{name} was never swapped out")
        shape, dtype = self._meta[name]
        if out is None:
            out = np.empty(shape, dtype=dtype)
        self.handle.async_pread(out, self._path(name))
        return out

    def wait(self) -> None:
        self.handle.wait()

    def swapped_names(self):
        return sorted(self._meta)

    def bytes_on_disk(self) -> int:
        return sum(os.path.getsize(self._path(n)) for n in self._meta
                   if os.path.exists(self._path(n)))


class OptimizerStateSwapper:
    """Swap a whole optimizer-state pytree (reference
    optimizer_utils.py:27 PartitionedOptimizerSwapper).

    ``swap_out_tree(state)`` writes every array leaf and returns a
    skeleton; ``swap_in_tree()`` reconstructs the pytree. The caller
    overlap-pattern is: swap_out right after step N's apply, swap_in right
    before step N+1's apply.
    """

    def __init__(self, swap_dir: str, num_threads: int = 4):
        self.swapper = AsyncTensorSwapper(swap_dir, num_threads)
        self._skeleton = None

    def swap_out_tree(self, state) -> None:
        import jax

        host = jax.device_get(state)
        flat = flatten_dots(host, keep_empty_nodes=True)
        self._skeleton = {}
        for name, leaf in flat.items():
            if hasattr(leaf, "shape") and getattr(leaf, "size", 0) > 0:
                self.swapper.swap_out(name, np.asarray(leaf))
                self._skeleton[name] = None  # swapped marker
            else:
                self._skeleton[name] = leaf  # scalars/empties stay resident
        self.swapper.wait()
        logger.info(
            f"optimizer state swapped out: "
            f"{self.swapper.bytes_on_disk() / 1e6:.1f} MB on disk")

    def swap_in_tree(self):
        if self._skeleton is None:
            raise RuntimeError("nothing swapped out")
        flat = {}
        for name, leaf in self._skeleton.items():
            if leaf is None:
                flat[name] = self.swapper.swap_in(name)
            else:
                flat[name] = leaf
        self.swapper.wait()
        return unflatten_dots(flat)

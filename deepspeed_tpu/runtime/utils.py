"""Runtime utility surface (reference ``deepspeed/runtime/utils.py``,
1018 LoC): the helpers user code and subsystems import — global-norm math,
gradient clipping, overflow checks, memory reporting, partitioners.

Functional forms: tensors are pytrees, nothing mutates in place.
``partition_uniform/balanced`` live in ``runtime/pipe/module.py`` (the
pipeline partitioner is their only producer) and are re-exported here under
the reference's import path.
"""

from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.pipe.module import (  # noqa: F401
    partition_balanced,
    partition_uniform,
)
from deepspeed_tpu.utils.logging import log_dist, logger


def get_global_norm(tree, norm_type: float = 2.0):
    """Global norm over a gradient pytree (reference get_global_norm /
    get_grad_norm). Trace-safe. The 2-norm delegates to optax.global_norm
    (the engine's implementation); other p-norms and inf are the
    extensions the reference API offers."""
    leaves = [l for l in jax.tree.leaves(tree) if hasattr(l, "dtype")]
    if not leaves:
        return jnp.float32(0.0)
    if norm_type == 2.0:
        import optax

        return optax.global_norm(leaves)
    if norm_type == float("inf"):
        return jnp.max(jnp.stack(
            [jnp.max(jnp.abs(l.astype(jnp.float32))) for l in leaves]))
    acc = sum(jnp.sum(jnp.abs(l.astype(jnp.float32)) ** norm_type)
              for l in leaves)
    return acc ** (1.0 / norm_type)


def clip_grad_norm_(grads, max_norm: float, norm_type: float = 2.0,
                    mpu=None):
    """Return (clipped grads, pre-clip global norm) — functional form of
    reference clip_grad_norm_ (which mutates .grad in place)."""
    del mpu  # mesh shardings already make the norm global
    norm = get_global_norm(grads, norm_type)
    factor = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree.map(lambda g: (g * factor).astype(g.dtype),
                        grads), norm


class CheckOverflow:
    """Inf/NaN detection across a grad pytree (reference CheckOverflow;
    the cross-rank allreduce is implicit in sharded arrays)."""

    def __init__(self, param_groups=None, mpu=None, zero_reduce_scatter=False):
        del param_groups, mpu, zero_reduce_scatter

    @staticmethod
    def has_overflow(grads) -> jnp.ndarray:
        from deepspeed_tpu.runtime.loss_scaler import has_overflow

        leaves = [l for l in jax.tree.leaves(grads) if hasattr(l, "dtype")]
        if not leaves:
            return jnp.bool_(False)
        return has_overflow(leaves)

    __call__ = staticmethod(has_overflow)


def see_memory_usage(message: str, force: bool = False) -> Optional[dict]:
    """Device + host memory report (reference see_memory_usage prints CUDA
    allocator stats; here per-device XLA memory stats when the backend
    exposes them)."""
    if not force:
        return None
    lines = [message]
    stats = None
    try:
        devs = jax.local_devices()
        stats = [d.memory_stats() for d in devs]
        for d, s in zip(devs, stats):
            if not s:
                continue
            used = s.get("bytes_in_use", 0) / 2 ** 30
            limit = s.get("bytes_limit", 0) / 2 ** 30
            peak = s.get("peak_bytes_in_use", 0) / 2 ** 30
            lines.append(
                f"  {d}: in_use {used:.2f} GB | peak {peak:.2f} GB | "
                f"limit {limit:.2f} GB")
    except Exception:
        lines.append("  (no device memory stats on this backend)")
    try:
        import resource

        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 2 ** 20
        lines.append(f"  host max RSS {rss:.2f} GB")
    except Exception:
        pass
    log_dist("\n".join(lines), ranks=[0])
    return {"devices": stats}


def call_to_str(base: str, *args, **kwargs) -> str:
    """'fn(a, b, k=v)' debug formatting (reference call_to_str)."""
    parts = [repr(a) for a in args]
    parts += [f"{k}={v!r}" for k, v in kwargs.items()]
    return f"{base}({', '.join(parts)})"

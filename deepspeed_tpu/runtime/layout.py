"""Mesh construction + state layout as a reusable module.

Historically ``DeepSpeedEngine.__init__`` inlined three layout decisions:
resolve a :class:`MeshTopology` from config, move the data-parallel axis to
``fsdp`` when a ZeRO stage shards over it, and build the
:class:`ZeroShardingRules` that turn the stage into per-leaf PartitionSpecs.
Elastic topology resume (``runtime/reshard.py``) needs the SAME decisions
outside any engine — a checkpoint saved at N devices must be re-laid-out
for N' before an engine on the new mesh exists — so they live here and the
engine calls in.

Also home to the manifest-facing serialization of a layout: a topology
metadata block (world size, zero stage, axis sizes) and JSON-safe
PartitionSpec encoding, written at save time and compared at load time to
*detect* a topology change instead of discovering it as a shape error deep
inside a compiled step.
"""

from typing import Any, Callable, Dict, List, Optional, Union

import jax
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.parallel.mesh import (
    AXIS_ORDER,
    MeshTopology,
    topology_from_config,
)
from deepspeed_tpu.runtime.zero.sharding import ZeroShardingRules
from deepspeed_tpu.utils.logging import log_dist
from deepspeed_tpu.utils.tree import path_str as _path_str


# ---------------------------------------------------------------------------
# mesh construction (extracted from DeepSpeedEngine.__init__)
# ---------------------------------------------------------------------------

def build_topology(config, topology: Optional[MeshTopology] = None
                   ) -> MeshTopology:
    """The engine's initial topology: an explicit one wins, otherwise the
    mesh config resolves against the visible devices."""
    if topology is not None:
        return topology
    return topology_from_config(config.tpu.mesh_config)


def apply_zero_fsdp_move(topology: MeshTopology, zero_stage: int,
                         compressed: bool = False) -> MeshTopology:
    """ZeRO shards over the fsdp axis: when the user asked for a ZeRO stage
    but left all data parallelism on ``dp``, move it to ``fsdp`` (the mesh
    expression of "partition across the DP world", reference
    stage_1_and_2.py partitioning over the DP group). Compressed modes keep
    the axis on ``dp``: the exchange needs the full momentum/gradient
    materialized per worker (reference 1-bit optimizers are likewise
    limited to ZeRO stages 0-1, onebit/adam.py)."""
    if (zero_stage >= 1 and topology.size("fsdp") == 1
            and topology.size("dp") > 1 and not compressed):
        sizes = dict(topology.axis_sizes)
        sizes["fsdp"] = sizes.pop("dp")
        sizes["dp"] = 1
        topology = MeshTopology(
            **sizes, devices=list(topology.mesh.devices.flat)
        )
        log_dist(
            f"zero stage {zero_stage}: data-parallel axis "
            f"moved to fsdp ({topology})", ranks=[0],
        )
    return topology


def build_sharding_rules(topology: MeshTopology, zero_stage: int,
                         param_persistence_threshold: int = 0,
                         tp_rules: Optional[Callable] = None
                         ) -> ZeroShardingRules:
    """The per-leaf layout policy for this (topology, stage) pair."""
    return ZeroShardingRules(
        topology,
        stage=zero_stage,
        param_persistence_threshold=(
            param_persistence_threshold if zero_stage >= 3 else 0),
        tp_rules=tp_rules,
    )


# ---------------------------------------------------------------------------
# manifest-facing layout serialization
# ---------------------------------------------------------------------------

def spec_to_json(spec: PartitionSpec) -> List[Any]:
    """JSON-safe PartitionSpec: each entry is None, an axis name, or a list
    of axis names (multi-axis sharding of one dim)."""
    out: List[Any] = []
    for entry in spec:
        if entry is None or isinstance(entry, str):
            out.append(entry)
        else:  # tuple of axis names
            out.append(list(entry))
    return out


def spec_from_json(entries: Optional[List[Any]]) -> PartitionSpec:
    if not entries:
        return PartitionSpec()
    parts = []
    for entry in entries:
        if entry is None or isinstance(entry, str):
            parts.append(entry)
        else:
            parts.append(tuple(entry))
    return PartitionSpec(*parts)


def describe_shardings(shardings_tree: Any, shapes_tree: Any = None
                       ) -> Dict[str, Dict[str, Any]]:
    """Flatten a pytree of NamedShardings into ``{dotted-path: {"spec":
    [...], "shape": [...]}}`` — the per-leaf layout record the manifest
    carries so a resharding load can verify the gathered (logical) shapes
    against what was saved."""
    out: Dict[str, Dict[str, Any]] = {}
    flat = jax.tree_util.tree_flatten_with_path(
        shardings_tree, is_leaf=lambda x: isinstance(x, NamedSharding))[0]
    shapes: Dict[str, Any] = {}
    if shapes_tree is not None:
        for path, leaf in jax.tree_util.tree_flatten_with_path(shapes_tree)[0]:
            shapes[_path_str(path)] = list(getattr(leaf, "shape", ()))
    for path, sharding in flat:
        key = _path_str(path)
        entry: Dict[str, Any] = {"spec": spec_to_json(sharding.spec)}
        if key in shapes:
            entry["shape"] = shapes[key]
        out[key] = entry
    return out


def topology_metadata(topology: MeshTopology, zero_stage: int,
                      partition_specs: Optional[Dict[str, Dict[str, Any]]]
                      = None) -> Dict[str, Any]:
    """The manifest ``topology`` block: enough to detect a mismatched load
    (world size + axis sizes), re-derive the saved layout (zero stage +
    per-leaf specs), and re-stride data (world size)."""
    meta: Dict[str, Any] = {
        "world_size": int(topology.num_devices),
        "zero_stage": int(zero_stage),
        "axis_sizes": {a: int(topology.axis_sizes[a]) for a in AXIS_ORDER},
    }
    if partition_specs:
        meta["partition_specs"] = partition_specs
    return meta


def topology_matches(saved: Dict[str, Any], topology: MeshTopology,
                     zero_stage: Optional[int] = None) -> List[str]:
    """Compare a saved topology block against a live topology; returns a
    list of human-readable mismatch descriptions (empty = same layout)."""
    mismatches: List[str] = []
    saved_world = saved.get("world_size")
    if saved_world is not None and int(saved_world) != topology.num_devices:
        mismatches.append(
            f"world_size {saved_world} -> {topology.num_devices}")
    saved_axes = saved.get("axis_sizes") or {}
    for axis in AXIS_ORDER:
        if axis not in saved_axes:
            continue
        cur = topology.axis_sizes[axis]
        if int(saved_axes[axis]) != cur:
            mismatches.append(f"{axis} {saved_axes[axis]} -> {cur}")
    if (zero_stage is not None and saved.get("zero_stage") is not None
            and int(saved["zero_stage"]) != int(zero_stage)):
        mismatches.append(
            f"zero_stage {saved['zero_stage']} -> {zero_stage}")
    return mismatches

"""Progressive Layer Dropping (reference ``runtime/progressive_layer_drop.py:5``,
the PLD paper's keep-probability schedule).

``theta(t) = (1 - theta) * exp(-gamma * t) + theta`` decays the layer keep
probability from 1.0 toward ``theta``. The engine injects
``pld_theta`` into the model forward; a scan-over-layers model applies it
as a per-layer Bernoulli keep gate with keep probability
``1 - (i / L) * (1 - theta)`` (deeper layers drop more), using an explicit
PRNG key — JAX's functional randomness replaces the reference's implicit
torch RNG.
"""

import math
from typing import Any, Dict


class ProgressiveLayerDrop:
    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0

    def get_theta(self) -> float:
        return self.current_theta

    def get_state(self) -> Dict[str, Any]:
        return {"progressive_layer_drop": True,
                "pld_theta": self.get_theta()}

    def update_state(self, global_step: int) -> float:
        self.current_theta = (
            (1.0 - self.theta) * math.exp(-self.gamma * global_step)
            + self.theta)
        return self.current_theta

"""Checkpoint loaders with tensor-parallel resharding
(reference ``runtime/state_dict_factory.py:20`` SDLoaderFactory /
``:214`` MegatronSDLoader).

Loads a checkpoint saved at one TP degree into a run at another: merging
per-rank slice files into globals (or splitting on the fly), with
qkv-aware merge strategies per parameter-name pattern. File formats:
flax msgpack (ours) and ``.npz``. The merge math lives in
``checkpoint/reshape_utils``; this module adds the file enumeration +
name-pattern routing the reference's loaders implement per architecture.
"""

import os
import re
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.checkpoint.reshape_utils import (
    merge_tp_slices,
    split_tp_param,
)
from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.utils.tree import flatten_dots, unflatten_dots


def _load_file(path: str) -> Dict[str, np.ndarray]:
    if path.endswith(".npz"):
        with np.load(path) as z:
            return {k: z[k] for k in z.files}
    from flax import serialization

    with open(path, "rb") as f:
        tree = serialization.msgpack_restore(f.read())
    if "module" in tree:
        tree = tree["module"]
    return flatten_dots(tree)


# default strategy routing (MegatronSDLoader's qkv/row/column knowledge,
# state_dict_factory.py:214-474, expressed as name patterns)
DEFAULT_STRATEGIES = (
    (r"(c_attn|query_key_value|qkv).*(kernel|weight|bias)$", "qkv", -1),
    (r"(c_fc|fc1|dense_h_to_4h|w1).*(kernel|weight|bias)$", "column", -1),
    (r"(c_proj|fc2|dense_4h_to_h|w2).*(kernel|weight)$", "row", 0),
    # position/type tables are TP-replicated (models/*.py sharding rules);
    # only the token-embedding table is vocab-sharded
    (r"(wpe|position_embeddings|token_type_embeddings)", "replicate", None),
    (r"(wte|word_embeddings)", "column", 0),
    (r".*", "replicate", None),
)


def strategy_for(name: str, strategies=DEFAULT_STRATEGIES):
    for pattern, strat, axis in strategies:
        if re.search(pattern, name):
            return strat, axis
    return "replicate", None


class SDLoaderBase:
    """Load N per-TP-rank files, expose state at any requested TP degree."""

    def __init__(self, ckpt_files: Sequence[str],
                 strategies=DEFAULT_STRATEGIES):
        if not ckpt_files:
            raise ValueError("no checkpoint files given")
        self.ckpt_files = list(ckpt_files)
        self.strategies = strategies
        self._shards: Optional[List[Dict[str, np.ndarray]]] = None

    def _load_all(self) -> List[Dict[str, np.ndarray]]:
        if self._shards is None:
            self._shards = [_load_file(p) for p in self.ckpt_files]
            keys = set(self._shards[0])
            for i, s in enumerate(self._shards[1:], 1):
                if set(s) != keys:
                    raise ValueError(
                        f"shard {i} has different parameter names")
        return self._shards

    def merge_state_dict(self) -> Dict[str, np.ndarray]:
        """TP-degree-N files -> one global flat state dict."""
        shards = self._load_all()
        if len(shards) == 1:
            return dict(shards[0])
        out = {}
        for name in shards[0]:
            slices = [s[name] for s in shards]
            if np.ndim(slices[0]) == 0:
                out[name] = slices[0]
                continue
            strat, axis = strategy_for(name, self.strategies)
            if strat != "replicate" and np.ndim(slices[0]) == 1:
                # 1-D tensors (biases): column/qkv concat, row replicate
                axis = 0 if strat in ("column", "qkv") else None
                strat = strat if axis is not None else "replicate"
            out[name] = merge_tp_slices(slices, strat, axis)
        return out

    def get_split_state_dict(self, mp_world_size: int,
                             mp_rank: int) -> Dict[str, np.ndarray]:
        """Global (or merged) state re-split at a new TP degree; returns
        this rank's flat dict (reference SDLoader.get_split_sd)."""
        merged = self.merge_state_dict()
        out = {}
        for name, arr in merged.items():
            if np.ndim(arr) == 0:
                out[name] = arr
                continue
            strat, axis = strategy_for(name, self.strategies)
            if strat == "replicate" or np.ndim(arr) == 1 and strat == "row":
                out[name] = arr
                continue
            if np.ndim(arr) == 1:
                axis = 0
            out[name] = split_tp_param(arr, mp_world_size, strat,
                                       axis)[mp_rank]
        return out

    def as_tree(self, flat: Dict[str, np.ndarray]):
        return unflatten_dots(flat)


class MegatronSDLoader(SDLoaderBase):
    """Alias with the reference's class name; the strategy table already
    encodes Megatron layer naming."""


class SDLoaderFactory:
    """reference state_dict_factory.py:20 — pick a loader for a checkpoint
    description (list of files or a directory of mp_rank files)."""

    @staticmethod
    def get_sd_loader(ckpt: "str | Sequence[str]",
                      sd_type: str = "Megatron",
                      strategies=DEFAULT_STRATEGIES) -> SDLoaderBase:
        if isinstance(ckpt, str):
            if os.path.isdir(ckpt):
                # numeric rank order: lexicographic sort breaks for
                # unpadded or >2-digit ranks (mp_rank_10 < mp_rank_2)
                named = [(int(re.search(r"mp_rank_(\d+)", f).group(1)), f)
                         for f in os.listdir(ckpt)
                         if re.search(r"mp_rank_\d+", f)]
                files = [os.path.join(ckpt, f)
                         for _, f in sorted(named)]
                if not files:
                    raise FileNotFoundError(
                        f"no mp_rank_* files under {ckpt}")
            else:
                files = [ckpt]
        else:
            files = list(ckpt)
        logger.info(f"SDLoader({sd_type}): {len(files)} shard file(s)")
        if sd_type.lower() == "megatron":
            return MegatronSDLoader(files, strategies)
        return SDLoaderBase(files, strategies)

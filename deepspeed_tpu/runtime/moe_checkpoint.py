"""Expert-sharded checkpoint format.

Reference parity: ``engine.py:2965 _save_moe_checkpoint`` writes each MoE
layer's experts to their own ``layer_#_expert_#`` files so no rank ever
gathers the full expert set, and pops expert keys from the dense model
states (``:2960``). Here the engine's params are ONE logical SPMD tree, so
the split is by leaf slice instead of by module walk: every
:class:`~deepspeed_tpu.moe.experts.StackedExperts` leaf is sliced along its
expert axis and each global expert id gets its own file. On a real pod a
``device_get`` of one expert slice only pulls that expert's ``ep`` shard —
the full expert set never materializes on one host. Optimizer moments that
mirror expert params (optax mu/nu subtrees end with the param path) split
the same way.

Pyramid/Residual MoE (different expert counts per layer, reference
PR-MoE) is supported: a leaf contributes to expert file ``e`` only while
``e < its own expert count``.
"""

from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.moe.layer import expert_axis
from deepspeed_tpu.utils.tree import flatten_dots, unflatten_dots


def find_expert_leaves(sd: Dict[str, Any]) -> Dict[str, int]:
    """{dotted_path: expert_axis} for every expert leaf in a state dict
    (params or optimizer state — optax mu/nu paths END with the param
    path, so the suffix match applies to both)."""
    out = {}
    for p, leaf in flatten_dots(sd).items():
        ax = expert_axis(p.replace(".", "/"), getattr(leaf, "ndim", 0))
        if ax is not None:
            out[p] = ax
    return out


def split_expert_sd(sd: Dict[str, Any], expert_info: Dict[str, int]
                    ) -> Tuple[Dict[str, Any], Dict[str, Any], int]:
    """State dict -> (dense_sd_without_experts, meta, num_expert_files).

    ``meta`` records each expert leaf's axis and expert count so the loader
    can re-stack without guessing.
    """
    # keep_empty_nodes: optax chain states contain EmptyState leaves that
    # a plain flatten would silently drop, breaking from_state_dict
    flat = flatten_dots(sd, keep_empty_nodes=True)
    counts = {p: int(flat[p].shape[ax]) for p, ax in expert_info.items()}
    for p in expert_info:
        flat.pop(p)
    meta = {"axes": dict(expert_info), "counts": counts}
    return unflatten_dots(flat), meta, max(counts.values())


def expert_slice(expert_leaves: Dict[str, Any], expert_info: Dict[str, int],
                 e: int) -> Dict[str, np.ndarray]:
    """One global expert id's slice of every expert leaf that has it.
    ``expert_leaves`` maps dotted path -> full leaf (flatten ONCE in the
    caller — a 64-expert save must not re-flatten the multi-GB tree per
    file). The ``jnp.take`` + host fetch per slice keeps the transfer to
    one expert's shard instead of the whole stack."""
    out = {}
    for p, ax in expert_info.items():
        leaf = expert_leaves[p]
        if e < leaf.shape[ax]:
            out[p] = np.asarray(jnp.take(leaf, e, axis=ax))
    return out


def merge_expert_slices(dense_sd: Dict[str, Any], meta: Dict[str, Any],
                        slices_by_expert: Dict[int, Dict[str, np.ndarray]]
                        ) -> Dict[str, Any]:
    """Inverse of the split: re-stack per-expert slices into full leaves
    and merge them back into the dense state dict."""
    flat = flatten_dots(dense_sd, keep_empty_nodes=True)
    for p, ax in meta["axes"].items():
        n = int(meta["counts"][p])
        stacked = np.stack(
            [slices_by_expert[e][p] for e in range(n)], axis=int(ax))
        flat[p] = stacked
    return unflatten_dots(flat)


def expert_states_filename(e: int, kind: str = "model") -> str:
    """Reference-flavored naming (engine.py _get_expert_ckpt_name uses
    ``..._expert_{id}_mp_rank_00_model_states.pt``)."""
    return f"expert_{e}_mp_rank_00_{kind}_states.msgpack"

"""The training engine.

Parity with reference ``deepspeed/runtime/engine.py`` (DeepSpeedEngine :179,
3.3k LoC): same lifecycle — ``initialize()`` → engine with
``forward/backward/step`` (and the fused ``train_batch``), gradient
accumulation boundaries, loss scaling, clipping, checkpoint save/load,
throughput/wall-clock telemetry.

TPU re-design (SURVEY.md §7): the hook-driven imperative engine collapses into
two compiled SPMD programs over a named mesh —

* ``_fwd_bwd``: value_and_grad of the (scaled) loss, accumulated into a grad
  buffer whose sharding encodes ZeRO stage (replicated → psum at use; sharded
  over fsdp → reduce-scatter), replacing the per-param backward hooks and
  bucketed reducers of stage_1_and_2.py:832-1038.
* ``_apply``: unscale → global-norm clip → overflow-gated optimizer update →
  loss-scale update, all under ``lax.cond`` (reference does this host-side in
  fused_optimizer.py:147 / stage_1_and_2.py:1744).

Parameter construction is jitted with output shardings (the ``zero.Init``
equivalent — params materialize already partitioned; reference
partition_parameters.py:537 hijacks nn.Module.__init__ for this).
"""

import dataclasses
import os
import shutil
import signal as signal_module
import threading
import time
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import serialization

from deepspeed_tpu.comm.logging import comms_logger
from deepspeed_tpu.parallel.mesh import (
    MeshTopology,
    set_default_topology,
)
from deepspeed_tpu.runtime import checkpoint_manifest as ckpt_manifest
from deepspeed_tpu.runtime import layout, reshard
from deepspeed_tpu.runtime.checkpoint_engine import (
    CheckpointEngine,
    select_checkpoint_engine,
)
from deepspeed_tpu.runtime.config import DeepSpeedConfig
from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader, RepeatingLoader
from deepspeed_tpu.runtime.loss_scaler import (
    LossScaleState,
    has_overflow,
    init_loss_scale,
    update_loss_scale,
)
from deepspeed_tpu.runtime.lr_schedules import (
    LRScheduler,
    build_lr_scheduler,
    schedule_fn_from_config,
)
from deepspeed_tpu.runtime.optimizer import (
    build_optimizer,
    is_compressed_optimizer,
)
from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer, ThroughputTimer

FORWARD_MICRO_TIMER = "fwd_bwd_microstep"
STEP_MICRO_TIMER = "step_microstep"

# shared no-op context for `_prof_phase` when the step profiler is off:
# the healthy path must gain zero device syncs and near-zero host work
import contextlib as _contextlib

_NULL_PROF_CTX = _contextlib.nullcontext()


def initialize(
    args=None,
    model=None,
    optimizer=None,
    model_parameters=None,
    training_data=None,
    lr_scheduler=None,
    topology: Optional[MeshTopology] = None,
    dist_init_required: Optional[bool] = None,
    collate_fn: Optional[Callable] = None,
    config=None,
    config_params=None,
    sample_batch=None,
    seed: int = 0,
):
    """Build the engine (reference deepspeed/__init__.py:51).

    Returns the reference 4-tuple ``(engine, optimizer, dataloader,
    lr_scheduler)``. ``model`` is a flax Module whose ``__call__(**batch)``
    returns a scalar loss (the JAX model contract replacing nn.Module;
    SURVEY.md §7 hard part (b)). ``optimizer`` may be an optax
    GradientTransformation to override the config block; ``lr_scheduler`` an
    LRScheduler or trace-safe ``step -> lr`` callable.

    ``model_parameters`` (reference: the params list handed to the
    optimizer) here takes a parameter PYTREE to fine-tune from — e.g. an HF
    checkpoint converted by module_inject.hf.import_hf_model — which the
    engine materializes onto the mesh with its ZeRO/TP shardings instead of
    randomly initializing.
    """
    from deepspeed_tpu import comm

    assert model is not None, "deepspeed_tpu.initialize: model is required"
    if config is None and config_params is not None:
        config = config_params
    if config is None and args is not None:
        config = getattr(args, "deepspeed_config", None)
    assert config is not None, "deepspeed_tpu.initialize: config is required"

    if dist_init_required is None or dist_init_required:
        comm.init_distributed()

    # Pipeline-module dispatch (reference __init__.py:123-147)
    from deepspeed_tpu.runtime.pipe import PipelineModule  # lazy, avoids cycle

    if isinstance(model, PipelineModule):
        if model_parameters is not None:
            raise NotImplementedError(
                "model_parameters (initial weights) is not supported for "
                "PipelineModule yet; load a checkpoint instead")
        cfg_obj = (config if isinstance(config, DeepSpeedConfig)
                   else DeepSpeedConfig(config))
        off_param = (cfg_obj.zero_config.offload_param or {})
        if off_param.get("device") == "nvme":
            # ZeRO-Infinity parameter SSD tier: host-driven layer sweep
            # over the LayerSpec list (runtime/zero/param_nvme.py)
            if training_data is not None or lr_scheduler is not None:
                raise NotImplementedError(
                    "offload_param nvme tier: pass batches to train_batch "
                    "directly, and configure lr schedules via the config "
                    "'scheduler' block (client scheduler objects and "
                    "dataloader wiring are not supported here)")
            from deepspeed_tpu.runtime.zero.param_nvme import NVMeParamEngine

            engine = NVMeParamEngine(module=model, config=cfg_obj, seed=seed)
            return engine, None, None, None
        from deepspeed_tpu.runtime.pipe.engine import PipelineEngine

        engine = PipelineEngine(
            model=model, config=config, topology=topology,
            optimizer=optimizer, lr_scheduler=lr_scheduler, seed=seed,
        )
    else:
        engine = DeepSpeedEngine(
            model=model,
            config=config,
            topology=topology,
            optimizer=optimizer,
            lr_scheduler=lr_scheduler,
            sample_batch=sample_batch,
            initial_params=model_parameters,
            seed=seed,
        )

    dataloader = None
    if training_data is not None:
        dataloader = engine.deepspeed_io(training_data, collate_fn=collate_fn)

    return engine, engine.optimizer_adapter, dataloader, engine.lr_scheduler


class _ParamGroup(dict):
    """One param group with torch-optim write-through: assigning ``lr``
    feeds the engine's compiled step (reference users mutate
    ``param_groups[0]["lr"]`` directly; see DeepSpeedEngine.set_lr for the
    scheduler interplay). Other keys are a read-only snapshot."""

    def __init__(self, engine, data):
        super().__init__(data)
        self._engine = engine

    _BAKED_KEYS = ("betas", "eps", "weight_decay", "momentum", "params")

    def __setitem__(self, key, value):
        if key == "lr":
            self._engine.set_lr(value)  # raises BEFORE the view mutates
        elif key in self._BAKED_KEYS:
            # these are compiled into the optimizer — a silently-accepted
            # write that changes nothing is worse than an error
            raise NotImplementedError(
                f"param_groups[{key!r}] is baked into the compiled "
                "optimizer; only 'lr' writes through (rebuild the engine "
                "to change it)")
        super().__setitem__(key, value)


class OptimizerAdapter:
    """Host-side view of the sharded optimizer state with the torch-optim
    attribute surface the reference returns from initialize()."""

    def __init__(self, engine: "DeepSpeedEngine"):
        self._engine = engine

    @property
    def state(self):
        return self._engine._opt_state

    @property
    def param_groups(self):
        """One group carrying the real hyperparameters and the engine's
        param leaves (reference torch-optim surface). ``group["lr"] = v``
        writes through to the compiled step (engine.set_lr); the other
        hyperparameters are baked into the compiled optimizer and the view
        of them is read-only."""
        eng = self._engine
        leaves = (jax.tree.leaves(eng._params)
                  if eng._params is not None else [])
        if eng._client_optimizer is not None:
            # a client optax transformation owns its hyperparameters;
            # don't fabricate config-block defaults it never saw. Still a
            # _ParamGroup so an lr write raises (via set_lr) instead of
            # silently doing nothing.
            return [_ParamGroup(eng, {"lr": eng.get_lr()[0],
                                      "params": leaves})]
        opt_p = dict(eng._config.optimizer.params or {})
        group = {"lr": eng.get_lr()[0], "params": leaves}
        # only surface hyperparameters the optimizer family actually has
        # (an SGD config must not report Adam-shaped betas/eps defaults)
        name = (eng._config.optimizer.type or "adamw").lower()
        if "adam" in name or "lamb" in name:
            betas = opt_p.get("betas", (0.9, 0.999))
            group["betas"] = (float(betas[0]), float(betas[1]))
            group["eps"] = float(opt_p.get("eps", 1e-8))
            group["weight_decay"] = float(opt_p.get("weight_decay", 0.0))
        elif "adagrad" in name:
            group["eps"] = float(opt_p.get("eps", 1e-10))
        elif "sgd" in name:
            group["momentum"] = float(opt_p.get("momentum", 0.0))
            group["weight_decay"] = float(opt_p.get("weight_decay", 0.0))
        else:
            # unknown/custom type: mirror the config block verbatim
            group.update({k: v for k, v in opt_p.items() if k != "lr"})
        return [_ParamGroup(eng, group)]

    def state_dict(self):
        return serialization.to_state_dict(self._engine._opt_state)


class DeepSpeedEngine:
    def __init__(
        self,
        model,
        config,
        topology: Optional[MeshTopology] = None,
        optimizer=None,
        lr_scheduler=None,
        sample_batch=None,
        initial_params=None,
        seed: int = 0,
    ):
        self._initial_params = initial_params
        if not isinstance(config, DeepSpeedConfig):
            # resolve triad after topology is known
            config = DeepSpeedConfig(config)
        self._config = config

        if config.sparse_attention is not None:
            # swap block-sparse attention into the model from config alone
            # (reference sparse_attention_utils.py:37 replace_model_self_
            # attention_with_sparse_self_attention)
            from deepspeed_tpu.ops.sparse_attention.sparse_attention_utils \
                import apply_sparse_attention

            model = apply_sparse_attention(model, config.sparse_attention)
            log_dist(
                f"sparse attention enabled: "
                f"{type(model.config.sparse_attention).__name__}", ranks=[0])

        # HBM-bounded step-config autotuner (runtime/step_autotune.py):
        # resolve a tuned (remat_policy, micro_batch, flash) for this
        # model/device through the mem -> disk -> PRETUNED -> live chain
        # and rebuild the module with the winner BEFORE anything compiles.
        # Default off; with no winner the module is untouched, so the
        # compiled program is bit-identical to the un-tuned engine.
        self._step_autotune_cfg = config.tpu.step_autotune_config
        self._fused_step_mode = self._step_autotune_cfg.fused_step
        self.step_autotune_winner = None
        if self._step_autotune_cfg.enabled:
            # key the tuner by the device count this engine will actually
            # run on (elastic resume on a shrunk/grown slice must re-tune,
            # not reuse the old topology's winner)
            ndev = (topology.num_devices if topology is not None
                    else jax.device_count())
            model = self._apply_step_autotune(model, config, ndev)
        self.module = model

        topology = layout.build_topology(config, topology)
        # Compressed gradient exchange (reference runtime/fp16/onebit +
        # runtime/comm/nccl.py:51): either a 1-bit optimizer type or
        # communication_data_type=int8. Both replace XLA's implicit grad
        # averaging with an explicit shard_mapped exchange over the
        # data-parallel axis, so the step keeps PER-WORKER gradients.
        self._compressed_mode = None
        self._comp_k = None
        self._bucket_plan = None  # comm/bucketed.py plan, set at state init
        self._gx_wire_dtype = jnp.bfloat16
        self._gx_num_slices = 1  # >1 = two-level ICI/DCN exchange
        if optimizer is None and is_compressed_optimizer(config.optimizer.type):
            self._compressed_mode = "onebit"
        elif config.communication_data_type == "int8":
            self._compressed_mode = "int8"
        elif (config.tpu.grad_exchange_config.deferred
              and topology.size("dp") > 1):
            # deferred bucketed exchange (comm/bucketed.py): the compressed
            # machinery at a bf16/fp32 wire — per-worker grads through the
            # accumulation window, ONE bucketed explicit exchange at the GAS
            # boundary instead of XLA's implicit psum every micro step
            self._compressed_mode = "deferred"
        if self._compressed_mode is not None:
            self._validate_compressed_config(config, topology)
        elif config.tpu.grad_exchange_config.hierarchical == "on":
            # "on" demands the two-level exchange; with no deferred
            # exchange engaged that is a config contradiction, not a
            # fallback case ("auto" is the degrade-quietly spelling)
            raise ValueError(
                "tpu.grad_exchange.hierarchical: on requires the deferred "
                "exchange (tpu.grad_exchange.deferred: true on a dp>1 "
                "mesh)")
        # whether the compressed step materializes a real averaged-grad norm
        # (int8/deferred: free from the post-exchange mean; onebit:
        # debug-gated)
        self._compressed_norm_available = (
            self._compressed_mode in ("int8", "deferred")
            or (self._compressed_mode == "onebit"
                and config.tpu.compressed_grad_norm))
        # mesh/layout decisions live in runtime/layout.py so the elastic
        # reshard pass can re-derive them without an engine
        topology = layout.apply_zero_fsdp_move(
            topology, config.zero_config.stage,
            compressed=self._compressed_mode is not None)
        self.topology = topology
        set_default_topology(topology)
        # (re)resolve the batch triad against the actual mesh; also validates
        # a pre-resolved triad for consistency with this topology
        config._resolve_batch_triad(topology.data_parallel_size)

        comms_logger.configure(config.comms_logger)

        self.zero_stage = config.zero_config.stage
        self.sharding_rules = layout.build_sharding_rules(
            topology, self.zero_stage,
            param_persistence_threshold=(
                config.zero_config.param_persistence_threshold),
            tp_rules=getattr(model, "tp_rules", None),
        )

        self.fp16_enabled = config.fp16.enabled
        self.bfloat16_enabled = config.bf16.enabled
        self.gradient_accumulation_steps = config.gradient_accumulation_steps
        self.train_micro_batch_size_per_gpu = config.train_micro_batch_size_per_gpu
        self.train_batch_size = config.train_batch_size
        self.gradient_clipping = config.gradient_clipping

        # optimizer + schedule

        self.lr_scheduler, self._schedule_fn = self._configure_lr(lr_scheduler)
        self._client_optimizer = optimizer
        self._tx = self._configure_optimizer(optimizer)
        self.optimizer_adapter = OptimizerAdapter(self)

        self.checkpoint_engine: CheckpointEngine = \
            select_checkpoint_engine(config)

        # runtime state (device) — params/opt created lazily at first batch
        self._params = None
        self._opt_state = None
        self._acc_grads = None
        self._ls_state, self._ls_config = init_loss_scale(
            self._config.fp16, enabled=self.fp16_enabled
        )
        self._initialized = False
        self._rng = jax.random.PRNGKey(seed)
        self._unit_scale = jnp.float32(1.0)
        # ZeRO-Offload (reference zero cpu_offload / ZeRO-Infinity nvme)
        off_cfg = config.zero_config.offload_optimizer or {}
        self._offload_device = off_cfg.get("device", "none")
        off_param_cfg = config.zero_config.offload_param or {}
        self._offload_param_device = off_param_cfg.get("device", "none")
        self._offload_opt = None
        self._zero_acc_fn = None
        self._host_grad_acc = None  # offload_param gas>1 host accumulator
        # device grad leaves whose host copies are in flight; consumed only
        # after the NEXT micro step is dispatched so transfer overlaps compute
        self._pending_grad_leaves = None

        # host counters
        self.micro_steps = 0
        self.global_steps = 0
        self.skipped_steps = 0
        self.global_samples = 0
        self._last_loss = None
        self._last_grad_norm = None
        self._backward_pending = False
        self._step_losses = []

        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(
            batch_size=self.train_batch_size,
            steps_per_output=config.steps_per_print,
        )
        self.wall_clock_breakdown = config.wall_clock_breakdown

        self.monitor = self._configure_monitor()

        # step-level performance tracer (config-gated; docs/observability.md).
        # None when disabled so the hot path pays one attribute check and
        # gains zero device syncs.
        self.step_profiler = None
        if config.step_profiler.enabled:
            from deepspeed_tpu.profiling.step_profiler import StepProfiler

            self.step_profiler = StepProfiler(
                config.step_profiler, timers=self.timers, monitor=self.monitor)

        # fault-tolerance telemetry (wall_clock_breakdown-style counters,
        # exported through the monitor as FaultTolerance/* events)
        self.ft_stats = {
            "ckpt_saves": 0,
            "ckpt_loads": 0,
            "ckpt_fallbacks": 0,
            "ckpt_reshards": 0,
            "graceful_shutdowns": 0,
        }
        # preemption grace handler (config-gated): the signal handler only
        # sets a flag; the save happens at the next step boundary where
        # host-side counters and device state are consistent
        self._preempt_signum = None
        self._old_signal_handlers = {}
        if config.graceful_shutdown.enabled:
            self._install_signal_handlers()

        # training health sentinel (config-gated; docs/recovery.md
        # "Divergence and hang recovery"): anomaly verdicts per optimizer
        # step, graduated skip→rollback→DivergenceError response, and a
        # daemon hang watchdog armed around each dispatched step.
        # _check_overflow widens the in-graph lax.cond overflow gate from
        # fp16-only to any precision; when it is False the step never
        # pulls the overflow scalar to host (the bf16 no-sync fast path).
        self.sentinel = None
        self._watchdog = None
        self._nonfinite_guard = False
        self._check_overflow = self.fp16_enabled
        self._sentinel_emitted = None
        self.training_dataloader = None
        if config.sentinel.enabled:
            from deepspeed_tpu.runtime.sentinel import (
                HangWatchdog,
                TrainingSentinel,
            )

            self.sentinel = TrainingSentinel(config.sentinel)
            self._nonfinite_guard = bool(config.sentinel.check_nonfinite)
            self._check_overflow = (self.fp16_enabled
                                    or self._nonfinite_guard)
            if config.sentinel.hang_timeout_s > 0:
                self._watchdog = HangWatchdog(
                    timeout_s=config.sentinel.hang_timeout_s,
                    action=config.sentinel.hang_action,
                    exit_code=config.sentinel.hang_exit_code,
                    on_fire=self._on_watchdog_fire)
                self._watchdog.start()

        # telemetry bus + crash-forensics flight recorder (default-on;
        # docs/observability.md "Flight recorder"). The ring always
        # records in memory — host timers only, no fences, no device
        # pulls. Blackbox dumps + crash handlers engage only when a dump
        # dir resolves (config, else DS_TPU_TELEMETRY_DIR exported by the
        # elastic agent / launcher), so ordinary runs never touch
        # signals, sys.excepthook or disk.
        self.flight_recorder = None
        self._telemetry_uninstall = None
        self._live_mem_sampling = False
        self._mem_static_captured = False
        if config.telemetry.enabled:
            from deepspeed_tpu.telemetry import (
                TELEMETRY_DIR_ENV,
                FlightRecorder,
                install_crash_handlers,
                telemetry_bus,
            )

            tcfg = config.telemetry
            rank = jax.process_index()
            telemetry_bus.set_rank(rank)
            dump_dir = tcfg.dump_dir or os.environ.get(TELEMETRY_DIR_ENV)
            self.flight_recorder = FlightRecorder(
                ring_steps=tcfg.ring_steps, ring_events=tcfg.ring_events,
                dump_dir=dump_dir, rank=rank, bus=telemetry_bus)
            dev = jax.devices()[0]
            self.flight_recorder.set_static(
                backend=jax.default_backend(),
                device_kind=str(getattr(dev, "device_kind", dev)),
                num_devices=jax.device_count(),
                num_processes=jax.process_count(),
                train_batch_size=self.train_batch_size,
                micro_batch_size=self.train_micro_batch_size_per_gpu,
                gradient_accumulation_steps=(
                    self.gradient_accumulation_steps),
            )
            self._live_mem_sampling = bool(tcfg.sample_memory)
            if getattr(self.monitor, "enabled", False):
                # CsvMonitor durability: counter csvs hit disk before any
                # blackbox dump (signal/excepthook paths included)
                self.flight_recorder.add_flush_hook(self.monitor.flush)
            if dump_dir:
                # installed AFTER graceful_shutdown's handlers: on SIGTERM
                # the dump runs first, then chains to the flag-setter
                self._telemetry_uninstall = install_crash_handlers(
                    self.flight_recorder,
                    signals=tuple(tcfg.dump_signals))

        # cluster health plane (docs/recovery.md "Cluster health & SDC
        # defense"): out-of-band TCP heartbeats between processes, a
        # coordinated exit-15 abort when a peer goes silent mid-step,
        # straggler skew telemetry, and the every-K-steps SDC param
        # digest. Auto-on exactly when there is a peer to watch
        # (process_count > 1); built AFTER the flight recorder so the
        # abort path can dump a blackbox.
        self.health_plane = None
        self._health_emitted = None
        self._health_cfg = config.tpu.cluster_health_config
        if self._health_cfg.resolve_enabled(jax.process_count()):
            from deepspeed_tpu.runtime.health import ClusterHealthPlane

            self.health_plane = ClusterHealthPlane(
                jax.process_index(), jax.process_count(), self._health_cfg,
                watchdog_probe=self._watchdog_armed,
                on_abort=self._on_health_abort)
            self.health_plane.start()

        # module-level activation checkpointing (reference engine.py:818
        # _configure_checkpointing): models that call
        # activation_checkpointing.checkpoint() pick up this policy
        from deepspeed_tpu.runtime import activation_checkpointing
        activation_checkpointing.configure(
            self._config, remat=self._config.tpu.remat)

        # curriculum learning / PLD / MoQ (reference engine.py:1629-1663,
        # :1636-1645, :1921-1930)
        self.curriculum_scheduler = None
        if config.curriculum_learning.enabled:
            from deepspeed_tpu.runtime.data_pipeline import \
                CurriculumScheduler
            self.curriculum_scheduler = CurriculumScheduler(
                config.curriculum_learning)
        self.progressive_layer_drop = None
        if config.progressive_layer_drop.enabled:
            from deepspeed_tpu.runtime.progressive_layer_drop import \
                ProgressiveLayerDrop
            self.progressive_layer_drop = ProgressiveLayerDrop(
                theta=config.progressive_layer_drop.theta,
                gamma=config.progressive_layer_drop.gamma)
        self.quantizer = None
        if config.quantize_training.get("enabled", False):
            from deepspeed_tpu.runtime.quantize import Quantizer
            self.quantizer = Quantizer.from_config(config.quantize_training)

        # autotuning metric drop (reference autotuning_metric_path): when
        # the launcher's --autotuning relaunched us, report measured
        # throughput through the file it watches (autotuning/cli.py)
        self._autotune_metric_path = os.environ.get(
            "DS_TPU_AUTOTUNING_RESULT")
        self._autotune_end_step = int(os.environ.get(
            "DS_TPU_AUTOTUNING_END_STEP", "5"))
        self._autotune_start_step = int(os.environ.get(
            "DS_TPU_AUTOTUNING_START_STEP", "1"))
        self._autotune_t0 = None
        self._autotune_t0_step = 0

        # compression-aware training from the compression_training block
        # (reference compression/compress.py init_compression, which users
        # call on the model; here the engine consumes the config directly
        # and projects params onto the compressed set at step boundaries —
        # the same step-boundary pattern as MoQ below)
        self.compression_compressor = None
        if config.compression_training:
            from deepspeed_tpu.compression import init_compression

            comp = init_compression(
                {"compression_training": config.compression_training})
            if comp.enabled():
                self.compression_compressor = comp

        # compiled fns (built on first use)
        self._flops_profiled = False
        self._reshard_params_fn = None
        self._train_step_fn = None
        self._fwd_bwd_fn = None
        self._apply_fn = None
        self._eval_fn = None
        # avals of the last device batch (a handful of leaves — cheap to
        # rebuild per put) so compiled_step_cost() can re-lower the step
        # without holding live buffers
        self._last_batch_aval = None
        # write-through param_groups["lr"]: an absolute lr override applied
        # as a multiplicative factor on the compiled step's updates (updates
        # are linear in lr). None = follow the schedule/config.
        self._lr_override = None

        log_dist(
            f"DeepSpeedEngine: mesh={topology}, zero_stage={self.zero_stage}, "
            f"dtype={config.precision_dtype}, micro_bs={self.train_micro_batch_size_per_gpu}, "
            f"gas={self.gradient_accumulation_steps}",
            ranks=[0],
        )

    # ------------------------------------------------------------------
    # configuration
    # ------------------------------------------------------------------
    def _validate_compressed_config(self, config, topology):
        """Constraints shared by the 1-bit optimizers and int8 grad comm.
        fp16 dynamic loss scaling composes (reference fp16/onebit/adam.py:10
        pairs OnebitAdam with the FP16 wrapper): the compressed step
        cond-skips the exchange+update on overflow with error-feedback
        state carried through untouched."""
        mode = self._compressed_mode
        max_stage = 1 if mode == "onebit" else 0
        if config.zero_config.stage > max_stage:
            raise ValueError(
                f"{mode} compressed gradient exchange requires ZeRO stage "
                f"<= {max_stage} (got {config.zero_config.stage}); the "
                "exchange needs the full gradient/momentum per worker — "
                "same limitation as the reference 1-bit optimizers")
        for ax in ("fsdp", "tp", "pp", "sp", "ep"):
            if topology.size(ax) > 1:
                raise ValueError(
                    f"compressed gradient exchange runs over the dp axis "
                    f"only; mesh axis {ax!r} has size {topology.size(ax)}")
        off = (config.zero_config.offload_optimizer or {}).get("device", "none")
        if off != "none":
            raise ValueError(
                f"{mode} compressed gradient exchange cannot combine with "
                "offload_optimizer (the host step bypasses the exchange)")
        if (config.tpu.grad_exchange_config.hierarchical != "off"
                and mode != "deferred"):
            raise ValueError(
                "tpu.grad_exchange.hierarchical requires the deferred "
                "bf16/fp32 exchange (grad_exchange.deferred: true); the "
                "onebit/int8 paths own their wire format end to end and "
                "carry error-feedback state the two-level exchange does "
                "not")
        if config.gradient_clipping and mode == "onebit":
            logger.warning(
                "gradient_clipping is ignored with the 1-bit optimizers: "
                "they exchange sign-compressed MOMENTUM, so the averaged "
                "gradient the clip would apply to never exists (divergence "
                "documented in docs/DIVERGENCES.md). The int8 "
                "communication_data_type path clips exactly.")
        if mode == "onebit" and config.zero_config.stage == 1:
            log_dist(
                "OnebitAdam with ZeRO stage 1: optimizer state stays "
                "replicated (the compressed exchange materializes the full "
                "momentum per worker)", ranks=[0])

    def _configure_lr(self, lr_scheduler):
        cfg = self._config
        if lr_scheduler is None and cfg.scheduler.type is not None:
            sched_fn = schedule_fn_from_config(cfg.scheduler.type, cfg.scheduler.params)
            return build_lr_scheduler(cfg.scheduler.type, cfg.scheduler.params), sched_fn
        if isinstance(lr_scheduler, LRScheduler):
            return lr_scheduler, lr_scheduler.schedule_fn
        if callable(lr_scheduler):
            return LRScheduler(lr_scheduler), lr_scheduler
        return None, None

    def _configure_optimizer(self, client_optimizer):
        cfg = self._config
        if client_optimizer is not None:
            if isinstance(client_optimizer, optax.GradientTransformation):
                return client_optimizer
            raise TypeError(
                "optimizer must be an optax.GradientTransformation; the "
                "reference's torch.optim objects have no TPU meaning"
            )
        lr = self._schedule_fn  # None -> use params lr
        kw = {}
        if self._compressed_mode == "onebit":
            kw = dict(compression_axis="dp",
                      compression_axis_size=self.topology.size("dp"))
        return build_optimizer(
            cfg.optimizer.type, cfg.optimizer.params, lr,
            use_pallas=cfg.tpu.use_pallas_optimizer, **kw,
        )

    def _configure_monitor(self):
        try:
            from deepspeed_tpu.monitor.monitor import MonitorMaster

            return MonitorMaster(self._config)
        except Exception:
            return None

    def _place_initial_params(self, param_shapes):
        """Materialize user-provided initial params (fine-tune entry, e.g.
        an imported HF checkpoint) onto the mesh with the engine's ZeRO/TP
        shardings — the pretrained-weights counterpart of zero.Init's
        shard-at-construction (reference partition_parameters.py:537)."""
        expect = jax.tree.structure(param_shapes)
        got = jax.tree.structure(self._initial_params)
        if expect != got:
            raise ValueError(
                "model_parameters tree does not match the model's params "
                f"structure:\n  expected {expect}\n  got      {got}")

        def place(leaf, shape_dtype, sharding):
            # stay on HOST until the sharded device_put: each device then
            # receives only its shard (an eager jnp.asarray would
            # materialize the full parameter on one chip first)
            arr = np.asarray(leaf, dtype=shape_dtype.dtype)
            if arr.shape != shape_dtype.shape:
                raise ValueError(
                    f"model_parameters leaf shape {arr.shape} != model "
                    f"shape {shape_dtype.shape}")
            return jax.device_put(arr, sharding)

        return jax.tree.map(place, self._initial_params, param_shapes,
                            self._param_shardings)

    def _apply_param_offload_shardings(self, param_shapes):
        """ZeRO-Infinity parameter tier (reference
        partition_parameters.py:537 remote_device="cpu" +
        partitioned_param_swapper.py:35): rewrite the shardings of the
        model's streamable leaves to the accelerator host's memory
        (``pinned_host``). The model's scan streams one layer back into
        HBM per iteration (ops/streaming.py), so device memory never holds
        the full parameter set."""
        if self._offload_param_device != "cpu":
            raise NotImplementedError(
                "offload_param device must be 'cpu' (pinned host memory) "
                "for the SPMD engine; the 'nvme' tier runs as a layer sweep "
                "over a PipelineModule (runtime/zero/param_nvme.py) — got "
                f"{self._offload_param_device!r}")
        if self._offload_device == "none":
            raise ValueError(
                "offload_param requires offload_optimizer: the host "
                "optimizer step is what writes updated params back to "
                "host memory (device-resident optimizer state would defeat "
                "the capacity win)")
        filt = getattr(self.module, "param_offload_filter", None)
        if filt is None:
            raise ValueError(
                "offload_param needs a model that streams host-resident "
                "params per layer — expose param_offload_filter(path) and "
                "stream inside the layer scan (see GPTConfig.param_offload, "
                "models/transformer_lm.py)")
        from jax.tree_util import keystr, tree_flatten_with_path

        flat, _ = tree_flatten_with_path(param_shapes)
        marked = [keystr(p) for p, _ in flat if filt(keystr(p))]
        if not marked:
            raise ValueError(
                "offload_param is configured but the model marks no params "
                "as streamable (is the model's param_offload flag set?)")
        platform = jax.devices()[0].platform
        if platform != "tpu":
            log_dist(
                f"offload_param: backend {platform!r} does not support "
                "host-memory placement under SPMD; params stay in device "
                "memory (structure-only mode for tests)", ranks=[0])
            return
        threshold = self._config.zero_config.param_persistence_threshold
        n_off = [0, 0]

        def is_offloaded(path, shape_dtype):
            return (filt(keystr(path))
                    and int(np.prod(shape_dtype.shape)) >= threshold)

        def rewrite(path, shape_dtype, sharding):
            return (sharding.with_memory_kind("pinned_host")
                    if is_offloaded(path, shape_dtype) else sharding)

        for p, sd in flat:
            if is_offloaded(p, sd):
                n_off[0] += 1
                n_off[1] += int(np.prod(sd.shape))
        self._param_shardings = jax.tree_util.tree_map_with_path(
            rewrite, param_shapes, self._param_shardings)
        # gradients of streamed params assemble in host memory too (the
        # streaming bwd ships each layer-slice cotangent out as it is
        # produced) — full grads in HBM would cancel the capacity win
        self._grad_shardings = jax.tree_util.tree_map_with_path(
            rewrite, param_shapes, self._grad_shardings)
        log_dist(
            f"offload_param: {n_off[0]} leaves / {n_off[1] / 1e6:.0f}M "
            "params placed in pinned host memory (persistence threshold "
            f"{threshold})", ranks=[0])

    # ------------------------------------------------------------------
    # lazy state init (zero.Init equivalent)
    # ------------------------------------------------------------------
    def _init_state(self, batch: Dict[str, Any]):
        model = self.module
        rng = self._rng
        init_rngs = {"params": rng, "dropout": jax.random.fold_in(rng, 1)}

        def init_fn(rngs):
            return model.init(rngs, **batch, deterministic=True)["params"]

        param_shapes = jax.eval_shape(init_fn, init_rngs)
        self._param_shardings = self.sharding_rules.param_sharding_tree(param_shapes)
        self._grad_shardings = self.sharding_rules.grad_sharding_tree(param_shapes)
        self._compute_dtype = jax.tree.leaves(param_shapes)[0].dtype
        if self._offload_param_device != "none":
            self._apply_param_offload_shardings(param_shapes)

        t0 = time.time()
        if self._initial_params is not None:
            self._params = self._place_initial_params(param_shapes)
            self._initial_params = None  # free the host copy
        else:
            self._params = jax.jit(
                init_fn, out_shardings=self._param_shardings)(init_rngs)
        if self._offload_device in ("cpu", "nvme"):
            # ZeRO-Offload: fp32 masters + moments on host (zero/offload.py)
            # — no device optimizer state is ever allocated
            from deepspeed_tpu.runtime.zero.offload import \
                HostOffloadOptimizer

            if self._client_optimizer is not None:
                raise ValueError(
                    "offload_optimizer cannot honor a client optax "
                    "optimizer: the host step runs the native fused Adam; "
                    "configure the optimizer via the config block or "
                    "disable offload")
            opt_type = (self._config.optimizer.type or "adamw").lower()
            if opt_type not in ("adam", "adamw", "fusedadam"):
                raise NotImplementedError(
                    f"offload_optimizer supports adam-family optimizers "
                    f"(cpu_adam kernel); got {self._config.optimizer.type}")
            off = self._config.zero_config.offload_optimizer or {}
            self._offload_opt = HostOffloadOptimizer(
                self._params, self._param_shardings,
                self._config.optimizer.params,
                compute_dtype=self._compute_dtype,
                gradient_clipping=self.gradient_clipping or 0.0,
                lr_schedule=self._schedule_fn,
                nvme_dir=(off.get("nvme_path", "/local_nvme")
                          if self._offload_device == "nvme" else None))
            self._opt_shardings = None
            self._opt_state = None
        elif self._compressed_mode is not None:
            self._init_compressed_state(param_shapes)
        else:
            opt_shapes = jax.eval_shape(self._tx.init, param_shapes)
            self._opt_shardings = self.sharding_rules.opt_sharding_tree(
                opt_shapes, param_shapes
            )
            self._opt_state = jax.jit(
                self._tx.init, out_shardings=self._opt_shardings
            )(self._params)
        self._acc_grads = jax.jit(
            lambda p: jax.tree.map(
                lambda x: jnp.zeros(
                    ((self._comp_k,) + x.shape) if self._compressed_mode
                    else x.shape, jnp.float32), p),
            out_shardings=self._grad_shardings,
        )(self._params)
        self._initialized = True
        n_params = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(self._params))
        log_dist(
            f"engine state materialized: {n_params/1e6:.1f}M params in "
            f"{time.time()-t0:.1f}s (zero stage {self.zero_stage})",
            ranks=[0],
        )

    # ------------------------------------------------------------------
    # compressed gradient exchange (1-bit optimizers / int8 grad comm)
    # ------------------------------------------------------------------
    def _resolve_dcn_slices(self, gx):
        """Inter-slice group count for the hierarchical deferred exchange
        (1 = flat single-level). ``dcn_slices`` overrides detection so the
        virtual CPU mesh can exercise the DCN leg; otherwise the slice
        factor the mesh derived for the dp axis
        (``MeshTopology.dcn_size``) is used."""
        if gx.hierarchical == "off":
            return 1
        w = self.topology.size("dp")
        n = gx.dcn_slices or self.topology.dcn_size("dp")
        if n <= 1:
            if gx.hierarchical == "on":
                raise ValueError(
                    "tpu.grad_exchange.hierarchical: on, but the dp axis "
                    "has no slice structure (single-slice mesh and "
                    "dcn_slices unset) — use hierarchical: auto to fall "
                    "back to the flat exchange, or set dcn_slices")
            return 1
        if w % n:
            raise ValueError(
                f"hierarchical exchange: {n} DCN slices do not divide the "
                f"dp axis of {w} ranks")
        return n

    def _init_compressed_state(self, param_shapes):
        """State for the shard_mapped compressed step.

        Gradients (and their accumulation buffer) carry a leading
        ``dp``-sized group axis — each worker's UNAVERAGED gradient, which
        the exchange consumes (the compression IS the allreduce; reference
        runtime/comm/nccl.py:51). Per-worker error-feedback buffers shard
        over dp; everything else is replicated.
        """
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self.topology.mesh
        axis = "dp"
        self._comp_k = self.topology.size(axis)
        pw = NamedSharding(mesh, P(axis))
        self._grad_shardings = jax.tree.map(lambda _: pw, param_shapes)
        self._param_specs = jax.tree.map(lambda _: P(), param_shapes)
        self._grad_specs = jax.tree.map(lambda _: P(axis), param_shapes)

        # bucket plan for the explicit exchange (comm/bucketed.py):
        # deferred always buckets (bucket_mb=0 -> one leaf per bucket);
        # int8 buckets only when asked — its error-feedback buffers change
        # shape with the plan, and the legacy per-leaf layout must stay the
        # default for existing checkpoints
        gx = self._config.tpu.grad_exchange_config
        self._bucket_plan = None
        if (self._compressed_mode == "deferred"
                or (self._compressed_mode == "int8" and gx.bucket_mb > 0)):
            from deepspeed_tpu.comm.bucketed import plan_for_tree

            self._bucket_plan = plan_for_tree(param_shapes, gx.bucket_mb)
        self._gx_wire_dtype = (jnp.float32
                               if gx.wire_dtype in ("fp32", "float32")
                               else jnp.bfloat16)
        self._gx_num_slices = (self._resolve_dcn_slices(gx)
                               if self._compressed_mode == "deferred" else 1)
        if self._gx_num_slices > 1:
            # discrete layout decision -> telemetry (docs/observability.md):
            # the flight recorder sees which ranks pay DCN and in what wire
            from deepspeed_tpu.telemetry.bus import (KIND_COMM_HIERARCHY,
                                                     publish)

            publish(KIND_COMM_HIERARCHY,
                    world=int(self._comp_k),
                    num_slices=int(self._gx_num_slices),
                    per_slice=int(self._comp_k // self._gx_num_slices),
                    ici_wire=str(jnp.dtype(self._gx_wire_dtype)),
                    dcn_wire="int8",
                    dcn_block=int(gx.dcn_block),
                    num_buckets=int(self._bucket_plan.num_buckets
                                    if self._bucket_plan else 0))

        if self._compressed_mode == "onebit":
            st_shape = jax.eval_shape(self._tx.init, param_shapes)
            cls = type(st_shape)
            rep = lambda t: jax.tree.map(lambda _: P(), t)  # noqa: E731
            dp_ = lambda t: jax.tree.map(lambda _: P(axis), t)  # noqa: E731
            self._opt_specs = cls(
                count=P(), exp_avg=rep(st_shape.exp_avg),
                exp_avg_sq=rep(st_shape.exp_avg_sq),
                worker_error=dp_(st_shape.worker_error),
                server_error=dp_(st_shape.server_error))
            tx = self._tx

            def init_global(params):
                st = tx.init(params)
                # per-worker buffers gain the leading group axis
                return st._replace(
                    worker_error=jax.tree.map(
                        lambda x: x[None], st.worker_error),
                    server_error=jax.tree.map(
                        lambda x: x[None], st.server_error))

            self._opt_state = jax.jit(jax.shard_map(
                init_global, mesh=mesh, in_specs=(self._param_specs,),
                out_specs=self._opt_specs, check_vma=False))(self._params)
        elif self._compressed_mode == "deferred":
            # bf16/fp32 wire: no quantization, no error feedback — state is
            # just the inner optimizer (1-tuple keeps the (inner, ...) shape
            # of the explicit-exchange family for checkpoints)
            inner = jax.jit(self._tx.init)(self._params)
            self._opt_state = (inner,)
            self._opt_specs = (jax.tree.map(lambda _: P(), inner),)
        elif self._bucket_plan is not None:
            # bucketed int8: residuals live on the flat concatenated bucket
            # payloads, one worker + one server buffer per BUCKET (the
            # compensation spans exactly what each exchange quantizes)
            from deepspeed_tpu.comm.compressed import server_shard_length

            inner = jax.jit(self._tx.init)(self._params)
            k = self._comp_k
            sizes = self._bucket_plan.bucket_sizes()
            err = tuple(
                jax.jit(lambda n=n: jnp.zeros((k, n), jnp.float32),
                        out_shardings=pw)() for n in sizes)
            serr = tuple(
                jax.jit(lambda m=server_shard_length(n, k): jnp.zeros(
                    (k, m), jnp.float32), out_shardings=pw)()
                for n in sizes)
            self._opt_state = (inner, err, serr)
            self._opt_specs = (
                jax.tree.map(lambda _: P(), inner),
                tuple(P(axis) for _ in err),
                tuple(P(axis) for _ in serr))
        else:  # int8 quantized grad allreduce, any optax optimizer
            from deepspeed_tpu.comm.compressed import server_shard_length

            inner = jax.jit(self._tx.init)(self._params)
            err = jax.jit(
                lambda p: jax.tree.map(
                    lambda x: jnp.zeros((self._comp_k,) + x.shape,
                                        jnp.float32), p),
                out_shardings=self._grad_shardings)(self._params)
            # phase-2 (server) error-feedback buffers: one reduced-shard
            # residual per worker per leaf (reference compressed_allreduce
            # compensates both quantization rounds, runtime/comm/nccl.py:51)
            serr_shardings = jax.tree.map(
                lambda x: x.sharding, err)
            serr = jax.jit(
                lambda p: jax.tree.map(
                    lambda x: jnp.zeros(
                        (self._comp_k,
                         server_shard_length(x.size, self._comp_k)),
                        jnp.float32), p),
                out_shardings=serr_shardings)(self._params)
            self._opt_state = (inner, err, serr)
            self._opt_specs = (
                jax.tree.map(lambda _: P(), inner),
                jax.tree.map(lambda _: P(axis), err),
                jax.tree.map(lambda _: P(axis), serr))
        self._opt_shardings = jax.tree.map(
            lambda x: x.sharding, self._opt_state)

    def _compressed_apply_core(self):
        """shard_map program: per-worker grads -> compressed exchange ->
        optimizer update -> replicated new params."""
        from jax.sharding import PartitionSpec as P

        tx = self._tx
        mesh = self.topology.mesh
        k = self._comp_k
        mode = self._compressed_mode
        plan = self._bucket_plan
        wire = self._gx_wire_dtype
        num_slices = self._gx_num_slices
        dcn_block = self._config.tpu.grad_exchange_config.dcn_block

        clip = self.gradient_clipping
        debug_norm = self._config.tpu.compressed_grad_norm

        def apply_step(params, opt_state, grads_pw, lr_factor):
            local_g = jax.tree.map(lambda g: g[0], grads_pw)  # [1,*s]->[*s]
            if mode == "onebit":
                if debug_norm:
                    # debug-only exact pmean: a full fp32 allreduce beside
                    # the compressed exchange (tpu.compressed_grad_norm)
                    g_avg = jax.tree.map(
                        lambda g: jax.lax.pmean(g, "dp"), local_g)
                    grad_norm = optax.global_norm(g_avg)
                else:
                    grad_norm = jnp.float32(0.0)
                st = opt_state._replace(
                    worker_error=jax.tree.map(
                        lambda x: x[0], opt_state.worker_error),
                    server_error=jax.tree.map(
                        lambda x: x[0], opt_state.server_error))
                # grads stay f32: the 1-bit state (momentum, errors) is f32
                updates, new_st = tx.update(local_g, st, params)
                updates = jax.tree.map(
                    lambda u: (u * lr_factor).astype(u.dtype), updates)
                new_params = optax.apply_updates(params, updates)
                new_opt = new_st._replace(
                    worker_error=jax.tree.map(
                        lambda x: x[None], new_st.worker_error),
                    server_error=jax.tree.map(
                        lambda x: x[None], new_st.server_error))
            elif mode == "deferred":
                from deepspeed_tpu.comm.bucketed import (
                    bucketed_all_reduce, hierarchical_all_reduce)

                (inner,) = opt_state
                if num_slices > 1:
                    # two-level ICI/DCN exchange: wire_dtype psum_scatter /
                    # all_gather inside each slice, bucketed int8 EQuARX
                    # exchange of the 1/P shard across slices
                    mean_g = hierarchical_all_reduce(
                        local_g, "dp", num_slices, plan,
                        block=dcn_block, wire_dtype=wire, mean=True,
                        log_name="hierarchical_grad_exchange")
                else:
                    # ONE bucketed explicit exchange at the GAS boundary:
                    # each bucket is an independent collective XLA may
                    # overlap with the others' cast/unpack compute
                    # (T3-style)
                    mean_g = bucketed_all_reduce(
                        local_g, "dp", plan, wire_dtype=wire, mean=True,
                        log_name="bucketed_grad_exchange")
                new_opt_tail = ()
            elif plan is not None:
                from deepspeed_tpu.comm.bucketed import (
                    bucketed_quantized_all_reduce)

                inner, err, serr = opt_state
                # per-BUCKET int8 exchange: independent collective chains
                # (vs the serial per-leaf loop) with residuals carried on
                # the flat bucket payloads
                summed, e2s, se2s = bucketed_quantized_all_reduce(
                    local_g, "dp", plan,
                    worker_errors=[e[0] for e in err],
                    server_errors=[se[0] for se in serr])
                mean_g = jax.tree.map(lambda r: r / k, summed)
                new_opt_tail = (tuple(e[None] for e in e2s),
                                tuple(se[None] for se in se2s))
            else:
                from deepspeed_tpu.comm.compressed import quantized_all_reduce

                inner, err, serr = opt_state
                reduced, new_err, new_serr = [], [], []
                flat_g, treedef = jax.tree.flatten(local_g)
                for g, e, se in zip(flat_g, jax.tree.leaves(err),
                                    jax.tree.leaves(serr)):
                    r, e2, se2 = quantized_all_reduce(
                        g + e[0], "dp", return_error=True,
                        server_error=se[0])
                    reduced.append(r / k)
                    new_err.append(e2[None])
                    new_serr.append(se2[None])
                mean_g = jax.tree.unflatten(treedef, reduced)
                new_opt_tail = (jax.tree.unflatten(treedef, new_err),
                                jax.tree.unflatten(treedef, new_serr))
            if mode != "onebit":
                # the post-exchange mean is materialized anyway: its norm is
                # free, and gradient_clipping gets exact semantics
                grad_norm = optax.global_norm(mean_g)
                if clip and clip > 0:
                    factor = jnp.minimum(1.0, clip / (grad_norm + 1e-6))
                    mean_g = jax.tree.map(lambda g: g * factor, mean_g)
                mean_g = jax.tree.map(lambda g, p: g.astype(p.dtype),
                                      mean_g, params)
                updates, new_inner = tx.update(mean_g, inner, params)
                updates = jax.tree.map(
                    lambda u: (u * lr_factor).astype(u.dtype), updates)
                new_params = optax.apply_updates(params, updates)
                new_opt = (new_inner,) + new_opt_tail
            return new_params, new_opt, grad_norm

        return jax.shard_map(
            apply_step, mesh=mesh,
            in_specs=(self._param_specs, self._opt_specs, self._grad_specs,
                      P()),
            out_specs=(self._param_specs, self._opt_specs, P()),
            check_vma=False)

    def _grouped_grads(self, params, batch, rng, step, loss_scale):
        """Per-worker gradients via a vmap over dp-sized batch groups: each
        group's gradient only depends on its batch shard, so the [k, ...]
        output shards over dp with NO collective — the exchange in the apply
        step is the only cross-worker traffic. Trace-level helper shared by
        the fused and unfused compressed step builders."""
        model = self.module
        k = self._comp_k
        rng = jax.random.fold_in(rng, step)
        rngs = jax.random.split(rng, k)

        pld_kwargs = self._pld_model_kwargs(
            step // self.gradient_accumulation_steps)

        def loss_fn(p, local_batch, r):
            loss = model.apply(
                {"params": p}, **local_batch, deterministic=False,
                rngs={"dropout": r, "gating": jax.random.fold_in(r, 7)},
                **pld_kwargs,
            )
            return loss * loss_scale, loss

        grouped = jax.tree.map(
            lambda x: x.reshape((k, x.shape[0] // k) + x.shape[1:]), batch)
        grads, losses = jax.vmap(
            jax.grad(loss_fn, has_aux=True), in_axes=(None, 0, 0)
        )(params, grouped, rngs)
        return grads, jnp.mean(losses)

    def _build_fwd_bwd_compressed(self):
        gas = self.gradient_accumulation_steps

        def fwd_bwd(params, acc_grads, batch, rng, step, scale):
            grads, loss = self._grouped_grads(
                params, batch, rng, step, scale / gas)
            new_acc = jax.tree.map(
                lambda a, g: a + g.astype(jnp.float32), acc_grads, grads)
            return new_acc, loss

        return jax.jit(
            fwd_bwd,
            donate_argnums=(1,),
            out_shardings=(self._grad_shardings, None),
        )

    def _guarded_compressed_update(self, core, params, opt_state, grads,
                                   ls_state, lr_factor):
        """Overflow-guarded compressed exchange (trace-level, shared by the
        fused and unfused step builders): on fp16 overflow the exchange and
        update are cond-skipped with the error-feedback buffers and the
        optimizer count untouched (reference fp16+onebit skip semantics,
        fp16/onebit/adam.py:10)."""
        overflow = (has_overflow(grads) if self._check_overflow
                    else jnp.bool_(False))

        def do_update(operand):
            params, opt_state, grads = operand
            return core(params, opt_state, grads, lr_factor)

        def skip_update(operand):
            params, opt_state, _ = operand
            return params, opt_state, jnp.float32(0.0)

        new_params, new_opt, grad_norm = jax.lax.cond(
            overflow, skip_update, do_update, (params, opt_state, grads))
        new_ls = update_loss_scale(ls_state, overflow, self._ls_config)
        return new_params, new_opt, new_ls, overflow, grad_norm

    def _build_apply_compressed(self):
        core = self._compressed_apply_core()

        def apply_step(params, opt_state, acc_grads, ls_state, lr_factor):
            grads = jax.tree.map(lambda g: g / ls_state.scale, acc_grads)
            new_params, new_opt, new_ls, overflow, grad_norm = \
                self._guarded_compressed_update(
                    core, params, opt_state, grads, ls_state, lr_factor)
            zero_acc = jax.tree.map(jnp.zeros_like, acc_grads)
            return (new_params, new_opt, zero_acc, new_ls,
                    overflow, grad_norm)

        return jax.jit(apply_step, donate_argnums=(0, 1, 2))

    def _build_train_step_compressed(self):
        core = self._compressed_apply_core()

        def train_step(params, opt_state, ls_state, batch, rng, step,
                       lr_factor):
            grads, loss = self._grouped_grads(
                params, batch, rng, step, ls_state.scale)
            grads = jax.tree.map(
                lambda g: g.astype(jnp.float32) / ls_state.scale, grads)
            new_params, new_opt, new_ls, overflow, grad_norm = \
                self._guarded_compressed_update(
                    core, params, opt_state, grads, ls_state, lr_factor)
            return (new_params, new_opt, new_ls, loss, overflow, grad_norm)

        return jax.jit(train_step, donate_argnums=(0, 1))

    # ------------------------------------------------------------------
    # compiled programs
    # ------------------------------------------------------------------
    def _pld_model_kwargs(self, global_step):
        """Extra model kwargs for stochastic-mode models under a PLD
        schedule: ``pld_theta`` computed IN-GRAPH from the (traced) step
        counter — theta(t) = (1 - theta)e^{-gamma t} + theta, exactly the
        host-side ProgressiveLayerDrop schedule — so the compiled step
        needs no per-step host transfer or recompile."""
        if self.progressive_layer_drop is None:
            return {}
        if not getattr(getattr(self.module, "config", None),
                       "stochastic_mode", False):
            return {}
        pc = self._config.progressive_layer_drop
        theta = pc.theta + (1.0 - pc.theta) * jnp.exp(
            -pc.gamma * jnp.asarray(global_step, jnp.float32))
        return {"pld_theta": theta}

    def _build_fwd_bwd(self):
        if self._compressed_mode is not None:
            return self._build_fwd_bwd_compressed()
        model = self.module
        gas = self.gradient_accumulation_steps
        # offload_param: grads of streamed layers land in HOST memory
        # (per-layer, from the streaming bwd); elementwise accumulation on
        # host tensors is not a device op, so the buffer is REPLACED each
        # micro step — with gas > 1 forward() accumulates host-side numpy
        # (the grads are host-resident anyway; the host optimizer consumes
        # them there)
        replace_acc = self._offload_param_device != "none"

        def fwd_bwd(params, acc_grads, batch, rng, step, scale):
            # fold the step counter in HERE: a host-side jax.random.split per
            # micro step costs a full small-op dispatch round-trip
            rng = jax.random.fold_in(rng, step)

            def loss_fn(p):
                loss = model.apply(
                    {"params": p}, **batch, deterministic=False,
                    rngs={"dropout": rng,
                          "gating": jax.random.fold_in(rng, 7)},
                    **self._pld_model_kwargs(step // gas),
                )
                # loss scaled by 1/gas (reference engine.py:1789 -> :1596)
                # and by the fp16 loss scale (loss_scaler.py)
                return loss * (scale / gas), loss

            grads, loss = jax.grad(loss_fn, has_aux=True)(params)
            if replace_acc:
                return grads, loss
            new_acc = jax.tree.map(
                lambda a, g: a + g.astype(jnp.float32), acc_grads, grads
            )
            return new_acc, loss

        # replace_acc with gas > 1: the previous micro step's grad leaves
        # stay alive until their in-flight host copies are drained
        # (double-buffered host accumulation), so the acc_grads argument
        # must NOT be donated out from under them. At gas == 1 the offload
        # step consumes the grads before the next dispatch — keep donating
        # so peak grad allocation stays at one tree.
        no_donate = replace_acc and gas > 1
        return jax.jit(
            fwd_bwd,
            donate_argnums=() if no_donate else (1,),
            out_shardings=(self._grad_shardings, None),
        )

    def _build_apply(self):
        if self._compressed_mode is not None:
            return self._build_apply_compressed()
        tx = self._tx
        clip = self.gradient_clipping
        # fp16 loss-scale gating, or the sentinel's any-dtype non-finite
        # guard: a NaN/Inf grad tree cond-skips the update either way
        # (update_loss_scale is a no-op when fp16 dynamic scaling is off)
        check_overflow = self._check_overflow
        ls_config = self._ls_config

        def apply_step(params, opt_state, acc_grads, ls_state, lr_factor):
            grads = jax.tree.map(lambda g: g / ls_state.scale, acc_grads)
            overflow = (has_overflow(grads) if check_overflow
                        else jnp.bool_(False))
            grad_norm = optax.global_norm(grads)
            if clip and clip > 0:
                factor = jnp.minimum(1.0, clip / (grad_norm + 1e-6))
                grads = jax.tree.map(lambda g: g * factor, grads)

            def do_update(operand):
                params, opt_state, grads = operand
                # grads ride in f32 for overflow/clip math; the optimizer
                # consumes them in each param's dtype so moment buffers keep
                # the dtype they were initialized with (pure-bf16 training:
                # param_dtype=bf16 means bf16 m/v — the lax.cond skip branch
                # must see identical state types)
                grads = jax.tree.map(lambda g, p: g.astype(p.dtype),
                                     grads, params)
                updates, new_opt = tx.update(grads, opt_state, params)
                # write-through lr: updates are linear in lr (see set_lr)
                updates = jax.tree.map(
                    lambda u: (u * lr_factor).astype(u.dtype), updates)
                new_params = optax.apply_updates(params, updates)
                return new_params, new_opt

            def skip_update(operand):
                params, opt_state, _ = operand
                return params, opt_state

            new_params, new_opt = jax.lax.cond(
                overflow, skip_update, do_update, (params, opt_state, grads)
            )
            new_ls = update_loss_scale(ls_state, overflow, ls_config)
            zero_acc = jax.tree.map(jnp.zeros_like, acc_grads)
            return new_params, new_opt, zero_acc, new_ls, overflow, grad_norm

        return jax.jit(
            apply_step,
            donate_argnums=(0, 1, 2),
            out_shardings=(
                self._param_shardings, self._opt_shardings, self._grad_shardings,
                None, None, None,
            ),
        )

    def _build_train_step(self):
        """Fused fwd+bwd+optimizer in ONE compiled program (used by
        train_batch when gas == 1): one dispatch instead of two, and XLA
        overlaps the optimizer update with the tail of the backward."""
        if self._compressed_mode is not None:
            return self._build_train_step_compressed()
        model = self.module
        tx = self._tx
        clip = self.gradient_clipping
        check_overflow = self._check_overflow  # see _build_apply
        ls_config = self._ls_config

        def train_step(params, opt_state, ls_state, batch, rng, step,
                       lr_factor):
            rng = jax.random.fold_in(rng, step)

            def loss_fn(p):
                loss = model.apply(
                    {"params": p}, **batch, deterministic=False,
                    rngs={"dropout": rng,
                          "gating": jax.random.fold_in(rng, 7)},
                    **self._pld_model_kwargs(step),
                )
                return loss * ls_state.scale, loss

            grads, loss = jax.grad(loss_fn, has_aux=True)(params)
            grads = jax.tree.map(
                lambda g: g.astype(jnp.float32) / ls_state.scale, grads)
            overflow = has_overflow(grads) if check_overflow \
                else jnp.bool_(False)
            grad_norm = optax.global_norm(grads)
            if clip and clip > 0:
                factor = jnp.minimum(1.0, clip / (grad_norm + 1e-6))
                grads = jax.tree.map(lambda g: g * factor, grads)

            def do_update(operand):
                params, opt_state, grads = operand
                # see _build_apply.do_update: optimizer math in param dtype
                grads = jax.tree.map(lambda g, p: g.astype(p.dtype),
                                     grads, params)
                updates, new_opt = tx.update(grads, opt_state, params)
                # write-through lr: updates are linear in lr (see set_lr)
                updates = jax.tree.map(
                    lambda u: (u * lr_factor).astype(u.dtype), updates)
                return optax.apply_updates(params, updates), new_opt

            def skip_update(operand):
                params, opt_state, _ = operand
                return params, opt_state

            new_params, new_opt = jax.lax.cond(
                overflow, skip_update, do_update,
                (params, opt_state, grads))
            new_ls = update_loss_scale(ls_state, overflow, ls_config)
            return new_params, new_opt, new_ls, loss, overflow, grad_norm

        return jax.jit(
            train_step,
            donate_argnums=(0, 1),
            out_shardings=(
                self._param_shardings, self._opt_shardings,
                None, None, None, None,
            ),
        )

    def _build_eval(self):
        model = self.module

        def eval_fn(params, batch):
            return model.apply({"params": params}, **batch, deterministic=True)

        return jax.jit(eval_fn)

    # ------------------------------------------------------------------
    # data
    # ------------------------------------------------------------------
    def deepspeed_io(self, dataset, collate_fn=None, shuffle=True):
        """reference engine.py:1539 deepspeed_io -> DeepSpeedDataLoader,
        or the packed streaming pipeline (deepspeed_tpu/data/, docs/data.md)
        when the ``data_pipeline`` block is enabled."""
        global_micro = (
            self.train_micro_batch_size_per_gpu * self.topology.data_parallel_size
        )
        dp_cfg = self._config.data_pipeline
        if dp_cfg.enabled:
            loader = self._build_data_pipeline(dataset, dp_cfg, global_micro,
                                               shuffle)
        else:
            loader = DeepSpeedDataLoader(
                dataset,
                batch_size=global_micro,
                shuffle=shuffle,
                drop_last=self._config.dataloader_drop_last,
                collate_fn=collate_fn,
            )
        # the engine keeps the training loader: checkpoints carry its
        # (epoch, seed) state, and the sentinel reseeds it on rollback so
        # re-entry doesn't replay the exact batch sequence that diverged
        self.training_dataloader = loader
        return loader

    def _build_data_pipeline(self, dataset, dp_cfg, global_micro, shuffle):
        from deepspeed_tpu.data import DevicePrefetcher, PackedDataPipeline

        shard_rank, num_shards = 0, 1
        if dp_cfg.shard == "process":
            shard_rank, num_shards = jax.process_index(), jax.process_count()
        seqlen_fn = None
        if dp_cfg.curriculum_pack and self.curriculum_scheduler is not None:
            sched = self.curriculum_scheduler
            # pack to the scheduler's quantized difficulty; compiled-shape
            # count stays bounded by the schedule's distinct values. Under
            # prefetch the packer can lag the schedule by queue depth —
            # the consume-time truncation in _apply_curriculum covers any
            # monotone schedule (docs/data.md).
            seqlen_fn = lambda: sched.current_difficulty  # noqa: E731
        pipeline = PackedDataPipeline(
            dataset,
            batch_size=global_micro,
            seq_length=dp_cfg.seq_length,
            pack_sequences=dp_cfg.pack_sequences,
            pad_token_id=dp_cfg.pad_token_id,
            shuffle=shuffle and dp_cfg.shuffle,
            seed=dp_cfg.seed,
            shard_rank=shard_rank,
            num_shards=num_shards,
            seqlen_fn=seqlen_fn,
        )
        if not dp_cfg.prefetch:
            return pipeline
        # the worker thread runs the engine's sharded device_put, so h2d
        # of batch N+1 overlaps compute of batch N; _put_batch passes
        # already-placed arrays through untouched at consume time
        return DevicePrefetcher(pipeline, put_fn=self._put_batch,
                                depth=dp_cfg.prefetch_depth)

    def _put_batch(self, batch: Dict[str, Any]):
        sharding = self.topology.batch_sharding()
        dp = self.topology.data_parallel_size
        sp = self.topology.size("sp")
        expected = self.train_micro_batch_size_per_gpu * dp

        def put(x):
            x = jnp.asarray(x)
            if x.ndim == 0 or x.shape[0] % dp != 0:
                raise ValueError(
                    f"batch leading dim {x.shape} must be the global micro "
                    f"batch (train_micro_batch_size_per_gpu * dp = "
                    f"{self.train_micro_batch_size_per_gpu} * {dp} = {expected})"
                )
            if sp > 1 and x.ndim >= 2 and x.shape[1] % sp == 0:
                # shard the sequence dim over sp (context parallelism)
                spec = list(sharding.spec) + [None] * (x.ndim - len(sharding.spec))
                spec[1] = "sp"
                target = self.topology.sharding(*spec)
            else:
                target = sharding
            # already placed (the prefetch worker ran this device_put in
            # the background): h2d at consume time is a no-op
            if isinstance(x, jax.Array) and x.sharding == target:
                return x
            return jax.device_put(x, target)

        device_batch = jax.tree.map(put, batch)
        self._last_batch_aval = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), device_batch)
        return device_batch

    # ------------------------------------------------------------------
    # train API (reference forward/backward/step protocol)
    # ------------------------------------------------------------------
    def _prof_phase(self, name: str):
        """Step-profiler phase context; when the flight recorder is on it
        wraps the same context to accumulate host dispatch time per phase
        (perf_counter only — the recorder never adds a fence). The shared
        no-op when both are off (one attribute check, no syncs)."""
        inner = (None if self.step_profiler is None
                 else self.step_profiler.phase(name))
        if self.flight_recorder is not None:
            return self.flight_recorder.phase(name, inner)
        return inner if inner is not None else _NULL_PROF_CTX

    def _prof_begin_step(self):
        if self.step_profiler is not None:
            self.step_profiler.begin_step(self.global_steps)
        if self.flight_recorder is not None:
            self.flight_recorder.begin_step(self.global_steps)

    def _prof_end_step(self):
        if self.step_profiler is not None:
            # prefetch queue-depth/starvation gauges ride the Perf/*
            # counter export (docs/observability.md)
            loader = self.training_dataloader
            if loader is not None and hasattr(loader, "counters"):
                self.step_profiler.set_aux_counters(loader.counters())
            # counters passed as a callable: only materialized if this
            # end_step closes the window and exports
            self.step_profiler.end_step(
                self.global_steps, comm_counters=comms_logger.counters,
                cost_cb=self.compiled_step_cost,
                mem_cb=self.compiled_step_memory,
                live_mem_cb=self._live_memory_sample)

    def _live_memory_sample(self) -> Optional[Dict[str, int]]:
        """Allocator watermarks for the flight recorder / profiler
        ``Mem/*`` export. A host-local PJRT query, not a device sync;
        permanently disabled after the first None (CPU backend) so the
        healthy path never re-asks a backend that has no answer."""
        if not self._live_mem_sampling:
            return None
        from deepspeed_tpu.telemetry.memory import live_memory_stats

        stats = live_memory_stats()
        if stats is None:
            self._live_mem_sampling = False
        return stats

    def compiled_step_memory(self) -> Optional[Dict[str, float]]:
        """XLA ``memory_analysis()`` of one optimizer step's compiled
        program(s): per-program argument/output/temp/aliased bytes plus
        the headline ``peak_working_set_bytes`` (max over sequentially-run
        programs), or None before the step has compiled. Same aval
        discipline as :meth:`compiled_step_cost` — lowering the live
        shapes is a compile-cache hit, captured once per program set."""
        from deepspeed_tpu.telemetry.memory import (
            compiled_memory_analysis,
            summarize_program_memory,
        )

        aval = partial(jax.tree.map,
                       lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype))
        if self._last_batch_aval is None or not self._initialized:
            return None
        scale = self._ls_state.scale if self.fp16_enabled else self._unit_scale
        lr_factor = jnp.float32(1.0)
        try:
            if self._train_step_fn is not None:
                mem = compiled_memory_analysis(
                    self._train_step_fn, aval(self._params),
                    aval(self._opt_state), aval(self._ls_state),
                    self._last_batch_aval, aval(self._rng),
                    self.micro_steps, lr_factor)
                return summarize_program_memory({"train_step": mem})
            if self._fwd_bwd_fn is None or self._apply_fn is None:
                return None
            fwd = compiled_memory_analysis(
                self._fwd_bwd_fn, aval(self._params), aval(self._acc_grads),
                self._last_batch_aval, aval(self._rng), self.micro_steps,
                aval(scale))
            app = compiled_memory_analysis(
                self._apply_fn, aval(self._params), aval(self._opt_state),
                aval(self._acc_grads), aval(self._ls_state), lr_factor)
            return summarize_program_memory({"fwd_bwd": fwd, "apply": app})
        except Exception as e:
            logger.warning(f"compiled_step_memory unavailable: {e}")
            return None

    def compiled_step_cost(self) -> Optional[Dict[str, float]]:
        """XLA cost analysis of one optimizer step's compiled program(s):
        ``{"flops", "bytes_accessed", "optimal_seconds"}`` per device, or
        None before the step has compiled. The fused path lowers the
        single step program; the unfused path charges the fwd/bwd program
        once per micro step plus the apply program (the honest per-step
        total). Used by the step profiler and the bench harnesses in
        place of hand-derived FLOP counts."""
        from deepspeed_tpu.profiling.flops_profiler.profiler import (
            cost_analysis)

        aval = partial(jax.tree.map,
                       lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype))
        if self._last_batch_aval is None or not self._initialized:
            return None
        scale = self._ls_state.scale if self.fp16_enabled else self._unit_scale
        lr_factor = jnp.float32(1.0)
        try:
            if self._train_step_fn is not None:
                return cost_analysis(
                    self._train_step_fn, aval(self._params),
                    aval(self._opt_state), aval(self._ls_state),
                    self._last_batch_aval, aval(self._rng),
                    self.micro_steps, lr_factor)
            if self._fwd_bwd_fn is None or self._apply_fn is None:
                return None
            gas = self.gradient_accumulation_steps
            fwd = cost_analysis(
                self._fwd_bwd_fn, aval(self._params), aval(self._acc_grads),
                self._last_batch_aval, aval(self._rng), self.micro_steps,
                aval(scale))
            app = cost_analysis(
                self._apply_fn, aval(self._params), aval(self._opt_state),
                aval(self._acc_grads), aval(self._ls_state), lr_factor)
            return {k: fwd[k] * gas + app[k] for k in fwd}
        except Exception as e:
            logger.warning(f"compiled_step_cost unavailable: {e}")
            return None

    def _apply_step_autotune(self, model, config, num_devices=None):
        """Resolve the tuned step config for this module/device and clone
        the module with the winner's remat policy / flash setting (the
        ``apply_sparse_attention`` pattern: the model is rebuilt from
        config before any state or program exists). With
        ``apply_micro_batch`` the winner's micro batch replaces the
        configured one and the batch triad re-derives against the mesh."""
        from deepspeed_tpu.models.transformer_lm import GPT
        from deepspeed_tpu.runtime import step_autotune as sa

        if not isinstance(model, GPT):
            log_dist("step_autotune: module is not a GPT model; skipping",
                     ranks=[0])
            return model
        sac = self._step_autotune_cfg
        cfg = model.config
        search_kwargs: Dict[str, Any] = {"live_steps": sac.live_steps}
        if sac.micro_batches:
            search_kwargs["micro_batches"] = tuple(sac.micro_batches)
        if sac.policies:
            search_kwargs["policies"] = tuple(sac.policies)
        if sac.hbm_gib:
            search_kwargs["hbm_override_gib"] = sac.hbm_gib
        winner = sa.get_step_config(
            sa.model_key(cfg), cfg.n_positions, cfg.dtype,
            num_devices=num_devices,
            autotune=True if sac.autotune else None,
            search_kwargs=search_kwargs)
        if winner is None:
            log_dist("step_autotune: no tuned entry for this model/device; "
                     "module unchanged", ranks=[0])
            return model
        self.step_autotune_winner = winner
        new_cfg = dataclasses.replace(
            cfg, remat=True, remat_policy=winner["remat_policy"],
            use_flash_attention=bool(winner["flash"]))
        if new_cfg != cfg:
            model = model.clone(config=new_cfg)
        if (sac.apply_micro_batch
                and int(winner["micro_batch"])
                != config.train_micro_batch_size_per_gpu):
            config.train_micro_batch_size_per_gpu = int(
                winner["micro_batch"])
            config.train_batch_size = None  # re-derived vs the actual mesh
        log_dist(
            "step_autotune: applied "
            f"{winner['remat_policy']}/micro{winner['micro_batch']}/"
            f"{'flash' if winner['flash'] else 'dense'} "
            f"(source={winner.get('source', '?')})", ranks=[0])
        return model

    def forward(self, batch: Dict[str, Any]):
        """Compute loss for one micro batch. Gradients are computed fused with
        the forward (JAX has no separate backward graph) and cached until
        ``backward()`` commits them — same cost, same calling convention."""
        set_default_topology(self.topology)
        batch = dict(batch)
        if self.curriculum_scheduler is not None:
            batch = self._apply_curriculum(batch)
        if not self._initialized:
            self._init_state(batch)
        compile_pending = self._fwd_bwd_fn is None
        if compile_pending:
            self._fwd_bwd_fn = self._build_fwd_bwd()
        # heartbeat: every micro step re-arms; the step boundary disarms.
        # The first call compiles (minutes, legitimately) — the watchdog
        # cannot tell that from a hang, so it stays disarmed around it;
        # size hang_timeout_s above any expected mid-run recompile
        # (e.g. a curriculum shape change).
        if self._watchdog is not None and not compile_pending:
            self._watchdog.arm()

        if self.wall_clock_breakdown:
            self.timers(FORWARD_MICRO_TIMER).start()
        self.tput_timer.start()

        # idempotent: train_batch() already opened the step envelope; a
        # direct forward/backward/step caller opens it here instead
        self._prof_begin_step()
        with self._prof_phase("h2d"):
            device_batch = self._put_batch(batch)
        scale = self._ls_state.scale if self.fp16_enabled else self._unit_scale

        # one-shot flops profile at the configured step (reference
        # engine.py:1629-1648 activates the profiler for a single step)
        fp_cfg = self._config.flops_profiler
        if (fp_cfg.enabled and not self._flops_profiled
                and self.global_steps >= fp_cfg.profile_step):
            from deepspeed_tpu.profiling.flops_profiler import FlopsProfiler

            log_dist(
                "flops profiler: compiling a one-off cost-analysis copy of "
                "the step program (XLA compile, happens once)", ranks=[0])
            prof = FlopsProfiler(self._fwd_bwd_fn)
            prof.profile_fn(self._params, self._acc_grads, device_batch,
                            self._rng, self.micro_steps, scale,
                            measure_time=False, params=self._params)
            prof.print_profile()
            self._flops_profiled = True

        # grads accumulate eagerly (the donated buffer is consumed here);
        # backward() is the protocol-parity bookkeeping step
        prev_pending = self._pending_grad_leaves
        with self._prof_phase("compiled_step"):
            self._acc_grads, loss = self._fwd_bwd_fn(
                self._params, self._acc_grads, device_batch, self._rng,
                self.micro_steps, scale
            )
        if (self._offload_param_device != "none"
                and self.gradient_accumulation_steps > 1):
            # streamed-param mode replaces the grad tree each micro step;
            # accumulate host-side f32 (the host optimizer consumes numpy
            # grads anyway, and each micro grad is already scaled by 1/gas).
            # Double-buffered: this step's leaves only have their async
            # copies STARTED here; they are materialized after the NEXT
            # micro step is dispatched (or at the boundary drain), so the
            # device->host transfer of step i overlaps the compute of
            # step i+1 instead of serializing the accumulation window.
            self._pending_grad_leaves = jax.tree.leaves(self._acc_grads)
            for leaf in self._pending_grad_leaves:
                if hasattr(leaf, "copy_to_host_async"):
                    leaf.copy_to_host_async()
            if prev_pending is not None:
                self._accumulate_host_grads(prev_pending)
        self._backward_pending = True
        self._last_loss = loss
        if self.wall_clock_breakdown:
            self.timers(FORWARD_MICRO_TIMER).stop()
        return loss

    def _accumulate_host_grads(self, dev_leaves):
        """Fold one micro step's (already copy-initiated) grad leaves into
        the host-side f32 accumulator."""
        leaves = [np.asarray(leaf) for leaf in dev_leaves]
        if self._host_grad_acc is None:
            self._host_grad_acc = [
                np.asarray(l, np.float32).copy() for l in leaves]
        else:
            for buf, l in zip(self._host_grad_acc, leaves):
                buf += np.asarray(l, np.float32)

    def _take_offload_step(self):
        """Host optimizer step (ZeRO-Offload): grads to host, native fused
        Adam over fp32 masters, compute-dtype params back to device."""
        scale = float(self._ls_state.scale) if self.fp16_enabled else 1.0
        if self._pending_grad_leaves is not None:
            # drain the last micro step's in-flight copies
            self._accumulate_host_grads(self._pending_grad_leaves)
            self._pending_grad_leaves = None
        grads_src = self._acc_grads
        if self._host_grad_acc is not None:
            grads_src = jax.tree.unflatten(
                jax.tree.structure(self._acc_grads), self._host_grad_acc)
            self._host_grad_acc = None
        self._params, overflow, grad_norm = self._offload_opt.step(
            grads_src, loss_scale=scale,
            global_step=self.global_steps, current_params=self._params,
            lr_override=self._lr_override)
        if np.isfinite(grad_norm):  # skipped overflow step: keep last valid
            self._last_grad_norm = grad_norm
        if self._offload_param_device == "none":
            if self._zero_acc_fn is None:
                self._zero_acc_fn = jax.jit(
                    lambda g: jax.tree.map(jnp.zeros_like, g),
                    donate_argnums=(0,),
                    out_shardings=self._grad_shardings)
            self._acc_grads = self._zero_acc_fn(self._acc_grads)
        # offload_param: the grad tree is REPLACED by the next forward
        # (host-memory buffers have no device zeroing program)
        if self.fp16_enabled:
            self._ls_state = update_loss_scale(
                self._ls_state, jnp.bool_(overflow), self._ls_config)
        return jnp.bool_(overflow)

    def backward(self, loss=None):
        """Record the micro-step loss (reference engine.py:1764; the gradient
        computation already ran fused with ``forward`` — JAX has no separate
        backward graph)."""
        assert self._backward_pending, (
            "backward() must follow forward() (fused grad computation)"
        )
        self._backward_pending = False
        self._step_losses.append(self._last_loss)
        return loss if loss is not None else self._last_loss

    def is_gradient_accumulation_boundary(self) -> bool:
        """reference engine.py:1855."""
        return (self.micro_steps + 1) % self.gradient_accumulation_steps == 0

    def step(self):
        """reference engine.py:1971 — model step only at the GAS boundary."""
        at_boundary = self.is_gradient_accumulation_boundary()
        if at_boundary:
            self._take_model_step()
        self.micro_steps += 1
        self.global_samples += (
            self.train_micro_batch_size_per_gpu * self.topology.data_parallel_size
        )
        self.tput_timer.stop(global_step=at_boundary)

    def _take_model_step(self):
        try:
            if self.wall_clock_breakdown:
                self.timers(STEP_MICRO_TIMER).start()
            if self._offload_opt is not None:
                with self._prof_phase("compiled_step"):
                    overflow = self._take_offload_step()
            else:
                if self._apply_fn is None:
                    self._apply_fn = self._build_apply()
                with self._prof_phase("compiled_step"):
                    (
                        self._params, self._opt_state, self._acc_grads,
                        self._ls_state, overflow, grad_norm,
                    ) = self._apply_fn(
                        self._params, self._opt_state, self._acc_grads,
                        self._ls_state, self._lr_factor_now()
                    )
                # gate short-circuit first: bool(overflow) on the device
                # scalar would force a host sync every step when neither
                # fp16 nor the sentinel's non-finite guard is on
                if (self._compressed_mode is None
                        or self._compressed_norm_available) and not (
                        self._check_overflow and bool(overflow)):
                    self._last_grad_norm = grad_norm
            self.global_steps += 1
            self._post_step_bookkeeping(overflow, self._step_losses)
            self._step_losses = []
            if self.wall_clock_breakdown:
                self.timers(STEP_MICRO_TIMER).stop()
                self.timers.log([FORWARD_MICRO_TIMER, STEP_MICRO_TIMER])
            self._prof_end_step()
        finally:
            # the step boundary is the heartbeat's end, even when the
            # bookkeeping raised (DivergenceError must not leave the
            # watchdog armed over user exception handling)
            if self._watchdog is not None:
                self._watchdog.disarm()

    def _post_step_bookkeeping(self, overflow, step_losses):
        """Host tail shared by the fused and unfused step paths: overflow
        accounting, lr schedule, PLD, MoQ, sentinel verdict, progress +
        monitor events."""
        update_skipped = self._check_overflow and bool(overflow)
        if update_skipped:
            self.skipped_steps += 1
            if self.fp16_enabled:
                log_dist(
                    f"overflow at step {self.global_steps}; loss scale -> "
                    f"{float(self._ls_state.scale)}", ranks=[0],
                )
            else:
                # the sentinel's non-finite guard tripped in-graph: the
                # optimizer state is untouched, only the batch was burned
                log_dist(
                    f"non-finite gradients at step {self.global_steps}; "
                    f"update skipped (sentinel)", ranks=[0],
                )
        elif self.lr_scheduler is not None:
            self.lr_scheduler.step()
            # torch parity: an explicit scheduler re-asserts the schedule
            # over a manual param_groups["lr"] set (see set_lr)
            self._lr_override = None
        if self.progressive_layer_drop is not None:
            self.progressive_layer_drop.update_state(self.global_steps)
        if self.quantizer is not None:
            self._rng, qrng = jax.random.split(self._rng)
            quantized = self.quantizer.quantize(
                self._params,
                overflow=update_skipped,
                eigenvalue_enabled=self.quantizer.q_eigenvalue,
                rng=qrng)
            if self._reshard_params_fn is None:
                # one cached jit: a fresh lambda per step would retrace the
                # identity resharding program every optimizer step
                self._reshard_params_fn = jax.jit(
                    lambda t: t, out_shardings=self._param_shardings)
            self._params = self._reshard_params_fn(quantized)
        if self.compression_compressor is not None and not update_skipped:
            self._rng, crng = jax.random.split(self._rng)
            compressed = self.compression_compressor.jitted_apply(
                self._params, self.global_steps, key=crng)
            if compressed is not self._params:
                if self._reshard_params_fn is None:
                    self._reshard_params_fn = jax.jit(
                        lambda t: t, out_shardings=self._param_shardings)
                self._params = self._reshard_params_fn(compressed)
        if self._autotune_metric_path is not None:
            from deepspeed_tpu.utils.timer import fence

            start = max(1, self._autotune_start_step)
            if self.global_steps >= start and self._autotune_t0 is None:
                # >= not ==: a script that resumes from a checkpoint may
                # enter past the nominal window start
                fence(self._params)
                self._autotune_t0 = time.time()
                self._autotune_t0_step = self.global_steps
            elif (self.global_steps >= max(self._autotune_end_step,
                                           self._autotune_t0_step + 1)
                    and self._autotune_t0 is not None):
                from deepspeed_tpu.autotuning.cli import write_metric_file

                fence(self._params)
                steps = self.global_steps - self._autotune_t0_step
                dt = (time.time() - self._autotune_t0) / max(steps, 1)
                gb = (self.train_micro_batch_size_per_gpu
                      * self.topology.data_parallel_size
                      * self.gradient_accumulation_steps)
                write_metric_file(self._autotune_metric_path,
                                  samples_per_sec=gb / dt,
                                  ms_per_step=dt * 1000.0)
                self._autotune_metric_path = None  # write once
        if self.global_steps % self._config.steps_per_print == 0:
            self._report_progress()
        # host-materialize the mean loss ONCE, and only for consumers that
        # were going to pay the device sync anyway (monitor export,
        # sentinel verdict); the flight recorder reuses it but never
        # triggers the pull itself (zero-added-syncs discipline)
        monitor_on = (self.monitor is not None
                      and getattr(self.monitor, "enabled", True))
        host_loss = None
        if step_losses and (monitor_on or self.sentinel is not None):
            host_loss = float(np.mean([float(l) for l in step_losses]))
        if monitor_on and host_loss is not None:
            self.monitor.write_events(
                [("Train/Samples/train_loss", host_loss,
                  self.global_samples)]
            )
        if self.flight_recorder is not None:
            self._record_flight_step(host_loss, update_skipped)
        if self.sentinel is not None:
            with self._prof_phase("sentinel"):
                self._sentinel_observe(update_skipped, host_loss)
        if self.health_plane is not None:
            self._health_step_hook()
        if self._preempt_signum is not None:
            self._graceful_shutdown()

    def _record_flight_step(self, host_loss, update_skipped):
        """Append this optimizer step to the flight recorder ring —
        BEFORE the sentinel verdict, so a diverging step's own loss is in
        the blackbox. Every field is already host-side: loss from the
        shared materialization above, grad-norm only when the sentinel
        already paid its ``float()``, comm/feed counters are plain host
        dicts, live memory is a host-local allocator query."""
        grad_norm = (self.get_global_grad_norm()
                     if self.sentinel is not None else None)
        if not self._mem_static_captured:
            # once, after the first step compiled: the static HBM budget
            # (memory_analysis() breakdown) rides in every blackbox even
            # on backends whose live memory_stats() is None (CPU). AOT
            # re-lowering with the same avals is an executable-cache hit.
            self._mem_static_captured = True
            try:
                mem = self.compiled_step_memory()
                if mem:
                    self.flight_recorder.set_static(compiled_memory=mem)
            except Exception:
                pass
        feed = None
        loader = self.training_dataloader
        if loader is not None and hasattr(loader, "counters"):
            feed = loader.counters()
        extra = {"skipped": True} if update_skipped else {}
        self.flight_recorder.record_step(
            self.global_steps, loss=host_loss, grad_norm=grad_norm,
            comm=comms_logger.counters() or None, feed=feed,
            mem=self._live_memory_sample(), **extra)

    def _apply_curriculum(self, batch):
        """Truncate sequence tensors to the scheduled difficulty (one
        compiled program per distinct value; shared with the pipeline
        engine)."""
        from deepspeed_tpu.runtime.data_pipeline import (
            truncate_batch_to_difficulty)

        seqlen = self.curriculum_scheduler.update_difficulty(
            self.global_steps + 1)
        return truncate_batch_to_difficulty(batch, seqlen)

    def train_batch(self, data_iter):
        """Full effective-batch step: gas micro steps + model update
        (PipelineEngine.train_batch parity, pipe/engine.py:296). Returns the
        mean micro loss. With gas == 1 the whole step runs as one fused
        compiled program (fwd+bwd+optimizer)."""
        # the step envelope opens before the dataloader pull so input-bound
        # steps show up as a fat `dataloader` phase, not missing time
        self._prof_begin_step()
        # tpu.step_autotune.fused_step: "off" forces the two-program
        # fwd/bwd + apply split (the A/B baseline), "on" fuses the
        # optimizer tail even under wall_clock_breakdown (phase detail
        # collapses into compiled_step), and "auto" additionally honors a
        # step-autotune winner whose live benchmark measured the fused
        # tail faster — the "optimizer tail on the critical path" signal.
        mode = self._fused_step_mode
        fusable = (self.gradient_accumulation_steps == 1
                   and not self._config.flops_profiler.enabled
                   and self._offload_device == "none"
                   and mode != "off")
        winner_fuses = bool(
            (self.step_autotune_winner or {}).get("fuse_optimizer"))
        if fusable and (mode == "on" or winner_fuses
                        or not self.wall_clock_breakdown):
            with self._prof_phase("dataloader"):
                batch = next(data_iter)
            return self._train_batch_fused(batch)
        losses = []
        for _ in range(self.gradient_accumulation_steps):
            with self._prof_phase("dataloader"):
                batch = next(data_iter)
            loss = self.forward(batch)
            self.backward()
            losses.append(loss)
            self.step()
        return jnp.mean(jnp.stack([jnp.asarray(l) for l in losses]))

    def _train_batch_fused(self, batch):
        # model modules (VocabEmbed, MoE constraints, sp attention) read the
        # ambient default topology at TRACE time — re-assert this engine's
        # mesh so interleaved construction of engines on different meshes
        # cannot leak a mismatched topology into a lazily-compiled step
        set_default_topology(self.topology)
        batch = dict(batch)
        if self.curriculum_scheduler is not None:
            batch = self._apply_curriculum(batch)
        if not self._initialized:
            self._init_state(batch)
        compile_pending = self._train_step_fn is None
        if compile_pending:
            self._train_step_fn = self._build_train_step()
        # arm the hang watchdog around the dispatched step (skipped on
        # the compiling first call — see forward())
        if self._watchdog is not None and not compile_pending:
            self._watchdog.arm()
        try:
            self.tput_timer.start()
            self._prof_begin_step()
            with self._prof_phase("h2d"):
                device_batch = self._put_batch(batch)
            with self._prof_phase("compiled_step"):
                (self._params, self._opt_state, self._ls_state, loss, overflow,
                 grad_norm) = self._train_step_fn(
                    self._params, self._opt_state, self._ls_state, device_batch,
                    self._rng, self.micro_steps, self._lr_factor_now())
            if (self._compressed_mode is None
                    or self._compressed_norm_available) and not (
                    self._check_overflow and bool(overflow)):
                self._last_grad_norm = grad_norm
            self._last_loss = loss
            self.micro_steps += 1
            self.global_steps += 1
            self.global_samples += (
                self.train_micro_batch_size_per_gpu
                * self.topology.data_parallel_size)

            self._post_step_bookkeeping(overflow, [loss])
            self.tput_timer.stop(global_step=True)
            self._prof_end_step()
            return loss
        finally:
            if self._watchdog is not None:
                self._watchdog.disarm()

    def eval_batch(self, batch: Dict[str, Any]):
        set_default_topology(self.topology)
        batch = dict(batch)
        if not self._initialized:
            self._init_state(batch)
        if self._eval_fn is None:
            self._eval_fn = self._build_eval()
        return self._eval_fn(self._params, self._put_batch(batch))

    def __call__(self, batch):
        return self.eval_batch(batch)

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------
    def get_lr(self):
        if self._lr_override is not None:
            return [self._lr_override]
        if self.lr_scheduler is not None:
            return self.lr_scheduler.get_last_lr()
        lr = self._config.optimizer.params.get("lr", 0.0)
        return [lr]

    def _scheduled_lr(self) -> float:
        """The lr the compiled optimizer will apply at the CURRENT step
        (what the baked-in schedule or config constant evaluates to). The
        compiled optimizer samples its own optax count, which only advances
        on non-skipped steps — index the schedule the same way, or the
        override factor would divide by the wrong base after fp16 skips."""
        if self._schedule_fn is not None:
            return float(self._schedule_fn(
                self.global_steps - self.skipped_steps))
        return float(self._config.optimizer.params.get("lr", 1e-3))

    def set_lr(self, lr: float) -> None:
        """Write-through lr (reference users mutate
        ``optimizer.param_groups[0]["lr"]`` directly): overrides the
        schedule with an absolute lr from the next step on. Torch-parity
        scheduler interplay: with an active lr_scheduler the override lasts
        one step (``scheduler.step()`` re-asserts the schedule, exactly as
        torch schedulers overwrite manual sets); without one it persists.
        Implemented as a per-step factor ``lr / scheduled_lr`` multiplied
        into the compiled step's updates — no recompile."""
        if self._client_optimizer is not None:
            raise NotImplementedError(
                "set_lr/param_groups['lr'] write-through needs the engine-"
                "built optimizer; a client optax transformation owns its "
                "own hyperparameters")
        self._lr_override = float(lr)

    def _lr_factor_now(self):
        """f32 scalar factor for the compiled step (1.0 = no override)."""
        if self._lr_override is None:
            return jnp.float32(1.0)
        base = self._scheduled_lr()
        if abs(base) < 1e-30:
            logger.warning(
                "param_groups lr override %.3g ignored for this step: the "
                "scheduled lr is 0 and updates scale multiplicatively",
                self._lr_override)
            return jnp.float32(1.0)
        return jnp.float32(self._lr_override / base)

    def get_global_grad_norm(self):
        """Pre-clip global gradient norm of the last optimizer step
        (reference engine.get_global_grad_norm). None before the first step
        and under the 1-bit optimizers unless ``tpu.compressed_grad_norm``
        enables the debug pmean; the int8 path always materializes it from
        the post-exchange mean."""
        if self._last_grad_norm is None:
            return None
        return float(self._last_grad_norm)

    @property
    def loss_scale(self):
        return float(self._ls_state.scale) if self._ls_state is not None else 1.0

    @property
    def params(self):
        return self._params

    def _report_progress(self):
        lr = self.get_lr()
        log_dist(
            f"step={self.global_steps}, skipped={self.skipped_steps}, "
            f"lr={lr}, loss_scale={self.loss_scale}",
            ranks=[0],
        )

    # ------------------------------------------------------------------
    # preemption-aware shutdown (no reference analogue; docs/recovery.md)
    # ------------------------------------------------------------------
    def _install_signal_handlers(self):
        cfg = self._config.graceful_shutdown
        if threading.current_thread() is not threading.main_thread():
            logger.warning(
                "graceful_shutdown: not on the main thread; signal "
                "handlers not installed")
            return
        for name in cfg.signals:
            signum = getattr(signal_module, str(name))
            self._old_signal_handlers[signum] = signal_module.signal(
                signum, self._signal_handler)
        log_dist(
            f"graceful_shutdown armed for {list(cfg.signals)} -> "
            f"{cfg.save_dir}", ranks=[0])

    def _restore_signal_handlers(self):
        handlers, self._old_signal_handlers = self._old_signal_handlers, {}
        for signum, old in handlers.items():
            try:
                signal_module.signal(signum, old)
            except (ValueError, TypeError):
                pass

    def _signal_handler(self, signum, frame):
        # async-signal context: only set the flag; the actual save runs at
        # the next step boundary (_post_step_bookkeeping)
        self._preempt_signum = signum
        logger.warning(
            "received signal %s: will checkpoint and exit at the next "
            "step boundary", signal_module.Signals(signum).name)

    def _graceful_shutdown(self):
        """Final save + commit, then exit (config-gated). Runs on the
        normal host control path, never inside the signal handler."""
        cfg = self._config.graceful_shutdown
        signum, self._preempt_signum = self._preempt_signum, None
        self._restore_signal_handlers()  # a second signal kills normally
        log_dist(
            f"graceful shutdown (signal "
            f"{signal_module.Signals(signum).name}): saving final "
            f"checkpoint at step {self.global_steps}", ranks=[0])
        self.save_checkpoint(cfg.save_dir, tag=cfg.tag)
        self.ft_stats["graceful_shutdowns"] += 1
        self._emit_ft_events()
        self._publish_telemetry(
            "shutdown.graceful",
            signal=signal_module.Signals(signum).name, tag=str(cfg.tag))
        if cfg.exit_after_save:
            if self._watchdog is not None:
                self._watchdog.stop()
            if self.health_plane is not None:
                # a preemption grace exit is sanctioned: our own plane
                # must not declare still-saving peers down and turn the
                # clean exit into a coordinated 15
                self.health_plane.stop()
            if self.monitor is not None:
                # flush/close TB, wandb and CSV before the process dies
                self.monitor.close()
            if self._telemetry_uninstall is not None:
                # a clean preemption exit is not a crash: drop the hooks
                # so the SystemExit below leaves no blackbox behind
                self._telemetry_uninstall()
                self._telemetry_uninstall = None
            if self.flight_recorder is not None:
                # the SIGTERM handler already dumped before it could know
                # the grace save would commit; the checkpoint is the real
                # evidence now, so withdraw the stale blackbox
                self.flight_recorder.retract_dump()
            raise SystemExit(cfg.exit_code)

    def _emit_ft_events(self):
        if self.monitor is None or not getattr(self.monitor, "enabled",
                                               False):
            return
        from deepspeed_tpu.monitor.monitor import counter_events

        counters = dict(self.ft_stats)
        counters["ckpt_io_retries"] = self.checkpoint_engine.io_retry_count
        self.monitor.write_events(
            counter_events("FaultTolerance", counters, self.global_steps))

    # ------------------------------------------------------------------
    # training health sentinel (docs/recovery.md "Divergence and hang
    # recovery"): detect → skip → rollback → diverge
    # ------------------------------------------------------------------
    def _sentinel_observe(self, update_skipped, host_loss):
        from deepspeed_tpu.runtime.sentinel import (
            VERDICT_ANOMALY,
            VERDICT_DIVERGED,
            VERDICT_ROLLBACK,
        )

        verdict, reason = self.sentinel.observe(
            loss=host_loss, grad_norm=self.get_global_grad_norm(),
            update_skipped=update_skipped, fp16=self.fp16_enabled,
            step=self.global_steps)
        if verdict == VERDICT_ANOMALY:
            logger.warning("sentinel: %s", reason)
            self._publish_telemetry(
                "sentinel.skip", severity="warning", reason=reason)
        elif verdict == VERDICT_ROLLBACK:
            logger.warning("sentinel: %s", reason)
            self._sentinel_rollback(reason)
        elif verdict == VERDICT_DIVERGED:
            self._sentinel_divergence(reason)  # raises
        self._emit_sentinel_events()

    def _publish_telemetry(self, kind, severity="info", **payload):
        """Bus publish, rank-tagged and step-stamped; a silent no-op when
        telemetry is disabled (the recorder is the only subscriber the
        engine guarantees, so no recorder means nobody is listening)."""
        if self.flight_recorder is None:
            return
        from deepspeed_tpu.telemetry import publish

        publish(kind, step=self.global_steps, severity=severity, **payload)

    def _sentinel_rollback(self, reason):
        """Restore the newest manifest-valid checkpoint and reseed the
        data order — replaying the exact batch sequence that just
        diverged would diverge again."""
        cfg = self._config.sentinel
        load_dir = cfg.rollback_dir
        tag = (ckpt_manifest.latest_valid_tag(load_dir)
               if load_dir else None)
        if tag is None:
            self._sentinel_divergence(
                reason + ("; no manifest-valid checkpoint to roll back "
                          f"to in {load_dir}" if load_dir else
                          "; sentinel.rollback_dir is not set"))
        self.sentinel.note_rollback()
        self._publish_telemetry(
            "sentinel.rollback", severity="warning", reason=reason,
            tag=str(tag),
            rollbacks_used=self.sentinel.stats["rollbacks"])
        log_dist(
            f"sentinel: rolling back to manifest-valid tag {tag} "
            f"({self.sentinel.stats['rollbacks']}/{cfg.rollback_budget} "
            f"rollbacks used)", ranks=[0])
        self.load_checkpoint(load_dir, tag=tag)
        loader = self.training_dataloader
        if (cfg.reseed_on_rollback and loader is not None
                and hasattr(loader, "reseed")):
            # offset by the rollback count: each re-entry gets a distinct
            # order, deterministically derived from the base seed
            loader.reseed(self.sentinel.stats["rollbacks"])
            log_dist(
                f"sentinel: reseeded data order (seed -> {loader.seed})",
                ranks=[0])

    def _sentinel_divergence(self, reason):
        from deepspeed_tpu.runtime.sentinel import DivergenceError

        cfg = self._config.sentinel
        self._publish_telemetry(
            "sentinel.diverged", severity="fatal", reason=reason)
        self._emit_sentinel_events()
        if self._watchdog is not None:
            self._watchdog.stop()
        if self.health_plane is not None:
            # divergence is terminal for the whole run: stop beating so
            # peers see clean silence, not a half-alive zombie
            self.health_plane.stop()
        logger.error("sentinel: training diverged: %s", reason)
        err = DivergenceError(
            f"training diverged: {reason}. Workers should exit with code "
            f"{cfg.divergence_exit_code} (DivergenceError.exit_code) so "
            f"the elastic agent stops restart-looping into it.",
            cfg.divergence_exit_code)
        if self.flight_recorder is not None:
            # dump HERE, not in excepthook: the sanctioned worker exit is
            # a *caught* DivergenceError + sys.exit(13), which never
            # reaches sys.excepthook (flight_recorder.py trigger matrix)
            self.flight_recorder.dump(
                "divergence", exit_code=cfg.divergence_exit_code, exc=err)
        raise err

    def _on_watchdog_fire(self, dump: str = ""):
        """HangWatchdog ``on_fire``: blackbox first (an ``abort`` hang
        action is ``os._exit``, which skips atexit), then the sentinel's
        own bookkeeping."""
        cfg = self._config.sentinel
        fatal = cfg.hang_action == "abort"
        self._publish_telemetry(
            "sentinel.watchdog_fire",
            severity="fatal" if fatal else "warning",
            timeout_s=cfg.hang_timeout_s, action=cfg.hang_action)
        if self.flight_recorder is not None and fatal:
            # a "warn" fire is survivable — dumping then would spend the
            # first-reason-wins slot a later real crash needs
            self.flight_recorder.dump(
                "hang_watchdog", exit_code=cfg.hang_exit_code)
        self.sentinel.note_watchdog_fire(dump)

    def _emit_sentinel_events(self):
        """Export the sentinel counters as ``Sentinel/*`` monitor events
        whenever they changed (the _emit_ft_events pattern; a healthy run
        writes nothing)."""
        if (self.sentinel is None or self.monitor is None
                or not getattr(self.monitor, "enabled", False)):
            return
        counters = self.sentinel.counters()
        if counters == self._sentinel_emitted:
            return
        from deepspeed_tpu.monitor.monitor import counter_events

        self.monitor.write_events(
            counter_events("Sentinel", counters, self.global_steps))
        self._sentinel_emitted = counters

    # ------------------------------------------------------------------
    # cluster health plane (docs/recovery.md "Cluster health & SDC
    # defense"): the engine side of runtime/health.py — step/digest
    # feed, Health/* export, blackbox-then-abort, SDC rollback routing
    # ------------------------------------------------------------------
    def _watchdog_armed(self) -> bool:
        """Beat payload probe: is this host currently mid-step? The
        survivors' beats carrying ``watchdog_armed=True`` while a peer is
        silent is the shared diagnosis ("everyone else is parked in the
        collective") no single-process watchdog can produce."""
        wd = self._watchdog
        return wd is not None and wd.armed

    def _health_step_hook(self):
        """Step-boundary feed for the health plane: advance the beat's
        step counter + step-time EWMA, run the every-K SDC digest probe,
        and route a pending mismatch (``sdc_action: rollback``) through
        the sentinel's rollback path."""
        plane = self.health_plane
        plane.notify_step(self.global_steps)
        k = self._health_cfg.digest_every_k
        if k > 0 and self.global_steps % k == 0:
            from deepspeed_tpu.runtime.health import param_digest

            with self._prof_phase("health_digest"):
                plane.submit_digest(self.global_steps,
                                    param_digest(self._params))
        fault = plane.take_sdc_fault()
        if fault is not None:
            reason = (f"SDC digest mismatch vs peer {fault['peer']} at "
                      f"step {fault['digest_step']} "
                      f"(ours={fault['ours']:#010x} "
                      f"theirs={fault['theirs']:#010x})")
            if self.flight_recorder is not None:
                # the mismatch evidence must survive even if the rollback
                # below fails and escalates
                self.flight_recorder.dump(
                    "sdc", exit_code=self._health_cfg.exit_code)
            if self.sentinel is not None \
                    and self._config.sentinel.rollback_dir:
                logger.error("cluster health: %s — rolling back", reason)
                self._sentinel_rollback(reason)
            else:
                # no in-process rollback target: fall back to the
                # coordinated abort so the agent relaunches the world
                # from the newest manifest-valid tag
                logger.error(
                    "cluster health: %s — no sentinel rollback path "
                    "(sentinel.enabled + sentinel.rollback_dir needed "
                    "for sdc_action=rollback); aborting instead", reason)
                plane.abort("sdc", **fault)
        self._emit_health_events()

    def _on_health_abort(self, reason, detail):
        """ClusterHealthPlane ``on_abort``: blackbox first (the abort is
        ``os._exit``, which skips atexit — the _on_watchdog_fire
        pattern), so the relaunched world has forensics for WHY every
        survivor exited 15 together."""
        self._publish_telemetry(
            "health.abort_dump", severity="fatal", reason=reason, **detail)
        if self.flight_recorder is not None:
            self.flight_recorder.dump(
                f"cluster_health_{reason}",
                exit_code=self._health_cfg.exit_code)

    def _emit_health_events(self):
        """Export the plane counters as ``Health/*`` monitor events
        whenever they changed (the _emit_sentinel_events pattern)."""
        if (self.health_plane is None or self.monitor is None
                or not getattr(self.monitor, "enabled", False)):
            return
        counters = self.health_plane.counters()
        if counters == self._health_emitted:
            return
        from deepspeed_tpu.monitor.monitor import counter_events

        self.monitor.write_events(
            counter_events("Health", counters, self.global_steps))
        self._health_emitted = counters

    # ------------------------------------------------------------------
    # checkpoint (reference engine.py:2545 load / :2889 save)
    # ------------------------------------------------------------------
    def _model_states_path(self, ckpt_dir, tag):
        return os.path.join(ckpt_dir, str(tag), "mp_rank_00_model_states.msgpack")

    def _engine_states_path(self, ckpt_dir, tag):
        # msgpack envelope holding pickled meta bytes (saved through the
        # checkpoint engine so it shares the commit barrier)
        return os.path.join(ckpt_dir, str(tag), "engine_states.msgpack")

    def _optim_states_path(self, ckpt_dir, tag):
        return os.path.join(
            ckpt_dir, str(tag), "zero_pp_rank_0_mp_rank_00_optim_states.msgpack"
        )

    def _expert_states_path(self, ckpt_dir, tag, e, kind="model"):
        from deepspeed_tpu.runtime.moe_checkpoint import expert_states_filename

        return os.path.join(ckpt_dir, str(tag), expert_states_filename(e, kind))

    def _save_sharded(self, sd, ckpt_dir, tag, kind, dense_payload):
        """Save a state dict with expert leaves split into per-expert files
        (reference _save_moe_checkpoint, engine.py:2965: no host ever
        gathers the full expert set); dense models save one file as before.
        ``dense_payload(dense_sd, meta)`` shapes the main file's dict."""
        from deepspeed_tpu.runtime import moe_checkpoint as mc

        from deepspeed_tpu.utils.tree import flatten_dots

        expert_info = mc.find_expert_leaves(sd)
        path = (self._model_states_path(ckpt_dir, tag) if kind == "model"
                else self._optim_states_path(ckpt_dir, tag))
        if not expert_info:
            self.checkpoint_engine.save(dense_payload(sd, None), path)
            return
        dense_sd, meta, n_files = mc.split_expert_sd(sd, expert_info)
        flat = flatten_dots(sd)  # once, not per expert file
        expert_leaves = {p: flat[p] for p in expert_info}
        for e in range(n_files):
            self.checkpoint_engine.save(
                {"experts": mc.expert_slice(expert_leaves, expert_info, e)},
                self._expert_states_path(ckpt_dir, tag, e, kind))
        self.checkpoint_engine.save(dense_payload(dense_sd, meta), path)

    def _merge_expert_files(self, dense_sd, meta, load_dir, tag, kind):
        """Load-side inverse of :meth:`_save_sharded`: re-stack per-expert
        files into the full leaves. No-op for dense checkpoints."""
        if not meta:
            return dense_sd
        from deepspeed_tpu.runtime import moe_checkpoint as mc

        n_files = int(max(meta["counts"].values()))
        slices = {
            e: self.checkpoint_engine.load(
                self._expert_states_path(load_dir, tag, e, kind))["experts"]
            for e in range(n_files)
        }
        return mc.merge_expert_slices(dense_sd, meta, slices)

    def save_checkpoint(self, save_dir, tag=None, client_state=None,
                        save_latest=True):
        # thin wrapper so a mid-training save (graceful shutdown, periodic
        # checkpointing inside the profiled window) is attributed to the
        # `checkpoint` phase; a no-op context when profiling is off
        with self._prof_phase("checkpoint"):
            return self._save_checkpoint_impl(save_dir, tag, client_state,
                                              save_latest)

    def _save_checkpoint_impl(self, save_dir, tag=None, client_state=None,
                              save_latest=True):
        assert self._initialized, "cannot checkpoint before first batch"
        if tag is None:
            tag = f"global_step{self.global_steps}"
        client_state = client_state or {}

        # stamp the manifest with this engine's layout (world size, zero
        # stage, axis sizes, per-leaf partition specs): a later load on a
        # different device count detects the mismatch and reshards
        # (runtime/reshard.py) instead of failing
        specs = {}
        if getattr(self, "_param_shardings", None) is not None:
            specs["params"] = layout.describe_shardings(
                self._param_shardings, self._params)
        if (getattr(self, "_opt_shardings", None) is not None
                and self._offload_opt is None):
            specs["opt_state"] = layout.describe_shardings(
                self._opt_shardings, self._opt_state)
        self.checkpoint_engine.set_topology_metadata(
            layout.topology_metadata(self.topology, self.zero_stage,
                                     partition_specs=specs or None))

        self._save_sharded(
            serialization.to_state_dict(self._params), save_dir, tag,
            "model",
            lambda sd, meta: ({"module": sd, "moe_experts": meta}
                              if meta else {"module": sd}),
        )
        meta = {
            "global_steps": self.global_steps,
            "global_samples": self.global_samples,
            "micro_steps": self.micro_steps,
            "skipped_steps": self.skipped_steps,
            "lr_scheduler": (self.lr_scheduler.state_dict()
                             if self.lr_scheduler else {}),
            "client_state": client_state,
        }
        # data-order state (epoch + seed): restore resumes the order
        # instead of restarting the epoch (rollback/resume parity)
        if (self.training_dataloader is not None
                and hasattr(self.training_dataloader, "state_dict")):
            meta["dataloader"] = self.training_dataloader.state_dict()
        import pickle

        # routed through the checkpoint engine (pickled meta as a uint8
        # array — the engine numpy-ifies leaves, and raw bytes would come
        # back as an undecodable |S dtype) so the meta participates in the
        # SAME commit durability barrier as the model/optim files — a
        # direct file write would land immediately under an async engine,
        # and a crash before commit() could pair a new meta with the
        # previous save's weights in a reused tag dir
        self.checkpoint_engine.save(
            {"meta": np.frombuffer(pickle.dumps(meta), np.uint8)},
            self._engine_states_path(save_dir, tag))
        ls_payload = {
            "scale": np.float32(self._ls_state.scale),
            "good_steps": np.int32(self._ls_state.good_steps),
            "hysteresis": np.int32(self._ls_state.hysteresis),
        }
        if self._offload_opt is not None:
            self.checkpoint_engine.save(
                {"optimizer": self._offload_opt.state_dict(),
                 "loss_scale": ls_payload},
                self._optim_states_path(save_dir, tag))
        else:
            self._save_sharded(
                serialization.to_state_dict(self._opt_state), save_dir, tag,
                "optim",
                lambda sd, meta: (
                    {"optimizer": sd, "moe_experts": meta,
                     "loss_scale": ls_payload}
                    if meta else
                    {"optimizer": sd, "loss_scale": ls_payload}),
            )
        # commit BEFORE advertising 'latest': with the async engine the
        # pointer must never name a tag whose files haven't durably landed
        self.checkpoint_engine.commit(tag)
        if save_latest:
            ckpt_manifest.write_latest(save_dir, tag)
        self._publish_telemetry("checkpoint.commit", tag=str(tag))
        self.ft_stats["ckpt_saves"] += 1
        self._gc_checkpoints(save_dir)
        self._emit_ft_events()
        return True

    def _gc_checkpoints(self, save_dir):
        """Retention policy ``checkpoint.keep_n``: keep the newest N valid
        tags; never delete the tag the ``latest`` pointer names (a GC race
        must not take down the reference recovery path), nor any tag the
        async engine still has writes in flight for — a concurrent
        ``wait()`` can drain the pending list while files are mid-write,
        and deleting such a tag would tear the checkpoint it is in the
        middle of persisting."""
        keep_n = self._config.checkpoint_keep_n
        if keep_n <= 0:
            return
        protected = {ckpt_manifest.read_latest(save_dir)} - {None}
        protected |= self.checkpoint_engine.pinned_tags()
        tags = ckpt_manifest.find_valid_tags(save_dir, check_data=False)
        for tag in tags[keep_n:]:
            if tag in protected:
                continue
            try:
                shutil.rmtree(os.path.join(save_dir, tag))
                log_dist(f"[ckpt] retention keep_n={keep_n}: removed old "
                         f"tag {tag}", ranks=[0])
            except OSError as e:
                logger.warning("checkpoint GC failed for %s: %s", tag, e)

    def save_16bit_model(self, save_dir, save_filename="pytorch_model.msgpack"):
        """Gathered half-precision weights in one file (reference
        engine.py:3289 save_16bit_model / :3219 _zero3_consolidated_16bit_
        state_dict — there a cross-rank gather dance, here a device_get of the
        logically-global params + a cast)."""
        assert self._initialized, "cannot save before first batch"
        # fp16 only when explicitly trained fp16; bfloat16 otherwise (range-
        # safe native TPU 16-bit type, incl. for pure-fp32 training)
        dtype = jnp.float16 if self.fp16_enabled else jnp.bfloat16
        half = jax.tree.map(lambda x: jnp.asarray(x, dtype), self._params)
        self.checkpoint_engine.save(
            {"module": serialization.to_state_dict(half)},
            os.path.join(save_dir, save_filename),
        )
        return True

    def _resolve_valid_tag(self, load_dir, tag):
        """Verify ``tag`` against its manifest; on mismatch/missing files
        fall back to the newest previous valid tag instead of crashing
        (the recovery path after a torn write or preempted save). Tags
        without a manifest (pre-manifest checkpoints) load unverified."""
        if not self._config.checkpoint_verify:
            return tag
        tag_dir = os.path.join(load_dir, str(tag))
        problems = ckpt_manifest.verify_tag_dir(tag_dir)
        if problems is None:
            logger.info(
                "checkpoint tag %s has no manifest (pre-manifest "
                "checkpoint); loading unverified", tag)
            return tag
        if not problems:
            return tag
        logger.warning(
            "checkpoint tag %s failed integrity verification (%s); "
            "falling back to the newest previous valid tag",
            tag, "; ".join(problems))
        fallback = ckpt_manifest.latest_valid_tag(
            load_dir, exclude={str(tag)})
        if fallback is None:
            raise RuntimeError(
                f"checkpoint tag {tag!r} at {load_dir} is corrupt "
                f"({'; '.join(problems)}) and no previous valid tag "
                f"exists to fall back to")
        self.ft_stats["ckpt_fallbacks"] += 1
        self._publish_telemetry(
            "checkpoint.fallback", severity="warning", tag=str(tag),
            fallback=str(fallback), problems="; ".join(problems))
        log_dist(f"[ckpt] falling back: {tag} -> {fallback}", ranks=[0])
        return fallback

    def load_checkpoint(self, load_dir, tag=None, load_optimizer_states=True,
                        load_lr_scheduler_states=True):
        if tag is None:
            tag = ckpt_manifest.read_latest(load_dir)
            if tag is None:
                # a relaunched elastic worker may know the last-valid tag
                # even when the 'latest' pointer is gone/unreadable
                tag = os.environ.get(ckpt_manifest.LAST_VALID_TAG_ENV)
            if tag is None:
                logger.warning("no 'latest' file at %s", load_dir)
                return None, {}
        tag = self._resolve_valid_tag(load_dir, tag)

        assert self._initialized, (
            "run one forward (or init) before load_checkpoint so state "
            "templates exist"
        )
        # detect a topology-changed load (elastic resume on N' != N): the
        # manifest's topology block vs this engine's live layout. A v1
        # manifest (no block) only supports same-topology resume —
        # reshard.decide raises a clear error naming the missing fields
        # when the elastic agent signalled a world-size change.
        reshard_decision = reshard.decide(
            load_dir, tag, self.topology, zero_stage=self.zero_stage)
        reshard_phases = {"detect_s": reshard_decision.detect_s}
        if reshard_decision.needed:
            log_dist(
                f"[reshard] tag {tag}: {reshard_decision.describe()}; "
                f"re-laying-out state for {self.topology}", ranks=[0])
        saved_specs = ((reshard_decision.saved or {}).get("partition_specs")
                       or {})
        _t_load = time.monotonic()
        model_state = self.checkpoint_engine.load(
            self._model_states_path(load_dir, tag)
        )
        import pickle

        engine_states = self._engine_states_path(load_dir, tag)
        legacy_states = os.path.join(load_dir, str(tag), "engine_states.pkl")
        if not os.path.exists(engine_states) and os.path.exists(legacy_states):
            # checkpoints saved before the msgpack rename wrote the meta as
            # a bare pickle file outside the checkpoint engine — load those
            # directly so old save dirs stay restorable
            log_dist(f"[ckpt] legacy engine_states.pkl found at {tag}; "
                     "loading pre-msgpack meta", ranks=[0])
            with open(legacy_states, "rb") as f:
                meta = pickle.load(f)
        else:
            meta = pickle.loads(np.asarray(self.checkpoint_engine.load(
                engine_states)["meta"]).tobytes())
        # a partial accumulation window from before the restore must not
        # leak into the first post-restore step
        self._host_grad_acc = None
        self._pending_grad_leaves = None
        model_sd = self._merge_expert_files(
            model_state["module"], model_state.get("moe_experts"),
            load_dir, tag, "model")
        reshard_phases["load_s"] = time.monotonic() - _t_load
        if reshard_decision.needed and "params" in saved_specs:
            # gather already happened at save (logical arrays on disk);
            # verify the loaded leaves against the saved per-leaf record
            # before trusting them with a re-layout
            _, verify_s = reshard.verify_state_dict(
                model_sd, saved_specs["params"], "model")
            reshard_phases["verify_params_s"] = verify_s
        restored = serialization.from_state_dict(self._params, model_sd)
        self._params, place_s = reshard.place_tree(
            restored, self._param_shardings)
        reshard_phases["place_params_s"] = place_s
        if self._offload_opt is not None and not load_optimizer_states:
            # offload steps rebuild device params FROM the host masters, so
            # restored weights must be copied into them (load_state_dict
            # does this when optimizer states are loaded)
            self._offload_opt.refresh_masters(self._params)
        self.global_steps = int(meta["global_steps"])
        self.global_samples = int(meta["global_samples"])
        self.micro_steps = int(meta["micro_steps"])
        self.skipped_steps = int(meta["skipped_steps"])
        dl_state = meta.get("dataloader")
        if (dl_state and self.training_dataloader is not None
                and hasattr(self.training_dataloader, "load_state_dict")):
            self.training_dataloader.load_state_dict(dl_state)
        if load_lr_scheduler_states and self.lr_scheduler is not None and (
            meta.get("lr_scheduler")
        ):
            self.lr_scheduler.load_state_dict(meta["lr_scheduler"])

        if load_optimizer_states:
            optim_state = self.checkpoint_engine.load(
                self._optim_states_path(load_dir, tag)
            )
            if self._offload_opt is not None:
                self._offload_opt.load_state_dict(optim_state["optimizer"])
            else:
                opt_sd = self._merge_expert_files(
                    optim_state["optimizer"],
                    optim_state.get("moe_experts"), load_dir, tag, "optim")
                if reshard_decision.needed and "opt_state" in saved_specs:
                    _, verify_s = reshard.verify_state_dict(
                        opt_sd, saved_specs["opt_state"], "optimizer")
                    reshard_phases["verify_opt_s"] = verify_s
                if (self._compressed_mode == "int8"
                        and isinstance(opt_sd, dict)
                        and "2" not in opt_sd and "1" in opt_sd):
                    # migrate pre-server-error int8 checkpoints (state was
                    # (inner, worker_err); "2" = the phase-2 residuals):
                    # fresh zeros are the correct cold-start for EF buffers
                    opt_sd = dict(opt_sd)
                    opt_sd["2"] = serialization.to_state_dict(
                        self._opt_state[2])
                restored_opt = serialization.from_state_dict(
                    self._opt_state, opt_sd
                )
                self._opt_state, place_s = reshard.place_tree(
                    restored_opt, self._opt_shardings)
                reshard_phases["place_opt_s"] = place_s
            ls = optim_state.get("loss_scale", {})
            if ls and self._ls_state is not None:
                self._ls_state = self._ls_state._replace(
                    scale=jnp.float32(ls["scale"]),
                    good_steps=jnp.int32(ls["good_steps"]),
                    hysteresis=jnp.int32(ls["hysteresis"]),
                )
        self.ft_stats["ckpt_loads"] += 1
        if reshard_decision.needed:
            reshard_phases["total_s"] = sum(reshard_phases.values())
            self.ft_stats["ckpt_reshards"] += 1
            self._publish_telemetry(
                "elastic.reshard", tag=str(tag),
                saved_world=reshard_decision.saved_world,
                current_world=self.topology.num_devices,
                mismatches="; ".join(reshard_decision.mismatches),
                **{k: round(v, 6) for k, v in reshard_phases.items()})
            log_dist(
                f"[reshard] tag {tag} re-laid-out in "
                f"{reshard_phases['total_s']:.3f}s "
                f"({reshard_decision.saved_world} -> "
                f"{self.topology.num_devices} devices)", ranks=[0])
        self._emit_ft_events()
        return tag, meta.get("client_state", {})

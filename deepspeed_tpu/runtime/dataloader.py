"""Data loading.

Parity with reference ``deepspeed/runtime/dataloader.py`` (DeepSpeedDataLoader
:33 with DP-sharded DistributedSampler, RepeatingLoader :10). TPU re-design:
one host process drives many devices, so the loader yields **global** batches
of ``micro_batch_per_device * dp_world`` and the engine device-puts them with
the batch PartitionSpec — the sharded transfer replaces the per-rank sampler.
"""

import math
from typing import Any, Callable, Iterable, Iterator, Optional

import numpy as np


def default_collate(samples):
    """Stack a list of dict/array samples into one batch."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: np.stack([s[k] for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(np.stack(cols) for cols in zip(*samples))
    return np.stack(samples)


class DeepSpeedDataLoader:
    """Iterates a map-style dataset in global batches.

    ``batch_size`` is the GLOBAL micro batch (micro_batch_per_device * dp).
    Sharding across DP ranks happens at device_put time in the engine, which
    is the SPMD equivalent of the reference's DistributedSampler split.
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        shuffle: bool = False,
        seed: int = 0,
        drop_last: bool = True,
        collate_fn: Optional[Callable] = None,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self._base_seed = seed
        self.drop_last = drop_last
        self.collate_fn = collate_fn or default_collate
        self.epoch = 0
        # bumped whenever (seed, epoch) changes out-of-band (reseed or
        # load_state_dict): RepeatingLoader watches it to restart its
        # iterator so the new order takes effect mid-epoch
        self.order_version = 0
        if drop_last:
            self.num_batches = len(dataset) // batch_size
        else:
            self.num_batches = math.ceil(len(dataset) / batch_size)
        if self.num_batches == 0:
            raise ValueError(
                f"dataset of {len(dataset)} samples yields zero batches of "
                f"global size {batch_size}"
            )

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def reseed(self, offset: int):
        """Derive a fresh shuffle order (seed = base seed + offset) — the
        sentinel's rollback re-entry path: replaying the exact batch
        sequence that diverged once would diverge again."""
        self.seed = self._base_seed + int(offset)
        self.order_version += 1

    def state_dict(self):
        """Data-order state carried through engine checkpoints so
        rollback/resume restores the order instead of restarting the
        epoch."""
        return {"epoch": self.epoch, "seed": self.seed}

    def load_state_dict(self, state):
        self.epoch = int(state.get("epoch", self.epoch))
        self.seed = int(state.get("seed", self.seed))
        self.order_version += 1

    def __len__(self):
        return self.num_batches

    def __iter__(self) -> Iterator[Any]:
        n = len(self.dataset)
        order = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.seed + self.epoch)
            rng.shuffle(order)
        for b in range(self.num_batches):
            idx = order[b * self.batch_size:(b + 1) * self.batch_size]
            samples = [self.dataset[int(i)] for i in idx]
            yield self.collate_fn(samples)


class RepeatingLoader:
    """reference dataloader.py:10 — restart the wrapped loader at exhaustion."""

    def __init__(self, loader: Iterable):
        self.loader = loader
        self.data_iter = iter(self.loader)
        self._order_version = getattr(loader, "order_version", None)

    def __iter__(self):
        return self

    def __next__(self):
        inner_version = getattr(self.loader, "order_version", None)
        if inner_version != self._order_version:
            # the wrapped loader was reseeded/restored out-of-band: the
            # in-flight iterator still walks the OLD order — restart it
            self._order_version = inner_version
            self.data_iter = iter(self.loader)
        try:
            return next(self.data_iter)
        except StopIteration:
            if hasattr(self.loader, "set_epoch"):
                self.loader.set_epoch(getattr(self.loader, "epoch", 0) + 1)
            self.data_iter = iter(self.loader)
            return next(self.data_iter)

    def state_dict(self):
        if hasattr(self.loader, "state_dict"):
            return self.loader.state_dict()
        return {}

    def load_state_dict(self, state):
        if hasattr(self.loader, "load_state_dict"):
            self.loader.load_state_dict(state)

"""Data loading.

Parity with reference ``deepspeed/runtime/dataloader.py`` (DeepSpeedDataLoader
:33 with DP-sharded DistributedSampler, RepeatingLoader :10). TPU re-design:
one host process drives many devices, so the loader yields **global** batches
of ``micro_batch_per_device * dp_world`` and the engine device-puts them with
the batch PartitionSpec — the sharded transfer replaces the per-rank sampler.
"""

import math
from typing import Any, Callable, Iterable, Iterator, Optional

import numpy as np


def default_collate(samples):
    """Stack a list of dict/array samples into one batch."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: np.stack([s[k] for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(np.stack(cols) for cols in zip(*samples))
    return np.stack(samples)


def _pad_to_batch_size(batch, batch_size: int):
    """Pad a (possibly ragged tail) batch to ``batch_size`` rows.

    Dict batches get zero rows plus an ``attention_mask`` that zeroes the
    pad rows out of attention AND the loss (the model's weighting path);
    the mask is emitted for full batches too so the pytree structure —
    and with it the compiled program — is identical for every batch.
    Non-dict batches just get zero rows (no mask channel to thread)."""
    if isinstance(batch, dict):
        n = next(iter(batch.values())).shape[0]
        pad = batch_size - n
        out = {}
        for k, v in batch.items():
            if pad:
                zeros = np.zeros((pad,) + v.shape[1:], v.dtype)
                out[k] = np.concatenate([v, zeros], axis=0)
            else:
                out[k] = v
        if "attention_mask" not in out and "input_ids" in out:
            mask = np.zeros(out["input_ids"].shape[:2], np.int32)
            mask[:n] = 1
            out["attention_mask"] = mask
        return out
    if isinstance(batch, (tuple, list)):
        return type(batch)(_pad_to_batch_size(v, batch_size) for v in batch)
    pad = batch_size - batch.shape[0]
    if not pad:
        return batch
    zeros = np.zeros((pad,) + batch.shape[1:], batch.dtype)
    return np.concatenate([batch, zeros], axis=0)


class DeepSpeedDataLoader:
    """Iterates a map-style dataset in global batches.

    ``batch_size`` is the GLOBAL micro batch (micro_batch_per_device * dp).
    Sharding across DP ranks happens at device_put time in the engine, which
    is the SPMD equivalent of the reference's DistributedSampler split.
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        shuffle: bool = False,
        seed: int = 0,
        drop_last: bool = True,
        collate_fn: Optional[Callable] = None,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self._base_seed = seed
        self.drop_last = drop_last
        self.collate_fn = collate_fn or default_collate
        # drop_last=False with a ragged tail: the tail is PADDED to the
        # full global batch and masked via attention_mask, so the engine
        # compiles exactly one batch shape instead of one per epoch tail.
        # The mask key must then exist on EVERY batch (a tail-only key
        # would change the pytree structure and force a retrace anyway).
        self._pad_tail = (not drop_last) and (len(dataset) % batch_size != 0)
        self.epoch = 0
        # bumped whenever (seed, epoch) changes out-of-band (reseed or
        # load_state_dict): RepeatingLoader watches it to restart its
        # iterator so the new order takes effect mid-epoch
        self.order_version = 0
        if drop_last:
            self.num_batches = len(dataset) // batch_size
        else:
            self.num_batches = math.ceil(len(dataset) / batch_size)
        if self.num_batches == 0:
            raise ValueError(
                f"dataset of {len(dataset)} samples yields zero batches of "
                f"global size {batch_size}"
            )

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def reseed(self, offset: int):
        """Derive a fresh shuffle order (seed = base seed + offset) — the
        sentinel's rollback re-entry path: replaying the exact batch
        sequence that diverged once would diverge again."""
        self.seed = self._base_seed + int(offset)
        self.order_version += 1

    def state_dict(self):
        """Data-order state carried through engine checkpoints so
        rollback/resume restores the order instead of restarting the
        epoch."""
        return {"epoch": self.epoch, "seed": self.seed}

    def load_state_dict(self, state):
        self.epoch = int(state.get("epoch", self.epoch))
        self.seed = int(state.get("seed", self.seed))
        self.order_version += 1

    def __len__(self):
        return self.num_batches

    def __iter__(self) -> Iterator[Any]:
        n = len(self.dataset)
        order = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.seed + self.epoch)
            rng.shuffle(order)
        for b in range(self.num_batches):
            idx = order[b * self.batch_size:(b + 1) * self.batch_size]
            samples = [self.dataset[int(i)] for i in idx]
            batch = self.collate_fn(samples)
            if self._pad_tail:
                batch = _pad_to_batch_size(batch, self.batch_size)
            yield batch


class RepeatingLoader:
    """reference dataloader.py:10 — restart the wrapped loader at exhaustion."""

    def __init__(self, loader: Iterable):
        self.loader = loader
        self.data_iter = iter(self.loader)
        self._order_version = getattr(loader, "order_version", None)

    def __iter__(self):
        return self

    def __next__(self):
        inner_version = getattr(self.loader, "order_version", None)
        if inner_version != self._order_version:
            # the wrapped loader was reseeded/restored out-of-band: the
            # in-flight iterator still walks the OLD order — restart it
            self._order_version = inner_version
            self.data_iter = iter(self.loader)
        try:
            return next(self.data_iter)
        except StopIteration:
            if hasattr(self.loader, "set_epoch"):
                self.loader.set_epoch(getattr(self.loader, "epoch", 0) + 1)
            self.data_iter = iter(self.loader)
            return next(self.data_iter)

    def state_dict(self):
        if hasattr(self.loader, "state_dict"):
            return self.loader.state_dict()
        return {}

    def load_state_dict(self, state):
        if hasattr(self.loader, "load_state_dict"):
            self.loader.load_state_dict(state)

"""DeepSpeed-style JSON config system for the TPU framework.

Capability parity with reference ``deepspeed/runtime/config.py`` (DeepSpeedConfig
:712, batch-triad resolution, per-feature config blocks). Differences are
TPU-motivated and documented per block:

* GPU-only knobs (cuda streams, NCCL tuning) parse but are inert.
* A new ``"tpu"`` block configures the device mesh (dp/fsdp/tp/pp/ep/sp axis
  sizes), remat policy, and buffer donation — concepts with no reference
  analogue because XLA owns scheduling.
"""

import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from deepspeed_tpu.runtime import constants as C
from deepspeed_tpu.runtime.config_utils import (
    ConfigModel,
    dict_raise_error_on_duplicate_keys,
    get_scalar_param,
    pretty_json,
)
from deepspeed_tpu.utils.logging import logger


class DeepSpeedConfigError(Exception):
    pass


# ---------------------------------------------------------------------------
# Precision blocks (reference runtime/config.py fp16/bf16/amp parsing)
# ---------------------------------------------------------------------------
@dataclass
class Fp16Config(ConfigModel):
    enabled: bool = C.FP16_ENABLED_DEFAULT
    loss_scale: float = C.FP16_LOSS_SCALE_DEFAULT
    initial_scale_power: int = C.FP16_INITIAL_SCALE_POWER_DEFAULT
    loss_scale_window: int = C.FP16_LOSS_SCALE_WINDOW_DEFAULT
    hysteresis: int = C.FP16_HYSTERESIS_DEFAULT
    min_loss_scale: float = C.FP16_MIN_LOSS_SCALE_DEFAULT
    fp16_master_weights_and_grads: bool = C.FP16_MASTER_WEIGHTS_AND_GRADS_DEFAULT
    auto_cast: bool = False  # inert on TPU: XLA handles dtype propagation

    @property
    def dynamic_loss_scale(self) -> bool:
        return self.loss_scale == 0


@dataclass
class Bf16Config(ConfigModel):
    enabled: bool = C.BFLOAT16_ENABLED_DEFAULT


@dataclass
class AmpConfig(ConfigModel):
    enabled: bool = C.AMP_ENABLED_DEFAULT
    opt_level: str = "O1"  # accepted for config compatibility; bf16 is the TPU path


# ---------------------------------------------------------------------------
# ZeRO block (reference deepspeed/runtime/zero/config.py:145)
# ---------------------------------------------------------------------------
@dataclass
class ZeroOffloadParamConfig(ConfigModel):
    device: str = "none"  # none | cpu | nvme
    nvme_path: str = "/local_nvme"
    buffer_count: int = 5
    buffer_size: int = 100_000_000
    max_in_cpu: int = 1_000_000_000
    pin_memory: bool = False


@dataclass
class ZeroOffloadOptimizerConfig(ConfigModel):
    device: str = "none"  # none | cpu | nvme
    nvme_path: str = "/local_nvme"
    buffer_count: int = 4
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False


@dataclass
class ZeroConfig(ConfigModel):
    stage: int = 0
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = 500_000_000
    allgather_partitions: bool = True
    allgather_bucket_size: int = 500_000_000
    overlap_comm: bool = False  # inert: XLA overlaps collectives automatically
    load_from_fp32_weights: bool = True
    elastic_checkpoint: bool = False
    offload_param: Optional[Dict[str, Any]] = None
    offload_optimizer: Optional[Dict[str, Any]] = None
    sub_group_size: int = 1_000_000_000
    cpu_offload: bool = False  # deprecated alias handled in __post_init__validate__
    cpu_offload_param: bool = False  # deprecated alias (reference zero/config.py)
    prefetch_bucket_size: int = 50_000_000
    param_persistence_threshold: int = 100_000
    model_persistence_threshold: int = 2 ** 62
    max_live_parameters: int = 1_000_000_000
    max_reuse_distance: int = 1_000_000_000
    gather_16bit_weights_on_model_save: bool = False
    stage3_gather_16bit_weights_on_model_save: bool = False
    ignore_unused_parameters: bool = True
    round_robin_gradients: bool = False
    zero_hpz_partition_size: int = 1
    # Aliases used by stage3-prefixed keys in real-world configs
    _aliases = {
        "stage3_prefetch_bucket_size": "prefetch_bucket_size",
        "stage3_param_persistence_threshold": "param_persistence_threshold",
        "stage3_model_persistence_threshold": "model_persistence_threshold",
        "stage3_max_live_parameters": "max_live_parameters",
        "stage3_max_reuse_distance": "max_reuse_distance",
    }

    def __post_init__validate__(self):
        if self.stage not in (0, 1, 2, 3):
            raise DeepSpeedConfigError(f"ZeRO stage must be 0..3, got {self.stage}")
        if self.cpu_offload and self.offload_optimizer is None:
            self.offload_optimizer = {"device": "cpu"}
        if self.cpu_offload_param and self.offload_param is None:
            self.offload_param = {"device": "cpu"}
        if self.stage3_gather_16bit_weights_on_model_save:
            self.gather_16bit_weights_on_model_save = True

    @property
    def offload_param_config(self) -> ZeroOffloadParamConfig:
        return ZeroOffloadParamConfig.from_dict(self.offload_param or {})

    @property
    def offload_optimizer_config(self) -> ZeroOffloadOptimizerConfig:
        return ZeroOffloadOptimizerConfig.from_dict(self.offload_optimizer or {})


# ---------------------------------------------------------------------------
# Optimizer / scheduler blocks
# ---------------------------------------------------------------------------
@dataclass
class OptimizerConfig(ConfigModel):
    type: Optional[str] = None
    params: Dict[str, Any] = field(default_factory=dict)
    legacy_fusion: bool = False


@dataclass
class SchedulerConfig(ConfigModel):
    type: Optional[str] = None
    params: Dict[str, Any] = field(default_factory=dict)


# ---------------------------------------------------------------------------
# Aux feature blocks
# ---------------------------------------------------------------------------
@dataclass
class ActivationCheckpointingConfig(ConfigModel):
    """Reference activation_checkpointing block. On TPU, ``partition_activations``
    maps to sharded remat residuals, ``cpu_checkpointing`` to host offload of
    remat residuals; ``contiguous_memory_optimization``/``synchronize`` are inert
    (XLA owns memory layout)."""

    partition_activations: bool = False
    cpu_checkpointing: bool = False
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False


@dataclass
class FlopsProfilerConfig(ConfigModel):
    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


@dataclass
class TensorboardConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


@dataclass
class WandbConfig(ConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed"


@dataclass
class CsvConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


@dataclass
class CommsLoggerConfig(ConfigModel):
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: List[str] = field(default_factory=list)


@dataclass
class StepProfilerConfig(ConfigModel):
    """Step-level performance tracer (docs/observability.md). Profiles the
    half-open optimizer-step window ``[start_step, start_step+num_steps)``:
    fenced per-step phase attribution, compiled-step cost analysis →
    analytic MFU, Chrome trace-event export, optional ``jax.profiler``
    capture. Disabled (the default) it adds zero device syncs."""

    enabled: bool = False
    start_step: int = 2          # skip compile + warmup steps
    num_steps: int = 8           # window length in optimizer steps
    trace_path: Optional[str] = None   # Chrome trace JSON ("" / None: off)
    jax_trace: bool = False            # jax.profiler capture over the window
    jax_trace_dir: Optional[str] = None
    peak_tflops: Optional[float] = None  # override the hardware-peak table
    emit_counters: bool = True           # Perf/* + Comm/* via the monitor

    def __post_init__validate__(self):
        if self.start_step < 0:
            raise DeepSpeedConfigError("step_profiler.start_step must be >= 0")
        if self.num_steps < 1:
            raise DeepSpeedConfigError("step_profiler.num_steps must be >= 1")
        if self.jax_trace and not self.jax_trace_dir:
            raise DeepSpeedConfigError(
                "step_profiler.jax_trace requires step_profiler.jax_trace_dir")


@dataclass
class DataPipelineConfig(ConfigModel):
    """Input data pipeline (deepspeed_tpu/data/, docs/data.md): swaps the
    engine's synchronous ``DeepSpeedDataLoader`` path for deterministic
    sharded streaming + sequence packing + background device prefetch.
    Disabled (the default) the input path is byte-identical to the
    historical loop — ``deepspeed_io`` builds the same loader as ever."""

    enabled: bool = False
    # bin-pack variable-length documents into [B, seq_length] with
    # segment_ids/positions; False collates one sample per row instead
    pack_sequences: bool = True
    seq_length: int = 1024
    pad_token_id: int = 0
    shuffle: bool = True
    seed: int = 0
    # "process": shard the sample stream by jax process (DP rank);
    # "none": every process sees the full stream
    shard: str = "process"
    # background worker that runs the engine's sharded device_put so h2d
    # of batch N+1 overlaps compute of batch N
    prefetch: bool = True
    prefetch_depth: int = 2
    # pack to the curriculum scheduler's quantized difficulty seq-len
    # (bounded compiled-shape count; see docs/data.md)
    curriculum_pack: bool = True

    def __post_init__validate__(self):
        if self.seq_length < 2:
            raise DeepSpeedConfigError(
                "data_pipeline.seq_length must be >= 2")
        if self.prefetch_depth < 1:
            raise DeepSpeedConfigError(
                "data_pipeline.prefetch_depth must be >= 1")
        if self.shard not in ("process", "none"):
            raise DeepSpeedConfigError(
                f"data_pipeline.shard must be 'process' or 'none', got "
                f"{self.shard!r}")


@dataclass
class CurriculumConfig(ConfigModel):
    enabled: bool = False
    curriculum_type: str = "seqlen"
    min_difficulty: int = 1
    max_difficulty: int = 1024
    schedule_type: str = "fixed_linear"
    schedule_config: Dict[str, Any] = field(default_factory=dict)


@dataclass
class ProgressiveLayerDropConfig(ConfigModel):
    enabled: bool = False
    theta: float = 0.5
    gamma: float = 0.001


@dataclass
class EigenvalueConfig(ConfigModel):
    enabled: bool = False
    verbose: bool = False
    max_iter: int = 100
    tol: float = 1e-2
    stability: float = 1e-6
    gas_boundary_resolution: int = 1
    layer_name: str = "bert.encoder.layer"
    layer_num: int = 0


@dataclass
class AioConfig(ConfigModel):
    block_size: int = 1048576
    queue_depth: int = 8
    thread_count: int = 1
    single_submit: bool = False
    overlap_events: bool = True


@dataclass
class PipelineConfig(ConfigModel):
    stages: Any = "auto"
    partition: str = "best"
    seed_layers: bool = False
    activation_checkpoint_interval: int = 0
    pipe_partitioned: bool = True
    grad_partitioned: bool = True


@dataclass
class GracefulShutdownConfig(ConfigModel):
    """Preemption grace handler (no reference analogue; docs/recovery.md).
    When enabled, the engine traps ``signals`` and, at the next step
    boundary, saves + commits a final checkpoint to ``save_dir`` before
    exiting — turning a slice preemption into a clean resume point."""

    enabled: bool = False
    save_dir: Optional[str] = None
    tag: Optional[str] = None  # None -> the default global_step<N> tag
    signals: List[str] = field(default_factory=lambda: ["SIGTERM", "SIGINT"])
    exit_after_save: bool = True
    exit_code: int = 0

    def __post_init__validate__(self):
        if self.enabled and not self.save_dir:
            raise DeepSpeedConfigError(
                "graceful_shutdown.enabled requires graceful_shutdown."
                "save_dir (where the final checkpoint goes)")
        import signal as _signal

        for name in self.signals:
            if not hasattr(_signal, str(name)):
                raise DeepSpeedConfigError(
                    f"graceful_shutdown.signals: unknown signal {name!r}")


@dataclass
class SentinelConfig(ConfigModel):
    """Training health sentinel (no reference analogue; docs/recovery.md
    "Divergence and hang recovery"). When enabled, the engine judges every
    optimizer step host-side — non-finite loss/grads (any dtype, not just
    the fp16 loss-scale path) plus rolling-window loss/grad-norm spike
    detection — and responds in graduated stages: cond-skip the bad batch
    (``skip_budget`` consecutive), roll back to the newest manifest-valid
    checkpoint (``rollback_budget`` times, reseeding the data order), then
    raise ``DivergenceError`` with ``divergence_exit_code``. A daemon
    hang watchdog arms around each step when ``hang_timeout_s > 0``."""

    enabled: bool = False
    check_nonfinite: bool = True
    window: int = 50            # rolling-window length (healthy steps)
    min_window: int = 10        # samples required before spike checks arm
    loss_spike_zscore: float = 6.0   # <=0 disables the z-score check
    loss_spike_ratio: float = 3.0    # <=0 disables the ratio check
    grad_spike_zscore: float = 6.0
    grad_spike_ratio: float = 10.0
    skip_budget: int = 3        # consecutive anomalies before rollback
    rollback_budget: int = 2    # rollbacks before DivergenceError
    rollback_dir: Optional[str] = None  # checkpoint root to roll back to
    reseed_on_rollback: bool = True
    divergence_exit_code: int = C.DIVERGENCE_EXIT_CODE_DEFAULT
    hang_timeout_s: float = 0.0  # 0 disables the watchdog
    hang_action: str = "warn"    # warn | abort
    hang_exit_code: int = C.SENTINEL_HANG_EXIT_CODE_DEFAULT

    def __post_init__validate__(self):
        if self.window < 2:
            raise DeepSpeedConfigError(
                f"sentinel.window must be >= 2, got {self.window}")
        if not (2 <= self.min_window <= self.window):
            raise DeepSpeedConfigError(
                f"sentinel.min_window must be in [2, window="
                f"{self.window}], got {self.min_window}")
        if self.skip_budget < 0 or self.rollback_budget < 0:
            raise DeepSpeedConfigError(
                "sentinel.skip_budget and sentinel.rollback_budget must "
                "be >= 0")
        if self.hang_timeout_s < 0:
            raise DeepSpeedConfigError(
                f"sentinel.hang_timeout_s must be >= 0 (0 disables), got "
                f"{self.hang_timeout_s}")
        if self.hang_action not in ("warn", "abort"):
            raise DeepSpeedConfigError(
                f"sentinel.hang_action must be 'warn' or 'abort', got "
                f"{self.hang_action!r}")
        for name in ("divergence_exit_code", "hang_exit_code"):
            code = getattr(self, name)
            if not (1 <= int(code) <= 255):
                raise DeepSpeedConfigError(
                    f"sentinel.{name} must be in [1, 255] (0 means "
                    f"success to the elastic agent), got {code}")


@dataclass
class TelemetryConfig(ConfigModel):
    """Telemetry bus + crash-forensics flight recorder
    (docs/observability.md "Telemetry events" / "Flight recorder").

    Enabled by default: the recorder is an in-memory ring (bounded, host
    timers only — no fences, no device pulls), so the healthy path pays
    microseconds per step and gains zero syncs. Blackbox dumps are
    written only when ``dump_dir`` resolves (the config field, else the
    ``DS_TPU_TELEMETRY_DIR`` env the elastic agent / launcher export);
    crash handlers (SIGTERM / excepthook / atexit) install only then."""

    enabled: bool = True
    ring_steps: int = 64          # step records kept (>= 32 for forensics)
    ring_events: int = 256        # bus events kept
    dump_dir: Optional[str] = None  # None -> DS_TPU_TELEMETRY_DIR env
    # live device.memory_stats() watermarks in each step record (host
    # query, no sync; auto-disabled after the first None on CPU)
    sample_memory: bool = True
    # fatal signals that trigger a dump (chained before any previous
    # handler, e.g. graceful_shutdown's flag-setter)
    dump_signals: List[str] = field(default_factory=lambda: ["SIGTERM"])

    def __post_init__validate__(self):
        if self.ring_steps < 1:
            raise DeepSpeedConfigError(
                f"telemetry.ring_steps must be >= 1, got {self.ring_steps}")
        if self.ring_events < 1:
            raise DeepSpeedConfigError(
                f"telemetry.ring_events must be >= 1, got "
                f"{self.ring_events}")
        import signal as _signal

        for name in self.dump_signals:
            if not hasattr(_signal, str(name)):
                raise DeepSpeedConfigError(
                    f"telemetry.dump_signals: unknown signal {name!r}")


@dataclass
class MeshConfig(ConfigModel):
    """TPU device-mesh axis sizes. -1 on ``dp`` means "use all remaining
    devices". No reference analogue: replaces mpu/process-group plumbing
    (reference utils/groups.py, pipe/topology.py) with named mesh axes."""

    dp: int = -1
    fsdp: int = 1
    tp: int = 1
    pp: int = 1
    ep: int = 1
    sp: int = 1


@dataclass
class GradExchangeConfig(ConfigModel):
    """Explicit bucketed gradient exchange (``comm/bucketed.py``).

    ``deferred=True`` replaces XLA's implicit per-micro-step gradient psum
    with the compressed-path machinery at a bf16/fp32 wire format: grads
    stay per-worker through the accumulation window and are exchanged ONCE
    per optimizer step in size-bounded buckets at the GAS boundary (T3-style
    — cuts gradient wire bytes by the accumulation factor and frees XLA to
    overlap per-bucket collectives). ``bucket_mb`` also buckets the int8
    ``communication_data_type`` exchange (error-feedback residuals become
    per-bucket). 0 keeps the legacy per-leaf exchange. Defaults are
    off/safe: nothing changes unless explicitly enabled.
    """

    bucket_mb: float = 0.0
    deferred: bool = False
    wire_dtype: str = "bf16"  # bf16 | fp32 (deferred exchange payload)
    # two-level ICI/DCN exchange (comm/bucketed.py hierarchical_all_reduce):
    # intra-slice wire_dtype psum over ICI, inter-slice bucketed int8
    # EQuARX exchange over DCN. "auto" activates when the mesh detects a
    # multi-slice dp axis (MeshTopology.dcn_size("dp") > 1) and falls back
    # to the flat exchange otherwise; "on" demands slice structure (loud
    # failure without it). Requires deferred=true.
    hierarchical: str = "off"  # off | auto | on
    # >0 forces the inter-slice group count (slice-major over the dp axis)
    # instead of detecting it from device.slice_index — how the virtual
    # CPU mesh exercises the DCN leg; 0 = detect
    dcn_slices: int = 0
    dcn_block: int = 512  # int8 quantization block for the DCN leg

    def __post_init__(self):
        if self.wire_dtype not in ("bf16", "bfloat16", "fp32", "float32"):
            raise DeepSpeedConfigError(
                "tpu.grad_exchange.wire_dtype must be one of bf16/bfloat16/"
                f"fp32/float32, got {self.wire_dtype!r}")
        if self.bucket_mb < 0:
            raise DeepSpeedConfigError(
                f"tpu.grad_exchange.bucket_mb must be >= 0, got "
                f"{self.bucket_mb}")
        if self.hierarchical not in ("off", "auto", "on"):
            raise DeepSpeedConfigError(
                "tpu.grad_exchange.hierarchical must be one of off/auto/on,"
                f" got {self.hierarchical!r}")
        if self.dcn_slices < 0:
            raise DeepSpeedConfigError(
                f"tpu.grad_exchange.dcn_slices must be >= 0, got "
                f"{self.dcn_slices}")
        if self.dcn_block < 1:
            raise DeepSpeedConfigError(
                f"tpu.grad_exchange.dcn_block must be >= 1, got "
                f"{self.dcn_block}")


@dataclass
class TpuPipelineConfig(ConfigModel):
    """Pipeline stage-to-stage transport (``runtime/pipe/transport.py``).

    ``transport`` picks how activations/cotangents hop between stage
    sub-meshes:

    - ``device_put`` — host-level cross-mesh transfer (the original
      single-process fast path; on a multi-process CPU mesh this path
      cannot be emulated and hangs — see tests/unit/test_multihost.py).
    - ``ppermute`` — one jitted ``lax.ppermute`` over the JOINT (pp, dp)
      mesh: works across process boundaries and lets XLA overlap the
      transfer with compute.
    - ``auto`` — ppermute when ``jax.process_count() > 1``, device_put
      otherwise. The transport never leaks into checkpoint layout.
    """

    transport: str = "auto"  # auto | ppermute | device_put

    def __post_init__(self):
        if self.transport not in ("auto", "ppermute", "device_put"):
            raise DeepSpeedConfigError(
                "tpu.pipeline.transport must be one of auto/ppermute/"
                f"device_put, got {self.transport!r}")


@dataclass
class StepAutotuneConfig(ConfigModel):
    """Step-config autotuner (``runtime/step_autotune.py``).

    ``enabled=True`` resolves a tuned (remat_policy, micro_batch, flash)
    for the engine's GPT module through the mem -> disk -> PRETUNED ->
    live chain and applies the winner's remat policy / flash setting to
    the module before any program compiles. Default off: the compiled
    program is bit-identical to today's. ``autotune`` additionally allows
    the LIVE search on a cache/pretuned miss (otherwise a miss is a
    no-op); ``apply_micro_batch`` opts into the winner's micro batch
    overriding ``train_micro_batch_size_per_gpu`` (the engine re-derives
    the batch triad, so callers must size batches off the engine AFTER
    init). ``fused_step`` controls the optimizer-tail fusion: "auto"
    keeps the engine's existing gating, "on" fuses the tail into the step
    even when ``wall_clock_breakdown`` would have split it (phase
    attribution collapses into ``compiled_step``), "off" always runs the
    two-program fwd/bwd + apply split (the A/B baseline
    ``benchmarks/mfu_search.py`` measures against)."""

    enabled: bool = False
    autotune: bool = False          # allow the live search on a miss
    apply_micro_batch: bool = False
    fused_step: str = "auto"        # auto | on | off
    hbm_gib: float = 0.0            # HBM ceiling override for the search
    live_steps: int = 3
    micro_batches: List[int] = field(default_factory=list)  # [] = default
    policies: List[str] = field(default_factory=list)

    def __post_init__(self):
        if self.fused_step not in ("auto", "on", "off"):
            raise DeepSpeedConfigError(
                "tpu.step_autotune.fused_step must be auto/on/off, got "
                f"{self.fused_step!r}")
        if self.hbm_gib < 0:
            raise DeepSpeedConfigError(
                f"tpu.step_autotune.hbm_gib must be >= 0, got "
                f"{self.hbm_gib}")
        if self.live_steps < 1:
            raise DeepSpeedConfigError(
                f"tpu.step_autotune.live_steps must be >= 1, got "
                f"{self.live_steps}")


@dataclass
class ClusterHealthConfig(ConfigModel):
    """Cluster health plane (``runtime/health.py``; docs/recovery.md
    "Cluster health & SDC defense"). An out-of-band TCP heartbeat mesh
    between training processes — daemon threads, never through XLA
    collectives, so it stays live while the main thread is wedged inside
    one. Peers are tracked with the healthy→suspect→down silence
    schedule shared with the serving fleet (utils/health_state.py); a
    peer declared down mid-step makes every survivor abort with
    ``exit_code`` (one world-level failure for the elastic agent instead
    of N staggered hang timeouts); per-host step-time skew emits
    ``health.straggler``; and every ``digest_every_k`` steps an SDC probe
    digests the fully-replicated param leaves and cross-checks the
    digests over the mesh."""

    # "auto" = off single-process, on when jax.process_count() > 1; also
    # accepts plain booleans from JSON
    enabled: Any = "auto"
    host: str = "127.0.0.1"      # address this rank's beat server binds
    port_base: int = 29700       # rank r listens on port_base + r
    peers: List[str] = field(default_factory=list)  # ["host:port", ...]
    beat_interval_s: float = 0.5
    suspect_after_s: float = 2.0
    down_after_s: float = 6.0
    recover_probes: int = 2
    abort_on_peer_loss: bool = True
    exit_code: int = C.PEER_LOSS_EXIT_CODE_DEFAULT
    # SDC parameter-digest probe cadence in optimizer steps (0 disables)
    digest_every_k: int = 0
    # "abort": coordinated exit_code abort (the agent relaunches the
    # world from the newest manifest-valid tag); "rollback": flag the
    # mismatch for the engine, which routes through the sentinel's
    # in-process rollback at the next step boundary
    sdc_action: str = "abort"
    # straggler detection: own step-time EWMA vs the fleet median
    straggler_ratio: float = 1.5       # <=0 disables
    straggler_min_peers: int = 2       # ewma samples needed before judging
    ewma_alpha: float = 0.2
    # peer step counters further apart than this emit health.desync
    step_skew_threshold: int = 10      # <=0 disables

    def __post_init__validate__(self):
        if self.enabled not in (True, False, "auto"):
            raise DeepSpeedConfigError(
                "tpu.cluster_health.enabled must be true/false/'auto', "
                f"got {self.enabled!r}")
        if self.beat_interval_s <= 0:
            raise DeepSpeedConfigError(
                "tpu.cluster_health.beat_interval_s must be > 0, got "
                f"{self.beat_interval_s}")
        if not 0 < self.suspect_after_s < self.down_after_s:
            raise DeepSpeedConfigError(
                "tpu.cluster_health needs 0 < suspect_after_s < "
                f"down_after_s, got {self.suspect_after_s} / "
                f"{self.down_after_s}")
        if self.beat_interval_s >= self.suspect_after_s:
            raise DeepSpeedConfigError(
                "tpu.cluster_health.beat_interval_s must be < "
                "suspect_after_s (a healthy peer must beat faster than "
                f"the schedule suspects it), got {self.beat_interval_s} "
                f">= {self.suspect_after_s}")
        if self.recover_probes < 1:
            raise DeepSpeedConfigError(
                "tpu.cluster_health.recover_probes must be >= 1, got "
                f"{self.recover_probes}")
        if not (1 <= int(self.exit_code) <= 255):
            raise DeepSpeedConfigError(
                "tpu.cluster_health.exit_code must be in [1, 255], got "
                f"{self.exit_code}")
        if self.digest_every_k < 0:
            raise DeepSpeedConfigError(
                "tpu.cluster_health.digest_every_k must be >= 0 "
                f"(0 disables), got {self.digest_every_k}")
        if self.sdc_action not in ("abort", "rollback"):
            raise DeepSpeedConfigError(
                "tpu.cluster_health.sdc_action must be 'abort' or "
                f"'rollback', got {self.sdc_action!r}")
        if not 0 < self.ewma_alpha <= 1:
            raise DeepSpeedConfigError(
                "tpu.cluster_health.ewma_alpha must be in (0, 1], got "
                f"{self.ewma_alpha}")
        if not (1 <= self.port_base <= 65535):
            raise DeepSpeedConfigError(
                "tpu.cluster_health.port_base must be a valid port, got "
                f"{self.port_base}")

    def resolve_enabled(self, process_count: int) -> bool:
        """Auto-on exactly when there is a peer to watch."""
        if self.enabled == "auto":
            return int(process_count) > 1
        return bool(self.enabled)


@dataclass
class TpuConfig(ConfigModel):
    mesh: Dict[str, Any] = field(default_factory=dict)
    remat: str = "none"  # none | full | selective (dots_saveable)
    donate_params: bool = True
    matmul_precision: str = "default"
    # route FusedAdam to the Pallas kernel (ops/pallas/fused_adam.py) instead
    # of optax's XLA-fused chain
    use_pallas_optimizer: bool = False
    # debug observability for the 1-bit optimizers: materialize the exact
    # averaged-gradient norm each step via an UNCOMPRESSED pmean (costs a
    # full fp32 allreduce — defeats the compression, debug only) so
    # get_global_grad_norm() and monitors keep working. The int8 path
    # materializes its post-exchange norm for free and ignores this flag.
    compressed_grad_norm: bool = False
    # explicit bucketed gradient exchange — see GradExchangeConfig
    grad_exchange: Dict[str, Any] = field(default_factory=dict)
    # HBM-bounded step-config autotuner — see StepAutotuneConfig
    step_autotune: Dict[str, Any] = field(default_factory=dict)
    # pipeline stage-to-stage transport — see TpuPipelineConfig
    pipeline: Dict[str, Any] = field(default_factory=dict)
    # out-of-band heartbeat mesh + SDC probes — see ClusterHealthConfig
    cluster_health: Dict[str, Any] = field(default_factory=dict)

    @property
    def mesh_config(self) -> MeshConfig:
        return MeshConfig.from_dict(self.mesh)

    @property
    def cluster_health_config(self) -> ClusterHealthConfig:
        return ClusterHealthConfig.from_dict(self.cluster_health)

    @property
    def pipeline_config(self) -> "TpuPipelineConfig":
        return TpuPipelineConfig.from_dict(self.pipeline)

    @property
    def grad_exchange_config(self) -> GradExchangeConfig:
        return GradExchangeConfig.from_dict(self.grad_exchange)

    @property
    def step_autotune_config(self) -> StepAutotuneConfig:
        return StepAutotuneConfig.from_dict(self.step_autotune)


# ---------------------------------------------------------------------------
# Main config
# ---------------------------------------------------------------------------
class DeepSpeedConfig:
    """Parses a DeepSpeed-style JSON config (path or dict) and resolves the
    batch triad ``train_batch_size = micro_batch * grad_accum * dp_world``
    exactly like reference ``runtime/config.py:712-1058``."""

    def __init__(self, config, dp_world_size: Optional[int] = None):
        if isinstance(config, str):
            if not os.path.exists(config):
                raise DeepSpeedConfigError(f"config path does not exist: {config}")
            with open(config, "r") as f:
                self._param_dict = json.load(
                    f, object_pairs_hook=dict_raise_error_on_duplicate_keys
                )
        elif isinstance(config, dict):
            self._param_dict = dict(config)
        else:
            raise DeepSpeedConfigError(
                f"config must be a path or dict, got {type(config)}"
            )

        self.dp_world_size = dp_world_size
        self._initialize(self._param_dict)

    # -- feature blocks ----------------------------------------------------
    def _initialize(self, pd: Dict[str, Any]):
        self.train_batch_size = get_scalar_param(pd, C.TRAIN_BATCH_SIZE, None)
        self.train_micro_batch_size_per_gpu = get_scalar_param(
            pd, C.TRAIN_MICRO_BATCH_SIZE_PER_GPU, None
        )
        self.gradient_accumulation_steps = get_scalar_param(
            pd, C.GRADIENT_ACCUMULATION_STEPS, None
        )
        self.steps_per_print = get_scalar_param(
            pd, C.STEPS_PER_PRINT, C.STEPS_PER_PRINT_DEFAULT
        )
        self.gradient_clipping = get_scalar_param(
            pd, C.GRADIENT_CLIPPING, C.GRADIENT_CLIPPING_DEFAULT
        )
        self.prescale_gradients = get_scalar_param(
            pd, C.PRESCALE_GRADIENTS, C.PRESCALE_GRADIENTS_DEFAULT
        )
        self.gradient_predivide_factor = get_scalar_param(
            pd, C.GRADIENT_PREDIVIDE_FACTOR, C.GRADIENT_PREDIVIDE_FACTOR_DEFAULT
        )
        self.sparse_gradients_enabled = get_scalar_param(
            pd, C.SPARSE_GRADIENTS, C.SPARSE_GRADIENTS_DEFAULT
        )
        self.wall_clock_breakdown = get_scalar_param(
            pd, C.WALL_CLOCK_BREAKDOWN, C.WALL_CLOCK_BREAKDOWN_DEFAULT
        )
        self.memory_breakdown = get_scalar_param(
            pd, C.MEMORY_BREAKDOWN, C.MEMORY_BREAKDOWN_DEFAULT
        )
        self.dump_state = get_scalar_param(pd, C.DUMP_STATE, C.DUMP_STATE_DEFAULT)
        self.dataloader_drop_last = get_scalar_param(
            pd, C.DATALOADER_DROP_LAST, C.DATALOADER_DROP_LAST_DEFAULT
        )
        self.zero_allow_untested_optimizer = get_scalar_param(
            pd, C.ZERO_ALLOW_UNTESTED_OPTIMIZER, C.ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT
        )
        self.communication_data_type = get_scalar_param(
            pd, C.COMMUNICATION_DATA_TYPE, C.COMMUNICATION_DATA_TYPE_DEFAULT
        )
        if self.communication_data_type is not None and (
                self.communication_data_type not in C.COMMUNICATION_DATA_TYPES):
            raise DeepSpeedConfigError(
                f"Invalid {C.COMMUNICATION_DATA_TYPE}. Supported: "
                f"{C.COMMUNICATION_DATA_TYPES}. "
                f"Got: {self.communication_data_type}"
            )

        self.fp16 = Fp16Config.from_dict(pd.get(C.FP16, {}))
        bf16_block = pd.get(C.BFLOAT16, pd.get(C.BFLOAT16_OLD, {}))
        self.bf16 = Bf16Config.from_dict(bf16_block)
        self.amp = AmpConfig.from_dict(pd.get(C.AMP, {}))
        if self.fp16.enabled and self.bf16.enabled:
            raise DeepSpeedConfigError("fp16 and bf16 cannot both be enabled")

        self.zero_config = ZeroConfig.from_dict(pd.get(C.ZERO_OPTIMIZATION, {}))
        self.optimizer = OptimizerConfig.from_dict(pd.get(C.OPTIMIZER, {}))
        self.scheduler = SchedulerConfig.from_dict(pd.get(C.SCHEDULER, {}))
        self.activation_checkpointing = ActivationCheckpointingConfig.from_dict(
            pd.get(C.ACTIVATION_CHECKPOINTING, {})
        )
        self.flops_profiler = FlopsProfilerConfig.from_dict(
            pd.get(C.FLOPS_PROFILER, {})
        )
        self.tensorboard = TensorboardConfig.from_dict(pd.get(C.MONITOR_TENSORBOARD, {}))
        self.wandb = WandbConfig.from_dict(pd.get(C.MONITOR_WANDB, {}))
        self.csv_monitor = CsvConfig.from_dict(pd.get(C.MONITOR_CSV, {}))
        self.comms_logger = CommsLoggerConfig.from_dict(pd.get(C.COMMS_LOGGER, {}))
        self.step_profiler = StepProfilerConfig.from_dict(
            pd.get(C.STEP_PROFILER, {}))
        self.data_pipeline = DataPipelineConfig.from_dict(
            pd.get(C.DATA_PIPELINE, {}))
        self.curriculum_learning = CurriculumConfig.from_dict(
            pd.get(C.CURRICULUM_LEARNING, {})
        )
        self.progressive_layer_drop = ProgressiveLayerDropConfig.from_dict(
            pd.get(C.PROGRESSIVE_LAYER_DROP, {})
        )
        self.eigenvalue = EigenvalueConfig.from_dict(pd.get(C.EIGENVALUE, {}))
        self.aio = AioConfig.from_dict(pd.get(C.AIO, {}))
        self.pipeline = PipelineConfig.from_dict(pd.get(C.PIPELINE, {}))
        self.tpu = TpuConfig.from_dict(pd.get(C.TPU, {}))
        # Dict-shaped blocks consumed by their own subsystems
        self.sparse_attention = pd.get(C.SPARSE_ATTENTION, None)
        self.elasticity = pd.get(C.ELASTICITY, {})
        self.autotuning = pd.get(C.AUTOTUNING, {})
        self.compression_training = pd.get(C.COMPRESSION_TRAINING, {})
        self.data_efficiency = pd.get(C.DATA_EFFICIENCY, {})
        self.quantize_training = pd.get(C.QUANTIZE_TRAINING, {})
        from deepspeed_tpu.nebula import NebulaConfig

        self.nebula = NebulaConfig.from_dict(pd.get(C.NEBULA, {}))
        ckpt = pd.get(C.CHECKPOINT, {}) or {}
        self.checkpoint_tag_validation = str(
            ckpt.get(C.CHECKPOINT_TAG_VALIDATION, C.CHECKPOINT_TAG_VALIDATION_DEFAULT)
        ).title()
        if self.checkpoint_tag_validation not in C.CHECKPOINT_TAG_VALIDATION_MODES:
            raise DeepSpeedConfigError(
                f"checkpoint.tag_validation must be one of "
                f"{C.CHECKPOINT_TAG_VALIDATION_MODES}"
            )
        self.load_universal_checkpoint = ckpt.get(
            C.LOAD_UNIVERSAL_CHECKPOINT, C.LOAD_UNIVERSAL_CHECKPOINT_DEFAULT
        )
        self.checkpoint_keep_n = int(ckpt.get(
            C.CHECKPOINT_KEEP_N, C.CHECKPOINT_KEEP_N_DEFAULT))
        if self.checkpoint_keep_n < 0:
            raise DeepSpeedConfigError(
                f"checkpoint.keep_n must be >= 0 (0 = keep all), got "
                f"{self.checkpoint_keep_n}")
        self.checkpoint_verify = bool(ckpt.get(
            C.CHECKPOINT_VERIFY, C.CHECKPOINT_VERIFY_DEFAULT))
        self.graceful_shutdown = GracefulShutdownConfig.from_dict(
            pd.get(C.GRACEFUL_SHUTDOWN, {}))
        self.sentinel = SentinelConfig.from_dict(pd.get(C.SENTINEL, {}))
        self.telemetry = TelemetryConfig.from_dict(pd.get(C.TELEMETRY, {}))

        if self.dp_world_size is not None:
            self._resolve_batch_triad(self.dp_world_size)

    # -- batch triad (reference runtime/config.py _batch_assertion etc.) ---
    def _resolve_batch_triad(self, dp_world_size: int):
        train = self.train_batch_size
        micro = self.train_micro_batch_size_per_gpu
        gas = self.gradient_accumulation_steps

        for name, v in ((C.TRAIN_BATCH_SIZE, train),
                        (C.TRAIN_MICRO_BATCH_SIZE_PER_GPU, micro),
                        (C.GRADIENT_ACCUMULATION_STEPS, gas)):
            if v is not None and v <= 0:
                raise DeepSpeedConfigError(f"{name} must be positive, got {v}")

        if train is not None and micro is not None and gas is not None:
            pass
        elif train is not None and micro is not None:
            gas = train // (micro * dp_world_size)
        elif train is not None and gas is not None:
            micro = train // (gas * dp_world_size)
        elif micro is not None and gas is not None:
            train = micro * gas * dp_world_size
        elif train is not None:
            gas = 1
            micro = train // dp_world_size
        elif micro is not None:
            gas = 1
            train = micro * dp_world_size
        else:
            raise DeepSpeedConfigError(
                "At least one of train_batch_size or "
                "train_micro_batch_size_per_gpu must be set"
            )

        if micro is None or micro <= 0 or gas is None or gas <= 0:
            raise DeepSpeedConfigError(
                f"Could not resolve a positive batch triad from "
                f"train={self.train_batch_size} micro="
                f"{self.train_micro_batch_size_per_gpu} "
                f"gas={self.gradient_accumulation_steps} dp={dp_world_size}"
            )
        if train != micro * gas * dp_world_size:
            raise DeepSpeedConfigError(
                f"Batch triad inconsistent: train_batch_size {train} != "
                f"micro_batch {micro} * grad_accum {gas} * dp {dp_world_size}"
            )
        self.train_batch_size = train
        self.train_micro_batch_size_per_gpu = micro
        self.gradient_accumulation_steps = gas

    # -- convenience -------------------------------------------------------
    @property
    def zero_enabled(self) -> bool:
        return self.zero_config.stage > 0

    @property
    def precision_dtype(self) -> str:
        if self.bf16.enabled:
            return "bfloat16"
        if self.fp16.enabled:
            return "float16"
        return "float32"

    def print_config(self):
        logger.info("DeepSpeedConfig:\n%s", pretty_json(self._param_dict))

"""Per-block curvature estimation by power iteration
(reference ``runtime/eigenvalue.py:7``, feeding the MoQ bit schedule).

The reference builds Hessian-vector products from retained autograd graphs;
in JAX an Hv product is the forward-over-reverse composition
``jvp(grad(loss))`` — exact, jittable, and per-block by restricting the
differentiation to the leaves under a parameter-path prefix. Returns
``{block_name: (eigenvalue, layer_id)}`` like the reference so the MoQ
quantizer can modulate quantization periods.
"""

from typing import Callable, Dict, List, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.utils.tree import path_str


class Eigenvalue:
    def __init__(self, verbose: bool = False, max_iter: int = 100,
                 tol: float = 1e-2, stability: float = 1e-6,
                 gas_boundary_resolution: int = 1,
                 layer_name: str = "", layer_num: int = 0):
        self.verbose = verbose
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution
        self.layer_name = layer_name
        self.layer_num = layer_num

    @classmethod
    def from_config(cls, cfg) -> "Eigenvalue":
        return cls(verbose=cfg.verbose, max_iter=cfg.max_iter, tol=cfg.tol,
                   stability=cfg.stability,
                   gas_boundary_resolution=cfg.gas_boundary_resolution,
                   layer_name=cfg.layer_name, layer_num=cfg.layer_num)

    # ------------------------------------------------------------------
    def _normalize(self, leaves: List[jax.Array]):
        sq = sum(jnp.vdot(x, x).real for x in leaves)
        norm = jnp.sqrt(sq) + self.stability
        return [x / norm for x in leaves], norm

    def top_eigenvalue(self, loss_fn: Callable, params, block_prefix: str,
                      rng: jax.Array) -> float:
        """Largest |eigenvalue| of the Hessian of ``loss_fn(params)``
        restricted to leaves whose path starts with ``block_prefix``
        (path format 'a/b/c', see utils.tree.path_str)."""
        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        block_ix = [i for i, (path, _) in enumerate(flat)
                    if path_str(path).startswith(block_prefix)]
        if not block_ix:
            raise KeyError(
                f"no parameters under block prefix {block_prefix!r}; "
                f"available roots: "
                f"{sorted({path_str(p).split('/')[0] for p, _ in flat})}")
        all_leaves = [leaf for _, leaf in flat]
        block_leaves = [all_leaves[i] for i in block_ix]

        def block_loss(b_leaves):
            merged = list(all_leaves)
            for i, leaf in zip(block_ix, b_leaves):
                merged[i] = leaf
            return loss_fn(jax.tree.unflatten(treedef, merged))

        grad_fn = jax.grad(block_loss)

        def hvp(v):
            return jax.jvp(grad_fn, (block_leaves,), (v,))[1]

        v = [jax.random.normal(jax.random.fold_in(rng, i), x.shape,
                               jnp.float32).astype(x.dtype)
             for i, x in enumerate(block_leaves)]
        v, _ = self._normalize(v)

        eig = 0.0
        for it in range(self.max_iter):
            hv = hvp(v)
            v_new, norm = self._normalize(hv)
            new_eig = float(norm)
            if eig and abs(new_eig - eig) / max(abs(eig),
                                                self.stability) < self.tol:
                eig = new_eig
                break
            eig, v = new_eig, v_new
        if self.verbose:
            logger.info(f"eigenvalue[{block_prefix}] converged to "
                        f"{eig:.4e} in {it + 1} iterations")
        return eig

    def compute_eigenvalue(self, loss_fn: Callable, params,
                           block_prefixes: List[str],
                           rng: jax.Array) -> Dict[str, Tuple[float, int]]:
        """Power-iterate every named block; returns
        ``{prefix: (eigenvalue, index)}`` (reference returns a layer-id
        keyed dict consumed by the quantizer)."""
        out = {}
        for i, prefix in enumerate(block_prefixes):
            out[prefix] = (
                self.top_eigenvalue(loss_fn, params, prefix,
                                    jax.random.fold_in(rng, i)), i)
        return out

from deepspeed_tpu.runtime.fp16.onebit.adam import (  # noqa: F401
    OnebitAdamState,
    compressed_allreduce,
    onebit_adam,
)
from deepspeed_tpu.runtime.fp16.onebit.lamb import (  # noqa: F401
    ZeroOneAdamState,
    onebit_lamb,
    zero_one_adam,
)

from deepspeed_tpu.runtime.fp16.onebit.adam import (  # noqa: F401
    OnebitAdamState,
    compressed_allreduce,
    onebit_adam,
)

"""1-bit LAMB and 0/1 Adam — the other compressed-communication optimizers
(reference ``runtime/fp16/onebit/lamb.py:11`` OnebitLamb,
``zoadam.py:10`` ZeroOneAdam).

Both reuse :func:`~deepspeed_tpu.runtime.fp16.onebit.adam.compressed_allreduce`
(int8 signs + fp32 scales over the dp axis with two-phase error feedback):

* ``onebit_lamb`` — 1-bit Adam's warmup/compression phases plus LAMB's
  layerwise trust ratio ||w|| / ||update|| applied at the step, so large
  layers keep stable effective LRs under compression noise.
* ``zero_one_adam`` — 0/1 Adam's looser sync schedule: the variance is
  refreshed every ``var_update_period`` steps (not frozen forever) and
  momentum sync can be skipped ``local_steps`` at a time between
  compressed exchanges.
"""

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from deepspeed_tpu.runtime.fp16.onebit.adam import (
    OnebitAdamState,
    _pad_to,
    compressed_allreduce,
    onebit_adam,
)


def onebit_lamb(learning_rate: float, b1: float = 0.9, b2: float = 0.999,
                eps: float = 1e-8, weight_decay: float = 0.0,
                warmup_steps: int = 100, axis: str = "dp",
                axis_size: Optional[int] = None,
                min_trust: float = 0.01, max_trust: float = 10.0):
    """1-bit Adam core + LAMB layerwise trust-ratio scaling."""
    inner = onebit_adam(1.0, b1, b2, eps, 0.0, warmup_steps, axis,
                        axis_size)

    def init(params):
        return inner.init(params)

    def update(grads, state, params):
        prev_count = state.count  # pre-increment: optax schedules are 0-based
        raw_updates, state = inner.update(grads, state, params)
        lr = (learning_rate(prev_count) if callable(learning_rate)
              else learning_rate)

        def scale_one(p, u):
            upd = -u  # inner returns the negative step at lr=1
            if weight_decay > 0:
                upd = upd + weight_decay * p
            wn = jnp.linalg.norm(p.astype(jnp.float32))
            un = jnp.linalg.norm(upd.astype(jnp.float32))
            trust = jnp.where(
                (wn > 0) & (un > 0),
                jnp.clip(wn / jnp.maximum(un, 1e-12), min_trust, max_trust),
                1.0)
            return (-lr * trust * upd).astype(p.dtype)

        return jax.tree.map(scale_one, params, raw_updates), state

    return optax.GradientTransformation(init, update)


class ZeroOneAdamState(NamedTuple):
    count: jnp.ndarray
    exp_avg: optax.Updates
    exp_avg_sq: optax.Updates
    worker_error: optax.Updates
    server_error: optax.Updates


def zero_one_adam(learning_rate: float, b1: float = 0.9, b2: float = 0.999,
                  eps: float = 1e-8, weight_decay: float = 0.0,
                  var_update_period: int = 16, axis: str = "dp",
                  axis_size: Optional[int] = None):
    """0/1 Adam: compressed momentum sync every step, exact variance
    refresh every ``var_update_period`` steps (reference zoadam.py's
    adaptive variance/momentum update policies, simplified to fixed
    periods)."""
    if axis_size is None:
        raise ValueError("pass axis_size (dp world size)")

    base = onebit_adam(learning_rate, b1, b2, eps, weight_decay,
                       warmup_steps=1, axis=axis, axis_size=axis_size)

    def init(params):
        s = base.init(params)
        return ZeroOneAdamState(*s)

    def update(grads, state, params):
        count = state.count + 1
        # refresh at step 1 too: an all-zero variance until the first
        # period boundary would make 1/(sqrt(v)+eps) explode
        refresh = ((count % var_update_period) == 0) | (count == 1)

        # compressed momentum exchange (always)
        local_m = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g,
                               state.exp_avg, grads)
        flat_m, treedef = jax.tree.flatten(local_m)
        flat_we = jax.tree.leaves(state.worker_error)
        flat_se = jax.tree.leaves(state.server_error)
        out_m, out_we, out_se = [], [], []
        for m, we, se in zip(flat_m, flat_we, flat_se):
            n = m.size
            red, we2, se2 = compressed_allreduce(
                _pad_to(m.reshape(-1).astype(jnp.float32), we.shape[0]),
                we, se, axis, n_valid=n)
            out_m.append(red[:n].reshape(m.shape))
            out_we.append(we2)
            out_se.append(se2)
        exp_avg = jax.tree.unflatten(treedef, out_m)

        # periodic exact variance refresh with pmean'd grads
        def refreshed(operand):
            grads, v = operand
            g_avg = jax.tree.map(lambda g: jax.lax.pmean(g, axis), grads)
            return jax.tree.map(lambda vv, g: b2 * vv + (1 - b2) * g * g,
                                v, g_avg)

        exp_avg_sq = jax.lax.cond(
            refresh, refreshed, lambda o: o[1], (grads, state.exp_avg_sq))

        bias1 = 1 - b1 ** count.astype(jnp.float32)
        # v sees one update per refresh (steps 1, P, 2P, ...); count them
        n_refresh = (1 + count // var_update_period).astype(jnp.float32)
        bias2 = 1 - b2 ** n_refresh
        lr = (learning_rate(state.count) if callable(learning_rate)
              else learning_rate)  # pre-increment: optax schedules are 0-based

        def step_one(p, m, v):
            denom = jnp.sqrt(v / bias2) + eps
            upd = m / bias1 / denom
            if weight_decay > 0:
                upd = upd + weight_decay * p
            return (-lr * upd).astype(p.dtype)

        updates = jax.tree.map(step_one, params, exp_avg, exp_avg_sq)
        return updates, ZeroOneAdamState(
            count=count, exp_avg=exp_avg, exp_avg_sq=exp_avg_sq,
            worker_error=jax.tree.unflatten(treedef, out_we),
            server_error=jax.tree.unflatten(treedef, out_se))

    return optax.GradientTransformation(init, update)

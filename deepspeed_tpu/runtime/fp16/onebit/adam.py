"""1-bit Adam: error-compensated sign-compressed communication
(reference ``runtime/fp16/onebit/adam.py:10`` OnebitAdam +
``runtime/comm/nccl.py:51`` compressed_allreduce).

Algorithm (1-bit Adam paper): run vanilla Adam for ``warmup_steps`` ("full
precision stage"), then FREEZE the variance and switch to the compression
stage — each step the momentum is updated locally and exchanged as
sign bits + one scale, with error feedback buffers absorbing the
compression residual on both the worker and server side.

TPU re-design: the two-phase NCCL gather dance becomes a shard_map
program over the ``dp`` axis — phase 1 compresses the local tensor and
``psum_scatter``s sign*scale (int8 signs over ICI), phase 2 compresses the
reduced chunk with server error feedback and ``all_gather``s it back.
Usable standalone via :func:`compressed_allreduce` or as the
:func:`onebit_adam` optax-style transformation inside a shard_mapped train
step (the per-worker gradient must not be pre-averaged — the compressor IS
the allreduce).
"""

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax


def padded_length(n: int, k: int) -> int:
    """Length of ``n`` rounded up to a multiple of the axis size ``k``
    (compressed_allreduce chunks the tensor k ways)."""
    return -(-n // k) * k


def _pad_to(flat, n_pad):
    n = flat.shape[0]
    if n == n_pad:
        return flat
    return jnp.concatenate([flat, jnp.zeros((n_pad - n,), flat.dtype)])


def _compress(x, error, valid_mask=None):
    """Sign compression with error feedback: returns (signs int8, scale,
    new_error). scale is the mean |corrected| so that scale*sign is the
    l1-optimal 1-bit approximation. ``valid_mask`` excludes padding slots:
    pads must not dilute the scale, and their error feedback is pinned to
    zero so they cannot oscillate into it either."""
    corrected = x + error
    if valid_mask is not None:
        corrected = jnp.where(valid_mask, corrected, 0.0)
        scale = (jnp.sum(jnp.abs(corrected))
                 / jnp.maximum(jnp.sum(valid_mask), 1))
    else:
        scale = jnp.mean(jnp.abs(corrected))
    signs = jnp.where(corrected >= 0, jnp.int8(1), jnp.int8(-1))
    decompressed = scale * signs.astype(x.dtype)
    new_error = corrected - decompressed
    if valid_mask is not None:
        new_error = jnp.where(valid_mask, new_error, 0.0)
    return signs, scale, new_error


def compressed_allreduce(x, worker_error, server_error, axis: str,
                         n_valid: Optional[int] = None):
    """Error-compensated mean-allreduce of ``x`` over mesh axis ``axis``
    (reference NcclBackend.compressed_allreduce, two-phase).

    Call inside shard_map. Shapes: x and worker_error [n] (padded to a
    multiple of the axis size); server_error [n / axis_size]. ``n_valid``
    (static) is the unpadded length: positions >= n_valid are excluded
    from the compression scales and their error feedback is pinned to 0
    (pads would otherwise dilute the scale ~k-fold for tiny leaves).
    Returns (allreduced mean, new_worker_error, new_server_error).

    The payloads that cross the interconnect are int8 sign tensors plus one
    fp32 scale per worker — n int8 (all_to_all) + n/k int8 (all_gather)
    instead of 2n fp32; decompression and summation happen locally after
    each exchange, exactly like the reference's gather-then-sum phases.
    """
    k = jax.lax.psum(1, axis)
    n = x.shape[0]
    if n % k:
        raise ValueError(f"tensor length {n} must be divisible by axis "
                         f"size {k}; pad first")
    chunk = n // k
    padded = n_valid is not None and n_valid < n
    # phase 1: compress locally; ship int8 signs chunk-to-owner via
    # all_to_all (worker j receives every worker's signs for chunk j) and
    # the fp32 scales via a scalar all_gather; sum after decompression.
    mask1 = (jnp.arange(n) < n_valid) if padded else None
    signs, scale, new_worker_error = _compress(x, worker_error, mask1)
    signs_by_chunk = signs.reshape(k, chunk)
    recv_signs = jax.lax.all_to_all(signs_by_chunk, axis, split_axis=0,
                                    concat_axis=0, tiled=False)  # [k, chunk]
    scales = jax.lax.all_gather(scale, axis)  # [k] fp32
    chunk_sum = jnp.sum(
        recv_signs.astype(jnp.float32) * scales[:, None], axis=0)
    # phase 2: compress the reduced chunk (mean over workers) with server
    # error feedback; ship int8 signs + fp32 scale, decompress locally.
    # Pads live only in the tail chunks: mask by this worker's global span.
    server_chunk = chunk_sum / k
    mask2 = None
    if padded:
        j = jax.lax.axis_index(axis)
        mask2 = (jnp.arange(chunk) + j * chunk) < n_valid
    s_signs, s_scale, new_server_error = _compress(server_chunk,
                                                   server_error, mask2)
    all_signs = jax.lax.all_gather(s_signs, axis)          # [k, chunk] int8
    all_scales = jax.lax.all_gather(s_scale, axis)         # [k] fp32
    result = (all_signs.astype(jnp.float32)
              * all_scales[:, None]).reshape(n)
    return result, new_worker_error, new_server_error


class OnebitAdamState(NamedTuple):
    count: jnp.ndarray
    exp_avg: optax.Updates
    exp_avg_sq: optax.Updates
    worker_error: optax.Updates
    server_error: optax.Updates


def onebit_adam(learning_rate: float, b1: float = 0.9, b2: float = 0.999,
                eps: float = 1e-8, weight_decay: float = 0.0,
                warmup_steps: int = 100, axis: str = "dp",
                axis_size: Optional[int] = None):
    """Optax-style 1-bit Adam for shard_mapped steps.

    ``update(grads, state, params)`` takes PER-WORKER gradients (not yet
    averaged); during warmup it psum-averages them exactly, afterwards the
    momentum itself is exchanged via :func:`compressed_allreduce` with the
    frozen variance (reference onebit/adam.py comp stage).
    """

    def init(params):
        zeros = jax.tree.map(lambda p: jnp.zeros_like(p, jnp.float32),
                             params)
        k = axis_size
        if k is None:
            raise ValueError("pass axis_size (dp world size) so server "
                             "error buffers can be shaped")

        return OnebitAdamState(
            count=jnp.zeros((), jnp.int32),
            exp_avg=zeros,
            exp_avg_sq=jax.tree.map(lambda p: jnp.zeros_like(
                p, jnp.float32), params),
            # error buffers are padded so any leaf size works (the exchange
            # chunks the flat tensor k ways)
            worker_error=jax.tree.map(
                lambda p: jnp.zeros((padded_length(p.size, k),),
                                    jnp.float32), params),
            server_error=jax.tree.map(
                lambda p: jnp.zeros((padded_length(p.size, k) // k,),
                                    jnp.float32), params),
        )

    def update(grads, state, params):
        count = state.count + 1
        in_warmup = count <= warmup_steps

        def warmup_branch(operand):
            grads, state = operand
            g_avg = jax.tree.map(lambda g: jax.lax.pmean(g, axis), grads)
            exp_avg = jax.tree.map(
                lambda m, g: b1 * m + (1 - b1) * g, state.exp_avg, g_avg)
            exp_avg_sq = jax.tree.map(
                lambda v, g: b2 * v + (1 - b2) * g * g,
                state.exp_avg_sq, g_avg)
            return exp_avg, exp_avg_sq, state.worker_error, \
                state.server_error

        def compressed_branch(operand):
            grads, state = operand
            # momentum updated with LOCAL grad, then compressed-allreduced;
            # variance frozen (reference: stops updating after warmup)
            local_m = jax.tree.map(
                lambda m, g: b1 * m + (1 - b1) * g, state.exp_avg, grads)

            flat_m, treedef = jax.tree.flatten(local_m)
            flat_we = jax.tree.leaves(state.worker_error)
            flat_se = jax.tree.leaves(state.server_error)
            out_m, out_we, out_se = [], [], []
            for m, we, se in zip(flat_m, flat_we, flat_se):
                shape, n = m.shape, m.size
                red, we2, se2 = compressed_allreduce(
                    _pad_to(m.reshape(-1).astype(jnp.float32),
                            we.shape[0]), we, se, axis, n_valid=n)
                out_m.append(red[:n].reshape(shape))
                out_we.append(we2)
                out_se.append(se2)
            exp_avg = jax.tree.unflatten(treedef, out_m)
            return exp_avg, state.exp_avg_sq, \
                jax.tree.unflatten(treedef, out_we), \
                jax.tree.unflatten(treedef, out_se)

        exp_avg, exp_avg_sq, worker_error, server_error = jax.lax.cond(
            in_warmup, warmup_branch, compressed_branch, (grads, state))

        bias1 = 1 - b1 ** count.astype(jnp.float32)
        # variance is frozen at the end of warmup; clamp the exponent to
        # >= 1 so warmup_steps=0 cannot produce bias2 == 0 (0/0 -> NaN)
        bias2 = 1 - b2 ** jnp.maximum(
            jnp.minimum(count, warmup_steps), 1).astype(jnp.float32)

        # schedules are sampled at the PRE-increment count: optax
        # transformations index schedules from step 0, and a compressed run
        # must see the same warmup point as the same config uncompressed
        lr = (learning_rate(state.count) if callable(learning_rate)
              else learning_rate)

        def step_one(p, m, v):
            denom = jnp.sqrt(v / bias2) + eps
            upd = m / bias1 / denom
            if weight_decay > 0:
                upd = upd + weight_decay * p
            return (-lr * upd).astype(p.dtype)

        updates = jax.tree.map(step_one, params, exp_avg, exp_avg_sq)
        return updates, OnebitAdamState(
            count=count, exp_avg=exp_avg, exp_avg_sq=exp_avg_sq,
            worker_error=worker_error, server_error=server_error)

    return optax.GradientTransformation(init, update)

"""Pipeline module/layer specs.

Parity with reference ``deepspeed/runtime/pipe/module.py`` (LayerSpec :23,
PipelineModule :85): a model expressed as a list of layer specs that the
pipeline engine partitions into stages. The TPU engine (pipe/engine.py) maps
stages onto the ``pp`` mesh axis and rotates microbatches with ppermute.
"""

from typing import Any, Callable, List, Optional, Sequence

import numpy as np


class LayerSpec:
    """Deferred layer constructor (reference pipe/module.py:23): holds the
    module class + args so stages build only their own layers."""

    def __init__(self, typename, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs

    def build(self):
        return self.typename(*self.module_args, **self.module_kwargs)

    def __repr__(self):
        return f"LayerSpec({getattr(self.typename, '__name__', self.typename)})"


class TiedLayerSpec(LayerSpec):
    """reference pipe/module.py TiedLayerSpec — layers sharing params across
    stages (e.g. embedding/unembedding)."""

    def __init__(self, key, typename, *module_args, forward_fn=None, **kwargs):
        super().__init__(typename, *module_args, **kwargs)
        self.key = key
        self.forward_fn = forward_fn


def partition_uniform(num_items: int, num_parts: int) -> List[int]:
    """Uniform split boundaries (reference runtime/utils.py partition_uniform)."""
    parts = [0] * (num_parts + 1)
    chunk = num_items // num_parts
    extra = num_items % num_parts
    offset = 0
    for p in range(num_parts):
        parts[p] = offset
        offset += chunk + (1 if p < extra else 0)
    parts[num_parts] = num_items
    return parts


def partition_balanced(weights: Sequence[float], num_parts: int) -> List[int]:
    """Weight-balanced contiguous partition via prefix sums + binary search
    (reference runtime/utils.py partition_balanced)."""
    prefix = np.concatenate([[0.0], np.cumsum(np.asarray(weights, np.float64))])
    total = prefix[-1]
    parts = [0]
    for p in range(1, num_parts):
        target = total * p / num_parts
        idx = int(np.searchsorted(prefix, target))
        idx = max(parts[-1] + 1, min(idx, len(weights) - (num_parts - p)))
        parts.append(idx)
    parts.append(len(weights))
    return parts


class PipelineModule:
    """A sequence of LayerSpecs with a partition method (reference
    pipe/module.py:85; partitioning logic :361-416).

    The flax modules built from the specs must each map
    ``(params, hidden, batch) -> hidden``; the first layer receives the batch
    inputs, the last produces the loss given labels (see pipe/engine.py for
    the stage program contract).
    """

    def __init__(
        self,
        layers: Sequence[LayerSpec],
        num_stages: Optional[int] = None,
        loss_fn: Optional[Callable] = None,
        partition_method: str = "uniform",
        activation_checkpoint_interval: int = 0,
        tp_rules: Optional[Callable] = None,
    ):
        self.layer_specs = list(layers)
        self.num_stages = num_stages
        self.loss_fn = loss_fn
        self.partition_method = partition_method
        self.activation_checkpoint_interval = activation_checkpoint_interval
        # tensor-parallel PartitionSpec rules applied per stage (the dense
        # engine reads these off the model; pipeline layers declare them here)
        self.tp_rules = tp_rules

    def partition(self, num_stages: int) -> List[int]:
        method = self.partition_method.lower()
        n = len(self.layer_specs)
        if method in ("uniform", "parameters", "type:regex", "best"):
            # parameter-balanced partitioning needs built layers; uniform is
            # the right default when layers are homogeneous transformer blocks
            return partition_uniform(n, num_stages)
        raise ValueError(f"unknown partition method {self.partition_method}")

    def __len__(self):
        return len(self.layer_specs)

"""Pipeline stage-to-stage activation transport.

The 1F1B interpreter (``pipe/engine.py``) is host-driven: per-stage
compute runs as separate jitted programs on per-stage sub-meshes. What
moves BETWEEN stages is the transport, and this module gives it two
implementations behind one API (``tpu.pipeline.transport``):

* ``device_put`` — the original host-level cross-mesh copy. Fast and
  simple in a single process, but it is a host-mediated transfer XLA can
  never overlap with compute, and on a multi-process mesh it needs the
  backend's cross-host transfer server (the CPU backend has none — the
  path hangs; see tests/unit/test_multihost.py).

* ``ppermute`` — the transfer re-expressed as an IN-PROGRAM collective
  over the JOINT ``(pp, dp, ...)`` mesh: every stage's shard of a
  ``[S, ...]``-stacked payload hops one ``pp`` coordinate per
  ``lax.ppermute`` (forward ``s -> s+1``, backward ``s+1 -> s``). The
  source stage contributes its real activation shards; every other pp
  coordinate contributes cached zero filler, so the one compiled shift
  program serves every hop of every micro batch. Filler hops ride
  otherwise-idle links in parallel with the real payload — per-device
  wire bytes equal the real transfer. Because the collective is a joint-
  mesh program, EVERY process participates (McJAX SPMD: all processes
  owning mesh devices must dispatch the same program), which is exactly
  what makes multi-process pipeline parallelism work where cross-mesh
  ``device_put`` cannot.

Ownership: a process "owns" a stage when it addresses at least one
device of that stage's sub-mesh. Per-stage compute must only be
dispatched by owners (a jit over a fully non-addressable mesh is
illegal); the joint-mesh transport and the ``[S]``-slot scalar psum are
dispatched by everyone. The transport never touches checkpoint layout —
both modes see identical per-stage param trees.

Multi-process data contract: every process must feed ``train_batch`` the
same GLOBAL batch stream (the standard McJAX pattern — each process
slices out its addressable shards in ``_put``).
"""

from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm.logging import comms_logger
from deepspeed_tpu.parallel.mesh import BATCH_AXES


def resolve_transport(configured: str) -> str:
    """``auto`` -> ppermute across processes, device_put within one."""
    if configured == "auto":
        return "ppermute" if jax.process_count() > 1 else "device_put"
    return configured


class StageTransport:
    """Stage-to-stage transfer over a joint mesh (or cross-mesh puts)."""

    def __init__(self, topology, stage_topos: List, mode: str):
        assert mode in ("ppermute", "device_put"), mode
        self.topology = topology
        self.stage_topos = stage_topos
        self.mode = mode
        self.num_stages = len(stage_topos)
        self.multiprocess = jax.process_count() > 1
        pid = jax.process_index()
        self._owns = [
            any(d.process_index == pid for d in t.mesh.devices.flat)
            for t in stage_topos
        ]
        self._dev_stage: Dict[Any, int] = {}
        for s, t in enumerate(stage_topos):
            for d in t.mesh.devices.flat:
                self._dev_stage[d] = s
        self._batch_axes = tuple(
            a for a in BATCH_AXES if topology.size(a) > 1)
        self._filler: Dict[Tuple, Any] = {}
        self._shift_fns: Dict[Tuple, Any] = {}
        self._psum_fns: Dict[Tuple, Any] = {}

    # ------------------------------------------------------------------
    def owns_stage(self, s: int) -> bool:
        """Whether this process addresses any device of stage ``s``."""
        return self._owns[s]

    def send_forward(self, tree: Optional[Any], from_stage: int,
                     avals: Any) -> Optional[Any]:
        """Move ``tree`` from ``from_stage`` to ``from_stage + 1``.

        ``tree`` is the source stage's output (None on processes that do
        not own the source); ``avals`` its ShapeDtypeStruct tree (known
        host-side everywhere from the init-time eval_shape chain).
        Returns the received tree on owners of the destination stage,
        None elsewhere. ALL processes must call this in ppermute mode —
        the shift is a joint-mesh collective.
        """
        if self.mode == "device_put":
            sharding = self.stage_topos[from_stage + 1].batch_sharding()
            return jax.tree.map(
                lambda v: jax.device_put(v, sharding), tree)
        return self._hop(tree, from_stage, from_stage + 1, avals, "fwd")

    def send_backward(self, tree: Optional[Any], from_stage: int,
                      avals: Any) -> Optional[Any]:
        """Move a cotangent tree from ``from_stage`` to ``from_stage - 1``
        (same contract as :meth:`send_forward`)."""
        if self.mode == "device_put":
            sharding = self.stage_topos[from_stage - 1].batch_sharding()
            return jax.tree.map(
                lambda v: jax.device_put(v, sharding), tree)
        return self._hop(tree, from_stage, from_stage - 1, avals, "bwd")

    def psum_stage_scalars(self, contribs: Dict[int, Any],
                           shape: Tuple[int, ...] = (),
                           dtype=np.float32) -> np.ndarray:
        """Sum per-stage host-readable values across stages; every process
        gets the (replicated) result. ``contribs[s]`` is stage ``s``'s
        ``shape``-shaped contribution, supplied by its owner(s) — owners
        of the same stage must supply the same value (it fills the same
        ``[S]``-slot once, not additively). Used for the cross-stage grad
        norm and for broadcasting the last stage's losses; in device_put
        (single-controller) mode it is a plain host sum.
        """
        if self.mode == "device_put":
            total = np.zeros(shape, dtype)
            for v in contribs.values():
                total = total + np.asarray(v, dtype)
            return total
        S = self.num_stages
        gshape = (S,) + tuple(shape)
        sh = NamedSharding(self.topology.mesh, P("pp"))
        host = {s: np.asarray(v, dtype).reshape(shape)
                for s, v in contribs.items()}
        zero = np.zeros((1,) + tuple(shape), dtype)
        arrays = []
        for dev in sh.addressable_devices_indices_map(gshape):
            v = host.get(self._dev_stage[dev])
            arrays.append(jax.device_put(
                zero if v is None else v[None], dev))
        joint = jax.make_array_from_single_device_arrays(gshape, sh, arrays)
        out = self._psum_fn(tuple(shape), np.dtype(dtype).str)(joint)
        return np.asarray(out.addressable_shards[0].data[0])

    # ------------------------------------------------------------------
    def _leaf_spec(self, aval) -> P:
        if self._batch_axes and len(aval.shape) >= 1:
            return P("pp", self._batch_axes)
        return P("pp")

    def _hop(self, tree, src, dst, avals, direction):
        aval_leaves, treedef = jax.tree.flatten(avals)
        if tree is not None:
            src_leaves = [self._canon(l, src)
                          for l in jax.tree.leaves(tree)]
            assert len(src_leaves) == len(aval_leaves), (
                f"stage {src} produced {len(src_leaves)} leaves but its "
                f"recorded avals have {len(aval_leaves)}")
        else:
            src_leaves = [None] * len(aval_leaves)
        joint = tuple(self._to_joint(l, a, src)
                      for l, a in zip(src_leaves, aval_leaves))
        specs = tuple(self._leaf_spec(a) for a in aval_leaves)
        shifted = self._shift_fn(direction, specs)(*joint)
        if not self._owns[dst]:
            return None
        out = [self._from_joint(j, a, dst)
               for j, a in zip(shifted, aval_leaves)]
        return jax.tree.unflatten(treedef, out)

    def _canon(self, leaf, stage):
        """Pin a source leaf to the stage's canonical batch sharding (a
        per-stage jit usually already produced exactly that; a mismatch
        reshards within the sub-mesh)."""
        sharding = self.stage_topos[stage].batch_sharding()
        if leaf.sharding.is_equivalent_to(sharding, leaf.ndim):
            return leaf
        return jax.device_put(leaf, sharding)

    def _to_joint(self, leaf, aval, src):
        """Stack one stage-local leaf into the ``[S, ...]`` joint-mesh
        array: the source stage's devices contribute their real shards
        (on-device reshape, no copy off the device), every other pp
        coordinate gets cached zero filler."""
        S = self.num_stages
        sh = NamedSharding(self.topology.mesh, self._leaf_spec(aval))
        gshape = (S,) + tuple(aval.shape)
        fshape = sh.shard_shape(gshape)
        shard_by_dev = ({s.device: s.data for s in leaf.addressable_shards}
                       if leaf is not None else {})
        arrays = []
        for dev in sh.addressable_devices_indices_map(gshape):
            piece = shard_by_dev.get(dev)
            arrays.append(self._zero_filler(dev, fshape, aval.dtype)
                          if piece is None else piece[None])
        return jax.make_array_from_single_device_arrays(gshape, sh, arrays)

    def _from_joint(self, joint, aval, dst):
        """Extract the destination stage's slot from the shifted joint
        array as a sub-mesh array in the stage's batch sharding."""
        sub = self.stage_topos[dst].batch_sharding()
        gshape = tuple(aval.shape)
        shard_by_dev = {s.device: s.data for s in joint.addressable_shards}
        arrays = [shard_by_dev[dev][0]
                  for dev in sub.addressable_devices_indices_map(gshape)]
        return jax.make_array_from_single_device_arrays(gshape, sub, arrays)

    def _zero_filler(self, dev, shape, dtype):
        key = (dev.id, tuple(shape), np.dtype(dtype).str)
        z = self._filler.get(key)
        if z is None:
            z = jax.device_put(np.zeros(shape, dtype), dev)
            self._filler[key] = z
        return z

    def _shift_fn(self, direction, specs):
        """One jitted joint-mesh ppermute per (direction, leaf-spec
        tuple); jax.jit's aval cache makes it serve every hop, micro
        batch, and step."""
        key = (direction, specs)
        fn = self._shift_fns.get(key)
        if fn is None:
            S = self.num_stages
            perm = ([(s, s + 1) for s in range(S - 1)] if direction == "fwd"
                    else [(s, s - 1) for s in range(1, S)])

            def shift(*leaves):
                out = []
                for x in leaves:
                    # trace-time wire metering: x is the per-device block,
                    # so bytes are the real per-device payload (filler
                    # hops ride idle links in parallel — not extra wire
                    # on the payload path)
                    comms_logger.append(
                        "ppermute", x, "pp",
                        log_name=f"pipe_transfer.{direction}", world=S)
                    out.append(lax.ppermute(x, "pp", perm))
                return tuple(out)

            fn = jax.jit(jax.shard_map(
                shift, mesh=self.topology.mesh, in_specs=specs,
                out_specs=specs, check_vma=False))
            self._shift_fns[key] = fn
        return fn

    def _psum_fn(self, shape, dtype_str):
        key = (tuple(shape), dtype_str)
        fn = self._psum_fns.get(key)
        if fn is None:
            def f(x):
                return lax.psum(x, "pp")

            fn = jax.jit(jax.shard_map(
                f, mesh=self.topology.mesh, in_specs=P("pp"),
                out_specs=P("pp"), check_vma=False))
            self._psum_fns[key] = fn
        return fn

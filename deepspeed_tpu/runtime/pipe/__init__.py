"""Pipeline parallelism (reference deepspeed/runtime/pipe/)."""

from deepspeed_tpu.runtime.pipe.module import (  # noqa: F401
    LayerSpec,
    PipelineModule,
    TiedLayerSpec,
    partition_balanced,
    partition_uniform,
)
from deepspeed_tpu.runtime.pipe.schedule import (  # noqa: F401
    InferenceSchedule,
    TrainSchedule,
)

"""Pipeline parallelism (reference deepspeed/runtime/pipe/)."""

from deepspeed_tpu.runtime.pipe.module import LayerSpec, PipelineModule  # noqa: F401

"""Pipeline instruction schedules.

Parity with reference ``deepspeed/runtime/pipe/schedule.py`` (PipeSchedule
:52, InferenceSchedule :129, TrainSchedule :182): a pipeline step is a
program of instructions per stage. The TPU engine executes ONE merged
clock-ordered stream on the host (single controller, all stages visible)
instead of per-rank streams — device-level overlap comes from JAX async
dispatch, and the clock order IS the 1F1B interleave.

1F1B timing model (equal fwd/bwd clocks, the reference's steady state):

* ``fwd(s, m)`` at clock ``s + 2m``
* ``bwd(s, m)`` at clock ``2*stages - 1 - s + 2m``

which gives the reference's ``2*(micro_batches + stages - 1)`` total clocks,
immediate bwd after fwd on the last stage, and at most ``stages - s``
in-flight activations on stage ``s`` (the 1F1B memory bound).
"""

from typing import List, NamedTuple, Sequence


class PipeInstruction(NamedTuple):
    """One instruction (reference schedule.py PipeInstruction / buffer ids)."""

    op: str           # forward | backward | load | optimizer_step
    stage: int
    micro_batch: int

    def __repr__(self):
        return f"{self.op}(s={self.stage}, mb={self.micro_batch})"


def ForwardPass(stage, mb):
    return PipeInstruction("forward", stage, mb)


def BackwardPass(stage, mb):
    return PipeInstruction("backward", stage, mb)


def LoadMicroBatch(stage, mb):
    return PipeInstruction("load", stage, mb)


def OptimizerStep(stage=-1, mb=-1):
    return PipeInstruction("optimizer_step", stage, mb)


class TrainSchedule:
    """1F1B train schedule (reference schedule.py:182).

    ``clocks()`` yields lists of instructions per clock tick; executing them
    in order is a valid topological order of the pipeline dataflow.
    """

    def __init__(self, micro_batches: int, stages: int):
        assert micro_batches >= 1 and stages >= 1
        self.micro_batches = micro_batches
        self.stages = stages

    @property
    def num_clocks(self) -> int:
        return 2 * (self.micro_batches + self.stages - 1)

    def _fwd_clock(self, stage: int, mb: int) -> int:
        return stage + 2 * mb

    def _bwd_clock(self, stage: int, mb: int) -> int:
        return 2 * self.stages - 1 - stage + 2 * mb

    def clocks(self) -> List[List[PipeInstruction]]:
        out: List[List[PipeInstruction]] = [[] for _ in range(self.num_clocks)]
        for m in range(self.micro_batches):
            for s in range(self.stages):
                fc = self._fwd_clock(s, m)
                if s == 0:
                    out[fc].append(LoadMicroBatch(s, m))
                out[fc].append(ForwardPass(s, m))
                out[self._bwd_clock(s, m)].append(BackwardPass(s, m))
        # instructions within a clock run first-stage-first for forwards,
        # last-stage-first for backwards (dependencies are cross-clock only)
        for cl in out:
            cl.sort(key=lambda ins: (ins.op == "backward",
                                     ins.stage if ins.op != "backward"
                                     else -ins.stage))
        return out

    def steps(self) -> List[PipeInstruction]:
        flat = [ins for clock in self.clocks() for ins in clock]
        flat.append(OptimizerStep())
        return flat

    def max_in_flight(self, stage: int) -> int:
        """Peak live activations on ``stage`` (1F1B bound: stages - stage)."""
        return min(self.micro_batches, self.stages - stage)


class InferenceSchedule:
    """Forward-only wavefront (reference schedule.py:129)."""

    def __init__(self, micro_batches: int, stages: int):
        self.micro_batches = micro_batches
        self.stages = stages

    @property
    def num_clocks(self) -> int:
        return self.micro_batches + self.stages - 1

    def clocks(self) -> List[List[PipeInstruction]]:
        out: List[List[PipeInstruction]] = [[] for _ in range(self.num_clocks)]
        for m in range(self.micro_batches):
            for s in range(self.stages):
                c = s + m
                if s == 0:
                    out[c].append(LoadMicroBatch(s, m))
                out[c].append(ForwardPass(s, m))
        return out

    def steps(self) -> List[PipeInstruction]:
        return [ins for clock in self.clocks() for ins in clock]


def validate_schedule(sched: Sequence[List[PipeInstruction]], stages: int,
                      micro_batches: int) -> None:
    """Assert the clock stream is a valid topological order of pipeline
    dataflow (used by tests; the reference trusts its construction)."""
    done = set()
    for clock in sched:
        for ins in clock:
            if ins.op == "forward":
                if ins.stage > 0:
                    assert ("forward", ins.stage - 1, ins.micro_batch) in done, ins
            if ins.op == "backward":
                assert ("forward", ins.stage, ins.micro_batch) in done, ins
                if ins.stage < stages - 1:
                    assert ("backward", ins.stage + 1, ins.micro_batch) in done, ins
        for ins in clock:
            done.add((ins.op, ins.stage, ins.micro_batch))
    for m in range(micro_batches):
        for s in range(stages):
            assert ("forward", s, m) in done
